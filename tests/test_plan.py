"""Parallelism planner (plan/): cost model, search, artifact, trainer wiring.

Correctness is pinned three ways (the ISSUE's acceptance bar):

- every emitted ``Plan`` is memory-feasible and round-trips through JSON +
  ``--plan <path>`` into an actual mesh the composed trainer runs on the
  8-virtual-device CPU fleet;
- on synthetic scenarios with a stubbed topology, the analytical ranking
  matches brute-force evaluation of the cost model over the same candidate
  set (search adds pruning/ordering, never a different answer);
- ``--plan`` omitted leaves the trainers bitwise identical: a plan file that
  pins the exact same layout produces bitwise-equal parameters to the
  plan-less run.
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import plan
from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
    Dataset, _normalize, _synthesize_split,
)
from csed_514_project_distributed_training_using_pytorch_tpu.plan import (
    Candidate, ModelStats, Plan, Topology,
)
from csed_514_project_distributed_training_using_pytorch_tpu.plan.search import (
    Ranked, Scenario, _sort_key,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
    ComposedConfig, LMConfig,
)

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)


@pytest.fixture(scope="module")
def tiny_datasets():
    xs, ys = _synthesize_split(128, seed=300)
    train = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    xs, ys = _synthesize_split(100, seed=301)
    test = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    return train, test


def _stub_scenario(*, num_devices=8, hbm_bytes=16 << 30, ici=1e10, dcn=1e9,
                   num_slices=1, global_batch=64, param_mb=4.0, layers=4,
                   heads=8, seq=256, embed=128, allow_fsdp=True,
                   allow_grad_accum=True, axes=("data", "model", "stage"),
                   optimizer_mult=2.0) -> Scenario:
    """A fully synthetic scenario: stubbed topology, analytic model stats —
    no jax, no live devices consulted."""
    stats = ModelStats(
        name="stub", param_bytes=param_mb * 1e6,
        flops_per_example=6 * param_mb * 1e6 / 4 * seq,
        num_layers=layers, num_heads=heads, seq_len=seq, embed_dim=embed,
        dtype_bytes=4, act_bytes_per_layer_per_example=seq * embed * 4 * 14,
        score_bytes_per_example=heads * seq * seq * 4.0,
        optimizer_mult=optimizer_mult, shardable_fraction=0.9)
    topo = Topology(num_devices=num_devices, device_kind="stub",
                    hbm_bytes=hbm_bytes, peak_flops=1e12, ici_bytes=ici,
                    dcn_bytes=dcn, num_slices=num_slices)
    return Scenario(run_type="composed", stats=stats, topo=topo,
                    global_batch=global_batch, axes=axes,
                    allow_fsdp=allow_fsdp, allow_grad_accum=allow_grad_accum)


# ------------------------------------------------------------------ topology


def test_topology_helpers_report_budget_and_granules():
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
        device_memory_budget, topology_summary,
    )

    nbytes, source = device_memory_budget()
    assert nbytes > 0 and source in ("env", "runtime", "spec", "nominal")
    t = topology_summary()
    assert t["device_count"] >= 8          # the conftest virtual CPU platform
    assert t["num_granules"] == 1          # single process, no slices
    assert t["hbm_bytes"] > 0 and t["platform"] == "cpu"


def test_hbm_env_override_wins(monkeypatch):
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
        device_memory_budget,
    )

    monkeypatch.setenv("PLAN_HBM_BYTES", str(123 << 20))
    assert device_memory_budget() == (123 << 20, "env")


# --------------------------------------------------------------- enumeration


def test_enumerate_candidates_are_legal():
    sc = _stub_scenario()
    cands = plan.enumerate_candidates(sc)
    assert cands, "search space must not be empty"
    assert len(set(cands)) == len(cands), "no duplicate candidates"
    for c in cands:
        assert c.num_devices == sc.topo.num_devices
        assert sc.global_batch % (c.grad_accum * c.data) == 0
        if c.model > 1:
            assert sc.stats.num_heads % c.model == 0
            assert sc.stats.embed_dim % c.model == 0
        if c.stage > 1:
            assert sc.stats.num_layers % c.stage == 0
            assert not c.fsdp, "FSDP never composes with a stage axis"
            step_batch = sc.global_batch // c.grad_accum
            assert step_batch % c.microbatches == 0
            assert (step_batch // c.microbatches) % c.data == 0
        else:
            assert c.microbatches == 1


def test_stage_split_must_divide_the_test_batch_too():
    """The composed trainer's eval engine pipelines the SAME microbatch split
    over ``batch_size_test`` — a stage plan whose split fails that guard must
    never be enumerated (review r6 finding: mb=16 vs the default test batch
    1000)."""
    sc = _stub_scenario(global_batch=64)
    with_test = dataclasses.replace(sc, test_batch=1000)
    for c in plan.enumerate_candidates(with_test):
        if c.stage > 1:
            assert 1000 % c.microbatches == 0
    # mb=16 exists without the constraint and is exactly what it removes.
    assert any(c.stage > 1 and c.microbatches == 16
               for c in plan.enumerate_candidates(sc))
    assert not any(c.stage > 1 and c.microbatches == 16
                   for c in plan.enumerate_candidates(with_test))


def test_gpipe_microbatching_never_buys_activation_memory():
    """GPipe keeps every in-flight microbatch's forward activations resident
    through the fill: at fixed grad_accum, a stage candidate's modeled
    activation bytes must be IDENTICAL across microbatch splits (the bubble
    term, not the memory gate, is what M improves) — and a plain-DP candidate
    with grad_accum really does shrink them."""
    sc = _stub_scenario()
    act = lambda c: plan.predict(sc.stats, sc.topo, c,
                                 global_batch=sc.global_batch).act_bytes_per_chip
    m1 = act(Candidate(data=4, stage=2, microbatches=1))
    m8 = act(Candidate(data=4, stage=2, microbatches=8))
    assert m1 == m8
    assert act(Candidate(data=8, grad_accum=4)) < act(Candidate(data=8))


def test_plan_missing_required_field_is_a_value_error():
    """Hand-edited artifacts (a documented workflow) with missing required
    fields must fail the load contract's ValueError, not a bare TypeError."""
    p = plan.resolve("auto", _stub_scenario())
    d = p.to_dict()
    del d["run_type"]
    with pytest.raises(ValueError, match="corrupt plan artifact"):
        Plan.from_dict(d)


def test_enumerate_respects_axis_allowlist():
    sc = _stub_scenario(axes=("data",), allow_fsdp=False,
                        allow_grad_accum=False)
    cands = plan.enumerate_candidates(sc)
    assert cands == [Candidate(data=8)]


def test_mesh_spec_always_names_the_data_axis():
    assert Candidate(data=1, model=4).mesh_spec() == "data=1,model=4"
    assert Candidate(data=8).mesh_spec() == "data=8"
    assert Candidate(data=2, model=2, stage=2).mesh_spec() == \
        "data=2,model=2,stage=2"


# ------------------------------------------- ranking vs brute force (stubbed)


@pytest.mark.parametrize("scenario_kwargs", [
    # Compute-rich, bandwidth-poor: collectives dominate the ranking.
    dict(ici=2e9, dcn=2e8, param_mb=64.0, global_batch=256),
    # Bandwidth-rich, two DCN granules: hierarchical DP splits engage.
    dict(ici=1e11, dcn=1e9, num_slices=2, param_mb=16.0, global_batch=128),
], ids=["bandwidth-poor", "two-granules"])
def test_ranking_matches_brute_force(scenario_kwargs):
    """The search's ordering IS brute force over the cost model: re-evaluating
    ``plan.predict`` independently for every enumerated candidate and sorting
    by (feasible, step_s, tie-break) must reproduce the ranked list exactly."""
    sc = _stub_scenario(**scenario_kwargs)
    ranked = plan.search(sc, top=10_000)
    brute = [Ranked(c, plan.predict(sc.stats, sc.topo, c,
                                    global_batch=sc.global_batch,
                                    hbm_fraction=sc.hbm_fraction))
             for c in plan.enumerate_candidates(sc)]
    brute.sort(key=_sort_key)
    assert [r.candidate for r in ranked] == [r.candidate for r in brute]
    # And the head really is the argmin over feasible predicted step time.
    feasible_min = min(r.costs.step_s for r in brute if r.costs.fits)
    assert ranked[0].costs.step_s == feasible_min
    assert ranked[0].costs.fits


def test_memory_pressure_prefers_sharded_state():
    """Shrinking the stubbed HBM until replicated optimizer state can't fit
    must push the pick to a layout that shards it (FSDP / TP / PP) — and the
    pick is always feasible."""
    roomy = plan.search(_stub_scenario(param_mb=64.0, hbm_bytes=16 << 30))[0]
    assert roomy.costs.fits
    # 64 MB params × (1 + 2 opt + 1 grad) = 256 MB replicated; a ~130 MB chip
    # forces sharding.
    tight = plan.search(_stub_scenario(param_mb=64.0, hbm_bytes=130 << 20))[0]
    assert tight.costs.fits
    c = tight.candidate
    assert c.fsdp or c.model > 1 or c.stage > 1
    assert tight.costs.total_bytes_per_chip <= tight.costs.hbm_budget_bytes


def test_nothing_fits_raises():
    with pytest.raises(ValueError, match="no layout fits"):
        plan.search(_stub_scenario(param_mb=64.0, hbm_bytes=1 << 20))


# ----------------------------------------------------------------- artifact


def test_plan_roundtrips_through_json():
    sc = _stub_scenario()
    p = plan.resolve("auto", sc)
    q = Plan.from_json(p.to_json())
    assert q == p
    assert q.candidate == p.candidate
    assert q.predicted["fits"] is True


def test_plan_rejects_corrupt_artifacts(tmp_path):
    sc = _stub_scenario()
    p = plan.resolve("auto", sc)
    d = p.to_dict()
    d["device_count"] = 5                       # axes product mismatch
    with pytest.raises(ValueError, match="product"):
        Plan.from_dict(d)
    with pytest.raises(ValueError, match="missing"):
        Plan.from_dict({"hello": 1})
    d2 = p.to_dict()
    d2["wat"] = 1                               # unknown key at our schema
    with pytest.raises(ValueError, match="unknown keys"):
        Plan.from_dict(d2)


def test_resolve_file_validates_run_type_and_devices(tmp_path):
    sc = _stub_scenario()
    p = plan.resolve("auto", sc)
    path = str(tmp_path / "p.json")
    p.save(path)
    lm_sc = dataclasses.replace(sc, run_type="lm")
    with pytest.raises(ValueError, match="made for the 'composed' trainer"):
        plan.resolve(path, lm_sc)
    small = dataclasses.replace(sc, topo=dataclasses.replace(sc.topo,
                                                             num_devices=4))
    with pytest.raises(ValueError, match="only 4 are addressable"):
        plan.resolve(path, small)
    loaded = plan.resolve(path, sc)
    assert loaded.source == "file" and loaded.mesh == p.mesh


# ----------------------------------------------------------------- autotune


def test_autotune_reranks_by_measurement_and_emits_events():
    sc = _stub_scenario()
    ranked = plan.search(sc, top=4)
    # Stub trial: reverse the analytical order among the measured rows; the
    # third candidate is "unbuildable" (returns None) and keeps its estimate.
    measured = {ranked[0].candidate: 3e-3, ranked[1].candidate: 1e-3}

    def trial(cand):
        if cand == ranked[2].candidate:
            return None
        return {"step_s": measured[cand], "compile_s": 0.5,
                "flops_per_step": 1e9}

    events = []
    sc = dataclasses.replace(sc, trial=trial)
    out = plan.autotune.refine(sc, ranked, top_k=3, emit=events.append)
    # Measured rows first, ordered by measurement; unmeasured keep model order.
    assert out[0].candidate == ranked[1].candidate
    assert out[0].measured_step_s == 1e-3
    assert out[1].candidate == ranked[0].candidate
    assert [r.measured_step_s for r in out[2:]] == [None] * (len(out) - 2)
    assert [e["event"] for e in events] == ["autotune"] * 3
    assert events[2]["measured_step_s"] is None       # the unbuildable one
    assert events[0]["rank"] == 0 and events[0]["compile_s"] == 0.5


def test_plan_telemetry_events_are_strict_jsonl(tmp_path):
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    p = plan.resolve("auto", _stub_scenario())
    path = str(tmp_path / "t.jsonl")
    w = T.TelemetryWriter(path)
    w.emit(T.plan_event(p))
    w.emit(T.autotune_event(mesh="data=8", fsdp=False, grad_accum=1,
                            microbatches=1, rank=0,
                            predicted_step_s=float("inf")))
    rows = [json.loads(line) for line in open(path)]
    assert [r["event"] for r in rows] == ["plan", "autotune"]
    assert rows[0]["mesh"] == p.mesh
    assert rows[0]["predicted_step_s"] == pytest.approx(
        p.predicted["step_s"])
    assert rows[1]["predicted_step_s"] is None        # non-finite -> null


# ------------------------------------------------- trainer integration (CPU)


def test_auto_plan_trains_and_saves_replayable_artifact(tmp_path,
                                                        tiny_datasets):
    """The tier-1 end-to-end pin: ``--plan auto`` picks a layout, the composed
    trainer builds a REAL multi-device CPU mesh from it and trains, the saved
    artifact is feasible, and replaying it through ``--plan <path>`` reproduces
    the run exactly."""
    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        composed,
    )

    cfg = ComposedConfig(mesh="data=2", plan="auto", epochs=1, batch_size=16,
                         batch_size_test=100,
                         results_dir=str(tmp_path / "auto"),
                         telemetry=str(tmp_path / "auto.jsonl"))
    state, hist = composed.main(cfg, datasets=tiny_datasets)
    path = str(tmp_path / "auto" / "plan_composed.json")
    saved = Plan.load(path)
    assert saved.source == "auto" and saved.device_count == 8
    assert saved.predicted["fits"] is True
    assert saved.predicted["total_bytes_per_chip"] <= \
        saved.predicted["hbm_budget_bytes"]
    events = [json.loads(line) for line in open(str(tmp_path / "auto.jsonl"))]
    (pe,) = [e for e in events if e["event"] == "plan"]
    assert pe["mesh"] == saved.mesh and pe["source"] == "auto"
    # The manifest records the PLANNED mesh — the one the run actually used.
    (me,) = [e for e in events if e["event"] == "manifest"]
    assert me["config"]["mesh"] == saved.mesh

    cfg2 = ComposedConfig(mesh="data=2", plan=path, epochs=1, batch_size=16,
                          batch_size_test=100, results_dir="")
    state2, hist2 = composed.main(cfg2, datasets=tiny_datasets)
    np.testing.assert_array_equal(np.asarray(state2.params["pos_embed"]),
                                  np.asarray(state.params["pos_embed"]))
    assert hist2.train_losses == hist.train_losses


def test_plan_omitted_is_bitwise_identical_to_pinned_plan(tmp_path,
                                                          tiny_datasets):
    """The zero-cost contract: no ``--plan`` touches nothing, and a plan file
    pinning the exact default layout produces bitwise-equal parameters — the
    apply path is pure configuration, never semantics."""
    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        composed,
    )

    base = ComposedConfig(mesh="data=8", epochs=1, batch_size=16,
                          batch_size_test=100, results_dir="")
    state_off, hist_off = composed.main(base, datasets=tiny_datasets)

    pinned = Plan(run_type="composed", device_count=8, mesh="data=8",
                  axes={"data": 8, "model": 1, "stage": 1})
    path = str(tmp_path / "pinned.json")
    pinned.save(path)
    cfg = dataclasses.replace(base, plan=path)
    state_plan, hist_plan = composed.main(cfg, datasets=tiny_datasets)
    import jax

    flat_off = jax.tree_util.tree_leaves(state_off.params)
    flat_plan = jax.tree_util.tree_leaves(state_plan.params)
    for a, b in zip(flat_off, flat_plan):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert hist_plan.train_losses == hist_off.train_losses


def test_apply_plan_returns_config_untouched_when_off():
    cfg = ComposedConfig()
    out, p = plan.apply_plan(cfg, "composed")
    assert out is cfg and p is None


def test_tune_mode_measures_and_plan_records_it(tmp_path, monkeypatch,
                                                tiny_datasets):
    """``--plan tune`` on the live CPU mesh: one candidate is AOT-compiled and
    short-trialed (top_k pinned to 1 to keep tier-1 fast); the emitted plan
    carries a measured step time and the telemetry an ``autotune`` line."""
    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        composed,
    )

    monkeypatch.setattr(plan, "AUTOTUNE_TOP_K", 1)
    cfg = ComposedConfig(mesh="data=2", plan="tune", epochs=1, batch_size=16,
                         batch_size_test=100,
                         results_dir=str(tmp_path / "tune"),
                         telemetry=str(tmp_path / "tune.jsonl"))
    composed.main(cfg, datasets=tiny_datasets)
    saved = Plan.load(str(tmp_path / "tune" / "plan_composed.json"))
    assert saved.source == "tune"
    assert saved.measured_step_s is not None and saved.measured_step_s > 0
    events = [json.loads(line) for line in open(str(tmp_path / "tune.jsonl"))]
    tuned = [e for e in events if e["event"] == "autotune"]
    assert len(tuned) == 1 and tuned[0]["measured_step_s"] > 0
    assert tuned[0]["compile_s"] > 0


@pytest.mark.slow
def test_lm_plan_auto_trains(tmp_path, tiny_datasets):
    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        lm as lm_train,
    )

    cfg = LMConfig(plan="auto", epochs=1, batch_size=16, eval_batch=100,
                   generate=0, results_dir=str(tmp_path / "lm"),
                   images_dir=str(tmp_path / "img"),
                   telemetry=str(tmp_path / "lm.jsonl"))
    lm_train.main(cfg, datasets=tiny_datasets)
    saved = Plan.load(str(tmp_path / "lm" / "plan_lm.json"))
    assert saved.run_type == "lm" and saved.predicted["fits"] is True
    events = [json.loads(line) for line in open(str(tmp_path / "lm.jsonl"))]
    assert "plan" in [e["event"] for e in events]


# -------------------------------------------------------------- report CLI


def test_plan_report_cli_renders(tmp_path):
    p = plan.resolve("auto", _stub_scenario())
    path = str(tmp_path / "p.json")
    p.save(path)
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "plan_report.py"), path],
        capture_output=True, text=True, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "chosen: mesh" in out.stdout
    assert "pred_ms" in out.stdout and "fits" in out.stdout


def test_plan_report_cli_joins_telemetry(tmp_path):
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    p = plan.resolve("auto", _stub_scenario())
    path = str(tmp_path / "p.json")
    p.save(path)
    tele = str(tmp_path / "run.jsonl")
    w = T.TelemetryWriter(tele)
    w.emit({"event": "epoch", "epoch": 0, "execute_s": 2.0, "steps": 100})
    w.emit(T.autotune_event(mesh=p.mesh, fsdp=p.fsdp,
                            grad_accum=p.grad_accum, microbatches=1, rank=0,
                            predicted_step_s=p.predicted["step_s"],
                            measured_step_s=0.02, compile_s=1.0))
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "plan_report.py"), path,
         "--telemetry", tele],
        capture_output=True, text=True, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "run measured (telemetry): best step 20.000 ms" in out.stdout


def test_bench_scaling_plan_prediction_rows():
    """``bench_scaling.py --plan``'s per-count prediction helper: a DP-only
    pick whose predicted epoch seconds scale with the step count."""
    sys.path.insert(0, _REPO)
    try:
        import bench_scaling
    finally:
        sys.path.pop(0)
    row = bench_scaling._plan_prediction(8, steps_per_epoch=100)
    assert row["planned_mesh"] == "data=8"
    # Rows round to 4 decimals for the JSON artifact.
    assert row["predicted_epoch_seconds"] == pytest.approx(
        row["predicted_step_s"] * 100, abs=1e-4)
