"""Test harness: force an 8-device virtual CPU platform.

This is the TPU-world analog of a fake distributed backend (SURVEY.md §4): multi-chip SPMD
logic (mesh construction, batch sharding, the fused gradient all-reduce, ppermute rings) runs
and is verified on 8 virtual CPU devices, no TPU pod required.

Ordering subtlety: this environment's ``sitecustomize`` may already have imported JAX and
registered a TPU PJRT plugin at interpreter start, so setting env vars here can be too late for
``import jax`` — we also push the platform choice through ``jax.config`` before any backend is
initialized, which keeps the (exclusive, possibly tunnelled) TPU unclaimed while tests run.
"""

import os

# Opt-in hardware mode: ``FRAMEWORK_TEST_PLATFORM=tpu pytest tests/ -k tpu`` leaves the
# real backend alone so the TPU-gated smokes (e.g. the Mosaic compile paths in
# test_pallas_attention.py) actually run when a chip is reachable. Default remains the
# 8-virtual-device CPU platform — the suite must never claim the (exclusive, tunnelled)
# TPU by accident.
_platform = os.environ.get("FRAMEWORK_TEST_PLATFORM", "cpu").strip().lower()
if _platform not in ("cpu", "tpu"):
    # Fail fast: a typo here must not silently skip the CPU pin and claim the
    # (exclusive, tunnelled) TPU for the whole suite.
    raise RuntimeError(
        f"FRAMEWORK_TEST_PLATFORM must be 'cpu' or 'tpu', got {_platform!r}")

if _platform == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if _platform == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
