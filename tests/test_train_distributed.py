"""End-to-end distributed trainer + smoke test on the 8-device virtual CPU mesh (the
multi-node-without-a-cluster setup the reference cannot do, SURVEY.md §4.4): full workflow of
reference src/train_dist.py, plus the index-plan layout contract."""

import os

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
    Dataset, _normalize, _synthesize_split,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train import distributed, smoke
from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (

    DistributedConfig,
)

# Heavyweight end-to-end/equivalence tests: full-suite runs only; deselect with
# -m "not slow" for the fast single-core signal (README).
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_datasets():
    xs, ys = _synthesize_split(2048, seed=200)
    train = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    xs, ys = _synthesize_split(400, seed=201)
    test = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    return train, test


def test_epoch_index_plan_layout():
    """Column-block r of the plan must be replica r's DistributedSampler shard."""
    world, per_b = 4, 8
    samplers = [ShardedSampler(1000, num_replicas=world, rank=r, seed=42)
                for r in range(world)]
    plan = distributed.epoch_index_plan(samplers, epoch=3, per_replica_batch=per_b)
    assert plan.shape == (1000 // world // per_b, world * per_b)
    for r in range(world):
        block = plan[:, r * per_b:(r + 1) * per_b].ravel()
        np.testing.assert_array_equal(block, samplers[r].epoch_indices(3)[:len(block)])


def test_distributed_trainer_end_to_end(tmp_path, tiny_datasets, capsys, devices8):
    cfg = DistributedConfig(
        epochs=3, global_batch_size=64, batch_size_test=100, learning_rate=0.05,
        momentum=0.5, results_dir=str(tmp_path / "results"),
        images_dir=str(tmp_path / "images"))
    state, history = distributed.main(cfg, num_devices=8, datasets=tiny_datasets)

    out = capsys.readouterr().out
    assert "Distributed training: 8 devices" in out
    assert "Epoch 0: train_loss:" in out and "Epoch 2: train_loss:" in out
    # 3 epochs -> 3 eval records; loss must clearly drop on the learnable task
    assert len(history.test_losses) == 3
    assert history.test_losses[-1] < history.test_losses[0] - 0.1
    # 2048/8 = 256 per replica, per-replica batch 8 -> 32 steps/epoch, 3 epochs
    assert int(state.step) == 96
    # process-0 final params export (≙ reference src/train_dist.py:163-164)
    assert os.path.exists(os.path.join(cfg.results_dir, "model_dist.msgpack"))


def test_distributed_matches_world1(tmp_path, tiny_datasets, devices8):
    """Same config on a 1-device vs 8-device mesh: same global batch sequence ⇒ same final
    val loss trajectory would require identical sampler layout, which differs (world-size
    enters the sharding); instead assert both converge and world-8 keeps replicas in one
    compiled program (state identical across devices by construction)."""
    cfg = DistributedConfig(epochs=2, global_batch_size=64, batch_size_test=100,
                            learning_rate=0.05, momentum=0.5,
                            results_dir=str(tmp_path / "r1"),
                            images_dir=str(tmp_path / "i1"))
    _, h1 = distributed.main(cfg, num_devices=1, datasets=tiny_datasets)
    _, h8 = distributed.main(cfg, num_devices=8, datasets=tiny_datasets)
    assert h1.test_losses[-1] < h1.test_losses[0]
    assert h8.test_losses[-1] < h8.test_losses[0]


def test_distributed_shard_eval(tmp_path, tiny_datasets, devices8):
    """shard_eval=True (the fixed version of quirk §2d.7) must give the same val metrics."""
    base = dict(epochs=1, global_batch_size=64, batch_size_test=50, learning_rate=0.05,
                momentum=0.5)
    cfg_rep = DistributedConfig(**base, results_dir=str(tmp_path / "r"),
                                images_dir=str(tmp_path / "i"))
    cfg_sh = DistributedConfig(**base, shard_eval=True,
                               results_dir=str(tmp_path / "rs"),
                               images_dir=str(tmp_path / "is"))
    _, h_rep = distributed.main(cfg_rep, num_devices=8, datasets=tiny_datasets)
    _, h_sh = distributed.main(cfg_sh, num_devices=8, datasets=tiny_datasets)
    np.testing.assert_allclose(h_rep.test_losses, h_sh.test_losses, rtol=1e-4)


def test_indivisible_batch_raises(tiny_datasets, devices8):
    with pytest.raises(ValueError):
        distributed.main(DistributedConfig(global_batch_size=60), num_devices=8,
                         datasets=tiny_datasets)


def test_smoke_ring(capsys, devices8):
    assert smoke.main(num_devices=8)
    out = capsys.readouterr().out
    assert "Device 1 has data 0.0" in out
    assert "OK — rendezvous + ring p2p verified" in out


def test_distributed_resume_reproduces_uninterrupted_run(tmp_path, tiny_datasets,
                                                         devices8):
    """Kill-and-resume oracle (r1 verdict item 8): train 4 epochs straight through; then
    train 2 epochs (the 'killed' run — its per-epoch model_dist.ckpt survives) and resume
    from that checkpoint for the remaining epochs. The resumed trajectory must land on the
    SAME final TrainState as the uninterrupted run — params, velocity, and step."""
    from flax import serialization

    base = dict(epochs=4, global_batch_size=64, batch_size_test=100,
                learning_rate=0.05, momentum=0.5)

    full_cfg = DistributedConfig(**base, results_dir=str(tmp_path / "full"),
                                 images_dir=str(tmp_path / "full_i"))
    full_state, full_hist = distributed.main(full_cfg, num_devices=8,
                                             datasets=tiny_datasets)

    killed_cfg = DistributedConfig(**{**base, "epochs": 2},
                                   results_dir=str(tmp_path / "killed"),
                                   images_dir=str(tmp_path / "killed_i"))
    distributed.main(killed_cfg, num_devices=8, datasets=tiny_datasets)
    ckpt = os.path.join(killed_cfg.results_dir, "model_dist.ckpt")
    assert os.path.exists(ckpt)

    resumed_cfg = DistributedConfig(**base, resume_from=ckpt,
                                    results_dir=str(tmp_path / "resumed"),
                                    images_dir=str(tmp_path / "resumed_i"))
    resumed_state, resumed_hist = distributed.main(resumed_cfg, num_devices=8,
                                                   datasets=tiny_datasets)

    assert int(resumed_state.step) == int(full_state.step)
    # Resumed run trains epochs 2..3 only (2 eval records vs the full run's 4).
    assert len(resumed_hist.test_losses) == 2
    np.testing.assert_allclose(resumed_hist.test_losses, full_hist.test_losses[2:],
                               rtol=1e-5)
    for k in full_state.params:
        np.testing.assert_allclose(np.asarray(resumed_state.params[k]),
                                   np.asarray(full_state.params[k]),
                                   rtol=1e-5, atol=1e-7, err_msg=f"param {k}")
        np.testing.assert_allclose(np.asarray(resumed_state.velocity[k]),
                                   np.asarray(full_state.velocity[k]),
                                   rtol=1e-5, atol=1e-7, err_msg=f"velocity {k}")


def _available_cores() -> int:
    try:
        return len(os.sched_getaffinity(0))    # respects cgroup/affinity limits (Linux)
    except AttributeError:
        return os.cpu_count() or 1


@pytest.mark.skipif(
    _available_cores() < 8,
    reason="the host-local per-step path runs a cross-module all-reduce whose 8 "
           "rendezvous participants spin-wait; on a host with fewer cores than mesh "
           "devices XLA:CPU can starve 3+ participants for its full 40s termination "
           "timeout and then hard-abort the process (observed at 1 visible core). "
           "Virtual-CPU-only artifact — the collective rides ICI on real chips, and the "
           "2-process fleet variant in test_multiprocess.py still covers the path here.")
def test_host_local_feed_matches_device_resident(tmp_path, tiny_datasets, devices8):
    """--host-local-feed (the multi-host input pipeline, SURVEY.md §7d) must produce the
    SAME final params as the device-resident scan fast path: identical plan, identical
    step math — only the feeding mechanism differs."""
    base = dict(epochs=1, global_batch_size=64, batch_size_test=100,
                learning_rate=0.05, momentum=0.5)
    cfg_fast = DistributedConfig(**base, results_dir=str(tmp_path / "fast"),
                                 images_dir=str(tmp_path / "fast_i"))
    cfg_host = DistributedConfig(**base, host_local_feed=True,
                                 results_dir=str(tmp_path / "host"),
                                 images_dir=str(tmp_path / "host_i"))
    s_fast, h_fast = distributed.main(cfg_fast, num_devices=8, datasets=tiny_datasets)
    s_host, h_host = distributed.main(cfg_host, num_devices=8, datasets=tiny_datasets)

    assert int(s_fast.step) == int(s_host.step)
    np.testing.assert_allclose(h_fast.test_losses, h_host.test_losses, rtol=1e-5)
    for k in s_fast.params:
        np.testing.assert_allclose(np.asarray(s_host.params[k]),
                                   np.asarray(s_fast.params[k]),
                                   rtol=1e-5, atol=1e-7, err_msg=f"param {k}")


def test_distributed_trainer_with_transformer_model(tmp_path, tiny_datasets, devices8):
    """--model transformer through the full SPMD trainer: the attention family trains
    data-parallel on the 8-device mesh with no CNN-specific assumptions."""
    cfg = DistributedConfig(
        epochs=1, global_batch_size=64, batch_size_test=100, learning_rate=0.05,
        momentum=0.5, model="transformer", results_dir=str(tmp_path / "results"),
        images_dir=str(tmp_path / "images"))
    state, history = distributed.main(cfg, num_devices=8, datasets=tiny_datasets)
    assert "pos_embed" in state.params
    assert np.isfinite(history.test_losses[-1])
    assert os.path.exists(os.path.join(cfg.results_dir, "model_dist.msgpack"))


def test_distributed_grad_accum(tmp_path, tiny_datasets, devices8):
    """--grad-accum through the SPMD epoch program: runs, trains, and rejects
    indivisible per-replica microbatches."""
    cfg = DistributedConfig(
        epochs=1, global_batch_size=64, batch_size_test=100, learning_rate=0.05,
        momentum=0.5, grad_accum=4, results_dir=str(tmp_path / "results"),
        images_dir=str(tmp_path / "images"))
    state, history = distributed.main(cfg, num_devices=8, datasets=tiny_datasets)
    assert np.isfinite(history.test_losses[-1])

    with pytest.raises(ValueError, match="grad_accum"):
        distributed.main(DistributedConfig(global_batch_size=64, grad_accum=3),
                         num_devices=8, datasets=tiny_datasets)


def test_distributed_fsdp_matches_plain_dp(tmp_path, tiny_datasets, devices8):
    """--fsdp (r5: ZeRO as a trainer mode) shards params + optimizer state over the
    data axis and must reproduce the plain-DP trajectory exactly — sharding is an
    execution layout. The transformer family actually shards (the CNN's leaves
    mostly replicate under the min-size rule), so it is the meaningful case."""
    def run(tag, **kw):
        cfg = DistributedConfig(
            epochs=2, global_batch_size=64, batch_size_test=100,
            learning_rate=0.05, model="transformer",
            results_dir=str(tmp_path / tag), images_dir=str(tmp_path / tag / "i"),
            **kw)
        return distributed.main(cfg, num_devices=8, datasets=tiny_datasets)

    state_dp, hist_dp = run("dp")
    state_fs, hist_fs = run("fsdp", fsdp=True)
    np.testing.assert_allclose(hist_fs.train_losses, hist_dp.train_losses,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hist_fs.test_losses, hist_dp.test_losses,
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(np.asarray(state_fs.params["pos_embed"]),
                    np.asarray(state_dp.params["pos_embed"])):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
    # The FSDP run's checkpoint is layout-standard (gathered before save): it
    # restores into the plain template.
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )
    import jax

    template = create_train_state(build_model("transformer"),
                                  jax.random.PRNGKey(3))
    restored = checkpoint.restore_train_state(
        os.path.join(str(tmp_path / "fsdp"), "model_dist.ckpt"), template)
    assert int(restored.step) == int(state_fs.step)
