"""MNIST downloader (data/download.py — ≙ torchvision ``download=True``, reference
src/train.py:26-31) against a local HTTP server serving the golden IDX fixture: no
network egress needed, and the fetched files must flow through the real ingest path."""

import functools
import hashlib
import http.server
import os
import threading

import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    download, load_mnist,
)

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "mnist_idx")


class _CountingHandler(http.server.SimpleHTTPRequestHandler):
    requests: list[str] = []

    def do_GET(self):
        type(self).requests.append(self.path)
        super().do_GET()

    def log_message(self, *a):      # keep pytest output clean
        pass


@pytest.fixture()
def fixture_server():
    handler = functools.partial(_CountingHandler, directory=FIXTURE_DIR)
    _CountingHandler.requests = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}/", _CountingHandler.requests
    finally:
        srv.shutdown()
        thread.join()


def _fixture_md5s():
    out = {}
    for name in download.FILES:
        with open(os.path.join(FIXTURE_DIR, name), "rb") as f:
            out[name] = hashlib.md5(f.read()).hexdigest()
    return out


def test_download_fetch_verify_and_load(tmp_path, fixture_server):
    """Full path: fetch all four archives, verify MD5s, then load them through
    load_mnist — the downloaded cache must be indistinguishable from a torchvision one."""
    url, _ = fixture_server
    data_dir = str(tmp_path / "files")
    paths = download.download_mnist(data_dir, mirrors=(url,),
                                    checksums=_fixture_md5s())
    assert [os.path.basename(p) for p in paths] == list(download.FILES)
    train, test = load_mnist(data_dir)
    assert train.source == "idx" and test.source == "idx"
    assert train.images.shape[1:] == (28, 28, 1)


def test_download_skips_existing_valid_files(tmp_path, fixture_server):
    url, requests = fixture_server
    data_dir = str(tmp_path / "files")
    sums = _fixture_md5s()
    download.download_mnist(data_dir, mirrors=(url,), checksums=sums)
    first = len(requests)
    assert first == len(download.FILES)
    download.download_mnist(data_dir, mirrors=(url,), checksums=sums)
    assert len(requests) == first       # second call: verified on disk, no re-fetch


def test_download_mirror_fallback(tmp_path, fixture_server):
    """A dead first mirror must not fail the download — the next mirror serves it."""
    url, _ = fixture_server
    dead = "http://127.0.0.1:9/"        # port 9 (discard): connection refused
    paths = download.download_mnist(str(tmp_path / "files"), mirrors=(dead, url),
                                    checksums=_fixture_md5s(), timeout=5.0)
    assert all(os.path.exists(p) for p in paths)


def test_download_checksum_mismatch_leaves_no_file(tmp_path, fixture_server):
    url, _ = fixture_server
    bad = dict(_fixture_md5s(), **{download.FILES[0]: "0" * 32})
    with pytest.raises(RuntimeError) as exc_info:
        download.download_mnist(str(tmp_path / "files"), mirrors=(url,),
                                checksums=bad)
    assert isinstance(exc_info.value.__cause__, ValueError)   # the MD5 mismatch
    dest = tmp_path / "files" / download.FILES[0]
    assert not dest.exists()            # no truncated/corrupt file installed
    assert not list((tmp_path / "files").glob("*.part-*"))    # no temp litter
