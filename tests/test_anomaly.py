"""The numerical immune system (train/step.py --guard + rollback-and-skip).

Layers under test, bottom-up:

- the in-step verdict + guarded IDENTITY update: nan/spike/bitflip grad poison
  is detected and never applied; a run whose poisoned step was skipped is
  bitwise identical to an oracle run with the same static ``--skip-steps``
  window; guard-off and anomaly-free-guard-on are bitwise identical to the
  unguarded trainer (the PR-3 flag-off pinning discipline);
- the checkpoint layer: GuardState rides the TrainState optional-field
  contract (reconciled across the flag, full + sharded), manifests carry
  health stamps, and ``newest_healthy_checkpoint`` prefers stamped-clean over
  merely-valid (the ``_newest_valid``-trusted-a-diverging-run regression);
- the supervisor: EXIT_POISONED classification, rollback to the newest
  HEALTHY checkpoint, ``--skip-steps`` accumulation with auto-widening and
  the scattered-poison fingerprint-verify escalation, and the cross-replica
  heartbeat-fingerprint desync detector;
- the observability surfaces: the ``anomaly`` event, the goodput ledger's
  ``rollback_badput`` segment, report/fleet_top rendering;
- doc-vs-grammar agreement: the README fault table must list exactly the
  ``resilience/faults.py`` + ``resilience/netfaults.py`` kinds.
"""

import json
import os
import re

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    faults,
    heartbeat,
    netfaults,
    poison,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "csed_514_project_distributed_training_using_pytorch_tpu"


# ---------------------------------------------------------------- jax-free units


class TestSkipWindows:
    def test_parse_format_roundtrip(self):
        spec = "4:7,9:10"
        windows = poison.parse_skip_steps(spec)
        assert windows == ((4, 7), (9, 10))
        assert poison.format_skip_steps(windows) == spec
        assert poison.parse_skip_steps("") == ()

    @pytest.mark.parametrize("bad", ["5", "7:5", "-1:3", "a:b"])
    def test_malformed_windows_raise(self, bad):
        with pytest.raises(ValueError):
            poison.parse_skip_steps(bad)

    def test_merge_disjoint_appends(self):
        merged, widened = poison.merge_windows(((4, 5),), (9, 10))
        assert merged == ((4, 5), (9, 10)) and not widened

    def test_merge_overlap_widens_by_new_length(self):
        # Repeated poison at an already-skipped site: union + one new-window
        # length of extra headroom — geometric escape from skip-one-loop-again.
        merged, widened = poison.merge_windows(((4, 6),), (5, 7))
        assert widened and merged == ((4, 9),)

    def test_marker_roundtrip_consumes(self, tmp_path):
        store = str(tmp_path)
        poison.write_marker(store, window=(6, 7), step=8, anomalies=1)
        marker = poison.read_marker(store)
        assert marker["window"] == (6, 7) and marker["anomalies"] == 1
        assert poison.read_marker(store) is None       # consumed
        assert poison.read_marker(str(tmp_path / "nope")) is None


class TestPoisonGrammar:
    def test_poison_kinds_registered(self):
        assert set(faults.POISON_KINDS) <= set(faults.KINDS)

    def test_poison_requires_exact_step(self):
        with pytest.raises(ValueError, match="exact step"):
            faults._parse("nan:proc=0")

    def test_poison_rejects_tick_keys(self):
        with pytest.raises(ValueError, match="epoch=/flag="):
            faults._parse("spike:step=3,flag=/tmp/x")

    def test_bitflip_requires_leaf(self):
        with pytest.raises(ValueError, match="leaf="):
            faults._parse("bitflip:step=3")

    def test_defaults(self):
        (spike,) = faults._parse("spike:step=3")
        assert spike.scale == faults.DEFAULT_SPIKE_SCALE
        (flip,) = faults._parse("bitflip:step=3,leaf=kernel,scale=1e12")
        assert flip.scale == 1e12 and flip.leaf == "kernel"

    def test_grad_poisons_filters_by_process(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "nan:step=3,proc=1;spike:step=4")
        monkeypatch.setenv("JAX_PROCESS_ID", "0")
        faults._parse.cache_clear()
        kinds = [f.kind for f in faults.grad_poisons()]
        assert kinds == ["spike"]
        monkeypatch.delenv(faults.ENV_VAR)
        assert faults.grad_poisons() == ()


def test_fingerprint_mismatch_detector(tmp_path):
    d = str(tmp_path)
    heartbeat.HeartbeatWriter(d, process_index=0).beat(
        step=8, epoch=2, fingerprint=672.5)
    heartbeat.HeartbeatWriter(d, process_index=1).beat(
        step=8, epoch=2, fingerprint=672.5)
    assert heartbeat.fingerprint_mismatch(d) is None
    # Different STEPS never compare (epoch-boundary skew is not divergence).
    heartbeat.HeartbeatWriter(d, process_index=1).beat(
        step=12, epoch=3, fingerprint=9.0)
    assert heartbeat.fingerprint_mismatch(d) is None
    heartbeat.HeartbeatWriter(d, process_index=1).beat(
        step=8, epoch=2, fingerprint=673.0)
    mismatch = heartbeat.fingerprint_mismatch(d)
    assert mismatch["step"] == 8
    assert mismatch["fingerprints"] == {0: 672.5, 1: 673.0}
    # Beats without fingerprints (guard-off trainers) never trip it.
    heartbeat.clear(d)
    heartbeat.HeartbeatWriter(d, process_index=0).beat(step=8, epoch=2)
    heartbeat.HeartbeatWriter(d, process_index=1).beat(step=8, epoch=2)
    assert heartbeat.fingerprint_mismatch(d) is None


def test_readme_fault_table_matches_grammar():
    """Doc-vs-grammar agreement: the README fault-injection table must list
    exactly the kinds both grammars implement — it drifted once (the PR-14
    chaos/stall additions predated it); this pins it closed."""
    with open(os.path.join(REPO, "README.md")) as f:
        readme = f.read()
    m = re.search(r"<!-- fault-grammar:begin -->(.*?)<!-- fault-grammar:end -->",
                  readme, re.S)
    assert m, "README fault-grammar table (marker comments) is missing"
    rows = re.findall(r"^\| `(\w+)` \| `(\w+)` \|", m.group(1), re.M)
    by_env: dict = {}
    for kind, env in rows:
        by_env.setdefault(env, set()).add(kind)
    assert by_env.get("RESILIENCE_FAULTS") == set(faults.KINDS)
    assert by_env.get("NETWORK_FAULTS") == set(netfaults.KINDS)


# ------------------------------------------------------------ in-program guard

@pytest.fixture(scope="module")
def cnn_setup():
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model,
    )

    model = build_model("cnn")
    rng = jax.random.PRNGKey(0)
    gen = np.random.default_rng(0)
    x = jnp.asarray(gen.normal(size=(8, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray((np.arange(8) % 10).astype(np.int32))
    return model, rng, x, y


def _run_steps(cnn_setup, *, steps=6, guard=None, guard_state=False,
               faults_env="", monkeypatch=None, **step_kw):
    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        step as S,
    )

    model, rng, x, y = cnn_setup
    if monkeypatch is not None:
        if faults_env:
            monkeypatch.setenv(faults.ENV_VAR, faults_env)
        else:
            monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults._parse.cache_clear()
    st = S.create_train_state(model, rng, guard=guard_state)
    fn = jax.jit(S.make_train_step(model, learning_rate=0.01, momentum=0.5,
                                   guard=guard, **step_kw))
    for _ in range(steps):
        st, _ = fn(st, x, y, rng)
    return st


def _assert_trees_equal(a, b):
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestGuardedStep:
    def test_clean_guard_bitwise_equals_unguarded(self, cnn_setup, monkeypatch):
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step as S,
        )

        off = _run_steps(cnn_setup, monkeypatch=monkeypatch)
        on = _run_steps(cnn_setup, guard=S.GuardSpec(), guard_state=True,
                        monkeypatch=monkeypatch)
        _assert_trees_equal(off.params, on.params)
        _assert_trees_equal(off.velocity, on.velocity)
        g = on.guard
        assert int(g.anomalies) == 0 and int(g.skipped) == 0
        assert int(g.count) == 6

    @pytest.mark.parametrize("env,field,at", [
        # nan detection is always armed; the z-test needs its warmup
        # (GuardSpec.warmup_steps clean samples) before a spike can trip.
        ("nan:step=2", "nonfinite", 2),
        ("spike:step=4,scale=1e6", "spikes", 4),
        ("bitflip:step=4,leaf=kernel,scale=1e15", "spikes", 4),
    ])
    def test_poison_detected_and_skipped(self, cnn_setup, monkeypatch, env,
                                         field, at):
        import jax

        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step as S,
        )

        st = _run_steps(cnn_setup, guard=S.GuardSpec(), guard_state=True,
                        faults_env=env, monkeypatch=monkeypatch)
        g = jax.device_get(st.guard)
        assert int(g.anomalies) == 1 and int(g.skipped) == 1
        assert int(getattr(g, field)) == 1
        assert int(g.first_anomaly_step) == int(g.last_anomaly_step) == at
        # The poisoned update never landed: every param is finite, and the
        # step counter still advanced through the skip (data/RNG alignment).
        for leaf in jax.tree_util.tree_leaves(st.params):
            assert np.isfinite(np.asarray(leaf)).all()
        assert int(st.step) == 6

    def test_poisoned_run_equals_skip_window_oracle(self, cnn_setup,
                                                    monkeypatch):
        """THE rollback-and-skip contract at step level: a guarded run whose
        poison was skipped is bitwise the oracle trained with the same static
        skip window — params, optimizer state, AND detector EMA."""
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step as S,
        )

        poisoned = _run_steps(cnn_setup, guard=S.GuardSpec(), guard_state=True,
                              faults_env="nan:step=3", monkeypatch=monkeypatch)
        oracle = _run_steps(cnn_setup, guard=S.GuardSpec(skip=((3, 4),)),
                            guard_state=True, monkeypatch=monkeypatch)
        _assert_trees_equal(poisoned.params, oracle.params)
        _assert_trees_equal(poisoned.velocity, oracle.velocity)
        np.testing.assert_array_equal(np.asarray(poisoned.guard.ema_mean),
                                      np.asarray(oracle.guard.ema_mean))
        # Window skips are deliberate: skipped counted, anomaly NOT.
        assert int(oracle.guard.anomalies) == 0
        assert int(oracle.guard.skipped) == 1

    def test_window_suppresses_redetection(self, cnn_setup, monkeypatch):
        """A replayed attempt skipping the poisoned step must not re-count the
        anomaly — or the --anomaly-exit policy would re-trip forever."""
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step as S,
        )

        st = _run_steps(cnn_setup, guard=S.GuardSpec(skip=((3, 4),)),
                        guard_state=True, faults_env="nan:step=3",
                        monkeypatch=monkeypatch)
        g = st.guard
        assert int(g.anomalies) == 0 and int(g.skipped) == 1

    def test_guard_composes_with_accum_clip_ema(self, cnn_setup, monkeypatch):
        import jax

        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step as S,
        )

        st = _run_steps(cnn_setup, guard=S.GuardSpec(), guard_state=True,
                        faults_env="nan:step=2", monkeypatch=monkeypatch,
                        grad_accum=2, clip_grad_norm=1.0)
        g = jax.device_get(st.guard)
        assert int(g.anomalies) == 1
        for leaf in jax.tree_util.tree_leaves(st.params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_guard_needs_guard_state(self, cnn_setup, monkeypatch):
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            step as S,
        )

        with pytest.raises(ValueError, match="guard=True"):
            _run_steps(cnn_setup, guard=S.GuardSpec(), guard_state=False,
                       monkeypatch=monkeypatch)


# ----------------------------------------------------- checkpoint health layer


class TestHealthyCheckpoints:
    def _store(self, tmp_path, stamps):
        import jax.numpy as jnp

        from csed_514_project_distributed_training_using_pytorch_tpu.models import (
            build_model,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
            create_train_state,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )
        import jax

        store = str(tmp_path / "store")
        st = create_train_state(build_model("cnn"), jax.random.PRNGKey(0))
        for step, health in stamps:
            C.save_versioned(store, st._replace(step=jnp.asarray(step,
                                                                 jnp.int32)),
                             keep=10, health=health)
        return store

    def test_clean_stamp_preferred_over_newest_valid(self, tmp_path):
        """The satellite-2 regression: the newest checkpoint decodes fine but
        its run was diverging — the rollback must land on the older CLEAN
        stamp, not the newest merely-valid file."""
        from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
            supervisor as sup,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        store = self._store(tmp_path, [
            (4, {"clean": True, "anomalies": 0}),
            (8, {"clean": False, "anomalies": 2}),
        ])
        assert C.newest_valid_checkpoint(store).endswith("00000008.msgpack")
        assert C.newest_healthy_checkpoint(store).endswith("00000004.msgpack")
        # The supervisor's one resume-scan owner makes the same choice.
        assert sup._newest_healthy(store).endswith("00000004.msgpack")

    def test_legacy_unstamped_manifest_back_compat(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        store = self._store(tmp_path, [(4, None), (8, None)])
        assert C.newest_healthy_checkpoint(store) == \
            C.newest_valid_checkpoint(store)

    def test_newer_legacy_progress_beats_older_clean_stamp(self, tmp_path):
        """A guard-off run's NEWER unstamped checkpoints must not be
        discarded in favor of an older stamped-clean one — only explicit
        clean:false stamps are skipped; unstamped entries rank by step."""
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        store = self._store(tmp_path, [
            (4, {"clean": True, "anomalies": 0}),
            (8, None),
            (12, None),
        ])
        assert C.newest_healthy_checkpoint(store).endswith("00000012.msgpack")

    def test_all_unclean_falls_back_to_newest_valid(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        store = self._store(tmp_path, [
            (4, {"clean": False, "anomalies": 1}),
            (8, {"clean": False, "anomalies": 2}),
        ])
        # An unclean resume beats no resume; the caller's skip window makes
        # the replay safe.
        assert C.newest_healthy_checkpoint(store).endswith("00000008.msgpack")

    def test_missing_store(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        assert C.newest_healthy_checkpoint(str(tmp_path / "nope")) is None

    def test_before_step_excludes_indicted_checkpoint(self, tmp_path):
        """The desync rollback bound: a fingerprint mismatch at step S
        indicts the step-S checkpoint even though it is clean-STAMPED
        (per-process counters cannot see cross-replica divergence) — the
        scan must land strictly before it."""
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        store = self._store(tmp_path, [
            (4, {"clean": True, "anomalies": 0}),
            (8, {"clean": True, "anomalies": 0}),   # diverged, stamp blind
        ])
        assert C.newest_healthy_checkpoint(store).endswith("00000008.msgpack")
        assert C.newest_healthy_checkpoint(
            store, before_step=8).endswith("00000004.msgpack")


class TestGuardStateCheckpointing:
    def test_full_roundtrip_and_flag_reconciliation(self, tmp_path):
        import jax

        from csed_514_project_distributed_training_using_pytorch_tpu.models import (
            build_model,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
            create_train_state,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        model = build_model("cnn")
        guarded = create_train_state(model, jax.random.PRNGKey(0), guard=True)
        plain = create_train_state(model, jax.random.PRNGKey(0))
        pg, pp = str(tmp_path / "g.ckpt"), str(tmp_path / "p.ckpt")
        C.save_train_state(pg, guarded)
        C.save_train_state(pp, plain)
        # Guard-off checkpoint bytes carry NO guard key (format pin): the raw
        # msgpack doc must look exactly like the pre-guard format.
        from flax import serialization

        raw = serialization.msgpack_restore(open(pp, "rb").read())
        assert "guard" not in raw
        # Cross-flag restores reconcile like ema.
        r = C.restore_train_state(pp, guarded)       # plain -> guarded ref
        assert r.guard is not None and int(r.guard.count) == 0
        assert C.restore_train_state(pg, plain).guard is None
        rt = C.restore_train_state(pg, guarded)      # roundtrip
        assert int(rt.guard.anomalies) == 0

    def test_sharded_roundtrip_and_reconciliation(self, tmp_path):
        import jax

        from csed_514_project_distributed_training_using_pytorch_tpu.models import (
            build_model,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
            create_train_state,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as C,
        )

        model = build_model("cnn")
        guarded = create_train_state(model, jax.random.PRNGKey(0), guard=True)
        plain = create_train_state(model, jax.random.PRNGKey(0))
        d = str(tmp_path / "sh.ckpt")
        C.save_train_state_sharded(d, guarded)
        assert C.restore_train_state_sharded(d, guarded).guard is not None
        assert C.restore_train_state_sharded(d, plain).guard is None
        d2 = str(tmp_path / "sh2.ckpt")
        C.save_train_state_sharded(d2, plain)
        seeded = C.restore_train_state_sharded(d2, guarded)
        assert seeded.guard is not None and int(seeded.guard.count) == 0


def test_cross_mesh_resume_interchange_bitwise(tmp_path):
    """The rollback-on-a-reshaped-fleet contract (utils/checkpoint.py:221):
    a sharded checkpoint written under an FSDP data-mesh layout restores
    through ``restore_for_resume(..., shardings=)`` onto a TP model-mesh
    BITWISE — tier-1 direct coverage for the interchange claim every
    supervised rollback on a reshaped fleet leans on."""
    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        fsdp,
        make_mesh,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        tensor_parallel as tp,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint as C,
    )

    model = TransformerClassifier(dropout_rate=0.0)
    mesh_a = make_mesh(8)                                  # data=8 (FSDP)
    state = fsdp.shard_train_state(
        mesh_a, create_train_state(model, jax.random.PRNGKey(0), guard=True))
    d = str(tmp_path / "sharded.ckpt")
    C.save_train_state_sharded(d, state)

    mesh_b = make_mesh(4, axis_names=("model",))           # TP, different shape
    template = create_train_state(model, jax.random.PRNGKey(9), guard=True)
    shardings = tp.state_shardings(mesh_b, template)
    restored, start_epoch, warning = C.restore_for_resume(
        d, template, process_index=0, process_count=1, steps_per_epoch=4,
        shardings=shardings)
    assert start_epoch == 0 and warning is None
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(restored)),
                    jax.tree_util.tree_leaves(jax.device_get(state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ...and the restored copy actually lives on mesh B's layout.
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf.sharding.mesh.shape.get("model") == 4


# ----------------------------------------------------------- goodput attribution


def _fake_streams(tmp_path, restart_reason):
    """Two-attempt telemetry + supervisor streams with epoch 1 replayed."""
    t0 = 1000.0
    run = tmp_path / "run.jsonl"
    rows = [
        {"event": "manifest", "unix_time": t0, "t_s": 0.0},
        {"event": "epoch", "epoch": 0, "steps": 4, "wall_s": 5.0,
         "execute_s": 4.0, "eval_s": 0.5, "data_s": 0.2, "t_s": 10.0},
        {"event": "epoch", "epoch": 1, "steps": 4, "wall_s": 5.0,
         "execute_s": 4.0, "eval_s": 0.5, "data_s": 0.2, "t_s": 16.0},
        # attempt 2 (resumed after the restart below), replays epoch 1
        {"event": "manifest", "unix_time": t0 + 25.0, "t_s": 0.0},
        {"event": "epoch", "epoch": 1, "steps": 4, "wall_s": 5.0,
         "execute_s": 4.0, "eval_s": 0.5, "data_s": 0.2, "t_s": 10.0},
        {"event": "epoch", "epoch": 2, "steps": 4, "wall_s": 5.0,
         "execute_s": 4.0, "eval_s": 0.5, "data_s": 0.2, "t_s": 16.0},
    ]
    with open(run, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    sup = tmp_path / "supervisor.jsonl"
    with open(sup, "w") as f:
        f.write(json.dumps({"event": "restart", "attempt": 1, "restart": 1,
                            "reason": restart_reason, "exit_code": 65,
                            "unix_time": t0 + 20.0, "t_s": 20.0}) + "\n")
        f.write(json.dumps({"event": "supervise_summary", "status": "ok",
                            "exit_code": 0, "attempts": 2, "restarts": 1,
                            "unix_time": t0 + 42.0, "t_s": 42.0}) + "\n")
    return [str(run), str(sup)]


@pytest.mark.parametrize("reason,rollback", [("poisoned", True),
                                             ("desync", True),
                                             ("crash", False)])
def test_goodput_attributes_rollback_badput_by_cause(tmp_path, reason,
                                                     rollback):
    from csed_514_project_distributed_training_using_pytorch_tpu.obs import (
        goodput,
    )

    report = goodput.decompose(_fake_streams(tmp_path, reason))
    seg = report["segments"]
    charged = seg["rollback_badput_s"] if rollback else seg["restart_badput_s"]
    other = seg["restart_badput_s"] if rollback else seg["rollback_badput_s"]
    # Gap (5s) + recovery init (5s) + replayed epoch 1 (5s) all charge to the
    # CAUSE's segment; the other badput account stays exactly zero.
    assert charged > 0.0 and other == 0.0
    assert report["rollbacks"] == (1 if rollback else 0)
    assert report["epochs_replayed"] == 1
    assert sum(seg.values()) == pytest.approx(report["wall_s"], rel=0.01)
    ev = goodput.goodput_event(report)
    assert ev["rollback_badput_s"] == seg["rollback_badput_s"]
    assert ev["rollbacks"] == report["rollbacks"]


def test_param_fingerprint_is_local_and_sensitive(tmp_path):
    """The fingerprint is a host-local fold over this process's addressable
    shards (a jitted global reduction would all-reduce the corruption into
    every replica's value): equal state -> equal value, one perturbed element
    -> different value, and a sharded-but-locally-covering layout (the
    8-virtual-device FSDP mesh) still fingerprints."""
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    st = create_train_state(build_model("cnn"), jax.random.PRNGKey(0))
    fp = T.param_fingerprint(st.params)
    assert fp is not None and fp > 0
    assert T.param_fingerprint(st.params) == fp          # deterministic
    leaves, treedef = jax.tree_util.tree_flatten(st.params)
    flat0 = leaves[0].reshape(-1)
    leaves[0] = flat0.at[0].set(flat0[0] + 1.0).reshape(leaves[0].shape)
    assert T.param_fingerprint(
        jax.tree_util.tree_unflatten(treedef, leaves)) != fp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        fsdp,
        make_mesh,
    )

    model = TransformerClassifier(dropout_rate=0.0)
    state = create_train_state(model, jax.random.PRNGKey(0))
    sharded = fsdp.shard_train_state(make_mesh(8), state)
    fp_plain = T.param_fingerprint(state.params)
    fp_sharded = T.param_fingerprint(sharded.params)
    assert fp_sharded is not None
    # Layout-invariant to f32 round-off (the fold order differs per layout).
    assert fp_sharded == pytest.approx(fp_plain, rel=1e-5)


def test_supervisor_seeds_skip_windows_from_command(tmp_path):
    """argparse last-occurrence-wins means the supervisor's appended
    --skip-steps REPLACES any user-supplied flag — so the supervisor must
    seed its skip set from the command, or the first poisoned restart would
    silently drop the user's known-bad windows."""
    from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
        poison,
        supervisor as sup,
    )

    store = tmp_path / "store"
    store.mkdir()
    argv_log = tmp_path / "argv.jsonl"
    # Synthetic trainer: first run writes a poison marker for step 9 and
    # exits 65; the rerun (marker consumed by the supervisor -> absent)
    # records its argv and exits 0.
    child = (
        "import json, os, sys\n"
        f"store = {str(store)!r}\n"
        f"log = {str(argv_log)!r}\n"
        "with open(log, 'a') as f:\n"
        "    f.write(json.dumps(sys.argv) + '\\n')\n"
        "marker = os.path.join(store, 'poison.json')\n"
        "flag = os.path.join(store, 'fired')\n"
        "if not os.path.exists(flag):\n"
        "    open(flag, 'w').close()\n"
        "    json.dump({'window': [9, 10], 'step': 12, 'anomalies': 1},\n"
        "              open(marker, 'w'))\n"
        "    sys.exit(65)\n"
        "sys.exit(0)\n"
    )
    cfg = sup.SupervisorConfig(num_processes=1, platform="cpu",
                               devices_per_process=1, max_restarts=1,
                               backoff_s=0.0, checkpoint_dir=str(store),
                               attempt_timeout_s=60)
    res = sup.supervise(["-c", child, "--skip-steps", "3:4"], cfg)
    assert res.status == "ok" and res.rollbacks == 1
    # The union, not just the new window: the user's 3:4 survived.
    assert res.skip_windows == ((3, 4), (9, 10))
    argvs = [json.loads(l) for l in open(argv_log)]
    final = argvs[-1]
    skips = [final[i + 1] for i, a in enumerate(final)
             if a == "--skip-steps"]
    assert skips[-1] == poison.format_skip_steps(((3, 4), (9, 10)))


def test_goodput_cause_alignment_survives_silent_attempt(tmp_path):
    """An attempt that died before writing ANY telemetry leaves no attempt
    entry — the restart-cause join is by TIME, so the surviving attempt still
    charges to the restart that actually spawned it (index-based alignment
    would read the earlier crash row and mis-charge the rollback)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.obs import (
        goodput,
    )

    t0 = 1000.0
    run = tmp_path / "run.jsonl"
    rows = [
        {"event": "manifest", "unix_time": t0, "t_s": 0.0},
        {"event": "epoch", "epoch": 0, "steps": 4, "wall_s": 5.0,
         "execute_s": 4.0, "eval_s": 0.5, "data_s": 0.2, "t_s": 10.0},
        # attempt 2 (spawned by the crash restart) wrote nothing at all;
        # attempt 3 (spawned by the poisoned restart) replays epoch 0.
        {"event": "manifest", "unix_time": t0 + 35.0, "t_s": 0.0},
        {"event": "epoch", "epoch": 0, "steps": 4, "wall_s": 5.0,
         "execute_s": 4.0, "eval_s": 0.5, "data_s": 0.2, "t_s": 10.0},
    ]
    with open(run, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    sup_path = tmp_path / "supervisor.jsonl"
    with open(sup_path, "w") as f:
        f.write(json.dumps({"event": "restart", "attempt": 1, "restart": 1,
                            "reason": "crash", "exit_code": 41,
                            "unix_time": t0 + 20.0, "t_s": 20.0}) + "\n")
        f.write(json.dumps({"event": "restart", "attempt": 2, "restart": 2,
                            "reason": "poisoned", "exit_code": 65,
                            "unix_time": t0 + 30.0, "t_s": 30.0}) + "\n")
    report = goodput.decompose([str(run), str(sup_path)])
    assert report["rollbacks"] == 1
    # The replayed epoch belongs to the attempt the POISONED restart spawned.
    assert report["segments"]["rollback_badput_s"] > 0.0


# -------------------------------------------------------- report + fleet_top


def test_report_renders_anomaly_and_rollback_rows(tmp_path, capsys):
    import tools.telemetry_report as tr

    path = tmp_path / "t.jsonl"
    rows = [
        {"event": "anomaly", "epoch": 2, "steps": 4, "anomalies": 2,
         "nonfinite": 1, "spikes": 1, "skipped": 3, "clean_steps": 9,
         "first_anomaly_step": 6, "last_anomaly_step": 9,
         "grad_norm_ema": 2.5, "grad_norm_std": 0.1, "fingerprint": 672.4,
         "skip": "6:7"},
        {"event": "restart", "attempt": 1, "restart": 1, "reason": "poisoned",
         "exit_code": 65, "resume_from": "x", "skip": "6:7",
         "rollback": True, "backoff_s": 0.0},
        {"event": "restart", "attempt": 2, "restart": 2, "reason": "crash",
         "exit_code": 41, "resume_from": "x", "backoff_s": 0.0},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    s = tr.summarize(str(path))
    assert s["anomalies"] == 2 and s["skipped_steps"] == 3
    assert s["rollbacks"] == 1 and s["restarts"] == 2
    assert s.get("unknown_events") is None     # "anomaly" is registered
    tr.print_summary(s)
    out = capsys.readouterr().out
    assert "anomaly guard: 2 anomalies" in out
    assert "1 rollback(s)" in out
    # The A-vs-B table carries the new rows.
    keys = [k for _, k in tr.COMPARE_ROWS]
    assert {"anomalies", "skipped_steps", "rollbacks",
            "rollback_badput_s"} <= set(keys)
    gp_keys = [k for _, k in tr.GOODPUT_ROWS]
    assert {"rollback_badput_s", "rollbacks"} <= set(gp_keys)


def test_fleet_top_renders_anomaly_line(tmp_path):
    from tools.fleet_top import FleetState, JsonlTail, render

    path = tmp_path / "f.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"event": "anomaly", "anomalies": 2, "nonfinite": 1,
                            "spikes": 1, "skipped": 3, "skip": "6:7",
                            "t_s": 1.0}) + "\n")
        f.write(json.dumps({"event": "restart", "reason": "poisoned",
                            "skip": "6:7", "t_s": 2.0}) + "\n")
    state = FleetState()
    state.feed(JsonlTail(str(path)).poll())
    frame = render(state, str(path))
    assert "anomalies 2" in frame and "skipped 3" in frame
    assert "rollbacks 1" in frame
    assert "restart (poisoned) skipping 6:7" in frame


# ------------------------------------------------- supervised rollback e2e


@pytest.fixture(autouse=True)
def _child_pythonpath(monkeypatch):
    existing = os.environ.get("PYTHONPATH", "")
    monkeypatch.setenv("PYTHONPATH", f"{REPO}:{existing}" if existing else REPO)


TRAIN = ["-m", f"{PKG}.train.distributed",
         "--epochs", "3", "--global-batch-size", "64",
         "--batch-size-test", "256",
         "--max-train-examples", "256", "--max-test-examples", "256",
         "--keep-checkpoints", "5", "--guard", "--anomaly-exit", "1"]


def test_supervisor_rolls_back_and_skips_to_bitwise_oracle(tmp_path,
                                                           monkeypatch):
    """The acceptance path in miniature (the committed
    bench_results/anomaly_train_cpu/ artifact runs the two-injection flavor):
    one spike injected mid-run -> the guard detects it, the trainer exits 65,
    the supervisor rolls back to the older CLEAN checkpoint (the unclean
    stamp is skipped — resume_history pins the choice) and restarts with
    --skip-steps; the finished run is bitwise identical to an unfaulted
    oracle trained with the same skip set."""
    import jax
    from flax import serialization

    from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
        supervisor as sup,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.launch import (
        launch,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint as C,
    )

    work = tmp_path / "supervised"
    work.mkdir()
    monkeypatch.chdir(work)
    monkeypatch.setenv("RESILIENCE_FAULTS", "spike:step=6,scale=1e6")
    store = str(work / "results" / "checkpoints")
    cfg = sup.SupervisorConfig(num_processes=1, platform="cpu",
                               devices_per_process=1, max_restarts=2,
                               backoff_s=0.0, checkpoint_dir=store,
                               attempt_timeout_s=300,
                               telemetry=str(work / "supervisor.jsonl"))
    res = sup.supervise(TRAIN + ["--telemetry", "run.jsonl"], cfg)
    assert (res.status, res.exit_code) == ("ok", 0)
    assert res.rollbacks == 1 and res.skip_windows == ((6, 7),)
    # Rollback landed on the CLEAN step-4 checkpoint, not the newest (step-8,
    # stamped unclean) one — the _newest_valid regression, pinned end-to-end.
    ckpt4 = os.path.join(store, C.versioned_name(4))
    assert res.resume_history == [None, ckpt4]
    restarts = [json.loads(l) for l in open(work / "supervisor.jsonl")
                if '"restart"' in l]
    assert restarts[0]["reason"] == "poisoned" and restarts[0]["skip"] == "6:7"

    monkeypatch.delenv("RESILIENCE_FAULTS")
    oracle = tmp_path / "oracle"
    oracle.mkdir()
    monkeypatch.chdir(oracle)
    assert launch(TRAIN + ["--skip-steps", "6:7"], num_processes=1,
                  platform="cpu", devices_per_process=1, timeout=300) == 0
    final_sup = C.newest_valid_checkpoint(store)
    final_or = C.newest_valid_checkpoint(
        str(oracle / "results" / "checkpoints"))
    a = serialization.msgpack_restore(open(final_sup, "rb").read())
    b = serialization.msgpack_restore(open(final_or, "rb").read())
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb) and int(a["step"]) == 12
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    # The anomaly events survived into the preserved multi-attempt history,
    # and the goodput ledger charges the replay to rollback (not restart)
    # badput, summing to wall.
    from csed_514_project_distributed_training_using_pytorch_tpu.obs import (
        goodput,
    )

    report = goodput.decompose([str(work / "run.jsonl"),
                                str(work / "supervisor.jsonl")])
    assert report["rollbacks"] == 1
    assert report["segments"]["rollback_badput_s"] > 0.0
    assert report["segments"]["restart_badput_s"] == 0.0
    assert sum(report["segments"].values()) == pytest.approx(
        report["wall_s"], rel=0.01)


def test_supervisor_desync_classification(tmp_path, monkeypatch):
    """Fingerprint-verify mode end-to-end with a synthetic fleet: two children
    report DIFFERENT param fingerprints at the same step -> the supervisor
    tears the fleet down with reason 'desync' (a rollback, not a crash); the
    restarted children (flag file present) exit clean."""
    from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
        supervisor as sup,
    )

    hb_dir = tmp_path / "hb"
    flag = tmp_path / "attempt2"
    child = (
        "import json, os, sys, time\n"
        f"flag = {str(flag)!r}\n"
        "if os.path.exists(flag):\n"
        "    sys.exit(0)\n"
        "open(flag + '.p' + os.environ['JAX_PROCESS_ID'], 'w').close()\n"
        "if len([f for f in os.listdir(os.path.dirname(flag))\n"
        "        if f.startswith(os.path.basename(flag))]) >= 2:\n"
        "    open(flag, 'w').close()\n"
        "from csed_514_project_distributed_training_using_pytorch_tpu."
        "resilience import heartbeat\n"
        "i = int(os.environ['JAX_PROCESS_ID'])\n"
        f"w = heartbeat.HeartbeatWriter({str(hb_dir)!r}, process_index=i)\n"
        "w.beat(step=8, epoch=2, fingerprint=100.0 + i)\n"
        "time.sleep(60)\n"
    )
    cfg = sup.SupervisorConfig(num_processes=2, platform="cpu",
                               devices_per_process=1, max_restarts=1,
                               backoff_s=0.0, heartbeat_dir=str(hb_dir),
                               fingerprint_verify=True, attempt_timeout_s=60,
                               telemetry=str(tmp_path / "supervisor.jsonl"))
    res = sup.supervise(["-c", child], cfg)
    assert res.status == "ok" and res.rollbacks == 1
    restarts = [json.loads(l) for l in open(tmp_path / "supervisor.jsonl")
                if '"restart"' in l]
    assert restarts[0]["reason"] == "desync"
    assert restarts[0]["exit_code"] == sup.EXIT_TORN_DOWN
