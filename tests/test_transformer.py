"""TransformerClassifier: the beyond-parity attention model family.

Pins (a) the ``models.cnn.Net``-compatible call contract that makes it drop-in for the
existing trainers (``train/step.py``), (b) training progress under the standard jitted
step, and (c) bit-level interchangeability of the dense and sequence-parallel ring
attention cores on shared parameters.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    TransformerClassifier,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import (
    param_count,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    make_mesh,
    make_ring_attention_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (

    create_train_state,
    make_eval_fn,
    make_train_step,
)

# Heavyweight end-to-end/equivalence tests: full-suite runs only; deselect with
# -m "not slow" for the fast single-core signal (README).
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def model():
    return TransformerClassifier()


@pytest.fixture(scope="module")
def state(model):
    return create_train_state(model, jax.random.PRNGKey(0))


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    images = jnp.asarray(rng.normal(size=(n, 28, 28, 1)).astype(np.float32))
    labels = jnp.asarray((np.arange(n) % 10).astype(np.int32))
    return images, labels


def test_output_shape_and_log_prob_rows(model, state):
    images, _ = _batch()
    log_probs = model.apply({"params": state.params}, images)
    assert log_probs.shape == (16, 10)
    np.testing.assert_allclose(np.asarray(jnp.sum(jnp.exp(log_probs), axis=-1)),
                               1.0, rtol=1e-5)


def test_accepts_pretokenized_sequence(model, state):
    images, _ = _batch()
    tokens = images.reshape(16, model.seq_len, -1)
    np.testing.assert_array_equal(
        np.asarray(model.apply({"params": state.params}, tokens)),
        np.asarray(model.apply({"params": state.params}, images)))


def test_deterministic_apply_reproducible(model, state):
    images, _ = _batch(seed=1)
    a = model.apply({"params": state.params}, images)
    b = model.apply({"params": state.params}, images)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dropout_draws_differ_across_keys(model, state):
    images, _ = _batch(seed=2)
    outs = [model.apply({"params": state.params}, images, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(s)}) for s in (0, 1)]
    assert float(jnp.max(jnp.abs(outs[0] - outs[1]))) > 1e-6


def test_drop_in_training_reduces_loss(model):
    """Same TrainState/step machinery as the CNN — the model family is trainer-agnostic."""
    state = create_train_state(model, jax.random.PRNGKey(0))
    assert param_count(state.params) > 50_000
    step = jax.jit(make_train_step(model, learning_rate=0.05, momentum=0.5))
    images, labels = _batch(n=32, seed=3)
    first = None
    for _ in range(40):
        state, loss = step(state, images, labels, jax.random.PRNGKey(7))
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.7, (first, float(loss))


def test_eval_fn_works(model, state):
    images, labels = _batch(n=20, seed=4)
    evaluate = jax.jit(make_eval_fn(model, batch_size=10))
    sum_nll, correct = evaluate(state.params, images, labels)
    assert np.isfinite(float(sum_nll))
    assert 0 <= int(correct) <= 20


def test_ring_core_matches_dense_core_on_shared_params(state):
    """Swapping the attention core changes no parameters and no numerics (to f32
    round-off): the sequence axis is simply sharded across the mesh."""
    mesh = make_mesh(8, axis_names=("seq",))
    dense_model = TransformerClassifier()
    ring_model = TransformerClassifier(attention_fn=make_ring_attention_fn(mesh))
    images, _ = _batch(seed=5)
    lp_dense = dense_model.apply({"params": state.params}, images)
    lp_ring = ring_model.apply({"params": state.params}, images)
    np.testing.assert_allclose(np.asarray(lp_ring), np.asarray(lp_dense),
                               rtol=1e-5, atol=1e-6)


def test_ring_core_trains_identically_to_dense_core():
    """One jitted optimizer step with each core from identical init → identical params
    (to f32 round-off). The SP story holds through the full value_and_grad path."""
    mesh = make_mesh(8, axis_names=("seq",))
    dense_model = TransformerClassifier(dropout_rate=0.0)
    ring_model = TransformerClassifier(dropout_rate=0.0,
                                       attention_fn=make_ring_attention_fn(mesh))
    s0 = create_train_state(dense_model, jax.random.PRNGKey(0))
    images, labels = _batch(n=16, seed=6)

    outs = []
    for m in (dense_model, ring_model):
        step = jax.jit(make_train_step(m, learning_rate=0.05, momentum=0.5))
        s1, loss = step(s0, images, labels, jax.random.PRNGKey(1))
        outs.append((s1, float(loss)))
    (sa, la), (sb, lb) = outs
    assert abs(la - lb) < 1e-5
    for pa, pb in zip(jax.tree_util.tree_leaves(sa.params),
                      jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_allclose(np.asarray(pb), np.asarray(pa),
                                   rtol=1e-4, atol=1e-6)


def test_causal_variant_forward():
    model = TransformerClassifier(causal=True)
    state = create_train_state(model, jax.random.PRNGKey(0))
    images, _ = _batch(seed=7)
    log_probs = model.apply({"params": state.params}, images)
    assert bool(jnp.all(jnp.isfinite(log_probs)))


@pytest.mark.parametrize("policy", ["", "save-dots"])
def test_remat_is_numerically_identical(policy):
    """remat=True (jax.checkpoint per block) is a memory knob only: forward, loss, and
    one optimizer step are bit-identical, on both the deterministic and dropout paths
    — under the default recompute-all policy AND the save-dots policy (which keeps
    MXU outputs and replays only elementwise work)."""
    base = TransformerClassifier(dropout_rate=0.1)
    remat = TransformerClassifier(dropout_rate=0.1, remat=True,
                                  remat_policy=policy)
    s0 = create_train_state(base, jax.random.PRNGKey(0))
    images, labels = _batch(seed=8)

    np.testing.assert_array_equal(
        np.asarray(base.apply({"params": s0.params}, images)),
        np.asarray(remat.apply({"params": s0.params}, images)))
    np.testing.assert_array_equal(
        np.asarray(base.apply({"params": s0.params}, images, deterministic=False,
                              rngs={"dropout": jax.random.PRNGKey(5)})),
        np.asarray(remat.apply({"params": s0.params}, images, deterministic=False,
                               rngs={"dropout": jax.random.PRNGKey(5)})))

    outs = []
    for m in (base, remat):
        step = jax.jit(make_train_step(m, learning_rate=0.05, momentum=0.5))
        s1, loss = step(s0, images, labels, jax.random.PRNGKey(1))
        outs.append((s1, float(loss)))
    (sa, la), (sb, lb) = outs
    assert la == lb
    for a, b in zip(jax.tree_util.tree_leaves(sa.params),
                    jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_activations_train_with_f32_master_weights():
    import jax.numpy as jnp

    model = TransformerClassifier(dtype=jnp.bfloat16, dropout_rate=0.0)
    state = create_train_state(model, jax.random.PRNGKey(0))
    assert all(p.dtype == jnp.float32
               for p in jax.tree_util.tree_leaves(state.params))
    images, labels = _batch(n=32, seed=9)
    step = jax.jit(make_train_step(model, learning_rate=0.05, momentum=0.5))
    first = None
    for _ in range(30):
        state, loss = step(state, images, labels, jax.random.PRNGKey(2))
        first = first if first is not None else float(loss)
    assert np.isfinite(float(loss)) and float(loss) < first


def test_build_model_factory_knobs():
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model,
    )

    assert build_model("transformer", bf16=True).dtype == jnp.bfloat16
    assert build_model("transformer", remat=True).remat is True
    assert build_model("cnn", bf16=True).dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="transformer family only"):
        build_model("cnn", remat=True)


def test_moe_blocks_forward_and_aux_loss():
    """num_experts>0 swaps each block's MLP for the Switch MoE; per-block load-balance
    aux losses arrive via the sown 'aux_loss' collection."""
    model = TransformerClassifier(num_experts=8, dropout_rate=0.0)
    state = create_train_state(model, jax.random.PRNGKey(0))
    assert "router_kernel" in state.params["block_0"]
    assert state.params["block_0"]["up_kernel"].shape == (8, 64, 256)
    images, _ = _batch(seed=10)
    log_probs, variables = model.apply({"params": state.params}, images,
                                       mutable=["aux_loss"])
    np.testing.assert_allclose(np.asarray(jnp.sum(jnp.exp(log_probs), axis=-1)),
                               1.0, rtol=1e-5)
    aux_leaves = jax.tree_util.tree_leaves(variables["aux_loss"])
    assert len(aux_leaves) == model.num_layers
    assert all(0.0 < float(a) <= 8.0 for a in aux_leaves)


def test_moe_expert_mesh_execution_identical():
    """Pinning dispatched tokens onto an 'expert' mesh axis (EP execution) changes
    nothing numerically."""
    mesh = make_mesh(8, axis_names=("expert",))
    local = TransformerClassifier(num_experts=8, dropout_rate=0.0)
    sharded = TransformerClassifier(num_experts=8, dropout_rate=0.0, expert_mesh=mesh)
    state = create_train_state(local, jax.random.PRNGKey(0))
    images, _ = _batch(seed=11)
    a, _ = local.apply({"params": state.params}, images, mutable=["aux_loss"])
    b, _ = sharded.apply({"params": state.params}, images, mutable=["aux_loss"])
    np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6)


def test_moe_trains_through_standard_train_step():
    """The MoE model is genuinely drop-in: make_train_step collects the sown aux losses
    into the objective automatically (aux_loss_weight), so the router trains — its
    gradient is nonzero and loss falls — through the SAME step every trainer uses."""
    model = TransformerClassifier(num_experts=8, dropout_rate=0.0)
    state = create_train_state(model, jax.random.PRNGKey(0))
    images, labels = _batch(n=32, seed=12)
    router0 = np.asarray(state.params["block_0"]["router_kernel"]).copy()
    step = jax.jit(make_train_step(model, learning_rate=0.05, momentum=0.5))
    first = None
    for _ in range(30):
        state, loss = step(state, images, labels, jax.random.PRNGKey(3))
        first = first if first is not None else float(loss)
    assert float(loss) < first
    assert np.max(np.abs(np.asarray(state.params["block_0"]["router_kernel"])
                         - router0)) > 0


def test_moe_expert_weights_shard_over_expert_axis():
    """tensor_parallel's rules recognize the in-model MoE leaves: on a mesh with an
    'expert' axis the stacked expert weights (and their velocity) shard per expert."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        tensor_parallel as tp,
    )

    mesh = make_mesh(8, axis_names=("expert",))
    model = TransformerClassifier(num_experts=8, dropout_rate=0.0)
    state = tp.shard_train_state(mesh, create_train_state(model, jax.random.PRNGKey(0)))
    up = state.params["block_0"]["up_kernel"]
    assert up.addressable_shards[0].data.shape == (1, 64, 256)  # one expert per device
    vel = state.velocity["block_0"]["up_kernel"]
    assert vel.addressable_shards[0].data.shape == (1, 64, 256)
    router = state.params["block_0"]["router_kernel"]
    assert router.addressable_shards[0].data.shape == tuple(router.shape)  # replicated


def test_attention_window_changes_output_and_validates():
    """build_model(attention_window=W) plugs the sliding-window dense core: output
    differs from full attention (the mask bites at seq_len 16 > W) while parameters
    and checkpoints stay identical; the CNN rejects the knob."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model, validate_model_config,
    )

    full = build_model("transformer")
    local = build_model("transformer", attention_window=4)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(2, 28, 28, 1)).astype(np.float32))
    params = full.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    out_full = full.apply({"params": params}, x)
    out_local = local.apply({"params": params}, x)   # same params — pluggable core
    assert not np.allclose(np.asarray(out_full), np.asarray(out_local))
    with pytest.raises(ValueError, match="transformer family only"):
        validate_model_config("cnn", attention_window=4)
    with pytest.raises(ValueError, match=">= 0"):
        validate_model_config("transformer", attention_window=-1)


def test_gqa_matches_repeated_kv_oracle():
    """GQA attention equals dense attention over explicitly group-broadcast K/V —
    and its parameters are the split q/kv layout with the smaller KV projection."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
        MultiHeadSelfAttention,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu import ops

    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.normal(size=(2, 8, 32)).astype(np.float32))
    mod = MultiHeadSelfAttention(num_heads=4, num_kv_heads=2, causal=True)
    params = mod.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    assert params["kv_kernel"].shape == (32, 2 * 2 * 8)   # 2 kv heads x 2 (k,v) x hd 8
    assert "qkv_kernel" not in params
    out = mod.apply({"params": params}, x)

    # Oracle: same projections by hand, K/V repeated per group, dense core.
    q = (x @ params["q_kernel"] + params["q_bias"]).reshape(2, 8, 4, 8)
    kv = (x @ params["kv_kernel"] + params["kv_bias"]).reshape(2, 8, 2, 2, 8)
    k = jnp.repeat(kv[:, :, 0], 2, axis=2)
    v = jnp.repeat(kv[:, :, 1], 2, axis=2)
    attn = ops.full_attention(q, k, v, causal=True).reshape(2, 8, 32)
    ref = attn @ params["out_kernel"] + params["out_bias"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_gqa_head_divisibility_enforced():
    from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
        MultiHeadSelfAttention,
    )

    x = jnp.zeros((1, 4, 32))
    with pytest.raises(ValueError, match="not divisible by"):
        MultiHeadSelfAttention(num_heads=4, num_kv_heads=3).init(
            {"params": jax.random.PRNGKey(0)}, x)


def test_gqa_params_shard_under_tp():
    """The split q/kv projections column-shard like the fused qkv kernel did."""
    from jax.sharding import PartitionSpec as P

    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.tensor_parallel import (
        param_partition_specs,
    )

    model = TransformerClassifier(num_kv_heads=2, dropout_rate=0.0)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 28, 28, 1)))["params"]
    specs = param_partition_specs(params)
    attn = specs["block_0"]["attn"]
    assert attn["q_kernel"] == P(None, "model")
    assert attn["kv_kernel"] == P(None, "model")
    assert attn["kv_bias"] == P("model")


def test_remat_policy_validation():
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model, validate_model_config,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
        remat_policy_fn,
    )

    assert remat_policy_fn("") is None
    assert remat_policy_fn("recompute-all") is None
    assert remat_policy_fn("save-dots") is not None
    with pytest.raises(ValueError, match="unknown remat policy"):
        remat_policy_fn("everything")
    with pytest.raises(ValueError, match="add --remat"):
        validate_model_config("transformer", remat_policy="save-dots")
    m = build_model("transformer", remat=True, remat_policy="save-dots")
    assert m.remat_policy == "save-dots"
