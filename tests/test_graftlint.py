"""graftlint (tools/graftlint): the invariants-as-code lint pass — tier-1.

Three layers, mirroring the tool's own structure:

1. **fixture tests** — per checker, at least one true-positive snippet (the
   violation is found) and one false-positive regression snippet (the
   sanctioned look-alike is NOT found), built as tiny synthetic repos in
   tmp_path so each rule's boundary is pinned independently of this repo's
   code;
2. **machinery tests** — pragmas, baseline matching/staleness, import-graph
   semantics (lazy vs top-level edges, parent-package edges);
3. **the meta-test** — the full pass over THIS repo must report zero
   non-baselined findings, and the CLI must exit 0 (and nonzero once a
   violation is introduced). This is the test that turns the house rules into
   a commit gate.

graftlint is stdlib-only and never imports repo code, so these tests run
without touching a jax backend (the fixture repos reference jax only as text).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:            # tools.* is a namespace package off the root
    sys.path.insert(0, REPO)

from tools.graftlint import (  # noqa: E402
    build_graph,
    load_baseline,
    run_lint,
)
from tools.graftlint.baseline import Baseline, default_baseline_path  # noqa: E402
from tools.graftlint.core import parse_pragmas  # noqa: E402

PKG = "csed_514_project_distributed_training_using_pytorch_tpu"

# The fixture package deliberately reuses this repo's rule paths (rules.py is
# package-relative), so e.g. fakepkg/serving/router.py is declared
# backend-free and fakepkg/train/lm.py must gate its writes.
BASE_FILES = {
    "fakepkg/__init__.py": "",
    "fakepkg/utils/__init__.py": "",
    "fakepkg/utils/telemetry_events.py":
        'EVENT_KINDS = {"known": "a registered kind"}\n',
    "fakepkg/serving/__init__.py": "",
    "fakepkg/train/__init__.py": "",
    "fakepkg/resilience/__init__.py": "",
}


def lint(tmp_path, files, checks=None):
    """Write ``files`` over the fixture skeleton and lint the tmp repo."""
    for rel, src in {**BASE_FILES, **files}.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    findings, _graph = run_lint(str(tmp_path), checks=checks)
    return findings


def by_check(findings, name):
    return [f for f in findings if f.check == name]


# -----------------------------------------------------------------------------------
# backend-purity
# -----------------------------------------------------------------------------------


def test_backend_purity_transitive_true_positive(tmp_path):
    fs = {
        "fakepkg/helper.py": "import jax\n",
        "fakepkg/serving/router.py": "from fakepkg import helper\n",
    }
    found = by_check(lint(tmp_path, fs, ["backend-purity"]), "backend-purity")
    assert len(found) == 1
    f = found[0]
    assert f.path == "fakepkg/serving/router.py"
    assert f.line == 1                      # the import line starting the chain
    assert "fakepkg.helper" in f.message and "jax" in f.message


def test_backend_purity_parent_package_edge(tmp_path):
    # launch.py itself is clean; the PARENT __init__ imports jax eagerly —
    # the exact leak class fixed in train/__init__.py when this tool landed.
    fs = {
        "fakepkg/train/__init__.py": "from fakepkg.train import step\n",
        "fakepkg/train/step.py": "import jax\n",
        "fakepkg/train/launch.py": "import os\n",
        "fakepkg/serving/router.py": "from fakepkg.train.launch import os\n",
    }
    found = by_check(lint(tmp_path, fs, ["backend-purity"]), "backend-purity")
    assert len(found) == 1
    assert "fakepkg.train" in found[0].message


def test_backend_purity_lazy_import_is_sanctioned(tmp_path):
    fs = {
        "fakepkg/serving/router.py": (
            "import os\n"
            "def resume():\n"
            "    import jax\n"
            "    return jax\n"),
    }
    assert lint(tmp_path, fs, ["backend-purity"]) == []


def test_backend_purity_pragma_excludes_edge(tmp_path):
    fs = {
        "fakepkg/serving/router.py":
            "import jax  # graftlint: disable=backend-purity\n",
    }
    assert lint(tmp_path, fs, ["backend-purity"]) == []


def test_backend_purity_out_of_scope_module_free(tmp_path):
    fs = {"fakepkg/models.py": "import jax\n"}        # not declared backend-free
    assert lint(tmp_path, fs, ["backend-purity"]) == []


# -----------------------------------------------------------------------------------
# resolve-guard
# -----------------------------------------------------------------------------------


def test_resolve_guard_true_positive(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            "def done(fut, value):\n"
            "    fut.set_result(value)\n"),
    }
    found = by_check(lint(tmp_path, fs, ["resolve-guard"]), "resolve-guard")
    assert len(found) == 1 and found[0].line == 2
    assert "set_result" in found[0].message


def test_resolve_guard_guarded_is_clean(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            "import concurrent.futures\n"
            "def done(fut, value, err):\n"
            "    try:\n"
            "        if err is not None:\n"
            "            fut.set_exception(err)\n"
            "        else:\n"
            "            fut.set_result(value)\n"
            "    except concurrent.futures.InvalidStateError:\n"
            "        pass\n"),
    }
    assert lint(tmp_path, fs, ["resolve-guard"]) == []


def test_resolve_guard_else_leg_not_guarded(tmp_path):
    # try/else runs OUTSIDE the guarded region — a resolve there can still
    # lose the race and kill the thread.
    fs = {
        "fakepkg/serving/server.py": (
            "def done(fut, value):\n"
            "    try:\n"
            "        x = 1\n"
            "    except InvalidStateError:\n"
            "        pass\n"
            "    else:\n"
            "        fut.set_result(value)\n"),
    }
    assert len(by_check(lint(tmp_path, fs, ["resolve-guard"]),
                        "resolve-guard")) == 1


def test_resolve_guard_wide_handler_and_tuple(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            "def done(fut, v):\n"
            "    try:\n"
            "        fut.set_result(v)\n"
            "    except (ValueError, InvalidStateError):\n"
            "        pass\n"
            "def done2(fut, v):\n"
            "    try:\n"
            "        fut.set_result(v)\n"
            "    except Exception:\n"
            "        pass\n"),
    }
    assert lint(tmp_path, fs, ["resolve-guard"]) == []


# -----------------------------------------------------------------------------------
# telemetry-schema
# -----------------------------------------------------------------------------------


def test_telemetry_schema_unregistered_kind(tmp_path):
    fs = {
        "fakepkg/serving/server.py":
            'def emit(w):\n    w.emit({"event": "mystery", "x": 1})\n',
    }
    found = by_check(lint(tmp_path, fs, ["telemetry-schema"]),
                     "telemetry-schema")
    assert len(found) == 1
    assert "'mystery'" in found[0].message


def test_telemetry_schema_registered_and_dynamic_kinds_clean(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            'def emit(w, kind):\n'
            '    w.emit({"event": "known"})\n'
            '    w.emit({"event": kind})\n'      # dynamic: reader passthrough
            '    d = {"event": "known"}\n'),
    }
    assert lint(tmp_path, fs, ["telemetry-schema"]) == []


def test_telemetry_schema_setdefault_form(tmp_path):
    fs = {
        "fakepkg/serving/server.py":
            'def emit(p):\n    p.setdefault("event", "drifted")\n',
    }
    assert len(by_check(lint(tmp_path, fs, ["telemetry-schema"]),
                        "telemetry-schema")) == 1


def test_telemetry_schema_missing_registry_is_loud(tmp_path):
    files = {k: v for k, v in BASE_FILES.items()
             if k != "fakepkg/utils/telemetry_events.py"}
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    findings, _ = run_lint(str(tmp_path), checks=["telemetry-schema"])
    assert len(findings) == 1
    assert "cannot read" in findings[0].message


def test_telemetry_schema_computed_registry_is_loud(tmp_path):
    fs = {"fakepkg/utils/telemetry_events.py":
          "EVENT_KINDS = dict(known='x')\n"}       # not a pure dict literal
    findings = lint(tmp_path, fs, ["telemetry-schema"])
    assert len(findings) == 1
    assert "pure dict literal" in findings[0].message


# -----------------------------------------------------------------------------------
# process0-gate
# -----------------------------------------------------------------------------------


def test_process0_gate_raw_write_true_positive(tmp_path):
    fs = {
        "fakepkg/train/lm.py": (
            "import json\n"
            "def run(path, history):\n"
            "    with open(path, 'w') as f:\n"
            "        json.dump(history, f)\n"),
    }
    found = by_check(lint(tmp_path, fs, ["process0-gate"]), "process0-gate")
    assert len(found) == 2                 # open('w') AND json.dump
    assert all("process-0 gate" in f.message for f in found)


def test_process0_gate_gated_write_is_clean(tmp_path):
    fs = {
        "fakepkg/train/lm.py": (
            "import json\n"
            "from fakepkg.utils import metrics as M\n"
            "def run(path, history, pidx):\n"
            "    if M.is_logging_process():\n"
            "        with open(path, 'w') as f:\n"
            "            json.dump(history, f)\n"
            "    if pidx.process_index() == 0:\n"
            "        open(path, 'a').close()\n"),
        "fakepkg/utils/metrics.py": "def is_logging_process():\n    return True\n",
    }
    assert lint(tmp_path, fs, ["process0-gate"]) == []


def test_process0_gate_reads_and_out_of_scope_clean(tmp_path):
    fs = {
        "fakepkg/train/lm.py": (
            "def run(path):\n"
            "    return open(path).read()\n"),    # read mode: no gate needed
        "fakepkg/serving/engine2.py": (
            "def run(path):\n"
            "    open(path, 'w').close()\n"),     # not an SPMD trainer module
    }
    assert lint(tmp_path, fs, ["process0-gate"]) == []


# -----------------------------------------------------------------------------------
# host-sync-hazard
# -----------------------------------------------------------------------------------


def test_host_sync_hot_method_true_positive(tmp_path):
    fs = {
        "fakepkg/serving/engine.py": (
            "class Engine:\n"
            "    def step(self):\n"
            "        cache, tok = self._step_jit(1)\n"
            "        return float(tok)\n"),
    }
    found = by_check(lint(tmp_path, fs, ["host-sync-hazard"]),
                     "host-sync-hazard")
    assert len(found) == 1 and found[0].line == 4
    assert "float" in found[0].message


def test_host_sync_reassignment_clears_taint(tmp_path):
    # The one sanctioned shape: a single batched np.asarray fetch (flagged —
    # in production it carries the pragma), after which the host copy is free.
    fs = {
        "fakepkg/serving/engine.py": (
            "import numpy as np\n"
            "class Engine:\n"
            "    def step(self):\n"
            "        cache, tok = self._step_jit(1)\n"
            "        tok = np.asarray(tok)\n"
            "        return int(tok[0])\n"),      # host data now: NOT flagged
    }
    found = by_check(lint(tmp_path, fs, ["host-sync-hazard"]),
                     "host-sync-hazard")
    assert len(found) == 1 and found[0].line == 5


def test_host_sync_host_values_and_cold_methods_clean(tmp_path):
    fs = {
        "fakepkg/serving/engine.py": (
            "import numpy as np\n"
            "class Engine:\n"
            "    def step(self):\n"
            "        n = int(self._prompt_len[0])\n"      # host array attr
            "        a = np.asarray([1, 2])\n"            # host literal
            "        return n + a[0]\n"
            "    def report(self):\n"                     # not a hot region
            "        _, tok = self._step_jit(1)\n"
            "        return float(tok)\n"),
    }
    assert lint(tmp_path, fs, ["host-sync-hazard"]) == []


def test_host_sync_scan_body_params_are_traced(tmp_path):
    fs = {
        "fakepkg/train/step.py": (
            "from jax import lax\n"
            "def make_epoch(xs):\n"
            "    def body(carry, x):\n"
            "        bad = float(x)\n"                    # sync on a tracer
            "        return carry, bad\n"
            "    return lax.scan(body, 0.0, xs)\n"
            "def host_helper(x):\n"
            "    return float(x)\n"),                     # not a scan body
    }
    found = by_check(lint(tmp_path, fs, ["host-sync-hazard"]),
                     "host-sync-hazard")
    assert len(found) == 1 and found[0].line == 4


def test_host_sync_pragma_sanctions_line(tmp_path):
    fs = {
        "fakepkg/serving/engine.py": (
            "import numpy as np\n"
            "class Engine:\n"
            "    def step(self):\n"
            "        cache, tok = self._step_jit(1)\n"
            "        tok = np.asarray(tok)"
            "  # graftlint: disable=host-sync-hazard\n"
            "        return int(tok[0])\n"),
    }
    assert lint(tmp_path, fs, ["host-sync-hazard"]) == []


# -----------------------------------------------------------------------------------
# retrace-hazard
# -----------------------------------------------------------------------------------


def test_retrace_immediate_invoke_true_positive(tmp_path):
    fs = {
        "fakepkg/serving/sampler.py": (
            "import jax\n"
            "def sample(params, key):\n"
            "    return jax.jit(lambda k: k)(key)\n"),
    }
    found = by_check(lint(tmp_path, fs, ["retrace-hazard"]), "retrace-hazard")
    assert len(found) == 1 and found[0].line == 3
    assert "fresh wrapper" in found[0].message


def test_retrace_jit_in_loop_true_positive(tmp_path):
    fs = {
        "fakepkg/serving/sweep.py": (
            "import jax\n"
            "def sweep(fns):\n"
            "    out = []\n"
            "    for fn in fns:\n"
            "        out.append(jax.jit(fn))\n"
            "    return out\n"),
    }
    found = by_check(lint(tmp_path, fs, ["retrace-hazard"]), "retrace-hazard")
    assert len(found) == 1
    assert "inside a loop" in found[0].message


def test_retrace_builders_and_memoization_clean(tmp_path):
    fs = {
        "fakepkg/parallel/dp.py": (
            "import jax\n"
            "STEP = jax.jit(lambda x: x)\n"               # module scope: once
            "def make_step(fn):\n"
            "    return jax.jit(fn)\n"                    # builder: caller caches
            "def cached(fn, cache, key):\n"
            "    if key not in cache:\n"
            "        cache[key] = jax.jit(fn)\n"          # memoized: sanctioned
            "    return cache[key]\n"),
    }
    assert lint(tmp_path, fs, ["retrace-hazard"]) == []


def test_retrace_scripts_exempt_from_per_call_rules(tmp_path):
    # One-shot harnesses (tools/, bench*.py) invoke each jit exactly once.
    fs = {
        "tools/bench_thing.py": (
            "import jax\n"
            "def leg(key):\n"
            "    return jax.jit(lambda k: k)(key)\n"),
    }
    assert lint(tmp_path, fs, ["retrace-hazard"]) == []


def test_retrace_unhashable_static_arg(tmp_path):
    fs = {
        "fakepkg/serving/compilecache.py": (
            "import jax\n"
            "def prog(x, sizes):\n"
            "    return x\n"
            "RUN = jax.jit(prog, static_argnames=('sizes',))\n"
            "def call(x):\n"
            "    return RUN(x, sizes=[1, 2])\n"),         # list: unhashable
    }
    found = by_check(lint(tmp_path, fs, ["retrace-hazard"]), "retrace-hazard")
    assert len(found) == 1
    assert "unhashable list" in found[0].message
    # Tuple literal in the same position is hashable: clean.
    fs["fakepkg/serving/compilecache.py"] = \
        fs["fakepkg/serving/compilecache.py"].replace("[1, 2]", "(1, 2)")
    assert lint(tmp_path, fs, ["retrace-hazard"]) == []


# -----------------------------------------------------------------------------------
# machinery: pragmas, baseline, graph
# -----------------------------------------------------------------------------------


def test_parse_pragmas_line_and_file_scopes():
    file_level, by_line = parse_pragmas(
        "# graftlint: disable-file=telemetry-schema\n"
        "x = 1  # graftlint: disable=host-sync-hazard,retrace-hazard\n"
        "y = 2  # ordinary comment\n")
    assert file_level == {"telemetry-schema"}
    assert by_line == {2: {"host-sync-hazard", "retrace-hazard"}}


def test_parse_pragmas_ignores_strings_and_docstrings():
    # Pragma syntax QUOTED in a docstring/string (someone documenting the
    # mechanism) must not disable anything — only real comments count.
    file_level, by_line = parse_pragmas(
        '"""Docs show: # graftlint: disable-file=resolve-guard"""\n'
        's = "# graftlint: disable=backend-purity"\n')
    assert file_level == set() and by_line == {}


def test_docstring_pragma_does_not_suppress(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            '"""Use `# graftlint: disable-file=resolve-guard` to opt out."""\n'
            "def done(fut, v):\n"
            "    fut.set_result(v)\n"),
    }
    assert len(by_check(lint(tmp_path, fs, ["resolve-guard"]),
                        "resolve-guard")) == 1


def test_file_pragma_suppresses_whole_file(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            "# graftlint: disable-file=resolve-guard\n"
            "def done(fut, v):\n"
            "    fut.set_result(v)\n"),
    }
    assert lint(tmp_path, fs, ["resolve-guard"]) == []


def test_baseline_matching_and_staleness(tmp_path):
    fs = {
        "fakepkg/serving/server.py": (
            "def done(fut, v):\n"
            "    fut.set_result(v)\n"),
    }
    findings = lint(tmp_path, fs, ["resolve-guard"])
    assert len(findings) == 1
    f = findings[0]
    stale_entry = {"check": "resolve-guard", "path": "gone.py", "message": "x"}
    baseline = Baseline(path=str(tmp_path / "b.json"), entries=[
        {"check": f.check, "path": f.path, "message": f.message}, stale_entry])
    new, baselined, stale = baseline.split(findings)
    assert new == [] and len(baselined) == 1 and stale == [stale_entry]
    # An un-baselined finding stays new.
    new2, _, _ = Baseline(path="", entries=[stale_entry]).split(findings)
    assert new2 == findings


def test_graph_lazy_vs_toplevel_edges(tmp_path):
    for rel, src in {**BASE_FILES, "fakepkg/mod.py": (
            "import os\n"
            "def f():\n"
            "    import json\n")}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    graph = build_graph(str(tmp_path))
    edges = graph.edges("fakepkg.mod", include_lazy=True)
    assert {(e.target, e.lazy) for e in edges} == {("os", False),
                                                   ("json", True)}
    assert [e.target for e in graph.edges("fakepkg.mod")] == ["os"]


# -----------------------------------------------------------------------------------
# the meta-test + CLI: this repo is clean, and the gate really gates
# -----------------------------------------------------------------------------------


def test_repo_is_clean_under_graftlint():
    """THE gate: zero non-baselined findings on this repository."""
    findings, graph = run_lint(REPO)
    baseline = load_baseline(default_baseline_path(REPO))
    new, _baselined, stale = baseline.split(findings)
    assert new == [], "graftlint findings:\n" + "\n".join(
        f.format() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"
    # Sanity: the scan actually covered the fleet-side modules the rules name.
    for rel in (f"{PKG}/serving/router.py", f"{PKG}/resilience/supervisor.py",
                "tools/serve_loadgen.py"):
        assert graph.module_for_relpath(rel) is not None, rel


def test_registry_and_report_agree():
    """KNOWN_EVENTS is derived, so the footer cannot drift from the emitters."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools", "telemetry_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)
    events = __import__(f"{PKG}.utils.telemetry_events",
                        fromlist=["EVENT_KINDS", "KNOWN_EVENTS"])
    assert report.KNOWN_EVENTS == events.KNOWN_EVENTS
    assert set(events.EVENT_KINDS) == set(events.KNOWN_EVENTS)
    assert all(isinstance(v, str) and v for v in events.EVENT_KINDS.values())


def test_cli_exit_codes_and_json(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO}
    # Clean repo: exit 0.
    ok = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    doc = json.loads(ok.stdout)
    assert doc["ok"] is True and doc["findings"] == []
    assert doc["modules"] > 50
    # Introduce a violation in a fixture repo: exit 1, finding in the JSON.
    for rel, src in {**BASE_FILES, "fakepkg/serving/router.py":
                     "import jax\n"}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    bad = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", str(tmp_path),
         "--json", "--baseline", str(tmp_path / "baseline.json")],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    doc = json.loads(bad.stdout)
    assert doc["ok"] is False
    assert any(f["check"] == "backend-purity" for f in doc["findings"])


def test_cli_update_baseline_roundtrip(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO}
    for rel, src in {**BASE_FILES, "fakepkg/serving/router.py":
                     "import jax\n"}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    base = str(tmp_path / "baseline.json")
    wrote = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", str(tmp_path),
         "--baseline", base, "--update-baseline"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    entries = json.loads(open(base).read())
    assert entries and entries[0]["check"] == "backend-purity"
    # Baselined: the same tree now gates green.
    rerun = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--root", str(tmp_path),
         "--baseline", base],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert rerun.returncode == 0, rerun.stdout + rerun.stderr
    assert "1 baselined" in rerun.stdout


def test_cli_update_baseline_rejects_filtered_run(tmp_path):
    # A filtered run saving the baseline would silently delete every other
    # checker's grandfathered entries.
    env = {**os.environ, "PYTHONPATH": REPO}
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--checks", "backend-purity",
         "--update-baseline", "--baseline", str(tmp_path / "b.json")],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert r.returncode == 2
    assert "full run" in r.stderr


def test_cli_unknown_check_is_usage_error(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO}
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--checks", "no-such-check"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert r.returncode == 2
    assert "unknown check" in r.stderr


def test_committed_baseline_ships_empty():
    """The satellite's bar: no grandfathered findings — everything was fixed."""
    baseline = load_baseline(default_baseline_path(REPO))
    assert baseline.entries == []
