"""Unit tests for the fleet launcher's plumbing (train/launch.py) — env contract assembly,
flag rewriting, CLI parsing — without spawning fleets (those run in test_multiprocess.py)."""

import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.train import launch as L


class TestChildEnv:
    def test_rendezvous_env_contract(self):
        env = L._child_env({}, port=12345, num_processes=4, process_id=2,
                           platform=None, devices_per_process=1)
        assert env["JAX_COORDINATOR_ADDRESS"] == "localhost:12345"
        assert env["JAX_NUM_PROCESSES"] == "4"
        assert env["JAX_PROCESS_ID"] == "2"
        assert "JAX_PLATFORMS" not in env

    def test_cpu_platform_sets_device_count(self):
        env = L._child_env({}, port=1, num_processes=2, process_id=0,
                           platform="cpu", devices_per_process=3)
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["XLA_FLAGS"] == "--xla_force_host_platform_device_count=3"

    def test_inherited_device_count_is_replaced(self):
        base = {"XLA_FLAGS": "--foo --xla_force_host_platform_device_count=8 --bar",
                "JAX_PLATFORMS": "cpu"}
        env = L._child_env(base, port=1, num_processes=2, process_id=1,
                           platform=None, devices_per_process=2)
        assert "device_count=8" not in env["XLA_FLAGS"]
        assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]
        assert "--foo" in env["XLA_FLAGS"] and "--bar" in env["XLA_FLAGS"]

    def test_non_cpu_platform_keeps_flags(self):
        base = {"XLA_FLAGS": "--keep-me"}
        env = L._child_env(base, port=1, num_processes=2, process_id=0,
                           platform="tpu", devices_per_process=4)
        assert env["XLA_FLAGS"] == "--keep-me"


class TestCli:
    def test_no_command_errors(self, capsys):
        with pytest.raises(SystemExit) as e:
            L.main(["--num-processes", "2"])
        assert e.value.code == 2

    def test_remainder_after_double_dash(self, monkeypatch):
        seen = {}

        def fake_launch(command, **kwargs):
            seen["command"] = command
            seen.update(kwargs)
            return 0

        monkeypatch.setattr(L, "launch", fake_launch)
        assert L.main(["--num-processes", "3", "--platform", "cpu", "--timeout", "9",
                       "--", "-m", "somemod", "--flag"]) == 0
        assert seen["command"] == ["-m", "somemod", "--flag"]
        assert seen["num_processes"] == 3
        assert seen["platform"] == "cpu"
        assert seen["timeout"] == 9.0


def test_free_port_is_bindable():
    import socket

    port = L._free_port()
    with socket.socket() as s:
        s.bind(("localhost", port))   # free at allocation time
