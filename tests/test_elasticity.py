"""Elastic fleet serving: autoscaler policy, drain-to-retire, warm-start, reload.

The PR 9 acceptance contract, in tiers:

- **policy tier** (pure, no processes): ``serving/autoscaler.py`` hysteresis —
  sustain counters, cooldown dead time, target-bounds — driven with synthetic
  ``fleet_snapshot`` dicts; plus the router-side pure pieces (affinity
  alive-filter/re-home, hot-prefix export, checkpoint argv rewrite).
- **echo tier** (cheap processes, no model): the lifecycle machinery —
  manual scale_up/scale_down, the graceful drain-to-retire invariant (zero
  lost requests, zero double-completions, including the shrink/submit race),
  prefix-cache warm-start protocol, rolling ``Router.reload`` with capacity
  never below N−1, and the full 2→4→1 elasticity run under a mid-flight kill.
- **engine tier** (slow, the CI elasticity-smoke job): the same 2→4→1 run
  against real jax replicas, every completion token-identical to an
  uninterrupted single-engine run.
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.serving.autoscaler import (
    AutoscalePolicy,
    FleetAutoscaler,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.router import (
    Router,
    _AffinityIndex,
    _with_checkpoint,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.scheduler import (
    QueueClosed,
    RequestQueue,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import trace
from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (
    load_metrics_jsonl,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "csed_514_project_distributed_training_using_pytorch_tpu"


@pytest.fixture(autouse=True)
def _child_pythonpath(monkeypatch):
    existing = os.environ.get("PYTHONPATH", "")
    monkeypatch.setenv("PYTHONPATH", f"{REPO}:{existing}" if existing else REPO)


# -----------------------------------------------------------------------------------------
# Policy tier: hysteresis over synthetic snapshots
# -----------------------------------------------------------------------------------------


def _snap(depth=0, age=0.0, util=0.0, target=2):
    return {"queue": {"depth": depth, "oldest_age_s": age},
            "utilization": util, "target": target}


def test_autoscale_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0).validate()
    with pytest.raises(ValueError):
        AutoscalePolicy(sustain_up=0).validate()
    with pytest.raises(ValueError):
        AutoscalePolicy(down_utilization=0.9, up_utilization=0.8).validate()
    AutoscalePolicy().validate()          # defaults are legal


def test_autoscaler_scale_up_needs_sustained_overload():
    a = FleetAutoscaler(AutoscalePolicy(sustain_up=3, up_queue_age_s=0.5,
                                        cooldown_s=0.0))
    hot = _snap(depth=4, age=1.0, util=1.0)
    assert a.observe(hot, 0.0) is None
    assert a.observe(hot, 1.0) is None
    assert a.observe(hot, 2.0) == "up"              # third consecutive
    # One calm snapshot resets the streak: sustain means CONSECUTIVE.
    assert a.observe(hot, 3.0) is None
    assert a.observe(_snap(), 4.0) is None
    assert a.observe(hot, 5.0) is None
    assert a.observe(hot, 6.0) is None
    assert a.observe(hot, 7.0) == "up"


def test_autoscaler_scale_down_needs_sustained_idle_and_empty_queue():
    a = FleetAutoscaler(AutoscalePolicy(sustain_down=2, down_utilization=0.25,
                                        cooldown_s=0.0))
    idle = _snap(depth=0, util=0.1)
    assert a.observe(idle, 0.0) is None
    assert a.observe(idle, 1.0) == "down"
    # Idle utilization but a non-empty queue is NOT idle.
    a2 = FleetAutoscaler(AutoscalePolicy(sustain_down=1, cooldown_s=0.0))
    assert a2.observe(_snap(depth=1, util=0.0), 0.0) is None
    # util None (no ready capacity at all) must never shrink the fleet.
    assert a2.observe({"queue": {"depth": 0}, "utilization": None,
                       "target": 2}, 1.0) is None


def test_autoscaler_cooldown_suppresses_then_reacts():
    a = FleetAutoscaler(AutoscalePolicy(sustain_up=1, up_queue_age_s=0.5,
                                        cooldown_s=5.0))
    hot = _snap(depth=4, age=1.0)
    assert a.observe(hot, 0.0) == "up"
    assert a.observe(hot, 1.0) is None              # inside the dead time
    assert a.observe(hot, 4.9) is None
    assert a.observe(hot, 5.1) == "up"              # still hot after cooldown


def test_autoscaler_bounds_check_target_not_ready_count():
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2, sustain_up=1,
                          sustain_down=1, up_queue_age_s=0.5, cooldown_s=0.0)
    a = FleetAutoscaler(pol)
    # target already at max (a spawn still compiling counts): no stacking.
    assert a.observe(_snap(depth=4, age=1.0, target=2), 0.0) is None
    assert a.observe(_snap(depth=4, age=1.0, target=1), 1.0) == "up"
    # target at min: no shrink below the floor.
    assert a.observe(_snap(depth=0, util=0.0, target=1), 2.0) is None
    assert a.observe(_snap(depth=0, util=0.0, target=2), 3.0) == "down"
    assert a.decisions and a.decisions[-1]["verdict"] == "down"


# -----------------------------------------------------------------------------------------
# Pure router pieces
# -----------------------------------------------------------------------------------------


def test_with_checkpoint_rewrites_or_appends():
    assert _with_checkpoint(["-m", "x"], "new.ckpt") == \
        ["-m", "x", "--checkpoint", "new.ckpt"]
    assert _with_checkpoint(["-m", "x", "--checkpoint", "old.ckpt", "--rope"],
                            "new.ckpt") == \
        ["-m", "x", "--checkpoint", "new.ckpt", "--rope"]
    assert _with_checkpoint(["-m", "x", "--checkpoint=old.ckpt"], "new.ckpt") \
        == ["-m", "x", "--checkpoint=new.ckpt"]
    cmd = ["-m", "x"]
    _with_checkpoint(cmd, "a")
    assert cmd == ["-m", "x"]             # pure: input never mutated


def test_affinity_lookup_skips_non_alive_replicas():
    idx = _AffinityIndex()
    long = np.arange(20, dtype=np.int32)
    idx.insert(long, 0)                   # best match homed on replica 0
    idx.insert(long[:10].copy(), 1)       # shorter match on replica 1
    assert idx.lookup(long, 8) == 0
    # Replica 0 drains: the shorter match on a READY replica wins; entries for
    # the draining replica are skipped, not deleted.
    assert idx.lookup(long, 8, alive={1}) == 1
    assert idx.lookup(long, 8, alive={0, 1}) == 0   # still there
    assert idx.lookup(long, 8, alive=set()) is None


def test_affinity_rehome_moves_entries_to_survivor():
    idx = _AffinityIndex()
    idx.insert(np.arange(12, dtype=np.int32), 0)
    idx.insert(np.arange(50, 62, dtype=np.int32), 0)
    idx.insert(np.arange(100, 112, dtype=np.int32), 1)
    assert idx.rehome(0, 2) == 2
    assert idx.lookup(np.arange(12, dtype=np.int32), 8, alive={1, 2}) == 2
    assert idx.lookup(np.arange(100, 112, dtype=np.int32), 8) == 1
    # No survivor: entries drop instead.
    assert idx.rehome(1, None) == 0
    assert idx.lookup(np.arange(100, 112, dtype=np.int32), 8) is None


def test_affinity_hot_prefixes_mru_first():
    idx = _AffinityIndex()
    a = np.arange(10, dtype=np.int32)
    b = np.arange(20, 30, dtype=np.int32)
    idx.insert(a, 0)
    idx.insert(b, 1)
    idx.lookup(a, 8)                      # touches a: most recently used
    hot = idx.hot_prefixes(2)
    np.testing.assert_array_equal(hot[0], a)
    np.testing.assert_array_equal(hot[1], b)
    assert idx.hot_prefixes(0) == []
    hot[0][0] = 99                        # copies: caller cannot poison the index
    assert idx.lookup(a, 8) == 0


def test_queue_closed_is_typed_and_requeue_still_works():
    q = RequestQueue(4)

    class R:
        arrival_s = deadline_s = None

    q.close()
    with pytest.raises(QueueClosed):
        q.submit(R())
    q.requeue(R())                        # redispatch ignores close
    assert len(q) == 1


def test_lifecycle_spans_excluded_from_trace_accounting():
    spans = [
        {"event": "span", "trace_id": "t1", "name": "queue_wait", "proc":
         "router", "ts": 1.0, "dur_s": 0.1},
        {"event": "span", "trace_id": "t1", "name": "resolve", "proc":
         "router", "ts": 1.2, "dur_s": 0.01},
        # The fleet's own history: one synthetic trace of scale/reload spans.
        {"event": "span", "trace_id": "fleet", "name": "scale", "proc":
         "router", "ts": 1.1, "dur_s": 0.0, "action": "up"},
        {"event": "span", "trace_id": "fleet", "name": "reload", "proc":
         "router", "ts": 1.3, "dur_s": 0.5, "replica": 0},
    ]
    summ = trace.summarize_traces(spans)
    assert summ["traces"] == 1            # the fleet trace is not a request
    assert summ["orphans"] == 0           # ... and never an orphan
    tl = trace.lifecycle_timeline(spans)
    assert [s["name"] for s in tl] == ["scale", "reload"]


# -----------------------------------------------------------------------------------------
# Echo tier: lifecycle machinery with model-free replicas
# -----------------------------------------------------------------------------------------


def _echo_cmd(*, num_slots=4, max_pending=8, delay=0.0, seq_len=32, levels=8):
    cmd = ["-m", f"{PKG}.serving.replica", "--echo",
           "--num-levels", str(levels), "--seq-len", str(seq_len),
           "--num-slots", str(num_slots), "--max-pending", str(max_pending)]
    if delay:
        cmd += ["--echo-delay-s", str(delay)]
    return cmd


def _echo_expected(prompt: np.ndarray, max_new: int, *, seq_len=32, levels=8):
    p = len(prompt)
    total = min(p + max_new, seq_len)
    base = int(prompt.sum()) if p else 0
    return np.asarray(list(prompt) + [(base + i) % levels
                                      for i in range(total - p)], np.int32)


def _router(tmp_path, cmd, n=2, **kw):
    kw.setdefault("heartbeat_dir", str(tmp_path / "hb"))
    kw.setdefault("heartbeat_timeout_s", 30.0)
    kw.setdefault("backoff_s", 0.2)
    kw.setdefault("telemetry", str(tmp_path / "router.jsonl"))
    kw.setdefault("drain_timeout_s", 20.0)
    return Router(cmd, num_replicas=n, **kw)


def _wait(pred, timeout=30.0, msg=""):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert pred(), msg or "condition not reached in time"


def test_router_bounds_validation(tmp_path):
    with pytest.raises(ValueError):
        Router(_echo_cmd(), num_replicas=1, min_replicas=2)
    with pytest.raises(ValueError):
        Router(_echo_cmd(), num_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        Router(_echo_cmd(), num_replicas=1, min_replicas=0)
    with pytest.raises(ValueError):
        # Autoscale without the snapshot loop that feeds it.
        Router(_echo_cmd(), num_replicas=1, autoscale=AutoscalePolicy())


def test_router_manual_scale_up_down_full_lifecycle(tmp_path):
    """2→4→1 on the echo tier: scale_up spawns through the full lifecycle,
    scale_down drains gracefully (zero lost, zero double-completions), bounds
    hold at both ends, and wait_ready tracks the CURRENT target — a
    min_replicas < num_replicas start neither hangs nor returns early."""
    router = _router(tmp_path, _echo_cmd(delay=0.02), n=2,
                     min_replicas=1, max_replicas=4).start()
    try:
        assert router.wait_ready(timeout=60)      # target-at-start = 2
        assert router.scale_up() == 2
        assert router.scale_up() == 3
        assert router.scale_up() is None          # at max_replicas
        assert router.wait_ready(timeout=60)      # now waits for 4
        assert sum(r.state == "ready" for r in router.replicas) == 4
        rng = np.random.default_rng(7)
        reqs = [(rng.integers(0, 7, size=1 + i % 5).astype(np.int32), 5)
                for i in range(24)]
        futs = [router.submit(p, max_new_tokens=n) for p, n in reqs]
        # Shrink 4 -> 1 while the work is in flight.
        retired = [router.scale_down(), router.scale_down(),
                   router.scale_down()]
        assert all(v is not None for v in retired)
        assert router.scale_down() is None        # at min_replicas
        comps = [f.result(timeout=60) for f in futs]
        assert all(c.ok for c in comps)           # zero lost
        for (p, n), c in zip(reqs, comps):
            np.testing.assert_array_equal(c.tokens, _echo_expected(p, n))
        _wait(lambda: sum(r.state == "retired" for r in router.replicas) == 3,
              msg="retires did not complete")
        # wait_ready after the shrink tracks the NEW target (1), instantly.
        t0 = time.monotonic()
        assert router.wait_ready(timeout=10)
        assert time.monotonic() - t0 < 5.0
        f = router.submit(np.asarray([1, 2], np.int32), max_new_tokens=3)
        assert f.result(timeout=30).ok            # the survivor still serves
    finally:
        summ = router.stop(timeout=60)
    assert summ["ok"] == 25                       # 24 + the post-shrink probe
    assert summ["requests"] == 25                 # zero double-completions
    assert summ["duplicates"] == 0
    assert summ["scale"] == {"scale_ups": 2, "scale_downs": 3, "retired": 3,
                             "reloads": 0}
    assert summ["scale_events"] == 5
    rows = load_metrics_jsonl(str(tmp_path / "router.jsonl"))
    scales = [r for r in rows if r["event"] == "scale"]
    assert [e["action"] for e in scales] == ["up", "up", "down", "down", "down"]
    assert [e["target"] for e in scales] == [3, 4, 3, 2, 1]
    retires = [r for r in rows if r["event"] == "replica"
               and r.get("action") == "retired"]
    assert len(retires) == 3 and all(r["mode"] == "retire" for r in retires)


def test_router_shrink_submit_race_zero_lost_zero_double(tmp_path):
    """A request submitted in the same tick a replica flips to draining either
    lands elsewhere or bounces off the replica's closed queue (``error:
    draining``) and rides the requeue — never lost, never completed twice."""
    router = _router(tmp_path, _echo_cmd(delay=0.03, num_slots=2,
                                         max_pending=4), n=2,
                     min_replicas=1).start()
    try:
        assert router.wait_ready(timeout=60)
        rng = np.random.default_rng(11)
        futs = []
        reqs = []
        for i in range(40):
            p = rng.integers(0, 7, size=2 + i % 4).astype(np.int32)
            reqs.append((p, 4))
            futs.append(router.submit(p, max_new_tokens=4))
            if i == 12:                   # mid-stream, work in flight
                assert router.scale_down() is not None
        comps = [f.result(timeout=120) for f in futs]
        assert all(c.ok for c in comps)
        for (p, n), c in zip(reqs, comps):
            np.testing.assert_array_equal(c.tokens, _echo_expected(p, n))
    finally:
        summ = router.stop(timeout=60)
    assert summ["ok"] == 40 == summ["requests"]   # exactly-once, all of them
    assert summ["duplicates"] == 0
    assert summ["scale"]["retired"] == 1


def test_router_scale_up_warm_starts_from_affinity_index(tmp_path):
    """A newly spawned replica replays the fleet's hottest prefixes before it
    is marked ready: the router ships them (``warming`` state), the replica
    acks ``warm_done``, and the affinity index re-homes those prefixes onto
    the warmed replica."""
    router = _router(tmp_path, _echo_cmd(delay=0.01), n=1,
                     max_replicas=2, warm_prefixes=4).start()
    try:
        assert router.wait_ready(timeout=60)
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 7, size=12).astype(np.int32)
                   for _ in range(6)]
        futs = [router.submit(p, max_new_tokens=3) for p in prompts]
        [f.result(timeout=60) for f in futs]
        idx = router.scale_up()
        assert router.wait_ready(timeout=60)
        rep = router.replicas[idx]
        assert rep.state == "ready"
        assert rep.warmed == 4            # the shipped prefixes were replayed
        with router._lock:
            homes = {r for _, r in router._affinity._entries.values()}
        assert idx in homes               # re-homed onto the warmed replica
    finally:
        router.stop(timeout=60)
    rows = load_metrics_jsonl(str(tmp_path / "router.jsonl"))
    evs = [r for r in rows if r["event"] == "replica"
           and r.get("replica") == idx]
    assert [e["action"] for e in evs][:2] == ["warming", "ready"]
    assert evs[0]["warm_prefixes"] == 4 and evs[1]["warmed"] == 4


def test_router_warm_prefixes_zero_stays_cold(tmp_path):
    """``warm_prefixes=0`` (or affinity off) skips the warm phase entirely —
    the new replica goes straight to ready, no warm op on the wire."""
    router = _router(tmp_path, _echo_cmd(), n=1, max_replicas=2,
                     warm_prefixes=0).start()
    try:
        assert router.wait_ready(timeout=60)
        futs = [router.submit(np.arange(10, dtype=np.int32) % 7,
                              max_new_tokens=2) for _ in range(3)]
        [f.result(timeout=60) for f in futs]
        idx = router.scale_up()
        assert router.wait_ready(timeout=60)
        assert router.replicas[idx].warmed == 0
    finally:
        router.stop(timeout=60)
    rows = load_metrics_jsonl(str(tmp_path / "router.jsonl"))
    evs = [r for r in rows if r["event"] == "replica"
           and r.get("replica") == idx]
    assert evs[0]["action"] == "ready"


def test_router_autoscale_grows_on_burst_shrinks_on_idle(tmp_path):
    """The full loop: a burst piles the queue up -> the policy's sustained
    -overload streak fires a scale-up; the idle tail -> a graceful retire.
    Zero lost requests throughout (the autoscaler must never break the
    at-least-once contract)."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3, up_queue_age_s=0.1,
                          up_utilization=0.95, down_utilization=0.3,
                          sustain_up=2, sustain_down=3, cooldown_s=0.5)
    router = _router(tmp_path, _echo_cmd(delay=0.05, num_slots=1,
                                         max_pending=1), n=1,
                     autoscale=pol, snapshot_interval_s=0.15).start()
    try:
        assert router.wait_ready(timeout=60)
        rng = np.random.default_rng(13)
        futs = [router.submit(rng.integers(0, 7, size=3).astype(np.int32),
                              max_new_tokens=8) for _ in range(24)]
        comps = [f.result(timeout=120) for f in futs]
        assert all(c.ok for c in comps)
        _wait(lambda: router._scale_counts["scale_ups"] >= 1, timeout=30,
              msg="no scale-up on a sustained burst")
        # Idle now: the sustained-idle streak must retire a replica.
        _wait(lambda: router._scale_counts["retired"] >= 1, timeout=30,
              msg="no graceful retire on sustained idle")
    finally:
        summ = router.stop(timeout=60)
    assert summ["ok"] == 24 == summ["requests"]
    assert summ["scale"]["scale_ups"] >= 1
    assert summ["scale"]["retired"] >= 1
    rows = load_metrics_jsonl(str(tmp_path / "router.jsonl"))
    snaps = [r for r in rows if r["event"] == "fleet_snapshot"]
    assert snaps and all({"target", "replicas_ready", "scale"} <= set(sn)
                         for sn in snaps)
    assert max(sn["replicas_ready"] for sn in snaps) >= 2


def test_router_reload_rolls_one_at_a_time_capacity_n_minus_1(tmp_path):
    """``Router.reload`` drains and restarts replicas ONE at a time under
    load: every request completes, the reload count matches the fleet, the
    new argv carries the new checkpoint, and the fleet_snapshot timeline
    never shows ready capacity below N−1 once the fleet is up."""
    router = _router(tmp_path, _echo_cmd(delay=0.02), n=2,
                     snapshot_interval_s=0.1).start()
    try:
        assert router.wait_ready(timeout=60)
        stop_load = []
        import threading

        futs = []

        def load():
            rng = np.random.default_rng(17)
            while not stop_load:
                futs.append(router.submit(
                    rng.integers(0, 7, size=3).astype(np.int32),
                    max_new_tokens=4))
                time.sleep(0.02)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        time.sleep(0.3)
        out = router.reload("new_params.ckpt", timeout_s=120)
        stop_load.append(True)
        t.join(timeout=10)
        assert out["reloaded"] == [0, 1]
        comps = [f.result(timeout=60) for f in futs]
        assert all(c.ok for c in comps)
        assert len(comps) > 0
        with router._lock:
            argv = list(router.replicas[0].fleet.procs[0].args)
        assert "new_params.ckpt" in argv          # post-roll spawns carry it
        assert router.replicas[0].state == "ready"
        assert router.replicas[1].state == "ready"
    finally:
        summ = router.stop(timeout=60)
    assert summ["ok"] == summ["requests"] == len(comps)
    assert summ["scale"]["reloads"] == 2
    rows = load_metrics_jsonl(str(tmp_path / "router.jsonl"))
    snaps = [r for r in rows if r["event"] == "fleet_snapshot"]
    # Capacity never below N-1: after the fleet first reached 2 ready, no
    # snapshot shows fewer than 1 ready replica — the rolling-reload invariant.
    ready = [sn["replicas_ready"] for sn in snaps]
    first_full = next(i for i, v in enumerate(ready) if v == 2)
    assert min(ready[first_full:]) >= 1
    reloads = [r for r in rows if r["event"] == "scale"
               and r.get("action") == "reload"]
    assert len(reloads) == 2
    assert all(r["checkpoint"] == "new_params.ckpt" for r in reloads)


def test_router_echo_elastic_2_4_1_with_kill_zero_loss(tmp_path, monkeypatch):
    """The acceptance shape on the echo tier: 2→4→1 under a mid-flight kill.
    Every request completes token-identical to the deterministic expectation
    (the echo analog of greedy idempotency), zero lost, zero orphan traces,
    the killed replica restarts, and the retires are graceful."""
    monkeypatch.setenv("RESILIENCE_FAULTS",
                       f"kill:proc=1,step=5,flag={tmp_path / 'kill'}")
    trace_dir = str(tmp_path / "trace")
    router = _router(tmp_path, _echo_cmd(delay=0.04), n=2,
                     min_replicas=1, max_replicas=4,
                     trace_dir=trace_dir, snapshot_interval_s=0.1).start()
    try:
        assert router.wait_ready(timeout=60)
        rng = np.random.default_rng(3)
        reqs = [(rng.integers(0, 7, size=1 + i % 5).astype(np.int32), 6)
                for i in range(24)]
        futs = [router.submit(p, max_new_tokens=n) for p, n in reqs[:12]]
        assert router.scale_up() is not None       # 2 -> 3
        assert router.scale_up() is not None       # 3 -> 4
        futs += [router.submit(p, max_new_tokens=n) for p, n in reqs[12:]]
        assert router.wait_ready(timeout=60)
        comps = [f.result(timeout=120) for f in futs]
        assert all(c.ok for c in comps)            # zero lost
        for (p, n), c in zip(reqs, comps):
            np.testing.assert_array_equal(c.tokens, _echo_expected(p, n))
        assert any(c.redispatches > 0 for c in comps)   # the kill landed
        _wait(lambda: router.replicas[1].restarts >= 1, timeout=60,
              msg="killed replica did not restart")
        # 4 -> 1.
        for _ in range(3):
            assert router.scale_down() is not None
        _wait(lambda: sum(r.state == "retired" for r in router.replicas) == 3,
              msg="retires did not complete")
    finally:
        summ = router.stop(timeout=60)
    assert summ["ok"] == 24 == summ["requests"]
    assert summ["redispatches"] >= 1
    assert summ["replica_restarts"] >= 1
    assert summ["scale"] == {"scale_ups": 2, "scale_downs": 3, "retired": 3,
                             "reloads": 0}
    spans, _ = trace.read_spans([trace_dir])
    tsumm = trace.summarize_traces(spans)
    assert tsumm["traces"] == 24
    assert tsumm["orphans"] == 0, tsumm["orphan_ids"]
    # The scale actions are on the trace timeline (excluded from per-request
    # accounting above, rendered by trace_report's fleet-lifecycle block).
    assert len(trace.lifecycle_timeline(spans)) == 5


# -----------------------------------------------------------------------------------------
# Engine tier (slow, the CI elasticity-smoke job): jax replicas, token-identity
# -----------------------------------------------------------------------------------------


_TINY = dict(seq_len=16, levels=9, embed=16, layers=1, heads=2, slots=3)


def _engine_cmd():
    return ["-m", f"{PKG}.serving.replica",
            "--num-levels", str(_TINY["levels"] - 1),
            "--seq-len", str(_TINY["seq_len"]),
            "--embed-dim", str(_TINY["embed"]),
            "--num-layers", str(_TINY["layers"]),
            "--num-heads", str(_TINY["heads"]),
            "--num-slots", str(_TINY["slots"]),
            "--max-pending", "8", "--seed", "0",
            "--heartbeat-interval-s", "0.02"]


def _tiny_workload(n=10, seed=11):
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n):
        p = rng.integers(0, _TINY["levels"] - 1,
                         size=int(rng.integers(1, 8))).astype(np.int32)
        reqs.append((p, int(rng.integers(2, 7))))
    return reqs


def _uninterrupted_reference(reqs):
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
        Request,
    )

    model = lm.TransformerLM(vocab_size=_TINY["levels"],
                             seq_len=_TINY["seq_len"],
                             embed_dim=_TINY["embed"],
                             num_layers=_TINY["layers"],
                             num_heads=_TINY["heads"])
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    engine = ContinuousBatchingEngine(model, params, num_slots=_TINY["slots"])
    comps = engine.run([Request(prompt=p, max_new_tokens=n, request_id=i)
                        for i, (p, n) in enumerate(reqs)])
    return {c.request.request_id: np.asarray(c.tokens) for c in comps}


@pytest.mark.slow
def test_fleet_elastic_2_4_1_kill_mid_decode_token_identical(
        tmp_path, monkeypatch):
    """The PR 9 acceptance gate on real engines: a 2→4→1 elasticity run with
    one replica hard-killed MID-DECODE completes every request with greedy
    output token-identical to an uninterrupted single-engine run — zero lost,
    zero orphan traces — and every scale-down retires gracefully."""
    monkeypatch.setenv("RESILIENCE_FAULTS",
                       f"kill:proc=1,step=4,flag={tmp_path / 'kill'}")
    reqs = _tiny_workload(30)
    ref = _uninterrupted_reference(reqs)
    trace_dir = str(tmp_path / "trace")
    router = _router(tmp_path, _engine_cmd(), n=2, min_replicas=1,
                     max_replicas=4, connect_timeout_s=300.0,
                     trace_dir=trace_dir, snapshot_interval_s=0.25,
                     drain_timeout_s=60.0).start()
    try:
        assert router.wait_ready(timeout=300)
        futs = [router.submit(p, max_new_tokens=n) for p, n in reqs[:15]]
        assert router.scale_up() is not None       # 2 -> 3
        assert router.scale_up() is not None       # 3 -> 4
        futs += [router.submit(p, max_new_tokens=n) for p, n in reqs[15:]]
        assert router.wait_ready(timeout=300)      # all four compiled + ready
        assert sum(r.state == "ready" for r in router.replicas) == 4
        comps = [f.result(timeout=300) for f in futs]
        _wait(lambda: router.replicas[1].restarts >= 1, timeout=120,
              msg="killed replica did not restart")
        for _ in range(3):                         # 4 -> 1
            assert router.scale_down() is not None
        _wait(lambda: sum(r.state == "retired" for r in router.replicas) == 3,
              timeout=120, msg="retires did not complete")
    finally:
        summ = router.stop(timeout=120)
    assert all(c.ok for c in comps)                # zero lost
    assert summ["timeout"] == 0
    for i, comp in enumerate(comps):
        np.testing.assert_array_equal(comp.tokens, ref[i])   # greedy idempotency
    assert summ["redispatches"] >= 1               # the kill landed on work
    assert summ["scale"] == {"scale_ups": 2, "scale_downs": 3, "retired": 3,
                             "reloads": 0}
    spans, _ = trace.read_spans([trace_dir])
    tsumm = trace.summarize_traces(spans)
    assert tsumm["traces"] == 30
    assert tsumm["orphans"] == 0, tsumm["orphan_ids"]


# -----------------------------------------------------------------------------------------
# Report tooling
# -----------------------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_telemetry_report_renders_scale_timeline(tmp_path, capsys):
    """The report joins scale events against the fleet_snapshot series and
    surfaces replicas p50/max + scale events as A-vs-B rows."""
    path = tmp_path / "router.jsonl"
    rows = [
        {"event": "fleet_snapshot", "t_s": 0.1, "queue":
         {"depth": 9, "oldest_age_s": 0.8}, "utilization": 1.0,
         "target": 1, "replicas_ready": 1, "inflight": 2, "capacity_up": 2,
         "redispatches": 0, "restarts": 0, "per_replica": []},
        {"event": "scale", "t_s": 0.2, "action": "up", "replica": 1,
         "target": 2, "reason": "autoscale"},
        {"event": "fleet_snapshot", "t_s": 0.3, "queue":
         {"depth": 0, "oldest_age_s": None}, "utilization": 0.0,
         "target": 2, "replicas_ready": 2, "inflight": 0, "capacity_up": 4,
         "redispatches": 0, "restarts": 0, "per_replica": []},
        {"event": "scale", "t_s": 0.4, "action": "down", "replica": 1,
         "target": 1, "reason": "autoscale"},
        {"event": "router_summary", "replicas": 2, "target": 1,
         "scale": {"scale_ups": 1, "scale_downs": 1, "retired": 1,
                   "reloads": 0},
         "scale_events": 2, "replicas_ready_p50": 1, "replicas_ready_max": 2,
         "replicas_ready_min": 1, "requests": 5, "ok": 5, "timeout": 0,
         "failed": 0, "redispatches": 0, "redispatched_requests": 0,
         "duplicates": 0, "affinity_hits": 0, "new_tokens": 40,
         "affinity": True, "wall_s": 1.0, "tokens_per_s": 40.0,
         "affinity_rate": 0.0, "replica_restarts": 0, "per_replica": [],
         "prefix_cache": None, "queue": {"depth": 0}, "ttft_s": None,
         "e2e_s": None, "queue_wait_s": None},
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    rep = _load_tool("telemetry_report")
    s = rep.summarize(str(path))
    assert s["scale_events"] == 2
    assert s["scale_ups"] == 1 and s["scale_downs"] == 1
    assert s["replicas_p50"] == 1.5 and s["replicas_max"] == 2
    # The up action joined the snapshot the autoscaler saw (depth 9, util 1).
    tl = s["scale_timeline"]
    assert tl[0]["action"] == "up" and tl[0]["queue_depth"] == 9
    assert tl[1]["action"] == "down" and tl[1]["queue_depth"] == 0
    assert not s.get("unknown_events")    # "scale" is a known event kind
    rep.print_summary(s)
    out = capsys.readouterr().out
    assert "scale timeline: 1 up, 1 down" in out
    assert "replica 1 -> target 2 [autoscale]" in out
    # A-vs-B rows exist for the elasticity metrics.
    keys = [k for _, k in rep.COMPARE_ROWS]
    assert {"replicas_p50", "replicas_max", "scale_events"} <= set(keys)
    rep.print_comparison([s, s])
    out = capsys.readouterr().out
    assert "replicas p50" in out and "scale events" in out
