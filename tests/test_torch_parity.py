"""Cross-framework numerical parity: this framework vs a PyTorch realization of the
reference's exact model/loss/optimizer contract.

The strongest correctness oracle available: the reference's semantics (model architecture
``src/model.py:4-22``, ``F.nll_loss`` objective ``src/train.py:74``, ``torch.optim.SGD``
update ``src/train.py:60-61``) realized in torch (CPU) must produce the same numbers as this
framework's JAX realization — same forward log-probs, same loss, same gradients, same
parameter trajectory — once weights are mapped between layouts (NHWC/HWIO + H,W,C flatten
here vs torch's NCHW/OIHW + C,H,W flatten).

The torch module below is written fresh from the architecture spec in SURVEY.md §3.4 to
serve as the oracle; it is not the reference's source.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402
from torch import nn  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from csed_514_project_distributed_training_using_pytorch_tpu import ops  # noqa: E402
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net  # noqa: E402
from csed_514_project_distributed_training_using_pytorch_tpu.ops.optim import (  # noqa: E402
    sgd_init, sgd_update,
)


class TorchNet(nn.Module):
    """The reference architecture (SURVEY.md §3.4): conv(1→10,k5) → maxpool2 → relu →
    conv(10→20,k5) → Dropout2d → maxpool2 → relu → flatten(320) → fc(320→50) → relu →
    dropout → fc(50→10) → log_softmax."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.conv2_drop = nn.Dropout2d()
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2_drop(self.conv2(x)), 2))
        x = x.reshape(-1, 320)   # ≡ the reference's view(-1, 320); robust to strides
        x = F.relu(self.fc1(x))
        x = F.dropout(x, training=self.training)
        x = self.fc2(x)
        return F.log_softmax(x, dim=1)


def flax_to_torch(params) -> dict:
    """Map this framework's NHWC/HWIO params onto the torch module's NCHW/OIHW layout."""
    p = {k: np.asarray(v) for k, v in params.items()}
    fc1 = p["fc1_kernel"].reshape(4, 4, 20, 50)          # flatten order here is (H, W, C)
    fc1 = fc1.transpose(2, 0, 1, 3).reshape(320, 50)     # → torch's (C, H, W) order
    sd = {
        "conv1.weight": p["conv1_kernel"].transpose(3, 2, 0, 1),   # HWIO → OIHW
        "conv1.bias": p["conv1_bias"],
        "conv2.weight": p["conv2_kernel"].transpose(3, 2, 0, 1),
        "conv2.bias": p["conv2_bias"],
        "fc1.weight": fc1.T,                                        # [in,out] → [out,in]
        "fc1.bias": p["fc1_bias"],
        "fc2.weight": p["fc2_kernel"].T,
        "fc2.bias": p["fc2_bias"],
    }
    return {k: torch.tensor(v) for k, v in sd.items()}


def torch_grads_to_flax(tnet) -> dict:
    """Inverse mapping, applied to .grad tensors, for gradient comparison."""
    g = {k: v.grad.numpy() for k, v in tnet.named_parameters()}
    fc1 = g["fc1.weight"].T.reshape(20, 4, 4, 50).transpose(1, 2, 0, 3).reshape(320, 50)
    return {
        "conv1_kernel": g["conv1.weight"].transpose(2, 3, 1, 0),
        "conv1_bias": g["conv1.bias"],
        "conv2_kernel": g["conv2.weight"].transpose(2, 3, 1, 0),
        "conv2_bias": g["conv2.bias"],
        "fc1_kernel": fc1,
        "fc1_bias": g["fc1.bias"],
        "fc2_kernel": g["fc2.weight"].T,
        "fc2_bias": g["fc2.bias"],
    }


@pytest.fixture(scope="module")
def setup():
    net = Net()
    variables = net.init({"params": jax.random.PRNGKey(0)}, jnp.zeros((2, 28, 28, 1)))
    params = variables["params"]
    tnet = TorchNet()
    tnet.load_state_dict(flax_to_torch(params))
    tnet.eval()
    rng = np.random.default_rng(7)
    x = rng.normal(size=(16, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=16).astype(np.int64)
    return net, params, tnet, x, y


def test_forward_parity(setup):
    net, params, tnet, x, y = setup
    ours = np.asarray(net.apply({"params": params}, jnp.asarray(x)))
    with torch.no_grad():
        theirs = tnet(torch.tensor(x).permute(0, 3, 1, 2).contiguous()).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_loss_and_grad_parity(setup):
    net, params, tnet, x, y = setup

    def loss_fn(p):
        log_probs = net.apply({"params": p}, jnp.asarray(x))
        return ops.nll_loss(log_probs, jnp.asarray(y.astype(np.int32)))

    our_loss, our_grads = jax.value_and_grad(loss_fn)(params)

    tnet.zero_grad()
    tloss = F.nll_loss(tnet(torch.tensor(x).permute(0, 3, 1, 2).contiguous()), torch.tensor(y))
    tloss.backward()
    their_grads = torch_grads_to_flax(tnet)

    np.testing.assert_allclose(float(our_loss), float(tloss), atol=1e-6)
    assert set(their_grads) == set(our_grads)
    for k in our_grads:
        np.testing.assert_allclose(np.asarray(our_grads[k]), their_grads[k],
                                   atol=2e-6, err_msg=f"grad mismatch at {k}")


def test_sum_reduction_eval_metric_parity(setup):
    """The eval objective: the deprecated ``size_average=False`` sum form the reference uses
    (src/train.py:94) must match reduction='sum'."""
    net, params, tnet, x, y = setup
    ours = float(ops.nll_loss(net.apply({"params": params}, jnp.asarray(x)),
                              jnp.asarray(y.astype(np.int32)), reduction="sum"))
    with torch.no_grad():
        theirs = float(F.nll_loss(tnet(torch.tensor(x).permute(0, 3, 1, 2).contiguous()),
                                  torch.tensor(y), reduction="sum"))
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_sgd_momentum_trajectory_parity():
    """Three optimizer steps under identical synthetic gradients: torch.optim.SGD's
    momentum-buffer semantics (src/train.py:60-61) vs ops.optim.sgd_update."""
    rng = np.random.default_rng(3)
    p0 = rng.normal(size=(7, 5)).astype(np.float32)
    grads = [rng.normal(size=(7, 5)).astype(np.float32) for _ in range(3)]

    tp = torch.nn.Parameter(torch.tensor(p0.copy()))
    opt = torch.optim.SGD([tp], lr=0.01, momentum=0.5)
    for g in grads:
        opt.zero_grad()
        tp.grad = torch.tensor(g)
        opt.step()

    params = {"w": jnp.asarray(p0)}
    vel = sgd_init(params)
    for g in grads:
        params, vel = sgd_update(params, vel, {"w": jnp.asarray(g)},
                                 learning_rate=0.01, momentum=0.5)

    np.testing.assert_allclose(np.asarray(params["w"]), tp.detach().numpy(), atol=1e-6)
