"""Chunked batched prefill + prefix KV reuse: the serving admission fast path.

The contracts pinned here (tier-1, tiny models, deterministic seeds):

1. **Token identity** — chunked prefill (and the prefix-cache hit path on top of
   it) is a SCHEDULE change, not a math change: the engine's output is
   token-identical to sequential ``models.lm.generate`` and to the legacy
   prefill-as-decode path, across MHA/GQA/windowed/RoPE configs, mixed prompt
   lengths, recycled slots, and repeated prompts.
2. **Bounded compiles** — a length-P prompt prefills in ``ceil(P / chunk)``
   program invocations for a single configured chunk size; each size in the
   chunk set traces AT MOST once regardless of the prompt mix
   (``prefill_trace_counts``), the decode program still traces exactly once,
   and batched multi-request admission is one scatter program.
3. **Lifecycle** — mid-prefill ``expire`` frees the slot with the partial
   teacher-forced prompt as its stream; prefix-cache hit/miss/eviction behave
   as an LRU keyed by longest common token prefix.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
    ContinuousBatchingEngine,
    PrefixCache,
    Request,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (
    load_metrics_jsonl,
)

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)

SMALL = dict(vocab_size=9, seq_len=16, embed_dim=32, num_layers=2, num_heads=4)


def _model(**kw):
    return lm.TransformerLM(**{**SMALL, **kw})


def _params(model, seed=0):
    ids = jnp.zeros((1, model.seq_len), jnp.int32)
    return model.init({"params": jax.random.PRNGKey(seed)}, ids)["params"]


def _mixed_requests(model, n, seed=0):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(0, model.seq_len - 1))
        reqs.append(Request(
            prompt=rng.integers(0, model.vocab_size - 1,
                                size=plen).astype(np.int32),
            max_new_tokens=int(rng.integers(1, model.seq_len)),
            request_id=i))
    return reqs


def _sequential_reference(model, params, req):
    p = len(req.prompt)
    total = min(p + req.max_new_tokens, model.seq_len)
    padded = np.zeros((1, model.seq_len), np.int32)
    padded[0, :p] = req.prompt
    out = lm.generate(model, params, jax.random.PRNGKey(0), batch=1,
                      temperature=0.0, prompt=jnp.asarray(padded), prompt_len=p)
    return np.asarray(out)[0, :total]


# -----------------------------------------------------------------------------------------
# Token identity across model variants (chunked prefill + prefix reuse on)
# -----------------------------------------------------------------------------------------


@pytest.mark.parametrize("cfg", [
    dict(),                                  # MHA
    dict(num_kv_heads=2),                    # GQA (smaller K/V planes)
    dict(attention_window=5),                # sliding-window prefill mask
    dict(rope=True),                         # per-position rotary in the chunk
], ids=["mha", "gqa", "window", "rope"])
def test_chunked_prefill_token_identity_with_generate(cfg):
    """Acceptance: chunked prefill + prefix KV reuse through recycled slots is
    token-identical to sequential ``generate`` — and every chunk size compiled
    at most once, with the decode program still compiling exactly once."""
    model = _model(**cfg)
    params = _params(model)
    reqs = _mixed_requests(model, 6, seed=7)
    # Repeat request 0's prompt verbatim -> the second pass is a full prefix hit.
    reqs.append(Request(prompt=reqs[0].prompt, max_new_tokens=4, request_id=6))
    engine = ContinuousBatchingEngine(
        model, params, num_slots=2, prefill_chunk_sizes=(4, 8),
        prefix_cache_entries=4)
    comps = {c.request.request_id: c for c in engine.run(reqs)}
    assert engine.trace_count == 1
    assert engine.admit_trace_count == 1
    assert all(n == 1 for n in engine.prefill_trace_counts.values())
    assert set(engine.prefill_trace_counts) <= {4, 8}
    for req in reqs:
        ref = _sequential_reference(model, params, req)
        np.testing.assert_array_equal(comps[req.request_id].tokens, ref)
        np.testing.assert_array_equal(
            comps[req.request_id].tokens[:len(req.prompt)], req.prompt)


def test_chunked_matches_legacy_prefill_as_decode():
    """The A/B pin: prefill on vs off emit byte-identical streams."""
    model = _model()
    params = _params(model)
    reqs = _mixed_requests(model, 6, seed=11)
    on = ContinuousBatchingEngine(model, params, num_slots=3,
                                  prefill_chunk_sizes=(4,))
    off = ContinuousBatchingEngine(model, params, num_slots=3,
                                   prefill_chunk_sizes=())
    got_on = {c.request.request_id: c.tokens for c in on.run(list(reqs))}
    got_off = {c.request.request_id: c.tokens for c in off.run(list(reqs))}
    assert off.prefill_invocations == 0 and on.prefill_invocations > 0
    for rid in got_off:
        np.testing.assert_array_equal(got_on[rid], got_off[rid])


# -----------------------------------------------------------------------------------------
# Invocation counts: ceil(P/chunk), greedy multi-size plans
# -----------------------------------------------------------------------------------------


@pytest.mark.parametrize("p_len,chunk", [(12, 4), (13, 4), (15, 8), (1, 4)])
def test_prefill_invocation_count_is_ceil(p_len, chunk):
    model = _model()
    params = _params(model)
    engine = ContinuousBatchingEngine(model, params, num_slots=1,
                                      prefill_chunk_sizes=(chunk,))
    prompt = np.arange(p_len, dtype=np.int32) % (model.vocab_size - 1)
    comps = engine.run([Request(prompt=prompt, max_new_tokens=2)])
    assert comps[0].ok
    assert engine.prefill_invocations == -(-p_len // chunk)
    assert engine.prefill_tokens == p_len
    # The decode loop only ran the generated suffix: total decode steps == new
    # tokens, not prompt_len + new (that was the prefill-as-decode tax).
    assert engine.steps == comps[0].new_tokens


def test_plan_prefill_greedy_and_padded_tail():
    model = _model()
    engine = ContinuousBatchingEngine(model, _params(model), num_slots=1,
                                      prefill_chunk_sizes=(4, 8))
    assert engine.plan_prefill(0, 15) == [(0, 8, 8), (8, 4, 4), (12, 3, 4)]
    assert engine.plan_prefill(5, 9) == [(5, 4, 4)]
    assert engine.plan_prefill(0, 3) == [(0, 3, 4)]     # padded, writes dropped
    assert engine.plan_prefill(7, 7) == []
    # Clipping: sizes larger than seq_len collapse onto seq_len.
    clipped = ContinuousBatchingEngine(model, _params(model), num_slots=1,
                                       prefill_chunk_sizes=(32, 128, 512))
    assert clipped.prefill_chunk_sizes == (16,)


def test_prefill_interleaves_with_decode_under_chunk_budget():
    """A long prompt admitted next to an active decode never stalls it: each
    engine step runs at most ``prefill_chunk_budget`` chunks AND the decode
    step, so the decoding slot advances one token per step throughout."""
    model = _model()
    params = _params(model)
    engine = ContinuousBatchingEngine(model, params, num_slots=2,
                                      prefill_chunk_sizes=(2,),
                                      prefill_chunk_budget=1)
    engine.admit(0, Request(prompt=np.zeros(0, np.int32), max_new_tokens=10,
                            request_id=0))
    engine.admit(1, Request(prompt=np.ones(8, np.int32), max_new_tokens=2,
                            request_id=1))
    assert engine.num_prefilling == 1
    for i in range(4):                      # 4 chunks of 2 cover the 8-prompt
        engine.step()
    assert engine.num_prefilling == 0
    assert engine.steps == 4                # decode never skipped a beat
    comps = {c.request.request_id: c for c in engine.run([])}
    for rid, req in ((0, None), (1, None)):
        assert comps[rid].ok


# -----------------------------------------------------------------------------------------
# Mid-prefill expire + slot recycling
# -----------------------------------------------------------------------------------------


def test_mid_prefill_expire_frees_slot_with_partial_prompt():
    model = _model()
    params = _params(model)
    engine = ContinuousBatchingEngine(model, params, num_slots=1,
                                      prefill_chunk_sizes=(4,))
    req = Request(prompt=np.arange(12, dtype=np.int32) % 8, max_new_tokens=3,
                  request_id=0, deadline_s=1e9)
    engine.admit(0, req)
    engine.step()                           # one 4-token chunk lands
    assert engine.num_prefilling == 1
    [comp] = engine.expire(now=2e9)
    assert comp.finish == "timeout" and comp.new_tokens == 0
    np.testing.assert_array_equal(comp.tokens, req.prompt[:4])
    assert engine.num_prefilling == 0 and engine.free_slots() == [0]
    # The recycled slot serves the next request bit-identically to a fresh one.
    follow = Request(prompt=np.asarray([3, 1, 4], np.int32), max_new_tokens=5,
                     request_id=1)
    got = engine.run([follow])[0]
    np.testing.assert_array_equal(
        got.tokens, _sequential_reference(model, params, follow))


# -----------------------------------------------------------------------------------------
# Prefix cache: hit / partial hit / miss / eviction
# -----------------------------------------------------------------------------------------


def test_prefix_cache_unit_lru_and_longest_prefix():
    cache = PrefixCache(capacity=2)
    a = np.asarray([1, 2, 3, 4], np.int32)
    cache.insert(a, {"planes": "A"})
    hit, planes = cache.lookup(np.asarray([1, 2, 3, 4, 5, 6], np.int32))
    assert hit == 4 and planes == {"planes": "A"}
    hit, _ = cache.lookup(np.asarray([1, 2, 9], np.int32))
    assert hit == 2                               # partial common prefix
    assert cache.lookup(np.asarray([7, 8], np.int32)) == (0, None)
    # Insertion covering an existing entry replaces it (same token prefix).
    cache.insert(np.asarray([1, 2, 3, 4, 5], np.int32), {"planes": "A+"})
    assert len(cache) == 1
    cache.insert(np.asarray([9, 9], np.int32), {"planes": "B"})
    cache.insert(np.asarray([8, 8], np.int32), {"planes": "C"})  # evicts LRU
    assert len(cache) == 2 and cache.evictions == 1
    assert cache.lookup(np.asarray([1, 2, 3], np.int32)) == (0, None)  # evicted
    with pytest.raises(ValueError, match="capacity"):
        PrefixCache(0)


def test_engine_prefix_hit_partial_hit_and_eviction():
    model = _model()
    params = _params(model)
    engine = ContinuousBatchingEngine(model, params, num_slots=1,
                                      prefill_chunk_sizes=(4,),
                                      prefix_cache_entries=1)
    base = np.asarray([1, 2, 3, 4, 5, 6, 7, 0], np.int32)
    r0 = Request(prompt=base, max_new_tokens=3, request_id=0)
    r1 = Request(prompt=base, max_new_tokens=3, request_id=1)       # full hit
    ext = np.concatenate([base, np.asarray([2, 4], np.int32)])
    r2 = Request(prompt=ext, max_new_tokens=3, request_id=2)        # partial hit
    other = np.asarray([5, 5, 5, 5], np.int32)
    r3 = Request(prompt=other, max_new_tokens=3, request_id=3)      # miss+evict
    r4 = Request(prompt=base, max_new_tokens=3, request_id=4)       # miss again
    comps = {c.request.request_id: c for c in engine.run([r0, r1, r2, r3, r4])}
    recs = {r["request_id"]: r for r in engine.take_prefill_records()}
    assert recs[0]["cache_hit_len"] == 0 and recs[0]["chunks"] == 2
    assert recs[1]["cache_hit_len"] == 8 and recs[1]["chunks"] == 0
    assert recs[2]["cache_hit_len"] == 8 and recs[2]["tokens"] == 2
    assert recs[3]["cache_hit_len"] == 0
    assert recs[4]["cache_hit_len"] == 0          # r0's entry was evicted by r3
    assert engine.prefix_cache.evictions >= 1
    for req in (r0, r1, r2, r3, r4):
        np.testing.assert_array_equal(
            comps[req.request_id].tokens,
            _sequential_reference(model, params, req))


def test_prefix_cache_requires_prefill_path():
    model = _model()
    with pytest.raises(ValueError, match="prefix cache"):
        ContinuousBatchingEngine(model, _params(model), num_slots=1,
                                 prefill_chunk_sizes=(),
                                 prefix_cache_entries=2)


# -----------------------------------------------------------------------------------------
# Batched admission: one scatter program for any admission count
# -----------------------------------------------------------------------------------------


def test_admit_many_single_scatter_program_and_occupancy_checks():
    model = _model()
    params = _params(model)
    engine = ContinuousBatchingEngine(model, params, num_slots=4)
    reqs = _mixed_requests(model, 4, seed=3)
    engine.admit_many(list(zip([0, 1, 2], reqs[:3])))
    assert engine.admit_trace_count == 1
    engine.admit_many([(3, reqs[3])])             # different count, same program
    assert engine.admit_trace_count == 1
    with pytest.raises(ValueError, match="occupied"):
        engine.admit_many([(0, _mixed_requests(model, 1, seed=9)[0])])
    comps = engine.run([])
    assert len(comps) == 4 and all(c.ok for c in comps)
    for req in reqs:
        got = next(c for c in comps if c.request.request_id == req.request_id)
        np.testing.assert_array_equal(
            got.tokens, _sequential_reference(model, params, req))


# -----------------------------------------------------------------------------------------
# Telemetry + loadgen: the long-prompt benchmark path end to end
# -----------------------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_long_prompt_dist_with_prefix_cache(tmp_path, capsys):
    """Acceptance walkthrough: a long-prompt loadgen run with prefill + prefix
    cache emits "prefill" telemetry the report CLI renders, prints prefill-token
    throughput, and writes the summary-JSON artifact with TTFT percentiles."""
    loadgen = _load_tool("serve_loadgen")
    report = _load_tool("telemetry_report")
    path = str(tmp_path / "serve.jsonl")
    summary = str(tmp_path / "summary.json")
    rc = loadgen.main([
        "--requests", "6", "--mode", "closed", "--concurrency", "2",
        "--num-slots", "2", "--seq-len", "16", "--embed-dim", "16",
        "--num-layers", "1", "--num-heads", "2", "--num-levels", "8",
        "--max-new-tokens", "4", "--seed", "0",
        "--prompt-dist", "long", "--shared-prefix-len", "6",
        "--prefill-chunks", "4", "--prefix-cache", "4",
        "--telemetry", path, "--summary-json", summary])
    assert rc == 0
    out = capsys.readouterr().out
    assert "6 completed (6 ok" in out and "decode compilations 1" in out
    assert "prefilled" in out and "prefix hits" in out
    rows = load_metrics_jsonl(path)
    prefill = [r for r in rows if r["event"] == "prefill"]
    assert len(prefill) == 6
    assert all(r["chunks"] >= 0 and r["prompt_len"] >= 8 for r in prefill)
    assert any(r["cache_hit_len"] > 0 for r in prefill)
    smry = [r for r in rows if r["event"] == "serve_summary"][0]
    assert smry["prefill_tokens"] > 0 and smry["prefix_cache"]["queries"] == 6
    doc = json.load(open(summary))
    assert doc["prefill_chunk_sizes"] == [4]
    assert doc["prefill_tokens"] > 0 and doc["ttft_s"]["p50"] >= 0
    assert doc["prefill_compilations"] == {"4": 1}
    rc = report.main([path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "prefill:" in out and "prefix hits" in out


def test_loadgen_legacy_prefill_off_still_runs(tmp_path, capsys):
    loadgen = _load_tool("serve_loadgen")
    rc = loadgen.main([
        "--requests", "4", "--mode", "closed", "--concurrency", "2",
        "--num-slots", "2", "--seq-len", "16", "--embed-dim", "16",
        "--num-layers", "1", "--num-heads", "2", "--num-levels", "8",
        "--max-new-tokens", "4", "--seed", "0", "--prompt-lens", "0,6,10",
        "--prefill-chunks", ""])
    assert rc == 0
    out = capsys.readouterr().out
    assert "prefilled 0 prompt tokens in 0 chunks" in out
