"""bench.py parent-loop contract (r1 verdict item 1: the round's perf artifact must
survive transient backend failures). The child measurement is faked at the
``_run_child`` seam so every branch — retry, success, labeled CPU fallback, structured
final error — is pinned without real TPU (or even real child) processes."""

import importlib.util
import json
import os
import time
import types

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # Replace bench's module-local `time` (not the process-global stdlib module) so
    # backoff sleeps vanish without affecting other threads in the test process.
    monkeypatch.setattr(mod, "time", types.SimpleNamespace(
        sleep=lambda s: None, monotonic=time.monotonic))
    # Budget large enough that a CI-VM pause between attempts can't flip the control
    # flow into the fallback path (sleeps are no-ops, so tests never actually wait);
    # zero-budget tests override this.
    monkeypatch.setenv("BENCH_TPU_RETRY_SECONDS", "100000")
    monkeypatch.setenv("BENCH_ATTEMPT_TIMEOUT_SECONDS", "60")
    return mod


def _scripted(monkeypatch, bench, script):
    """Replace _run_child with a scripted sequence; record each call's env overrides."""
    calls = []

    def fake(env_overrides, timeout_s):
        calls.append(env_overrides)
        return script.pop(0)

    monkeypatch.setattr(bench, "_run_child", fake)
    return calls


def test_transient_failure_then_success(bench, monkeypatch, capsys):
    """The exact r1 failure (one UNAVAILABLE init error) must cost one retry, not the
    round's perf number."""
    good = json.dumps({"metric": "m", "value": 1.5, "unit": "s"})
    _scripted(monkeypatch, bench, [
        (1, "", "RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE"),
        (0, good + "\n", ""),
    ])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 1.5 and payload["attempts"] == 2
    assert "fallback_reason" not in payload


def test_timeout_counts_as_failure_then_fallback(bench, monkeypatch, capsys):
    """A hung child (rc=None) burns the budget; the CPU fallback must then run with
    JAX_PLATFORMS=cpu and without the TPU-plugin sitecustomize on PYTHONPATH, and its
    result must be labeled with the TPU failure."""
    monkeypatch.setenv("BENCH_TPU_RETRY_SECONDS", "0")       # one attempt, then fallback
    monkeypatch.setenv("PYTHONPATH", "/keep/me:/root/.axon_site/x")
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    calls = _scripted(monkeypatch, bench, [
        (None, "", ""),                                      # hung attempt
        (0, good + "\n", ""),                                # CPU fallback child
    ])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 9.0
    assert "timed out" in payload["fallback_reason"]
    assert calls[0] == {}                                    # attempt: inherit env
    assert calls[1]["JAX_PLATFORMS"] == "cpu"
    assert "/keep/me" in calls[1]["PYTHONPATH"]
    assert "axon_site" not in calls[1]["PYTHONPATH"]


def test_total_failure_emits_structured_error(bench, monkeypatch, capsys):
    """Even with every child dead, stdout must carry ONE parseable JSON line (r1:
    BENCH_r01.json was a stack trace with rc=1 and nothing parseable)."""
    monkeypatch.setenv("BENCH_TPU_RETRY_SECONDS", "0")
    _scripted(monkeypatch, bench, [
        (1, "", "boom"),
        (1, "", "cpu fallback also broken"),
    ])
    assert bench.main() == 1
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] is None and payload["error"]
    assert payload["cpu_fallback_error"] == ["cpu fallback also broken"]


def test_unparseable_child_stdout_is_retried(bench, monkeypatch, capsys):
    """rc=0 with garbage stdout (a child that printed warnings over the JSON) must not
    be accepted as a measurement."""
    good = json.dumps({"metric": "m", "value": 2.0, "unit": "s"})
    _scripted(monkeypatch, bench, [
        (0, "not json at all\n", ""),
        (0, "some warning line\n" + good + "\n", ""),        # JSON on the LAST line: ok
    ])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 2.0 and payload["attempts"] == 2
