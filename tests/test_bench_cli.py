"""bench.py parent-loop contract (r1 verdict item 1: the round's perf artifact must
survive transient backend failures; r2 item 1: probe-first attempts + embedded hardware
capture). The child measurement is faked at the ``_run_child``/``_probe_chip`` seams so
every branch — probe gating, retry, success, labeled CPU fallback, structured final
error — is pinned without real TPU (or even real child) processes."""

import importlib.util
import json
import os
import sys
import time
import types

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # Replace bench's module-local `time` (not the process-global stdlib module) so
    # backoff sleeps vanish without affecting other threads in the test process.
    monkeypatch.setattr(mod, "time", types.SimpleNamespace(
        sleep=lambda s: None, monotonic=time.monotonic))
    # Budget large enough that a CI-VM pause between attempts can't flip the control
    # flow into the fallback path (sleeps are no-ops, so tests never actually wait).
    monkeypatch.setenv("BENCH_TPU_RETRY_SECONDS", "100000")
    monkeypatch.setenv("BENCH_ATTEMPT_TIMEOUT_SECONDS", "60")
    return mod


def _chip_alive(monkeypatch, bench):
    monkeypatch.setattr(bench, "_probe_chip", lambda t: ("tpu", "tpu x1"))


def _scripted(monkeypatch, bench, script):
    """Replace _run_child with a scripted sequence; record each call's env overrides.
    A scripted rc=None also marks the child abandoned, mirroring the real
    grace-expired path."""
    calls = []

    def fake(env_overrides, timeout_s, argv=None):
        calls.append(env_overrides)
        rc, out, err = script.pop(0)
        if rc is None:
            bench._ABANDONED.append(object())
        return rc, out, err

    monkeypatch.setattr(bench, "_run_child", fake)
    return calls


def test_transient_failure_then_success(bench, monkeypatch, capsys):
    """The exact r1 failure (one UNAVAILABLE init error) must cost one retry, not the
    round's perf number."""
    _chip_alive(monkeypatch, bench)
    good = json.dumps({"metric": "m", "value": 1.5, "unit": "s"})
    _scripted(monkeypatch, bench, [
        (1, "", "RuntimeError: Unable to initialize backend 'axon': UNAVAILABLE"),
        (0, good + "\n", ""),
    ])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 1.5 and payload["attempts"] == 2
    assert payload["probes"] == 2                 # one probe gated each attempt
    assert "fallback_reason" not in payload


def test_bench_emits_typed_telemetry_event(bench, monkeypatch, capsys, tmp_path):
    """The bench artifact is one `"event": "bench"` line in the utils/telemetry.py
    schema, and --telemetry PATH appends the same line to a JSONL file so
    tools/telemetry_report.py can compare bench runs against training runs."""
    _chip_alive(monkeypatch, bench)
    good = json.dumps({"metric": "m", "value": 1.5, "unit": "s"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])
    tele = tmp_path / "tele.jsonl"
    monkeypatch.setattr(sys, "argv", ["bench.py", "--telemetry", str(tele)])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["event"] == "bench" and payload["value"] == 1.5
    rows = [json.loads(l) for l in open(tele)]
    assert rows == [payload]


def test_hung_attempt_goes_straight_to_fallback(bench, monkeypatch, capsys):
    """A hung measurement child is abandoned still holding (or queued on) the exclusive
    TPU claim, so no further probe can succeed — the loop must skip the rest of the
    budget and run the CPU fallback (labeled, clean env) immediately."""
    monkeypatch.setenv("PYTHONPATH", "/keep/me:/root/.axon_site/x")
    _chip_alive(monkeypatch, bench)
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    calls = _scripted(monkeypatch, bench, [
        (None, "", ""),                                      # hung attempt → abandoned
        (0, good + "\n", ""),                                # CPU fallback child
    ])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 9.0
    assert "timed out" in payload["fallback_reason"]
    assert calls[0] == {}                                    # attempt: inherit env
    assert calls[1]["JAX_PLATFORMS"] == "cpu"
    assert "/keep/me" in calls[1]["PYTHONPATH"]
    assert "axon_site" not in calls[1]["PYTHONPATH"]


def test_non_tpu_backend_skips_retries_and_embeds_capture(bench, monkeypatch, capsys):
    """A probe that reaches a healthy non-TPU backend is a deterministic condition:
    ONE probe, zero attempts, straight to the labeled fallback — and the fallback
    payload must embed the newest committed hardware capture (r2 verdict item 1c)."""
    monkeypatch.setattr(bench, "_probe_chip",
                        lambda t: ("other", "backend is 'cpu', not tpu"))
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])    # only the fallback runs
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["probes"] == 1 and payload["attempts"] == 0
    assert "not tpu" in payload["fallback_reason"]
    capture = payload["last_hardware_capture"]               # real committed artifact
    assert capture["payload"]["platform"] == "tpu"
    assert capture["file"].startswith("bench_results/")


def test_total_failure_emits_structured_error(bench, monkeypatch, capsys):
    """Even with every child dead, stdout must carry ONE parseable JSON line (r1:
    BENCH_r01.json was a stack trace with rc=1 and nothing parseable)."""
    monkeypatch.setattr(bench, "_probe_chip",
                        lambda t: ("other", "backend is 'cpu', not tpu"))
    _scripted(monkeypatch, bench, [
        (1, "", "cpu fallback also broken"),
    ])
    assert bench.main() == 1
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] is None and payload["error"]
    assert payload["cpu_fallback_error"] == ["cpu fallback also broken"]


def test_unparseable_child_stdout_is_retried(bench, monkeypatch, capsys):
    """rc=0 with garbage stdout (a child that printed warnings over the JSON) must not
    be accepted as a measurement."""
    _chip_alive(monkeypatch, bench)
    good = json.dumps({"metric": "m", "value": 2.0, "unit": "s"})
    _scripted(monkeypatch, bench, [
        (0, "not json at all\n", ""),
        (0, "some warning line\n" + good + "\n", ""),        # JSON on the LAST line: ok
    ])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 2.0 and payload["attempts"] == 2


def test_wedged_probe_burns_probes_not_attempts(bench, monkeypatch, capsys):
    """A wedged chip claim (probe timeouts) must never commit a measurement attempt;
    on budget exhaustion the fallback runs with the probe failure as the reason."""
    monkeypatch.setenv("BENCH_TPU_RETRY_SECONDS", "0.2")     # a few real-clock probes
    monkeypatch.setattr(
        bench, "_probe_chip",
        lambda t: ("timeout", "probe timed out after 90s (claim likely wedged)"))
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["attempts"] == 0 and payload["probes"] >= 1
    assert "wedged" in payload["fallback_reason"]


def test_wedge_signature_triggers_one_patient_probe(bench, monkeypatch, capsys):
    """r4: 9/9 quick probes timed out against a stale claim. After two consecutive
    probe timeouts the loop must queue ONE patient probe spanning (nearly) the whole
    remaining budget, then — if that too times out — go straight to the fallback
    instead of cycling more quick probes."""
    deadlines = []

    def fake_probe(t):
        deadlines.append(t)
        return "timeout", f"probe timed out after {t:.0f}s (claim likely wedged)"

    monkeypatch.setattr(bench, "_probe_chip", fake_probe)
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])    # only the fallback runs
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["attempts"] == 0 and payload["probes"] == 3
    assert deadlines[0] <= 90 and deadlines[1] <= 90
    assert deadlines[2] > 10_000                  # patient: budget minus the reserve
    assert payload["probe_log"] == [[round(t), "timeout"] for t in deadlines]


def test_patient_probe_win_still_measures(bench, monkeypatch, capsys):
    """A stale lease that expires mid-round is caught by the queued patient probe,
    and the measurement attempt must still run with the remaining budget."""
    script = iter([
        ("timeout", "probe timed out after 90s (claim likely wedged)"),
        ("timeout", "probe timed out after 90s (claim likely wedged)"),
        ("tpu", "tpu x1"),                        # the patient claimant wins
    ])
    deadlines = []

    def fake_probe(t):
        deadlines.append(t)
        return next(script)

    monkeypatch.setattr(bench, "_probe_chip", fake_probe)
    good = json.dumps({"metric": "m", "value": 0.19, "unit": "s", "platform": "tpu"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["value"] == 0.19 and payload["attempts"] == 1
    assert payload["probes"] == 3 and deadlines[2] > 10_000
    assert "fallback_reason" not in payload
    # The patient-win artifact must carry the diagnostic sequence too.
    assert payload["probe_log"] == [[round(t), s] for t, s in
                                    zip(deadlines, ["timeout", "timeout", "tpu"])]


def test_fast_failing_patient_probe_keeps_patience_available(bench, monkeypatch,
                                                             capsys):
    """A patient probe that FAILS FAST means the claim answered — the lease isn't
    stale — so the wedge signature resets and a genuine wedge later in the budget
    must still earn a fresh patient probe."""
    script = iter([
        ("timeout", "probe timed out after 90s (claim likely wedged)"),
        ("timeout", "probe timed out after 90s (claim likely wedged)"),
        ("retry", "RuntimeError: UNAVAILABLE: transient init error"),   # patient, fast
        ("timeout", "probe timed out after 90s (claim likely wedged)"),
        ("timeout", "probe timed out after 90s (claim likely wedged)"),
        ("timeout", "probe timed out after 3000s (claim likely wedged)"),  # patient #2
    ])
    deadlines = []

    def fake_probe(t):
        deadlines.append(t)
        return next(script)

    monkeypatch.setattr(bench, "_probe_chip", fake_probe)
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])    # only the fallback runs
    assert bench.main() == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["probes"] == 6 and payload["attempts"] == 0
    assert deadlines[2] > 10_000 and deadlines[5] > 10_000   # both patient probes
    assert all(t <= 90 for i, t in enumerate(deadlines) if i not in (2, 5))


def test_quick_probe_errors_do_not_trip_the_wedge_signature(bench, monkeypatch):
    """Probes that FAIL FAST (rc!=0, not a timeout) are transient init errors, not the
    stale-lease signature — they must keep ordinary quick-probe cadence."""
    monkeypatch.setenv("BENCH_TPU_RETRY_SECONDS", "0.2")
    deadlines = []

    def fake_probe(t):
        deadlines.append(t)
        return "retry", "RuntimeError: UNAVAILABLE: transient init error"

    monkeypatch.setattr(bench, "_probe_chip", fake_probe)
    good = json.dumps({"metric": "m", "value": 9.0, "unit": "s", "platform": "cpu"})
    _scripted(monkeypatch, bench, [(0, good + "\n", "")])
    assert bench.main() == 0
    assert all(t <= 90 for t in deadlines)        # never escalated to patient


def test_latest_hardware_capture_prefers_highest_round_best(bench):
    cap = bench._latest_hardware_capture()
    assert cap is not None
    # Highest round wins across both naming layouts (bench_r*_tpu*.json and
    # hw_r*/bench_defaults*.json); the selected payload is a real TPU capture.
    # Glob anchored at bench.py's own directory, as the function under test is —
    # a cwd-relative glob made this fail confusingly when pytest ran from outside
    # the repo root (r4 advisor finding).
    import glob as globmod
    import re

    root = os.path.join(os.path.dirname(_BENCH_PATH), "bench_results")
    # Regex on the bench_results-RELATIVE path, exactly as the function under test
    # ranks — a checkout path that itself contains 'hw_rN' must not corrupt this.
    rounds = [int(m.group(1)) for m in
              (re.search(r"(?:bench|hw)_r(\d+)", os.path.relpath(f, root)) for f in
               globmod.glob(os.path.join(root, "bench_r*_tpu*.json"))
               + globmod.glob(os.path.join(root, "hw_r*", "bench_defaults*.json")))
              if m]
    m = re.search(r"(?:bench|hw)_r(\d+)", cap["file"])
    assert m and int(m.group(1)) == max(rounds)
    assert cap["payload"]["platform"] == "tpu"


def test_bench_attention_row_schema(monkeypatch, capsys, tmp_path):
    """The attention bench's row contract (r4 verdict item 2): roofline fields
    per impl, causal-aware model FLOPs, converged flags, speedup — pinned with
    the measurement faked so the schema test costs milliseconds."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_attention_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "bench_attention.py"))
    ba = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ba)

    monkeypatch.setattr(ba, "_measure", lambda fn, q, k, v: (0.5, True))
    monkeypatch.setattr(sys, "argv",
                        ["bench_attention.py", "--seq-lens", "256",
                         "--out", str(tmp_path / "rows.jsonl")])
    assert ba.main() == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    s = 256
    pairs = s * (s + 1) // 2                      # causal attended pairs
    assert row["fwdbwd_model_flops"] == 3 * 4 * ba.B * ba.H * ba.D * pairs
    assert row["flash_fwdbwd_s"] == 0.5 and row["dense_fwdbwd_s"] == 0.5
    assert row["flash_converged"] is True and row["dense_converged"] is True
    assert row["flash_achieved_flops_per_s"] == round(
        row["fwdbwd_model_flops"] / 0.5)
    assert row["dense_achieved_flops_per_s"] == row["flash_achieved_flops_per_s"]
    # CPU run: no bf16 peak — explicit nulls, not missing keys.
    assert row["flash_pct_of_bf16_peak"] is None
    assert row["dense_pct_of_bf16_peak"] is None
    assert row["speedup_flash_vs_dense"] == 1.0
    assert (tmp_path / "rows.jsonl").exists()


def test_bench_attention_windowed_flops_accounting():
    """_attended_pairs: the causal+window closed form equals brute-force counting."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_attention_under_test2",
        os.path.join(os.path.dirname(__file__), os.pardir, "bench_attention.py"))
    ba = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ba)

    import numpy as np
    for s, w in ((8, None), (8, 3), (16, 16), (16, 40), (5, 1)):
        q = np.arange(s)[:, None]
        k = np.arange(s)[None, :]
        visible = (q >= k) & ((q - k) < (w or s))
        assert ba._attended_pairs(s, w) == int(visible.sum()), (s, w)
