"""Fused whole-step Pallas kernel (ops/pallas_fused.py) vs the framework's own autodiff:
every weight gradient, the loss, and a full SGD step must match the flax-model path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.ops import pallas_fused as pf
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state, make_train_step,
)

B = 32


@pytest.fixture(scope="module")
def setup():
    state = create_train_state(Net(), jax.random.PRNGKey(0))
    k = jax.random.PRNGKey(9)
    x = jax.random.normal(k, (B, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(10), (B,), 0, 10)
    return state, x, y


def masked_model_loss(params, x, y, drop2, drop1):
    """The model's math with explicit dropout-scale masks, built from the framework's own
    audited ops and differentiated by jax AD — the independent oracle for the kernel."""
    z1 = ops.conv2d(x, params["conv1_kernel"], params["conv1_bias"])
    a1 = ops.relu(ops.max_pool2d(z1, 2))
    z2 = ops.conv2d(a1, params["conv2_kernel"], params["conv2_bias"])
    zd2 = z2 * drop2[:, None, None, :]
    a2 = ops.relu(ops.max_pool2d(zd2, 2))
    f = a2.reshape(a2.shape[0], -1)
    a3 = ops.relu(ops.dense(f, params["fc1_kernel"], params["fc1_bias"]))
    z4 = ops.dense(a3 * drop1, params["fc2_kernel"], params["fc2_bias"])
    return ops.nll_loss(ops.log_softmax(z4), y)


@pytest.mark.parametrize("dropout", [False, True])
def test_loss_and_grads_match_autodiff(setup, dropout):
    state, x, y = setup
    if dropout:
        drop2 = (jax.random.bernoulli(jax.random.PRNGKey(3), 0.5, (B, pf.C2))
                 .astype(jnp.float32) * 2.0)
        drop1 = (jax.random.bernoulli(jax.random.PRNGKey(4), 0.5, (B, pf.F_HID))
                 .astype(jnp.float32) * 2.0)
    else:
        drop2 = jnp.ones((B, pf.C2))
        drop1 = jnp.ones((B, pf.F_HID))

    want_loss, want_grads = jax.value_and_grad(masked_model_loss)(
        state.params, x, y, drop2, drop1)
    got_loss, got = pf.fused_loss_and_grads(
        pf.flatten_params(state.params), x, y, drop2, drop1)
    got_grads = pf.unflatten_grads(got)

    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-5)
    assert set(got_grads) == set(want_grads)
    for k in want_grads:
        np.testing.assert_allclose(np.asarray(got_grads[k]), np.asarray(want_grads[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=f"grad mismatch: {k}")


def test_deterministic_forward_matches_flax_model(setup):
    """With all-ones masks the kernel's objective must equal the real flax model's
    (deterministic) nll — the end-to-end architecture check."""
    state, x, y = setup
    model = Net()
    log_probs = model.apply({"params": state.params}, x)
    want = float(ops.nll_loss(log_probs, y))
    got, _ = pf.fused_loss_and_grads(
        pf.flatten_params(state.params), x, y,
        jnp.ones((B, pf.C2)), jnp.ones((B, pf.F_HID)))
    np.testing.assert_allclose(float(got), want, rtol=1e-5)


def test_full_step_matches_unfused_with_dropout_off(setup):
    """One complete optimizer step, fused kernel vs the standard path, with dropout rates 0
    (so both paths see identical math regardless of mask RNG): same new params/velocity."""
    state, x, y = setup
    model = Net(conv_dropout_rate=0.0, fc_dropout_rate=0.0)
    unfused = make_train_step(model, learning_rate=0.01, momentum=0.5)
    fused = pf.make_fused_train_step(learning_rate=0.01, momentum=0.5,
                                     conv_dropout_rate=0.0, fc_dropout_rate=0.0)
    rng = jax.random.PRNGKey(7)
    s_a, loss_a = unfused(state, x, y, rng)
    s_b, loss_b = fused(state, x, y, rng)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    assert int(s_a.step) == int(s_b.step) == 1
    for (ka, a), (kb, bv) in zip(sorted(s_a.params.items()), sorted(s_b.params.items())):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(bv), rtol=1e-4, atol=1e-6,
                                   err_msg=f"param mismatch after step: {ka}")
    for (ka, a), (kb, bv) in zip(sorted(s_a.velocity.items()),
                                 sorted(s_b.velocity.items())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bv), rtol=1e-4, atol=1e-6,
                                   err_msg=f"velocity mismatch after step: {ka}")


@pytest.mark.slow
def test_batch_block_independence(setup):
    """Grid accumulation: results must not depend on the batch-block size."""
    state, x, y = setup
    flat = pf.flatten_params(state.params)
    ones2, ones1 = jnp.ones((B, pf.C2)), jnp.ones((B, pf.F_HID))
    l8, g8 = pf.fused_loss_and_grads(flat, x, y, ones2, ones1, batch_block=8)
    l32, g32 = pf.fused_loss_and_grads(flat, x, y, ones2, ones1, batch_block=32)
    np.testing.assert_allclose(float(l8), float(l32), rtol=1e-6)
    for a, bv in zip(g8, g32):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bv), rtol=1e-5, atol=1e-7)


def test_indivisible_batch_rejected(setup):
    """An explicit batch_block that does not divide the batch must raise, per the
    documented contract — never silently clamp (r1 verdict: the old min() clamp meant
    this contract could not fire)."""
    state, x, y = setup
    flat = pf.flatten_params(state.params)
    with pytest.raises(ValueError, match="not divisible"):
        pf.fused_loss_and_grads(flat, x[:30], y[:30],
                                jnp.ones((30, pf.C2)), jnp.ones((30, pf.F_HID)),
                                batch_block=16)
    # batch_block=None auto-picks a dividing block: any batch size must work.
    loss, _ = pf.fused_loss_and_grads(flat, x[:30], y[:30],
                                      jnp.ones((30, pf.C2)), jnp.ones((30, pf.F_HID)))
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_epoch_trajectory_pinned_to_unfused(setup):
    """One full scanned epoch (16 steps), fused kernel vs the standard flax/XLA path, with
    dropout rates 0 so both see identical math: every parameter and the velocity must track
    step-for-step.  This is the end-to-end wiring oracle — a mis-wired fused trainer
    diverges immediately even when single-step micro-tests pass."""
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        make_epoch_from_step,
    )

    state, _, _ = setup
    n, batch = 256, 16
    x = jax.random.normal(jax.random.PRNGKey(20), (n, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(21), (n,), 0, 10)
    idx = jnp.arange(n, dtype=jnp.int32).reshape(n // batch, batch)
    rng = jax.random.PRNGKey(7)

    unfused_step = make_train_step(Net(conv_dropout_rate=0.0, fc_dropout_rate=0.0),
                                   learning_rate=0.05, momentum=0.5)
    fused_step = pf.make_fused_train_step(learning_rate=0.05, momentum=0.5,
                                          conv_dropout_rate=0.0, fc_dropout_rate=0.0)
    s_a, losses_a = jax.jit(make_epoch_from_step(unfused_step))(state, x, y, idx, rng)
    s_b, losses_b = jax.jit(make_epoch_from_step(fused_step))(state, x, y, idx, rng)

    np.testing.assert_allclose(np.asarray(losses_a), np.asarray(losses_b),
                               rtol=1e-4, atol=1e-6)
    assert int(s_a.step) == int(s_b.step) == idx.shape[0]
    for k in s_a.params:
        np.testing.assert_allclose(np.asarray(s_a.params[k]), np.asarray(s_b.params[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=f"param diverged: {k}")
        np.testing.assert_allclose(np.asarray(s_a.velocity[k]),
                                   np.asarray(s_b.velocity[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=f"velocity: {k}")


@pytest.mark.slow
def test_trainer_with_fused_step_trains(tmp_path):
    """End-to-end single trainer with --experimental-fused-step: the whole-model kernel drives real
    epochs and the loss drops on a learnable task.  Settings (lr=0.1, 4 epochs) are chosen
    so the UNFUSED trainer also clears the same threshold under dropout — r1's version
    failed on settings where neither path learned fast enough, which said nothing about
    the kernel."""
    from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
        Dataset, _normalize, _synthesize_split,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train import single
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
        SingleProcessConfig,
    )

    xs, ys = _synthesize_split(1024, seed=30)
    train = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    xs, ys = _synthesize_split(200, seed=31)
    test = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")

    cfg = SingleProcessConfig(
        n_epochs=4, batch_size_train=64, batch_size_test=100,
        learning_rate=0.1, log_interval=8, experimental_fused_step=True,
        results_dir=str(tmp_path / "results"), images_dir=str(tmp_path / "images"))
    state, history = single.main(cfg, datasets=(train, test))
    assert int(state.step) == 4 * 16
    assert history.test_losses[-1] < history.test_losses[0] - 0.3


@pytest.mark.slow
def test_compile_probe_and_fallback(monkeypatch):
    """The probe must pass on every backend where the suite runs (interpret mode off-TPU,
    Mosaic on TPU), and the fallback path must produce a working unfused step when the
    probe reports failure on a TPU backend (the only place the probe runs — in interpret
    mode it proves nothing this suite doesn't already)."""
    assert pf.probe_compiles(batch=4) is None

    # Force the failure branch (pretend we're on TPU with a probe that fails) and confirm
    # the returned step still trains.
    monkeypatch.setattr(pf.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(pf, "probe_compiles", lambda batch=4: RuntimeError("forced"))
    try:
        with pytest.warns(RuntimeWarning, match="falling back"):
            step = pf.make_fused_train_step(learning_rate=0.05, momentum=0.5,
                                            fallback_on_compile_error=True)
    finally:
        monkeypatch.undo()
    state = create_train_state(Net(), jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
    new_state, loss = jax.jit(step)(state, x, y, jax.random.PRNGKey(3))
    assert np.isfinite(float(loss)) and int(new_state.step) == 1


@pytest.mark.skipif(pf._configured_platform() != "cpu",
                    reason="exercises the explicit-CPU fast path; under hardware mode "
                           "the platform is deliberately unpinned")
def test_subprocess_probe_skips_on_explicit_cpu_platform():
    """With the platform explicitly configured to CPU (this suite's conftest), the probe
    must answer 'nothing Mosaic to probe' without even spawning the child — and the
    parent must not fall back (interpret mode is the tested path off the chip). Named
    without the accelerator substring so the hardware-mode filter `-k` on that substring
    never selects it (on a chip this probe would really compile, for minutes)."""
    assert pf.probe_compiles_subprocess((4,), timeout_s=120.0) is None


def test_subprocess_probe_spawns_child_when_platform_unconfigured(monkeypatch):
    """When no platform is pinned, the verdict must come from the child interpreter
    (which decides backend applicability itself). Forcing the platform string empty here
    drives the child path on CPU: the child sees default_backend()=='cpu' and reports
    'nothing to probe'."""
    monkeypatch.setattr(pf, "_configured_platform", lambda: "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")   # the child itself must still be CPU
    assert pf.probe_compiles_subprocess((4,), timeout_s=120.0) is None


def test_subprocess_probe_timeout_is_a_failure(monkeypatch):
    """A compile slower than the deadline (or a child blocked on a parent-held chip
    claim) must come back as an exception, not a hang — this is the property that keeps
    --experimental-fused-step from wedging a trainer at startup."""
    monkeypatch.setattr(pf, "_configured_platform", lambda: "")
    monkeypatch.setattr(pf, "_PROBE_STARTUP_ALLOWANCE_S", 0.0)
    monkeypatch.setenv("FUSED_PROBE_TEST_SLEEP", "30")
    err = pf.probe_compiles_subprocess((4,), timeout_s=2.0)
    assert isinstance(err, TimeoutError)


def test_probe_result_short_circuits_in_process_probe(monkeypatch):
    """A precomputed subprocess verdict must be honored without re-probing in-process
    (the in-process probe is uncancellable — the very thing the trainer avoids)."""
    def boom(batch=4):
        raise AssertionError("in-process probe must not run when probe_result is given")

    monkeypatch.setattr(pf, "probe_compiles", boom)
    # Failure verdict -> fallback (works even off-TPU: the verdict was computed early).
    with pytest.warns(RuntimeWarning, match="falling back"):
        step = pf.make_fused_train_step(
            learning_rate=0.05, momentum=0.5, fallback_on_compile_error=True,
            probe_result=TimeoutError("probe exceeded budget"))
    state = create_train_state(Net(), jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10)
    new_state, loss = jax.jit(step)(state, x, y, jax.random.PRNGKey(3))
    assert np.isfinite(float(loss)) and int(new_state.step) == 1
    # Success verdict -> fused step, still no in-process probe.
    pf.make_fused_train_step(learning_rate=0.05, momentum=0.5,
                             fallback_on_compile_error=True, probe_result=None)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="real Mosaic compile path only exists on TPU hardware")
def test_fused_step_on_tpu_matches_unfused(setup):
    """TPU-gated hardware smoke (advisor r1): compile the fused kernel through Mosaic (not
    the interpreter) and pin one full optimizer step against the unfused XLA path."""
    state, x, y = setup
    unfused = make_train_step(Net(conv_dropout_rate=0.0, fc_dropout_rate=0.0),
                              learning_rate=0.01, momentum=0.5)
    fused = pf.make_fused_train_step(learning_rate=0.01, momentum=0.5,
                                     conv_dropout_rate=0.0, fc_dropout_rate=0.0)
    rng = jax.random.PRNGKey(7)
    s_a, loss_a = jax.jit(unfused)(state, x, y, rng)
    s_b, loss_b = jax.jit(fused)(state, x, y, rng)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-4)
    for k in s_a.params:
        np.testing.assert_allclose(np.asarray(s_a.params[k]), np.asarray(s_b.params[k]),
                                   rtol=1e-3, atol=1e-5, err_msg=k)
