"""Pallas kernel parity: the fused loss/optimizer kernels match the ops reference exactly.

``ops/pallas_kernels.py`` holds the first-party TPU kernels (fused log-softmax+NLL with a
custom-VJP backward kernel, and the fused SGD-momentum update). On the CPU test platform the
kernels run in Pallas interpret mode — same kernel code, same blocking — so these tests
verify the kernel logic itself, not just a fallback.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
    pallas_kernels as pk,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.optim import sgd_update
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state, make_train_step,
)


@pytest.fixture(scope="module")
def logits_labels():
    rng = np.random.default_rng(42)
    logits = jnp.asarray(rng.normal(size=(37, 10)).astype(np.float32) * 3)
    labels = jnp.asarray(rng.integers(0, 10, size=37).astype(np.int32))
    return logits, labels


class TestFusedNll:
    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_forward_parity(self, logits_labels, reduction):
        logits, labels = logits_labels
        got = pk.nll_from_logits(logits, labels, reduction)
        want = ops.nll_loss(ops.log_softmax(logits), labels, reduction=reduction)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("reduction", ["mean", "sum"])
    def test_grad_parity(self, logits_labels, reduction):
        logits, labels = logits_labels
        g_pallas = jax.grad(lambda l: pk.nll_from_logits(l, labels, reduction))(logits)
        g_ref = jax.grad(
            lambda l: ops.nll_loss(ops.log_softmax(l), labels, reduction=reduction))(logits)
        np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_vjp_per_example_cotangent(self, logits_labels):
        logits, labels = logits_labels
        ct = jnp.asarray(np.random.default_rng(1).normal(size=37).astype(np.float32))
        _, vjp = jax.vjp(lambda l: pk.nll_from_logits(l, labels, "none"), logits)
        _, vjp_ref = jax.vjp(
            lambda l: ops.nll_loss(ops.log_softmax(l), labels, reduction="none"), logits)
        np.testing.assert_allclose(np.asarray(vjp(ct)[0]), np.asarray(vjp_ref(ct)[0]),
                                   rtol=1e-5, atol=1e-6)

    def test_idempotent_on_log_probs(self, logits_labels):
        """Feeding log-probs (the model's actual output) gives the same loss as logits —
        the property that lets the train step fuse on ``Net``'s log_softmax output."""
        logits, labels = logits_labels
        a = pk.nll_from_logits(logits, labels, "mean")
        b = pk.nll_from_logits(ops.log_softmax(logits), labels, "mean")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    def test_jit_and_odd_batch(self):
        """Batch sizes that are not tile-aligned (padding path) under jit."""
        rng = np.random.default_rng(3)
        for b in (1, 7, 256, 300):
            logits = jnp.asarray(rng.normal(size=(b, 10)).astype(np.float32))
            labels = jnp.asarray(rng.integers(0, 10, size=b).astype(np.int32))
            got = jax.jit(lambda l, y: pk.nll_from_logits(l, y, "mean"))(logits, labels)
            want = ops.nll_loss(ops.log_softmax(logits), labels)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-6, atol=1e-6)


class TestFusedSgd:
    def test_leaf_shapes_and_parity(self):
        rng = np.random.default_rng(0)
        params = {"conv": jnp.asarray(rng.normal(size=(5, 5, 1, 10)).astype(np.float32)),
                  "w": jnp.asarray(rng.normal(size=(320, 50)).astype(np.float32)),
                  "b": jnp.asarray(rng.normal(size=(50,)).astype(np.float32)),
                  "scalarish": jnp.asarray(rng.normal(size=(1,)).astype(np.float32))}
        velocity = {k: jnp.asarray(rng.normal(size=v.shape).astype(np.float32)) * 0.1
                    for k, v in params.items()}
        grads = {k: jnp.asarray(rng.normal(size=v.shape).astype(np.float32))
                 for k, v in params.items()}
        p1, v1 = pk.sgd_momentum_step(params, velocity, grads,
                                      learning_rate=0.02, momentum=0.5)
        p2, v2 = sgd_update(params, velocity, grads, learning_rate=0.02, momentum=0.5)
        for k in params:
            assert p1[k].shape == params[k].shape
            np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                       rtol=1e-6, atol=1e-7)
            np.testing.assert_allclose(np.asarray(v1[k]), np.asarray(v2[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_momentum_sequence_matches_torch_semantics(self):
        """Two chained steps reproduce v2 = mu*(mu*v0+g1)+g2 exactly."""
        p = {"x": jnp.ones((130,), jnp.float32)}   # deliberately not lane-aligned
        v = {"x": jnp.zeros((130,), jnp.float32)}
        g = {"x": jnp.full((130,), 2.0, jnp.float32)}
        p, v = pk.sgd_momentum_step(p, v, g, learning_rate=0.1, momentum=0.5)
        p, v = pk.sgd_momentum_step(p, v, g, learning_rate=0.1, momentum=0.5)
        np.testing.assert_allclose(np.asarray(v["x"]), 3.0, rtol=1e-6)      # 0.5*2+2
        np.testing.assert_allclose(np.asarray(p["x"]), 1 - 0.1 * 2 - 0.1 * 3, rtol=1e-6)


class TestTrainStepIntegration:
    def test_full_step_parity_with_reference_path(self):
        """One full train step (forward+backward+update) through the Pallas path equals the
        XLA-fused default path on the real model."""
        model = Net()
        state0 = create_train_state(model, jax.random.PRNGKey(0))
        rng = np.random.default_rng(5)
        images = jnp.asarray(rng.normal(size=(16, 28, 28, 1)).astype(np.float32))
        labels = jnp.asarray(rng.integers(0, 10, size=16).astype(np.int32))
        key = jax.random.PRNGKey(7)

        step_ref = jax.jit(make_train_step(model, learning_rate=0.01, momentum=0.5))
        step_pal = jax.jit(make_train_step(model, learning_rate=0.01, momentum=0.5,
                                           use_pallas=True))
        s1, loss1 = step_ref(state0, images, labels, key)
        state0b = create_train_state(model, jax.random.PRNGKey(0))
        s2, loss2 = step_pal(state0b, images, labels, key)

        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5, atol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                    rtol=1e-5, atol=1e-6),
            s1.params, s2.params)
