"""Train-step tests (SURVEY.md §4): SGD-momentum vs the torch update-rule oracle, gradient
parity vs finite differences, scan-epoch == stepwise equivalence, eval semantics, checkpoint
roundtrip/resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.ops.optim import (
    sgd_init, sgd_update,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state, make_epoch_fn, make_eval_fn, make_train_step,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint

# Heavyweight end-to-end/equivalence tests: full-suite runs only; deselect with
# -m "not slow" for the fast single-core signal (README).
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def model_state():
    model = Net()
    state = create_train_state(model, jax.random.PRNGKey(0))
    return model, state


@pytest.fixture(scope="module")
def batch():
    k = jax.random.PRNGKey(42)
    x = jax.random.normal(k, (16, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(43), (16,), 0, 10)
    return x, y


def test_sgd_matches_torch_update_rule():
    """v <- mu*v + g ; p <- p - lr*v, iterated — the torch.optim.SGD semantics
    (reference src/train.py:60-61)."""
    p = {"w": jnp.asarray([1.0, -2.0])}
    v = sgd_init(p)
    lr, mu = 0.1, 0.5
    g_seq = [jnp.asarray([0.5, 1.0]), jnp.asarray([-1.0, 0.25])]
    pn, vn = np.asarray([1.0, -2.0]), np.zeros(2)
    for g in g_seq:
        p, v = sgd_update(p, v, {"w": g}, learning_rate=lr, momentum=mu)
        vn = mu * vn + np.asarray(g)
        pn = pn - lr * vn
    np.testing.assert_allclose(np.asarray(p["w"]), pn, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v["w"]), vn, rtol=1e-6)


def test_gradients_match_finite_differences(model_state, batch):
    """jax.value_and_grad (the autograd-engine analog, reference src/train.py:75) against
    central finite differences on a few coordinates of fc2."""
    model, state = model_state
    x, y = batch

    def loss_at(params):
        log_probs = model.apply({"params": params}, x)  # deterministic: no dropout noise
        return float(ops.nll_loss(log_probs, y))

    grads = jax.grad(lambda p: ops.nll_loss(model.apply({"params": p}, x), y))(state.params)
    eps = 1e-3
    for (i, j) in [(0, 0), (17, 5), (49, 9)]:
        params_hi = jax.tree_util.tree_map(lambda a: a, state.params)
        params_hi["fc2_kernel"] = state.params["fc2_kernel"].at[i, j].add(eps)
        params_lo = jax.tree_util.tree_map(lambda a: a, state.params)
        params_lo["fc2_kernel"] = state.params["fc2_kernel"].at[i, j].add(-eps)
        fd = (loss_at(params_hi) - loss_at(params_lo)) / (2 * eps)
        ad = float(grads["fc2_kernel"][i, j])
        np.testing.assert_allclose(ad, fd, rtol=5e-2, atol=1e-4)


def test_train_step_decreases_loss(model_state, batch):
    model, state = model_state
    x, y = batch
    step = jax.jit(make_train_step(model, learning_rate=0.05, momentum=0.5))
    rng = jax.random.PRNGKey(7)
    losses = []
    for _ in range(30):
        state, loss = step(state, x, y, rng)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.3, losses[:3] + losses[-3:]


def test_epoch_scan_equals_stepwise(model_state):
    """The scanned epoch (make_epoch_fn) must produce bitwise-identical state/losses to
    applying the jitted step sequentially — the fast path changes scheduling, not math."""
    model, _ = model_state
    state_a = create_train_state(model, jax.random.PRNGKey(1))
    state_b = create_train_state(model, jax.random.PRNGKey(1))
    images = jax.random.normal(jax.random.PRNGKey(2), (32, 28, 28, 1))
    labels = jax.random.randint(jax.random.PRNGKey(3), (32,), 0, 10)
    idx = jnp.arange(32).reshape(4, 8)
    rng = jax.random.PRNGKey(9)

    epoch_fn = jax.jit(make_epoch_fn(model, learning_rate=0.01, momentum=0.5))
    state_a, losses_a = epoch_fn(state_a, images, labels, idx, rng)

    step = jax.jit(make_train_step(model, learning_rate=0.01, momentum=0.5))
    losses_b = []
    for row in idx:
        state_b, loss = step(state_b, images[row], labels[row], rng)
        losses_b.append(loss)

    np.testing.assert_allclose(np.asarray(losses_a), np.asarray(losses_b), rtol=1e-6)
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(state_a.params),
                              jax.tree_util.tree_leaves(state_b.params)):
        # scan vs unrolled can fuse differently; tolerance covers one-ulp drift
        np.testing.assert_allclose(np.asarray(leaf_a), np.asarray(leaf_b),
                                   rtol=1e-5, atol=1e-6)


def test_eval_fn_semantics(model_state):
    """evaluate == (summed NLL, argmax correct) over the split, computed batch-at-a-time
    (reference src/train.py:87-104 with batch_size_test=1000 ⇒ here 4 batches of 5)."""
    model, state = model_state
    x = jax.random.normal(jax.random.PRNGKey(11), (20, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(12), (20,), 0, 10)
    sum_nll, correct = make_eval_fn(model, batch_size=5)(state.params, x, y)

    log_probs = model.apply({"params": state.params}, x)
    want_nll = float(ops.nll_loss(log_probs, y, reduction="sum"))
    want_correct = int(np.sum(np.argmax(np.asarray(log_probs), -1) == np.asarray(y)))
    np.testing.assert_allclose(float(sum_nll), want_nll, rtol=1e-5)
    assert int(correct) == want_correct


def test_step_rng_varies_per_step(model_state, batch):
    """Dropout keys are folded with the global step: two consecutive steps from the same base
    rng must not reuse masks (SURVEY.md §7 hard part (b)) — detectable via different losses on
    the same batch with frozen params (lr=0)."""
    model, state = model_state
    x, y = batch
    step = jax.jit(make_train_step(model, learning_rate=0.0, momentum=0.0))
    rng = jax.random.PRNGKey(21)
    state, loss1 = step(state, x, y, rng)
    state, loss2 = step(state, x, y, rng)  # params unchanged (lr=0); only step index moved
    assert float(loss1) != float(loss2)


def test_checkpoint_roundtrip(tmp_path, model_state, batch):
    model, state = model_state
    x, y = batch
    step = jax.jit(make_train_step(model, learning_rate=0.01, momentum=0.5))
    state, _ = step(state, x, y, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt.msgpack")
    checkpoint.save_train_state(path, state)

    fresh = create_train_state(model, jax.random.PRNGKey(99))
    restored = checkpoint.restore_train_state(path, fresh)
    assert int(restored.step) == int(state.step)
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resumed training continues identically to uninterrupted training
    cont_a, _ = step(state, x, y, jax.random.PRNGKey(5))
    cont_b, _ = step(restored, x, y, jax.random.PRNGKey(5))
    np.testing.assert_allclose(np.asarray(cont_a.params["fc2_bias"]),
                               np.asarray(cont_b.params["fc2_bias"]), rtol=1e-7)


def test_params_export_roundtrip(tmp_path, model_state):
    model, state = model_state
    path = str(tmp_path / "model.msgpack")
    checkpoint.save_params(path, state.params)
    loaded = checkpoint.load_params(path, jax.device_get(state.params))
    np.testing.assert_array_equal(np.asarray(loaded["conv1_bias"]),
                                  np.asarray(state.params["conv1_bias"]))


def test_epoch_unroll_is_semantics_preserving(model_state):
    """unroll>1 is a codegen knob only: the scanned epoch must produce the same state and
    losses as the sequential (unroll=1) program — including the shipped bench default
    (unroll=8) and a step count (11) that 8 does not divide, so remainder handling is
    covered too."""
    model, state0 = model_state
    x = jax.random.normal(jax.random.PRNGKey(5), (64, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(6), (64,), 0, 10)
    # 11 steps of batch 8, indices repeating across rows — 11 % 8 != 0 on purpose.
    idx = jax.random.randint(jax.random.PRNGKey(8), (11, 8), 0, 64).astype(jnp.int32)
    rng = jax.random.PRNGKey(7)

    outs = {}
    for unroll in (1, 4, 8):
        fn = jax.jit(make_epoch_fn(model, learning_rate=0.01, momentum=0.5,
                                   unroll=unroll))
        outs[unroll] = fn(state0, x, y, idx, rng)

    for unroll in (4, 8):
        np.testing.assert_allclose(np.asarray(outs[1][1]), np.asarray(outs[unroll][1]),
                                   rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(outs[1][0].params),
                        jax.tree_util.tree_leaves(outs[unroll][0].params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_epoch_pregather_is_semantics_preserving(model_state):
    """pregather=True (one epoch-wide gather before the scan instead of one per step) is
    a data-movement knob only: same state and losses as the per-step-gather program,
    including with a shuffled, repeated index plan."""
    model, state0 = model_state
    x = jax.random.normal(jax.random.PRNGKey(5), (48, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(6), (48,), 0, 10)
    # Shuffled plan with repeats across rows — the gather must honor arbitrary indexing.
    idx = jax.random.randint(jax.random.PRNGKey(8), (8, 8), 0, 48).astype(jnp.int32)
    rng = jax.random.PRNGKey(7)

    outs = {}
    # (pregather, unroll): includes the shipped bench default combination (True, 8).
    for key in ((False, 1), (True, 1), (True, 8)):
        pregather, unroll = key
        fn = jax.jit(make_epoch_fn(model, learning_rate=0.01, momentum=0.5,
                                   pregather=pregather, unroll=unroll))
        outs[key] = fn(state0, x, y, idx, rng)

    for key in ((True, 1), (True, 8)):
        np.testing.assert_allclose(np.asarray(outs[(False, 1)][1]),
                                   np.asarray(outs[key][1]), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(outs[(False, 1)][0].params),
                        jax.tree_util.tree_leaves(outs[key][0].params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_grad_accum_equals_full_batch_step(model_state):
    """grad_accum=N is a memory knob only: with dropout off, the accumulated update
    equals the full-batch step to f32 round-off (equal-size microbatch means average to
    the batch mean); with dropout on it still trains (distinct mask per microbatch)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )

    det_model = TransformerClassifier(dropout_rate=0.0)
    state0 = create_train_state(det_model, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(5), (32, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(6), (32,), 0, 10)
    rng = jax.random.PRNGKey(7)

    outs = {}
    for accum in (1, 4):
        fn = jax.jit(make_train_step(det_model, learning_rate=0.05, momentum=0.5,
                                     grad_accum=accum))
        outs[accum] = fn(state0, x, y, rng)
    assert abs(float(outs[1][1]) - float(outs[4][1])) < 1e-6
    for a, b in zip(jax.tree_util.tree_leaves(outs[1][0].params),
                    jax.tree_util.tree_leaves(outs[4][0].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_grad_accum_rejects_indivisible_batch(model_state):
    model, state0 = model_state
    fn = make_train_step(model, learning_rate=0.05, momentum=0.5, grad_accum=3)
    x = jax.random.normal(jax.random.PRNGKey(5), (32, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(6), (32,), 0, 10)
    with pytest.raises(ValueError, match="not divisible"):
        fn(state0, x, y, jax.random.PRNGKey(7))


def test_grad_accum_epoch_with_dropout_trains(model_state):
    """The accumulated step drives the scanned epoch path end-to-end (dropout on)."""
    model, state0 = model_state
    fn = jax.jit(make_epoch_fn(model, learning_rate=0.05, momentum=0.5, grad_accum=4))
    x = jax.random.normal(jax.random.PRNGKey(8), (64, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(9), (64,), 0, 10)
    idx = jnp.arange(64, dtype=jnp.int32).reshape(4, 16)
    state, losses = fn(state0, x, y, idx, jax.random.PRNGKey(10))
    assert int(state.step) == 4
    assert bool(jnp.all(jnp.isfinite(losses)))
