"""The shared epoch-timing protocol (utils/benchmarks.py) on tiny shapes: correct step
count, positive times, loss actually improving, and the divisibility guard."""

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data import mnist
from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import Dataset
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import make_mesh
from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
    time_epochs,
)


@pytest.fixture(scope="module")
def tiny_ds():
    rng = np.random.default_rng(0)
    images = rng.normal(size=(256, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(256) % 10).astype(np.int32)
    return Dataset(images, labels, "synthetic")


def test_time_epochs_protocol(tiny_ds):
    result = time_epochs(make_mesh(4), tiny_ds, global_batch=32, timed_epochs=2)
    assert result.devices == 4
    assert result.steps_per_epoch == 256 // 32
    assert len(result.epoch_seconds) == 2
    assert all(t > 0 for t in result.epoch_seconds)
    assert result.median_seconds == pytest.approx(
        float(np.median(result.epoch_seconds)))
    assert np.isfinite(result.final_train_loss)


@pytest.mark.slow
def test_time_epochs_trains():
    """Several epochs on 512 learnable synthetic digits must pull the loss well below the
    uniform-prediction level (ln 10 ≈ 2.30)."""
    imgs_u8, labels = mnist._synthesize_split(512, seed=3)
    ds = Dataset(mnist._normalize(imgs_u8), labels.astype(np.int32), "synthetic")
    result = time_epochs(make_mesh(2), ds, global_batch=64,
                         learning_rate=0.05, timed_epochs=25)
    assert result.final_train_loss < 1.5


def test_chained_diff_time_converged_flag(monkeypatch):
    """The two-point protocol must SAY when it never reached min_delta of chained
    work (r4 advisor finding): a fast fake chain that scales with n converges; one
    whose time never grows exhausts max_n with converged=False."""
    import csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks as B

    clock = [0.0]
    monkeypatch.setattr(B.time, "perf_counter", lambda: clock[0])

    def scaling_chain(n):          # 1 ms per iteration: converges once n2 is large
        def run():
            clock[0] += 0.001 * n
        return run

    per_iter, (n1, _), (n2, _), conv = B.chained_diff_time(
        scaling_chain, n1=2, grow=8, max_n=4096, min_delta=0.25, reps=1, warmup=0)
    assert conv and per_iter == pytest.approx(0.001)

    def flat_chain(n):             # pure dispatch tax: never adds delta
        def run():
            clock[0] += 0.070
        return run

    per_iter, _, (n2, _), conv = B.chained_diff_time(
        flat_chain, n1=2, grow=8, max_n=4096, min_delta=0.25, reps=1, warmup=0)
    assert not conv and n2 == 4096


def test_indivisible_batch_rejected(tiny_ds):
    with pytest.raises(ValueError, match="not divisible"):
        time_epochs(make_mesh(3), tiny_ds, global_batch=64)


def test_flops_constants_and_peak_lookup():
    """Static model-FLOPs arithmetic (SURVEY.md §3.4 shapes) and the device-kind → peak
    mapping behind the bench's MFU estimate."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import benchmarks as B

    assert B.FWD_FLOPS_PER_EXAMPLE == 288_000 + 640_000 + 32_000 + 1_000
    assert B.TRAIN_FLOPS_PER_EXAMPLE == 3 * B.FWD_FLOPS_PER_EXAMPLE
    assert B.peak_flops("TPU v5 lite") == 197e12
    assert B.peak_flops("TPU v5p") == 459e12
    assert B.peak_flops("TPU v4") == 275e12
    assert B.peak_flops("warp drive") is None


@pytest.mark.slow
def test_batch_sweep_functional(tmp_path, monkeypatch):
    """run_batch_sweep on tiny data: one row per admissible batch size, skip markers for
    inadmissible ones, throughput fields populated, and the plot artifact written."""
    import json
    import bench_scaling

    imgs, labels = mnist._synthesize_split(512, seed=5)
    ds = Dataset(mnist._normalize(imgs), labels.astype(np.int32), "synthetic")
    monkeypatch.setattr(bench_scaling, "load_mnist", lambda _: (ds, ds))
    monkeypatch.chdir(tmp_path)

    rows = bench_scaling.run_batch_sweep([64, 256, 4096], timed_epochs=1)
    assert [r["global_batch"] for r in rows] == [64, 256]   # 4096 > 512 examples: skipped
    for r in rows:
        assert r["epoch_seconds"] > 0
        assert r["examples_per_s"] > 0
        assert r["per_device_batch"] * r["devices"] == r["global_batch"]
    assert (tmp_path / "images" / "time_vs_global_batch.png").exists()
