"""The shared epoch-timing protocol (utils/benchmarks.py) on tiny shapes: correct step
count, positive times, loss actually improving, and the divisibility guard."""

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data import mnist
from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import Dataset
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import make_mesh
from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
    time_epochs,
)


@pytest.fixture(scope="module")
def tiny_ds():
    rng = np.random.default_rng(0)
    images = rng.normal(size=(256, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(256) % 10).astype(np.int32)
    return Dataset(images, labels, "synthetic")


def test_time_epochs_protocol(tiny_ds):
    result = time_epochs(make_mesh(4), tiny_ds, global_batch=32, timed_epochs=2)
    assert result.devices == 4
    assert result.steps_per_epoch == 256 // 32
    assert len(result.epoch_seconds) == 2
    assert all(t > 0 for t in result.epoch_seconds)
    assert result.median_seconds == pytest.approx(
        float(np.median(result.epoch_seconds)))
    assert np.isfinite(result.final_train_loss)


@pytest.mark.slow
def test_time_epochs_trains():
    """Several epochs on 512 learnable synthetic digits must pull the loss well below the
    uniform-prediction level (ln 10 ≈ 2.30)."""
    imgs_u8, labels = mnist._synthesize_split(512, seed=3)
    ds = Dataset(mnist._normalize(imgs_u8), labels.astype(np.int32), "synthetic")
    result = time_epochs(make_mesh(2), ds, global_batch=64,
                         learning_rate=0.05, timed_epochs=25)
    assert result.final_train_loss < 1.5


def test_indivisible_batch_rejected(tiny_ds):
    with pytest.raises(ValueError, match="not divisible"):
        time_epochs(make_mesh(3), tiny_ds, global_batch=64)


def test_flops_constants_and_peak_lookup():
    """Static model-FLOPs arithmetic (SURVEY.md §3.4 shapes) and the device-kind → peak
    mapping behind the bench's MFU estimate."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import benchmarks as B

    assert B.FWD_FLOPS_PER_EXAMPLE == 288_000 + 640_000 + 32_000 + 1_000
    assert B.TRAIN_FLOPS_PER_EXAMPLE == 3 * B.FWD_FLOPS_PER_EXAMPLE
    assert B.peak_flops("TPU v5 lite") == 197e12
    assert B.peak_flops("TPU v5p") == 459e12
    assert B.peak_flops("TPU v4") == 275e12
    assert B.peak_flops("warp drive") is None


def test_batch_sweep_functional(tmp_path, monkeypatch):
    """run_batch_sweep on tiny data: one row per admissible batch size, skip markers for
    inadmissible ones, throughput fields populated, and the plot artifact written."""
    import json
    import bench_scaling

    imgs, labels = mnist._synthesize_split(512, seed=5)
    ds = Dataset(mnist._normalize(imgs), labels.astype(np.int32), "synthetic")
    monkeypatch.setattr(bench_scaling, "load_mnist", lambda _: (ds, ds))
    monkeypatch.chdir(tmp_path)

    rows = bench_scaling.run_batch_sweep([64, 256, 4096], timed_epochs=1)
    assert [r["global_batch"] for r in rows] == [64, 256]   # 4096 > 512 examples: skipped
    for r in rows:
        assert r["epoch_seconds"] > 0
        assert r["examples_per_s"] > 0
        assert r["per_device_batch"] * r["devices"] == r["global_batch"]
    assert (tmp_path / "images" / "time_vs_global_batch.png").exists()
