"""Quantized execution: int8 KV cache, quantized weights/matmuls, byte accounting.

The quantization contract, pinned here (tier-1):

1. **Accuracy is a budget, not a vibe** — greedy-decode token-match rate vs the
   fp32 oracle across MHA/GQA/window/RoPE stays above an explicit bound, the
   teacher-forced NLL delta through the quantized serving path stays within an
   explicit bound, and temperature>0 sampling under the dequantized-logits path
   stays distribution-close to fp32.
2. **Policy off is bitwise off** — ``quantize_params`` returns the identical
   tree, ``init_cache`` builds the exact planes it always built, ``dense_any``
   on a plain kernel IS ``ops.dense``; the quantization code cannot perturb the
   fp32 path it sits next to.
3. **One program, still** — an int8-KV engine traces exactly one decode program
   and at most one prefill program per chunk size: scales are data, not shape.
4. **Bytes are measured, never assumed** — ``byte_accounting`` sums live
   buffers; int8 KV + int8 weights cut measured decode bytes/token >= 1.8x and
   multiply slots-per-HBM-budget >= 1.9x; a plane snapshot written under one
   layout can never install into an engine running another.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
from csed_514_project_distributed_training_using_pytorch_tpu.ops import quant
from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
    ContinuousBatchingEngine,
    Request,
    SamplingParams,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.prefix_cache import (
    PrefixCache,
)

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)

SMALL = dict(vocab_size=9, seq_len=16, embed_dim=32, num_layers=2, num_heads=4)

# The tier-1 accuracy budget for TINY RANDOM-INIT models (near-uniform logits —
# the hardest case for argmax stability; measured 0.95-1.0 across configs and
# seeds). The committed real-checkpoint artifact documents the trained-model
# budget, which is tighter.
TOKEN_MATCH_BOUND = 0.90
NLL_DELTA_BOUND = 0.05


def _model(**kw):
    return lm.TransformerLM(**{**SMALL, **kw})


def _params(model, seed=0):
    ids = jnp.zeros((1, model.seq_len), jnp.int32)
    return model.init({"params": jax.random.PRNGKey(seed)}, ids)["params"]


def _mixed_requests(model, n, seed=0, temperature=0.0):
    rng = np.random.default_rng(seed)
    sampling = SamplingParams(temperature=temperature)
    return [Request(
        prompt=rng.integers(0, model.vocab_size - 2,
                            size=int(rng.integers(0, model.seq_len // 2)))
        .astype(np.int32),
        max_new_tokens=int(rng.integers(1, model.seq_len - 1)),
        sampling=sampling, request_id=i) for i in range(n)]


def _run_engine(model, params, reqs, **kw):
    eng = ContinuousBatchingEngine(model, params, num_slots=3, **kw)
    comps = {c.request.request_id: np.asarray(c.tokens)
             for c in eng.run(list(reqs))}
    return eng, comps


# -----------------------------------------------------------------------------------------
# Scale math: quant/dequant roundtrips and the int8 matmul paths
# -----------------------------------------------------------------------------------------


def test_quantize_rows_roundtrip_error_bound():
    """Per-row symmetric int8: |x - dequant(quant(x))| <= amax/127 per element
    (half-step rounding, exactly representable scales aside), zero rows exact."""
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 4, 32)) * \
        jnp.arange(1, 6)[:, None, None]          # heterogeneous row magnitudes
    q, scale = quant.quantize_rows(x, jnp.int8)
    assert q.dtype == jnp.int8 and scale.shape == (5, 4)
    err = jnp.abs(quant.dequantize_rows(q, scale) - x)
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    assert float(jnp.max(err - amax / 127.0)) <= 1e-6
    # All-zero rows: scale 1.0, dequant exact zeros.
    qz, sz = quant.quantize_rows(jnp.zeros((3, 8)), jnp.int8)
    np.testing.assert_array_equal(np.asarray(sz), np.ones((3,), np.float32))
    np.testing.assert_array_equal(np.asarray(quant.dequantize_rows(qz, sz)),
                                  np.zeros((3, 8), np.float32))


@pytest.mark.skipif(quant.fp8_dtype() is None,
                    reason="no float8_e4m3fn in this jax build")
def test_quantize_rows_fp8_roundtrip():
    """fp8 planes quantize/dequantize within e4m3's ~2^-3 relative step."""
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16)) * 3.0
    q, scale = quant.quantize_rows(x, quant.fp8_dtype())
    rel = jnp.abs(quant.dequantize_rows(q, scale) - x) / (jnp.abs(x) + 1e-6)
    assert float(jnp.max(rel)) < 0.13


@pytest.mark.parametrize("mode,tol", [("w8", 0.02), ("w8a8", 0.05)])
def test_int8_matmul_paths_match_fp32_within_bound(mode, tol):
    """Weight-only and w8a8 matmuls track the fp32 product within a relative
    Frobenius bound — the trainer-usable int8 matmul paths."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = jax.random.normal(k1, (16, 64))
    w = jax.random.normal(k2, (64, 32)) * 0.1
    qt = quant.quantize_tensor(w, mode=mode)
    ref = x @ w
    got = quant.int8_matmul(x, qt)
    rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
    assert rel < tol
    # w8a8 really accumulates in int32 (int8 x int8 lane path).
    if mode == "w8a8":
        xq, _ = quant.quantize_rows(x, jnp.int8)
        acc = jax.lax.dot_general(xq, qt.q, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        assert acc.dtype == jnp.int32


def test_dense_any_plain_kernel_is_ops_dense_bitwise():
    """The policy-off pin at the op level: a plain array kernel takes the exact
    ``ops.dense`` path — same bits out."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    x = jax.random.normal(k1, (8, 32))
    w = jax.random.normal(k2, (32, 16))
    b = jnp.arange(16, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(quant.dense_any(x, w, b)),
                                  np.asarray(ops.dense(x, w, b)))


def test_quantize_params_rewrites_kernels_only():
    """``quantize_params``: 2-D ``*_kernel`` leaves become QuantizedTensor,
    embeddings/LN/biases stay the same objects; ``weights='off'`` returns the
    identical tree (not a copy) — the bitwise-off guarantee."""
    model = _model()
    params = _params(model)
    off = quant.quantize_params(params, quant.QuantPolicy())
    assert off is params
    qp = quant.quantize_params(params, quant.QuantPolicy(weights="w8"))
    attn = qp["block_0"]["attn"]
    assert isinstance(attn["qkv_kernel"], quant.QuantizedTensor)
    assert isinstance(qp["head_kernel"], quant.QuantizedTensor)
    assert qp["head_kernel"].q.dtype == jnp.int8
    assert qp["tok_embed"] is params["tok_embed"]
    assert qp["block_0"]["ln1_scale"] is params["block_0"]["ln1_scale"]
    assert attn["qkv_bias"] is params["block_0"]["attn"]["qkv_bias"]
    # The quantized tree round-trips jax pytree plumbing (device_put, tree_map).
    moved = jax.tree_util.tree_map(jnp.asarray, qp)
    assert isinstance(moved["head_kernel"], quant.QuantizedTensor)
    assert moved["head_kernel"].mode == "w8"


def test_quant_policy_validation():
    with pytest.raises(ValueError):
        quant.QuantPolicy(kv_dtype="int4")
    with pytest.raises(ValueError):
        quant.QuantPolicy(weights="w4")
    assert quant.QuantPolicy().off


# -----------------------------------------------------------------------------------------
# Quantized KV-cache planes in the model layer
# -----------------------------------------------------------------------------------------


def test_init_cache_layouts():
    """Default cache is exactly the legacy structure (no scale planes); int8
    adds f32 ``k_scale``/``v_scale`` planes of per-head-per-position shape."""
    model = _model(num_kv_heads=2)
    legacy = lm.init_cache(model, 3)
    assert set(legacy["block_0"]) == {"k", "v"}
    assert legacy["block_0"]["k"].dtype == model.dtype
    q = lm.init_cache(model, 3, kv_dtype="int8")
    layer = q["block_0"]
    assert set(layer) == {"k", "v", "k_scale", "v_scale"}
    assert layer["k"].dtype == jnp.int8
    assert layer["k_scale"].shape == (3, model.seq_len, 2)
    assert layer["k_scale"].dtype == jnp.float32


def test_decode_step_rejects_quantized_cache():
    """decode_step reads raw planes only — it must refuse a quantized cache
    loudly (silently it would astype values into int8 codes with no scale and
    attend against garbage, and drop the scale planes from the returned tree)."""
    model = _model()
    params = _params(model)
    cache = lm.init_cache(model, 1, kv_dtype="int8")
    with pytest.raises(ValueError, match="decode_step_slots"):
        lm.decode_step(model, params, cache, jnp.array([1]), jnp.int32(0))


def test_reset_slots_wipes_scale_planes():
    model = _model()
    params = _params(model)
    cache = lm.init_cache(model, 2, kv_dtype="int8")
    cache, _ = lm.decode_step_slots(model, params, cache,
                                    jnp.array([1, 2]), jnp.array([0, 0]))
    assert float(jnp.sum(jnp.abs(cache["block_0"]["k_scale"]))) > 0
    wiped = lm.reset_slots(cache, jnp.array([True, False]))
    assert float(jnp.sum(jnp.abs(wiped["block_0"]["k_scale"][0]))) == 0.0
    assert float(jnp.sum(jnp.abs(wiped["block_0"]["k_scale"][1]))) > 0.0


def test_prefill_chunk_rows_bitwise_match_decode_path_int8():
    """Quantize-on-write parity: a chunk-prefilled int8 slot holds bit-identical
    quantized rows AND scales to the same prompt fed through the per-token
    decode path — prefill is a schedule change even under quantization."""
    model = _model()
    params = _params(model)
    prompt = jnp.zeros((2, model.seq_len), jnp.int32)
    prompt = prompt.at[0, :8].set(jnp.arange(8) % (model.vocab_size - 1))
    c_pre = lm.init_cache(model, 2, kv_dtype="int8")
    c_pre = lm.prefill_chunk(model, params, c_pre, prompt, jnp.int32(0),
                             jnp.int32(0), jnp.int32(8), jnp.asarray(True),
                             chunk=8)
    c_dec = lm.init_cache(model, 2, kv_dtype="int8")
    ids_t = jnp.full((2,), model.vocab_size - 1, jnp.int32)
    for t in range(8):
        c_dec, _ = lm.decode_step_slots(model, params, c_dec, ids_t,
                                        jnp.array([t, 0]))
        ids_t = jnp.array([prompt[0, t], 0])
    for name in ("k", "v", "k_scale", "v_scale"):
        np.testing.assert_array_equal(
            np.asarray(c_pre["block_0"][name][0, :8]),
            np.asarray(c_dec["block_0"][name][0, :8]), err_msg=name)


def test_decode_nll_fp32_matches_teacher_forced_loss():
    """The NLL harness itself is pinned: scored through the fp32 decode path it
    reproduces ``next_token_loss`` to float tolerance — so a quantized delta
    measured with it is attributable to quantization, not the harness."""
    model = _model()
    params = _params(model)
    targets = jax.random.randint(jax.random.PRNGKey(5), (4, model.seq_len),
                                 0, model.vocab_size - 1)
    via_decode = float(lm.decode_nll(model, params, targets))
    ref = float(lm.next_token_loss(model, params, targets, None,
                                   deterministic=True))
    assert abs(via_decode - ref) < 1e-5


@pytest.mark.parametrize("kv,policy", [("int8", "off"), ("int8", "w8"),
                                       ("bf16", "off")])
def test_nll_delta_within_budget(kv, policy):
    """The LM-level accuracy budget: teacher-forced NLL through the quantized
    serving path moves < NLL_DELTA_BOUND vs the fp32 oracle."""
    model = _model()
    params = _params(model)
    qparams = quant.quantize_params(
        params, quant.QuantPolicy(kv_dtype=kv, weights=policy))
    targets = jax.random.randint(jax.random.PRNGKey(6), (4, model.seq_len),
                                 0, model.vocab_size - 1)
    base = float(lm.decode_nll(model, params, targets))
    quantized = float(lm.decode_nll(model, qparams, targets, kv_dtype=kv))
    assert abs(quantized - base) < NLL_DELTA_BOUND


# -----------------------------------------------------------------------------------------
# Engine-level accuracy budget + one-program pins
# -----------------------------------------------------------------------------------------


@pytest.mark.parametrize("cfg", [
    dict(), dict(num_kv_heads=2), dict(attention_window=5), dict(rope=True),
], ids=["mha", "gqa", "window", "rope"])
def test_engine_int8_greedy_token_match_budget(cfg):
    """Acceptance: the int8-KV + int8-weight engine's greedy streams match the
    fp32 engine's token-for-token above TOKEN_MATCH_BOUND across model configs,
    with the decode program still compiled exactly once and every prefill size
    compiled at most once (quantization changes plane I/O, never shape)."""
    model = _model(**cfg)
    params = _params(model)
    reqs = _mixed_requests(model, 6, seed=7)
    _, ref = _run_engine(model, params, reqs)
    eng, got = _run_engine(model, params, reqs,
                           kv_dtype="int8", quant_policy="w8")
    assert eng.trace_count == 1
    assert all(v <= 1 for v in eng.prefill_trace_counts.values())
    agree = total = 0
    for req in reqs:
        p = len(req.prompt)
        a, b = ref[req.request_id], got[req.request_id]
        # The teacher-forced prompt prefix survives bit-exactly regardless.
        np.testing.assert_array_equal(a[:p], b[:p])
        n = min(len(a), len(b)) - p
        agree += int((a[p:p + n] == b[p:p + n]).sum())
        total += n
    assert total > 0
    assert agree / total >= TOKEN_MATCH_BOUND, \
        f"token match {agree / total:.3f} under budget {TOKEN_MATCH_BOUND}"


def test_engine_fp32_paths_bitwise_unchanged_when_policy_off():
    """Policy off ⇒ the engine is the legacy engine: same params object, same
    cache structure, token-identical output to a default-constructed engine."""
    model = _model()
    params = _params(model)
    reqs = _mixed_requests(model, 4, seed=9)
    eng_default, toks_default = _run_engine(model, params, reqs)
    eng_off, toks_off = _run_engine(model, params, reqs,
                                    kv_dtype="model", quant_policy="off")
    assert set(eng_off._cache["block_0"]) == {"k", "v"}
    for i in toks_default:
        np.testing.assert_array_equal(toks_default[i], toks_off[i])
    # And "fp32" (an explicit spec) on an fp32 model is the same planes too.
    eng_f32, toks_f32 = _run_engine(model, params, reqs, kv_dtype="fp32")
    for i in toks_default:
        np.testing.assert_array_equal(toks_default[i], toks_f32[i])


def test_engine_temperature_sampling_distribution_under_quant():
    """Distribution-level budget for temperature>0: sampling through the
    dequantized-logits path (same seed, same step schedule) yields a
    first-token distribution within small total-variation distance of fp32 —
    the sampler consumes quantized logits, not a different program."""
    model = _model()
    params = _params(model)
    n = 64
    sampling = SamplingParams(temperature=1.0, top_k=0, top_p=1.0)
    reqs = [Request(prompt=np.zeros(0, np.int32), max_new_tokens=1,
                    sampling=sampling, request_id=i) for i in range(n)]

    def first_tokens(**kw):
        eng = ContinuousBatchingEngine(model, params, num_slots=4, seed=123,
                                       **kw)
        return np.array([int(c.tokens[0]) for c in eng.run(list(reqs))])

    a = first_tokens()
    b = first_tokens(kv_dtype="int8", quant_policy="w8")
    v = model.vocab_size
    pa = np.bincount(a, minlength=v) / n
    pb = np.bincount(b, minlength=v) / n
    tv = 0.5 * float(np.abs(pa - pb).sum())
    assert tv <= 0.15, f"total-variation distance {tv:.3f} too large"


# -----------------------------------------------------------------------------------------
# Prefix-cache dtype/layout compatibility (satellite regression)
# -----------------------------------------------------------------------------------------


def test_prefix_cache_layout_mismatch_never_hits():
    """Unit guard: an entry stored under one plane layout is invisible to
    lookups under another — counted, not silently installed."""
    cache = PrefixCache(4, layout="fp32-layout")
    tokens = np.arange(8, dtype=np.int32)
    cache.insert(tokens, {"planes": "A"})
    hit, planes = cache.lookup(tokens, layout="fp32-layout")
    assert hit == 8 and planes is not None
    hit, planes = cache.lookup(tokens, layout="int8-layout")
    assert hit == 0 and planes is None
    assert cache.layout_rejects > 0
    assert cache.stats()["layout_rejects"] == cache.layout_rejects


def test_prefix_cache_written_at_fp32_never_installs_into_int8_engine():
    """The regression the satellite names: hand an fp32 engine's populated
    prefix cache to an int8 engine — every lookup must miss (layout reject),
    the engine chunk-prefills from scratch, and its output still matches its
    own fresh-cache output token-for-token."""
    model = _model()
    params = _params(model)
    prompt = np.arange(8, dtype=np.int32) % (model.vocab_size - 1)
    req = lambda i: Request(prompt=prompt, max_new_tokens=4, request_id=i)  # noqa: E731

    eng_f = ContinuousBatchingEngine(model, params, num_slots=2,
                                     prefix_cache_entries=4)
    eng_f.run([req(0)])
    assert len(eng_f.prefix_cache) == 1          # fp32-layout snapshot stored

    eng_q = ContinuousBatchingEngine(model, params, num_slots=2,
                                     kv_dtype="int8", prefix_cache_entries=4)
    ref = np.asarray(eng_q.run([req(1)])[0].tokens)   # own-cache baseline
    eng_q2 = ContinuousBatchingEngine(model, params, num_slots=2,
                                      kv_dtype="int8", prefix_cache_entries=4)
    eng_q2.prefix_cache = eng_f.prefix_cache          # the foreign cache
    comp = eng_q2.run([req(2)])[0]
    np.testing.assert_array_equal(np.asarray(comp.tokens), ref)
    assert eng_f.prefix_cache.layout_rejects > 0      # rejected, not installed
    # Sanity: the layouts really differ (that is what the guard keys on).
    assert eng_f.plane_layout != eng_q2.plane_layout


def test_prefix_cache_hit_roundtrip_same_layout_int8():
    """Same-layout int8 snapshots still hit and reproduce identical streams —
    the guard blocks cross-layout installs, not the feature."""
    model = _model()
    params = _params(model)
    prompt = (np.arange(10) % (model.vocab_size - 1)).astype(np.int32)
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   kv_dtype="int8", prefix_cache_entries=4)
    first = np.asarray(eng.run([Request(prompt=prompt, max_new_tokens=4,
                                        request_id=0)])[0].tokens)
    again = np.asarray(eng.run([Request(prompt=prompt, max_new_tokens=4,
                                        request_id=1)])[0].tokens)
    assert eng.prefix_cache.hits >= 1
    np.testing.assert_array_equal(first, again)


# -----------------------------------------------------------------------------------------
# Byte-true accounting
# -----------------------------------------------------------------------------------------


def test_byte_accounting_matches_live_buffers_and_hits_ratios():
    """The accounting is the sum of real leaf bytes, and at a serving-shaped
    config int8 KV (+ int8 weights) clears the committed ratios: >= 1.8x fewer
    measured decode bytes/token, >= 1.9x slots under the same HBM budget."""
    model = lm.TransformerLM(vocab_size=9, seq_len=128, embed_dim=32,
                             num_layers=2, num_heads=4)
    params = _params(model)
    eng_a = ContinuousBatchingEngine(model, params, num_slots=4)
    eng_b = ContinuousBatchingEngine(model, params, num_slots=4,
                                     kv_dtype="int8", quant_policy="w8")
    acct_a, acct_b = eng_a.byte_accounting(), eng_b.byte_accounting()
    # Byte-true: recompute from the engines' actual arrays.
    for eng, acct in ((eng_a, acct_a), (eng_b, acct_b)):
        assert acct["kv_bytes_resident"] == quant.tree_bytes(eng._cache)
        assert acct["params_bytes"] == quant.tree_bytes(eng.params)
    # int8 planes + f32 scales: 4 / (1 + 4/Dh) per element vs fp32.
    hd = model.embed_dim // model.num_heads
    expect = 4.0 / (1.0 + 4.0 / hd)
    assert acct_a["kv_bytes_per_slot"] / acct_b["kv_bytes_per_slot"] == \
        pytest.approx(expect, rel=0.01)
    assert acct_a["decode_bytes_per_token"] / \
        acct_b["decode_bytes_per_token"] >= 1.8
    assert acct_b["slots_at_budget"] / acct_a["slots_at_budget"] >= 1.9


def test_tree_bytes_counts_quantized_tensors_exactly():
    w = jnp.ones((64, 32))
    qt = quant.quantize_tensor(w)
    assert quant.tree_bytes({"w": qt}) == 64 * 32 * 1 + 32 * 4
    assert qt.nbytes == 64 * 32 * 1 + 32 * 4


def test_serve_summary_event_carries_byte_accounting():
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    ev = T.serve_summary_event(requests=1, ok=1, timeout=0, new_tokens=4,
                               wall_s=1.0,
                               byte_accounting={"kv_dtype": "int8",
                                                "decode_bytes_per_token": 10.0})
    assert ev["bytes"]["kv_dtype"] == "int8"


def test_estimate_mfu_reports_bytes_side():
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    ev = T.estimate_mfu(1e9, 0.01, bytes_per_step=1e6)
    assert ev["bytes_accessed_per_step"] == 1e6
    assert ev["achieved_bytes_per_s_per_device"] == pytest.approx(1e8)
    # Off-TPU the roofline fraction is None — never a guess.
    assert ev["hbm_frac"] is None
    # And the AOT path actually measures bytes on this backend.
    compiled = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.ones((32, 32))).compile()
    measured = T.compiled_bytes_accessed(compiled)
    assert measured is None or measured > 0


# -----------------------------------------------------------------------------------------
# CLI plumbing: loadgen flags, summary artifact, report rows
# -----------------------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util

    path = os.path.join(_REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_kv_dtype_flags_recorded_in_summary(tmp_path, capsys):
    """Satellite: --kv-dtype/--quant-policy plumb through engine construction
    and land in --summary-json, so A/B runs are one flag apart."""
    loadgen = _load_tool("serve_loadgen")
    summary = tmp_path / "quant_on.json"
    tele = tmp_path / "serve.jsonl"
    rc = loadgen.main([
        "--requests", "4", "--mode", "closed", "--concurrency", "2",
        "--seq-len", "16", "--embed-dim", "16", "--num-layers", "1",
        "--num-heads", "2", "--num-levels", "8", "--num-slots", "2",
        "--prompt-lens", "0,4", "--max-new-tokens", "4",
        "--prefill-chunks", "8", "--warmup", "0",
        "--kv-dtype", "int8", "--quant-policy", "w8",
        "--telemetry", str(tele), "--summary-json", str(summary)])
    assert rc == 0
    doc = json.loads(summary.read_text())
    assert doc["kv_dtype"] == "int8" and doc["quant_policy"] == "w8"
    assert doc["bytes"]["kv_dtype"] == "int8"
    assert doc["bytes"]["decode_bytes_per_token"] > 0
    assert doc["decode_compilations"] == 1
    out = capsys.readouterr().out
    assert "bytes (measured)" in out
    # The serve telemetry's summary event carries the same accounting.
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (
        load_metrics_jsonl,
    )

    rows = load_metrics_jsonl(str(tele))
    summaries = [r for r in rows if r.get("event") == "serve_summary"]
    assert summaries and summaries[-1]["bytes"]["kv_dtype"] == "int8"


def test_telemetry_report_renders_bytes_ab_rows(tmp_path, capsys):
    """Satellite: the report CLI renders decode bytes/token, KV bytes/slot and
    slots-at-budget as A-vs-B rows — the quant artifact renders like the
    prefill and affinity ones."""
    report = _load_tool("telemetry_report")

    def write(path, dtype, bpt, per_slot, slots):
        with open(path, "w") as f:
            f.write(json.dumps({
                "event": "serve_summary", "requests": 4, "ok": 4, "timeout": 0,
                "new_tokens": 64, "wall_s": 1.0, "tokens_per_s": 64.0,
                "bytes": {"kv_dtype": dtype, "quant_policy": "off",
                          "decode_bytes_per_token": bpt,
                          "kv_bytes_per_slot": per_slot,
                          "slots_at_budget": slots}}) + "\n")

    a, b = str(tmp_path / "fp32.jsonl"), str(tmp_path / "int8.jsonl")
    write(a, "model", 1000.0, 4096, 100)
    write(b, "int8", 400.0, 1280, 320)
    assert report.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "decode bytes/tok" in out and "kv bytes/slot" in out
    assert "slots @ budget" in out
    assert "bytes: kv model" in out and "bytes: kv int8" in out


@pytest.mark.slow
def test_bench_decode_analysis_quant_ab_smoke(tmp_path):
    """The --quant-ab artifact generator end to end at a tiny shape: ratios,
    accuracy fields and one-program pins all present and internally coherent."""
    import subprocess

    out = tmp_path / "quant_ab.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "bench_decode_analysis.py"),
         "--seq", "256", "--d-model", "32", "--layers", "1", "--heads", "2",
         "--gen-batch", "2", "--no-bf16", "--quant-ab", "--ab-requests", "4",
         "--ab-new-tokens", "8", "--ab-nll-batch", "2",
         "--curve-chunks", "32,128", "--out", str(out)],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(out.read_text())
    ab = doc["quant_ab"]
    assert ab["decode_bytes_per_token_reduction"] >= 1.8
    assert ab["slots_at_budget_ratio"] >= 1.9
    assert ab["one_program_pins"]["decode_trace_count_ok"]
    assert ab["one_program_pins"]["prefill_trace_counts_ok"]
    assert abs(ab["nll_delta"]) <= ab["nll_delta_bound"]
    assert 0.0 <= ab["token_match_rate"] <= 1.0
