"""ops/paged_attention.py: kernel vs gather reference vs dense contiguous.

The reference must match the contiguous decode attention bitwise on a
contiguously-mapped table (same einsum structure); the Pallas kernel
(interpret mode on CPU) must match the reference allclose-tight — its
online softmax reorders the reduction, so bitwise is not the contract.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
    quant as quant_ops,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
    MASK_VALUE,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.paged_attention import (
    paged_attend,
    paged_attend_reference,
)


def _setup(seed, *, b=3, g=2, rep=2, d=8, ps=4, s=16, quantized=False,
           shuffle=True):
    """Random pool + per-slot table covering the full context, with free
    pages poisoned so any out-of-reservation read shows up."""
    rng = np.random.default_rng(seed)
    p_max = s // ps
    num_pages = 1 + b * p_max + 2          # null + slots + poisoned spares
    kd = np.float32
    k_pool = rng.normal(size=(num_pages, ps, g, d)).astype(kd)
    v_pool = rng.normal(size=(num_pages, ps, g, d)).astype(kd)
    scales = {}
    if quantized:
        kq, ks = quant_ops.quantize_rows(jnp.asarray(k_pool), jnp.int8)
        vq, vs = quant_ops.quantize_rows(jnp.asarray(v_pool), jnp.int8)
        k_pool, v_pool = np.asarray(kq), np.asarray(vq)
        scales = dict(k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs))
    ids = np.arange(1, 1 + b * p_max)
    if shuffle:
        rng.shuffle(ids)                   # non-contiguous page assignment
    table = ids.reshape(b, p_max).astype(np.int32)
    q = rng.normal(size=(b, g, rep, d)).astype(np.float32)
    t = rng.integers(0, s, size=b).astype(np.int32)
    return (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(t), scales)


def _dense_oracle(q, k_pool, v_pool, table, t, *, s, window=0, scales=None):
    """decode_step_slots' attention block on the explicitly gathered view."""
    b, g, rep, d = q.shape
    ps = k_pool.shape[1]
    view = lambda pool: pool[table].reshape(
        (b, table.shape[1] * ps) + pool.shape[2:])[:, :s]
    k_read, v_read = view(k_pool), view(v_pool)
    if scales:
        k_read = quant_ops.dequantize_rows(k_read, view(scales["k_scale"]))
        v_read = quant_ops.dequantize_rows(v_read, view(scales["v_scale"]))
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    pos = jnp.arange(s)[None]
    visible = pos <= t[:, None]
    if window:
        visible &= t[:, None] - pos < window
    scores = jnp.einsum("bgrd,bsgd->bgrs", q * scale, k_read)
    scores = jnp.where(visible[:, None, None, :], scores, MASK_VALUE)
    return jnp.einsum("bgrs,bsgd->bgrd", jax.nn.softmax(scores, -1), v_read)


@pytest.mark.parametrize("window", [0, 5], ids=["full", "window"])
@pytest.mark.parametrize("quantized", [False, True], ids=["fp32", "int8"])
def test_reference_matches_dense_bitwise(window, quantized):
    q, k_pool, v_pool, table, t, scales = _setup(0, quantized=quantized)
    ref = paged_attend_reference(q, k_pool, v_pool, table, t, seq_len=16,
                                 window=window, **scales)
    dense = _dense_oracle(q, k_pool, v_pool, table, t, s=16, window=window,
                          scales=scales or None)
    assert np.array_equal(np.asarray(ref), np.asarray(dense))


@pytest.mark.parametrize("window", [0, 5], ids=["full", "window"])
@pytest.mark.parametrize("quantized", [False, True], ids=["fp32", "int8"])
@pytest.mark.parametrize("rep", [1, 2], ids=["mha", "gqa"])
def test_kernel_matches_reference(window, quantized, rep):
    q, k_pool, v_pool, table, t, scales = _setup(1, rep=rep,
                                                 quantized=quantized)
    ref = paged_attend_reference(q, k_pool, v_pool, table, t, seq_len=16,
                                 window=window, **scales)
    out = paged_attend(q, k_pool, v_pool, table, t, window=window, **scales)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_kernel_ignores_unmapped_pages():
    """Poison every page a slot does NOT own (including the spares) with huge
    values: output must be unchanged — the mask plus the reservation
    invariant keep unowned pages invisible."""
    q, k_pool, v_pool, table, t, _ = _setup(2, shuffle=True)
    out = paged_attend(q, k_pool, v_pool, table, t)
    owned = set(np.asarray(table).ravel().tolist())
    poison_ids = [p for p in range(k_pool.shape[0]) if p not in owned]
    k_np, v_np = np.asarray(k_pool).copy(), np.asarray(v_pool).copy()
    k_np[poison_ids] = 1e9
    v_np[poison_ids] = 1e9
    out2 = paged_attend(q, jnp.asarray(k_np), jnp.asarray(v_np), table, t)
    assert np.array_equal(np.asarray(out), np.asarray(out2))


def test_kernel_t_zero_and_t_max():
    """Edge positions: a slot at t=0 attends over exactly one row; a slot at
    t=S-1 over all of them."""
    q, k_pool, v_pool, table, _, _ = _setup(3, b=2)
    t = jnp.asarray([0, 15], jnp.int32)
    ref = paged_attend_reference(q, k_pool, v_pool, table, t, seq_len=16)
    out = paged_attend(q, k_pool, v_pool, table, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
