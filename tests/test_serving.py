"""Serving engine: continuous batching over the KV-cache decoder.

The two serving invariants, pinned here (tier-1 — these are the smoke contract of
the subsystem, tiny models, deterministic seeds):

1. **Parity** — the slot-engine output is token-identical to sequential
   ``models.lm.generate`` for every request, across MHA/GQA/windowed/RoPE configs
   and a mixed-length request stream (greedy decode, so the comparison is exact).
2. **One program** — serving any mix of requests through ``num_slots`` slots traces
   the decode program exactly once (``engine.trace_count``): admission is data,
   never shape.

Plus the front-end contracts (thread-safe submit, backpressure, deadlines, drain),
the serve-telemetry schema end to end through the load generator and the report
CLI, and a ``slow``-marked sustained open-loop run.
"""

import importlib.util
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
    ContinuousBatchingEngine,
    QueueFull,
    Request,
    RequestQueue,
    SamplingParams,
    Server,
    ServerStopped,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
    filter_logits_per_slot,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (
    load_metrics_jsonl,
)

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)

SMALL = dict(vocab_size=9, seq_len=16, embed_dim=32, num_layers=2, num_heads=4)


def _model(**kw):
    return lm.TransformerLM(**{**SMALL, **kw})


def _params(model, seed=0):
    ids = jnp.zeros((1, model.seq_len), jnp.int32)
    return model.init({"params": jax.random.PRNGKey(seed)}, ids)["params"]


def _mixed_requests(model, n, seed=0):
    """A mixed-length request stream: varying prompt lengths AND output budgets."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(0, model.seq_len // 2))
        reqs.append(Request(
            prompt=rng.integers(0, model.vocab_size - 1,
                                size=plen).astype(np.int32),
            max_new_tokens=int(rng.integers(1, model.seq_len)),
            request_id=i))
    return reqs


def _sequential_reference(model, params, req):
    """What ``generate`` emits for this request, greedy, as a [L] stream."""
    p = len(req.prompt)
    total = min(p + req.max_new_tokens, model.seq_len)
    padded = np.zeros((1, model.seq_len), np.int32)
    padded[0, :p] = req.prompt
    out = lm.generate(model, params, jax.random.PRNGKey(0), batch=1,
                      temperature=0.0, prompt=jnp.asarray(padded), prompt_len=p)
    return np.asarray(out)[0, :total]


# -----------------------------------------------------------------------------------------
# Parity + the one-compilation contract
# -----------------------------------------------------------------------------------------


@pytest.mark.parametrize("cfg,n_req", [
    (dict(), 8),                                  # MHA, the full 8-request mix
    (dict(num_kv_heads=2), 4),                    # GQA (smaller per-slot cache)
    (dict(attention_window=5), 4),                # sliding-window decode mask
    (dict(rope=True), 4),                         # per-slot rotary positions
], ids=["mha", "gqa", "window", "rope"])
def test_engine_greedy_parity_with_sequential_generate(cfg, n_req):
    """Acceptance: the continuous-batched engine is token-identical to sequential
    ``generate`` per request — through FEWER slots than requests, so slots are
    freed and recycled mid-stream — and the decode program compiles exactly once."""
    model = _model(**cfg)
    params = _params(model)
    reqs = _mixed_requests(model, n_req, seed=7)
    engine = ContinuousBatchingEngine(model, params, num_slots=3)
    comps = {c.request.request_id: c for c in engine.run(reqs)}
    assert engine.trace_count == 1
    assert sorted(comps) == list(range(n_req))
    for req in reqs:
        ref = _sequential_reference(model, params, req)
        got = comps[req.request_id]
        assert got.ok and got.prompt_len == len(req.prompt)
        np.testing.assert_array_equal(got.tokens, ref)
        # The prompt prefix survives teacher-forcing verbatim.
        np.testing.assert_array_equal(got.tokens[:len(req.prompt)], req.prompt)


def test_engine_serves_more_requests_than_slots_single_compile():
    """Acceptance: >= 8 concurrent requests of different lengths through fewer
    slots, exactly one decode-program compilation, all completions accounted."""
    model = _model()
    params = _params(model)
    reqs = _mixed_requests(model, 10, seed=3)
    engine = ContinuousBatchingEngine(model, params, num_slots=4)
    comps = engine.run(reqs)
    assert engine.trace_count == 1
    assert len(comps) == 10 and all(c.ok for c in comps)
    assert engine.slot_occupancy is not None and engine.slot_occupancy > 0.5
    lens = {len(c.tokens) for c in comps}
    assert len(lens) > 1                          # genuinely mixed lengths


def test_engine_slot_recycling_matches_fresh_cache():
    """A recycled slot decodes identically to a fresh engine: reset_slots + the
    per-slot mask make slot history invisible to the next occupant."""
    model = _model()
    params = _params(model)
    req = Request(prompt=np.asarray([1, 2, 3], np.int32), max_new_tokens=6,
                  request_id=0)
    fresh = ContinuousBatchingEngine(model, params, num_slots=1)
    first = fresh.run([Request(prompt=np.asarray([5] * 7, np.int32),
                               max_new_tokens=8, request_id=9), req])
    again = ContinuousBatchingEngine(model, params, num_slots=1).run([req])
    np.testing.assert_array_equal(
        next(c for c in first if c.request.request_id == 0).tokens,
        again[0].tokens)


def test_engine_admission_validation():
    model = _model()
    engine = ContinuousBatchingEngine(model, _params(model), num_slots=2)
    with pytest.raises(ValueError, match="seq_len"):
        engine.validate(Request(prompt=np.zeros(model.seq_len, np.int32),
                                max_new_tokens=1))
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.validate(Request(prompt=np.zeros(2, np.int32), max_new_tokens=0))
    with pytest.raises(ValueError, match="top_p"):
        engine.validate(Request(prompt=np.zeros(2, np.int32), max_new_tokens=1,
                                sampling=SamplingParams(top_p=0.0)))
    with pytest.raises(ValueError, match="occupied"):
        engine.admit(0, Request(prompt=np.zeros(1, np.int32), max_new_tokens=2))
        engine.admit(0, Request(prompt=np.zeros(1, np.int32), max_new_tokens=2))


def test_filter_logits_per_slot_matches_static_filter():
    """The data-driven per-row filter agrees with models.lm.filter_logits row by
    row for every (top_k, top_p) policy in the batch mix."""
    rng = np.random.default_rng(0)
    lp = jnp.asarray(jax.nn.log_softmax(
        jnp.asarray(rng.normal(size=(6, 9)).astype(np.float32)), axis=-1))
    # (2, 0.7) is the compose-order probe: the nucleus must be taken over the
    # top-k-RENORMALIZED distribution (filter_logits applies k first), which
    # keeps strictly fewer entries than a nucleus over the raw distribution.
    policies = [(0, 1.0), (3, 1.0), (0, 0.6), (2, 0.8), (2, 0.7), (1, 0.3)]
    got = filter_logits_per_slot(
        lp, jnp.asarray([k for k, _ in policies], jnp.int32),
        jnp.asarray([p for _, p in policies], jnp.float32))
    for row, (k, p) in enumerate(policies):
        want = lm.filter_logits(lp[row:row + 1], top_k=k, top_p=p)
        np.testing.assert_allclose(np.asarray(got[row:row + 1]),
                                   np.asarray(want), rtol=1e-6)


def test_engine_mixed_sampling_policies_one_compile():
    """Greedy and sampled requests share one program; sampled output stays in the
    pixel vocabulary (BOS never emitted) and within the requested bounds."""
    model = _model()
    params = _params(model)
    reqs = [
        Request(prompt=np.asarray([1, 2], np.int32), max_new_tokens=5,
                request_id=0),                                   # greedy
        Request(prompt=np.zeros(0, np.int32), max_new_tokens=5, request_id=1,
                sampling=SamplingParams(temperature=1.0, top_k=3)),
        Request(prompt=np.asarray([4], np.int32), max_new_tokens=5, request_id=2,
                sampling=SamplingParams(temperature=0.7, top_p=0.9)),
    ]
    engine = ContinuousBatchingEngine(model, params, num_slots=3, seed=11)
    comps = {c.request.request_id: c for c in engine.run(reqs)}
    assert engine.trace_count == 1
    for c in comps.values():
        assert c.ok
        assert c.tokens.max() < model.vocab_size - 1             # BOS masked
    np.testing.assert_array_equal(
        comps[0].tokens, _sequential_reference(model, params, reqs[0]))


# -----------------------------------------------------------------------------------------
# Scheduler: backpressure + queued deadlines
# -----------------------------------------------------------------------------------------


def test_request_queue_backpressure_and_deadline_expiry():
    q = RequestQueue(max_pending=2)
    r = lambda i, dl=None: Request(prompt=np.zeros(0, np.int32), max_new_tokens=1,
                                   request_id=i, deadline_s=dl)
    q.submit(r(0))
    q.submit(r(1, dl=-1.0))                      # already expired (monotonic < 0)
    with pytest.raises(QueueFull):
        q.submit(r(2))
    admitted, expired = q.take(now=time.monotonic(), max_n=4)
    assert [x.request_id for x in admitted] == [0]
    assert [x.request_id for x in expired] == [1]
    q.close()
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(r(3))


def test_request_queue_snapshot_and_requeue():
    """The snapshot is the backpressure/health signal (depth, oldest-age,
    cumulative rejects), and requeue is the router's redispatch door: front of
    the line, allowed even after close (the request was already accepted)."""
    q = RequestQueue(max_pending=2)
    r = lambda i, arr=None: Request(prompt=np.zeros(0, np.int32),
                                    max_new_tokens=1, request_id=i,
                                    arrival_s=arr)
    snap = q.snapshot()
    assert (snap["depth"], snap["rejected"], snap["oldest_age_s"]) == (0, 0, None)
    now = time.monotonic()
    q.submit(r(0, arr=now - 2.0))
    q.submit(r(1, arr=now))
    for _ in range(3):
        with pytest.raises(QueueFull):
            q.submit(r(9))
    snap = q.snapshot(now=now)
    assert snap["depth"] == 2 and snap["rejected"] == 3
    assert snap["oldest_age_s"] == pytest.approx(2.0)      # head waited longest
    assert snap["max_pending"] == 2 and not snap["closed"]
    q.close()
    q.requeue(r(7))                    # redispatch beats both close and capacity
    admitted, _ = q.take(now=now, max_n=1)
    assert admitted[0].request_id == 7                     # front of the line
    assert q.snapshot()["closed"]


# -----------------------------------------------------------------------------------------
# Server: concurrency, timeouts, drain, telemetry
# -----------------------------------------------------------------------------------------


def _tiny_server(tmp_path=None, *, num_slots=4, max_pending=0, cfg=(),
                 **server_kw):
    model = _model(num_layers=1, embed_dim=16, num_heads=2, **dict(cfg))
    engine = ContinuousBatchingEngine(model, _params(model), num_slots=num_slots)
    telemetry = str(tmp_path / "serve.jsonl") if tmp_path is not None else None
    return Server(engine, max_pending=max_pending, telemetry=telemetry,
                  **server_kw)


def test_server_concurrent_submitters_all_complete_one_compile(tmp_path):
    """8+ requests from 4 submitter threads through 4 slots: every future
    resolves ok, latency fields are populated, one decode compilation."""
    server = _tiny_server(tmp_path).start()
    futures: list = []
    flock = threading.Lock()

    def client(base):
        for i in range(3):
            fut = server.submit(np.arange(base + i, dtype=np.int32) % 8,
                                max_new_tokens=3 + (base + i) % 4)
            with flock:
                futures.append(fut)

    threads = [threading.Thread(target=client, args=(b,)) for b in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    comps = [f.result(timeout=120) for f in futures]
    server.stop()
    assert len(comps) == 12 and all(c.ok for c in comps)
    assert server.engine.trace_count == 1
    for c in comps:
        assert c.queue_wait_s >= 0 and c.ttft_s >= c.queue_wait_s
        assert c.e2e_s >= c.ttft_s
    rows = load_metrics_jsonl(str(tmp_path / "serve.jsonl"))
    assert [r["event"] for r in rows[:2]] == ["manifest", "serve_config"]
    serve = [r for r in rows if r["event"] == "serve"]
    assert len(serve) == 12
    assert all(r["finish"] == "ok" and r["ttft_s"] >= 0 for r in serve)
    summary = [r for r in rows if r["event"] == "serve_summary"]
    assert len(summary) == 1 and summary[0]["requests"] == 12
    assert summary[0]["tokens_per_s"] > 0
    assert set(summary[0]["ttft_s"]) == {"p50", "p95", "p99"}


def test_server_backpressure_raises_queue_full():
    server = _tiny_server(max_pending=2)         # not started: queue can only grow
    server.submit([1], max_new_tokens=2)
    server.submit([1], max_new_tokens=2)
    with pytest.raises(QueueFull):
        server.submit([1], max_new_tokens=2)
    server.start()
    server.stop()                                # drains the two accepted requests


def test_server_queued_deadline_expires_without_decoding(tmp_path):
    """A request whose deadline passes while queued resolves as a timeout with
    zero tokens; requests ahead of it still complete."""
    server = _tiny_server(tmp_path, num_slots=1)
    fa = server.submit([1, 2], max_new_tokens=4)
    fb = server.submit([3], max_new_tokens=4, timeout_s=0.0)
    time.sleep(0.01)                             # deadline passes pre-start
    server.start()
    a, b = fa.result(timeout=120), fb.result(timeout=120)
    server.stop()
    assert a.ok and len(a.tokens) == 6
    assert b.finish == "timeout" and b.new_tokens == 0
    rows = load_metrics_jsonl(str(tmp_path / "serve.jsonl"))
    finishes = {r["request_id"]: r["finish"] for r in rows
                if r["event"] == "serve"}
    assert finishes == {0: "ok", 1: "timeout"}


def test_server_mid_decode_deadline_returns_partial_tokens():
    server = _tiny_server(num_slots=1, default_timeout_s=None)
    # Long request with an immediate deadline admitted into the slot: the engine
    # expires it mid-decode on a later loop pass, keeping the partial stream.
    fut = server.submit(np.zeros(0, np.int32),
                        max_new_tokens=SMALL["seq_len"] - 1, timeout_s=0.2)
    server.start()
    comp = fut.result(timeout=120)
    server.stop()
    # Either it finished fast (ok, tiny model) or timed out with partial output —
    # on both paths the stream length is bounded and fields are consistent.
    assert comp.finish in ("ok", "timeout")
    assert len(comp.tokens) <= SMALL["seq_len"] - 1
    if comp.finish == "timeout":
        assert comp.new_tokens == len(comp.tokens)


def test_server_graceful_drain_completes_accepted_work():
    server = _tiny_server(num_slots=2)
    futures = [server.submit([i % 5], max_new_tokens=3) for i in range(6)]
    server.start()
    server.stop(drain=True)                      # returns only after the drain
    assert all(f.done() for f in futures)
    assert all(f.result().ok for f in futures)
    with pytest.raises(RuntimeError, match="closed"):
        server.submit([1], max_new_tokens=2)


def test_server_stop_without_drain_expires_outstanding_work():
    server = _tiny_server(num_slots=1)
    futures = [server.submit(np.zeros(0, np.int32),
                             max_new_tokens=SMALL["seq_len"] - 1)
               for _ in range(3)]
    server.start()
    server.stop(drain=False)
    comps = [f.result(timeout=120) for f in futures]
    assert all(c.finish in ("ok", "timeout") for c in comps)
    assert any(c.finish == "timeout" for c in comps)


def test_server_drain_timeout_fails_pending_futures_with_server_stopped(tmp_path):
    """Regression (PR 6 satellite): stop(drain=True, timeout=...) on a drain
    that cannot finish in time must fail the still-pending futures with the
    typed ServerStopped error — never leave callers hung on Future.result()."""
    server = _tiny_server(tmp_path, num_slots=1)
    server.start()
    rng = np.random.default_rng(5)
    futures = [server.submit(rng.integers(0, 8, size=3).astype(np.int32),
                             max_new_tokens=10) for _ in range(12)]
    with pytest.raises(ServerStopped):
        # A 12-request drain through one slot cannot finish in 1e-4 s.
        server.stop(timeout=1e-4)
    # Every future is resolved NOW (result or typed failure), no hung waiters.
    stopped = 0
    for f in futures:
        assert f.done()
        try:
            f.result(timeout=0)
        except ServerStopped:
            stopped += 1
    assert stopped >= 1
    # ServerStopped subclasses TimeoutError: pre-existing catch sites still work.
    assert issubclass(ServerStopped, TimeoutError)
    # The loop thread was reaped and the drain-time summary still written.
    assert server._thread is None
    rows = load_metrics_jsonl(str(tmp_path / "serve.jsonl"))
    summaries = [r for r in rows if r["event"] == "serve_summary"]
    assert len(summaries) == 1
    # Satellite: the summary carries the admission queue's snapshot.
    assert summaries[0]["queue"]["rejected"] == 0
    assert "depth" in summaries[0]["queue"]


def test_redispatch_replay_on_fresh_engine_is_token_identical():
    """The correctness keystone of at-least-once redispatch (PR 6): a greedy
    request that died mid-decode on one engine and is replayed from scratch on
    a FRESH engine yields a token-identical stream — greedy decode consults no
    RNG and no cross-request state, so replay is idempotent."""
    model = _model()
    params = _params(model)
    req = Request(prompt=np.arange(1, 6, dtype=np.int32), max_new_tokens=8,
                  request_id=0)
    ref = _sequential_reference(model, params, req)

    # Engine A: admit and decode PARTWAY (strictly between prompt end and
    # completion), then abandon — the crash-mid-decode analog.
    crashed = ContinuousBatchingEngine(model, params, num_slots=2)
    crashed.admit(0, req)
    for _ in range(3):
        assert not crashed.step()           # mid-flight: nothing finished yet
    # Engine B: a fresh engine (what a restarted replica is) replays fully.
    fresh = ContinuousBatchingEngine(model, params, num_slots=2, seed=123)
    replay = Request(prompt=req.prompt, max_new_tokens=req.max_new_tokens,
                     request_id=0)
    comps = fresh.run([replay])
    assert comps[0].ok
    np.testing.assert_array_equal(comps[0].tokens, ref)


# -----------------------------------------------------------------------------------------
# Load generator + report rendering (the CLI walkthrough, in miniature)
# -----------------------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_LOADGEN_ARGS = [
    "--seq-len", "16", "--embed-dim", "16", "--num-layers", "1",
    "--num-heads", "2", "--num-levels", "8", "--max-new-tokens", "5",
    "--prompt-lens", "0,3,6", "--seed", "0",
]


def test_loadgen_closed_loop_smoke_and_report_render(tmp_path, capsys):
    """Acceptance: the load generator against the in-process server emits a serve
    JSONL that the report CLI renders with p50/p95/p99 TTFT and tokens/s."""
    loadgen = _load_tool("serve_loadgen")
    report = _load_tool("telemetry_report")
    path = str(tmp_path / "serve.jsonl")
    rc = loadgen.main(["--requests", "8", "--mode", "closed",
                       "--concurrency", "3", "--num-slots", "3",
                       "--telemetry", path, *_LOADGEN_ARGS])
    assert rc == 0
    out = capsys.readouterr().out
    assert "8 completed (8 ok" in out and "decode compilations 1" in out
    rows = load_metrics_jsonl(path)
    assert sum(r["event"] == "serve" for r in rows) == 8
    rc = report.main([path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serve: 8 requests" in out
    assert "p50" in out and "p95" in out and "p99" in out
    assert "ttft_s" in out and "tpot_s" in out and "tokens/s" in out


def test_loadgen_open_loop_a_vs_b_comparison(tmp_path, capsys):
    loadgen = _load_tool("serve_loadgen")
    report = _load_tool("telemetry_report")
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, slots in ((a, "1"), (b, "4")):
        rc = loadgen.main(["--requests", "6", "--mode", "open", "--rate", "200",
                           "--num-slots", slots, "--telemetry", path,
                           *_LOADGEN_ARGS])
        assert rc == 0
    capsys.readouterr()
    assert report.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "B/A" in out and "serve tokens/s" in out and "ttft_s p50" in out


def test_loadgen_traced_run_carries_trace_in_summary(tmp_path, capsys):
    """--trace-dir: the loadgen is the trace origin (client spans in
    loadgen.jsonl), the in-process server writes server.jsonl, and
    --summary-json records the trace dir plus span-derived critical-path
    percentiles whose TTFT reconciles with the serve events' own."""
    import json

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        trace,
    )

    loadgen = _load_tool("serve_loadgen")
    trace_dir = str(tmp_path / "trace")
    summary = tmp_path / "summary.json"
    rc = loadgen.main(["--requests", "8", "--mode", "closed",
                       "--concurrency", "3", "--num-slots", "3",
                       "--telemetry", str(tmp_path / "serve.jsonl"),
                       "--trace-dir", trace_dir,
                       "--summary-json", str(summary), *_LOADGEN_ARGS])
    assert rc == 0
    out = capsys.readouterr().out
    assert "trace: 8 traces" in out and "0 orphans" in out
    assert sorted(os.listdir(trace_dir)) == ["loadgen.jsonl", "server.jsonl"]

    spans, _ = trace.read_spans([trace_dir])
    ts = trace.summarize_traces(spans)
    assert ts["traces"] == 8 and ts["orphans"] == 0
    # Every trace's outermost span is the loadgen's client span.
    clients = [s for s in spans if s["name"] == "client"]
    assert len(clients) == 8 and all(s["proc"] == "loadgen" for s in clients)
    assert {s["name"] for s in spans} >= {"client", "queue_wait", "decode",
                                          "resolve"}

    doc = json.loads(summary.read_text())
    tr = doc["trace"]
    assert tr["dir"] == trace_dir and tr["orphans"] == 0
    assert tr["segments"]["decode_tail"]["p50"] > 0
    rec = tr["ttft_reconciliation"]
    # The span plane and the latency telemetry measure the same reality.
    assert rec["source"] == "serve"
    assert 0.8 < rec["p50_ratio"] < 1.25


@pytest.mark.slow
def test_loadgen_sustained_open_loop_with_timeouts(tmp_path):
    """Sustained open-loop load at a rate the engine may not keep up with:
    deadlines and backpressure engage, the run drains cleanly, and the telemetry
    stays schema-valid under churn."""
    loadgen = _load_tool("serve_loadgen")
    path = str(tmp_path / "sustained.jsonl")
    rc = loadgen.main(["--requests", "60", "--mode", "open", "--rate", "300",
                       "--num-slots", "2", "--max-pending", "8",
                       "--timeout-s", "5.0", "--telemetry", path,
                       *_LOADGEN_ARGS])
    assert rc == 0
    rows = load_metrics_jsonl(path)
    serve = [r for r in rows if r["event"] == "serve"]
    assert serve and all(r["finish"] in ("ok", "timeout") for r in serve)
    summary = [r for r in rows if r["event"] == "serve_summary"]
    assert len(summary) == 1
    assert summary[0]["requests"] == len(serve)
    assert summary[0]["ok"] + summary[0]["timeout"] == summary[0]["requests"]
