"""Unit tests for the resilience layer: fault-spec parsing, heartbeats, preemption,
the supervisor's retry/classify loop (against tiny jax-free child processes), the
versioned checkpoint store's manifest/retention/newest-valid selection, and the
checkpoint-corruption edges the supervisor depends on. The real 2-process fleet
integration lives in test_resilience_fleet.py."""

import json
import os
import signal
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import resilience
from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    faults, heartbeat, preemption,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    supervisor as sup,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.launch import launch
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    TrainState,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "csed_514_project_distributed_training_using_pytorch_tpu"


def make_state(step: int = 4) -> TrainState:
    return TrainState(params={"w": np.arange(4, dtype=np.float32) + step},
                      velocity={"w": np.zeros(4, dtype=np.float32)},
                      step=np.int32(step), ema=None)


# =========================================================================================
# faults: spec parsing + triggers
# =========================================================================================


class TestFaults:
    def test_parse_spec(self):
        fs = faults._parse("kill:proc=1,step=8,exit=9,flag=/tmp/f;"
                           "torn:match=ckpt_;freeze:epoch=2;preempt:")
        assert [f.kind for f in fs] == ["kill", "torn", "freeze", "preempt"]
        assert fs[0].proc == 1 and fs[0].step == 8 and fs[0].exit == 9
        assert fs[1].match == "ckpt_" and fs[2].epoch == 2

    def test_parse_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults._parse("explode:step=1")
        with pytest.raises(ValueError, match="unknown fault key"):
            faults._parse("kill:when=later")

    def test_parse_rejects_untriggerable_torn_specs(self):
        # step/epoch keys never fire on the write path — fail loudly at parse time
        # instead of letting a test arranged that way pass vacuously.
        with pytest.raises(ValueError, match="torn faults trigger by path match"):
            faults._parse("torn:match=ckpt,step=8")
        with pytest.raises(ValueError, match="needs a match"):
            faults._parse("torn:flag=/tmp/f")

    def test_inactive_without_env(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        assert not faults.active()
        faults.on_tick(step=100, epoch=100)        # must be a no-op, not a crash
        assert not faults.heartbeat_frozen(step=100, epoch=100)
        assert faults.mangle_write("ckpt", b"data") == b"data"

    def test_freeze_trigger_thresholds(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "freeze:step=10")
        assert not faults.heartbeat_frozen(step=9, epoch=0)
        assert faults.heartbeat_frozen(step=10, epoch=0)
        monkeypatch.setenv(faults.ENV_VAR, "freeze:proc=3,step=0")
        assert not faults.heartbeat_frozen(step=5, epoch=0)   # we are proc 0

    def test_torn_truncates_matching_write_once(self, monkeypatch, tmp_path):
        flag = tmp_path / "torn"
        monkeypatch.setenv(faults.ENV_VAR, f"torn:match=target,flag={flag}")
        assert faults.mangle_write("/x/other.msgpack", b"12345678") == b"12345678"
        assert faults.mangle_write("/x/target.msgpack", b"12345678") == b"1234"
        # flag claimed: the same write path is clean on the next (restarted) try
        assert faults.mangle_write("/x/target.msgpack", b"12345678") == b"12345678"

    def test_kill_fault_fires_once_across_processes(self, monkeypatch, tmp_path):
        """The kill fault hard-exits the process, so probe it in a child; the flag
        marker must keep a second (restarted) child alive at the same step."""
        flag = tmp_path / "killflag"
        env = dict(os.environ,
                   RESILIENCE_FAULTS=f"kill:proc=0,step=5,exit=9,flag={flag}")
        prog = (f"from {PKG}.resilience import faults\n"
                "faults.on_tick(step=4, epoch=0)\n"     # below threshold: no fire
                "faults.on_tick(step=5, epoch=0)\n")
        p = subprocess.run([sys.executable, "-c", prog], env=env, cwd=REPO,
                           timeout=60)
        assert p.returncode == 9
        assert flag.with_name(flag.name + ".p0").exists()
        p = subprocess.run([sys.executable, "-c", prog], env=env, cwd=REPO,
                           timeout=60)
        assert p.returncode == 0                         # marker: fired once, ever

    def test_preempt_fault_sets_handler_latch(self, monkeypatch, tmp_path):
        monkeypatch.setenv(faults.ENV_VAR, f"preempt:step=3,flag={tmp_path / 'f'}")
        with preemption.PreemptionHandler() as h:
            faults.on_tick(step=2, epoch=0)
            assert not h.requested
            faults.on_tick(step=3, epoch=0)
            time.sleep(0.05)                             # let the signal deliver
            assert h.requested and h.signum == signal.SIGTERM


# =========================================================================================
# heartbeat: beats, staleness, clearing
# =========================================================================================


class TestHeartbeat:
    def test_beat_roundtrip(self, tmp_path):
        hb = heartbeat.HeartbeatWriter(str(tmp_path), process_index=2)
        hb.beat(step=7, epoch=1)
        beats = heartbeat.read_heartbeats(str(tmp_path))
        assert beats[2]["step"] == 7 and beats[2]["epoch"] == 1
        assert beats[2]["status"] == heartbeat.STATUS_RUNNING
        assert abs(beats[2]["time"] - time.time()) < 5

    def test_staleness_uses_fleet_start_before_first_beat(self, tmp_path):
        # Process 0 beat just now; process 1 never did — its silence is measured
        # from fleet start (``since``), so an old fleet is stale but a young one
        # still has its startup grace.
        heartbeat.HeartbeatWriter(str(tmp_path), process_index=0).beat(step=1,
                                                                       epoch=0)
        now = time.time()
        assert heartbeat.stale_processes(str(tmp_path), num_processes=2,
                                         timeout_s=30, since=now - 50,
                                         now=now + 1) == [1]
        assert heartbeat.stale_processes(str(tmp_path), num_processes=2,
                                         timeout_s=30, since=now - 20,
                                         now=now + 1) == []

    def test_old_attempts_beats_never_vouch(self, tmp_path):
        old = time.time() - 100
        heartbeat.HeartbeatWriter(str(tmp_path), process_index=0).beat(step=9,
                                                                       epoch=2)
        # A beat written BEFORE this attempt started is clamped to fleet start.
        now = time.time()
        assert heartbeat.stale_processes(str(tmp_path), num_processes=1,
                                         timeout_s=5, since=now + 50,
                                         now=now + 60) == [0]
        del old

    def test_clear(self, tmp_path):
        heartbeat.HeartbeatWriter(str(tmp_path), process_index=0).beat(step=1,
                                                                       epoch=0)
        heartbeat.clear(str(tmp_path))
        assert heartbeat.read_heartbeats(str(tmp_path)) == {}


# =========================================================================================
# preemption: handler latch + Preempted
# =========================================================================================


class TestPreemption:
    def test_handler_latches_and_restores(self):
        before = signal.getsignal(signal.SIGTERM)
        with preemption.PreemptionHandler() as h:
            assert not h.requested
            signal.raise_signal(signal.SIGTERM)
            assert h.requested and h.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before

    def test_preempted_carries_step_and_checkpoint(self):
        e = preemption.Preempted(12, "results/model.ckpt")
        assert e.step == 12 and e.checkpoint == "results/model.ckpt"
        assert "12" in str(e) and preemption.EXIT_PREEMPTED == 75


# =========================================================================================
# RunHooks: the trainers' wiring surface
# =========================================================================================


class TestRunHooks:
    def test_inactive_hooks_never_touch_state(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        rt = resilience.RunHooks()

        class Untouchable:
            @property
            def step(self):                     # zero-cost-off contract: no sync
                raise AssertionError("flag-off tick read state.step")

        rt.epoch_tick(Untouchable(), 0)
        rt.check_preempt(epoch=0, state=Untouchable())   # no handler: no-op

    def test_tick_beats_and_freeze_fault_suppresses(self, monkeypatch, tmp_path):
        rt = resilience.RunHooks(heartbeat_dir=str(tmp_path), process_index=0)
        state = types.SimpleNamespace(step=np.int32(3))
        rt.epoch_tick(state, epoch=0)
        assert heartbeat.read_heartbeats(str(tmp_path))[0]["step"] == 3
        monkeypatch.setenv(faults.ENV_VAR, "freeze:step=4")
        rt.epoch_tick(types.SimpleNamespace(step=np.int32(4)), epoch=1)
        assert heartbeat.read_heartbeats(str(tmp_path))[0]["step"] == 3  # frozen

    def test_check_preempt_saves_emits_and_raises(self, tmp_path):
        rt = resilience.RunHooks(heartbeat_dir=str(tmp_path),
                                 handle_preemption=True)
        try:
            saved = []
            signal.raise_signal(signal.SIGTERM)
            with pytest.raises(resilience.Preempted) as ei:
                rt.check_preempt(epoch=2, state=types.SimpleNamespace(step=8),
                                 checkpoint="ck", save=lambda: saved.append(1))
            assert saved == [1]
            assert ei.value.step == 8 and ei.value.checkpoint == "ck"
            beats = heartbeat.read_heartbeats(str(tmp_path))
            assert beats[0]["status"] == heartbeat.STATUS_PREEMPTED
        finally:
            rt.preemption.uninstall()


# =========================================================================================
# versioned checkpoint store: manifest, GC, newest-valid
# =========================================================================================


class TestVersionedStore:
    def test_retention_gc(self, tmp_path):
        store = str(tmp_path / "store")
        for step in (4, 8, 12):
            checkpoint.save_versioned(store, make_state(step), keep=2)
        files = sorted(f for f in os.listdir(store) if f.startswith("ckpt_"))
        assert files == ["ckpt_00000008.msgpack", "ckpt_00000012.msgpack"]
        entries = checkpoint.load_manifest(store)["entries"]
        assert [e["step"] for e in entries] == [8, 12]
        assert all(e["sha256"] and e["bytes"] > 0 for e in entries)

    def test_newest_valid_skips_torn_write(self, tmp_path):
        store = str(tmp_path / "store")
        for step in (4, 8):
            checkpoint.save_versioned(store, make_state(step), keep=3)
        newest = os.path.join(store, checkpoint.versioned_name(8))
        data = open(newest, "rb").read()
        with open(newest, "wb") as f:                  # torn write, manifest intact
            f.write(data[:len(data) // 2])
        picked = checkpoint.newest_valid_checkpoint(store)
        assert picked == os.path.join(store, checkpoint.versioned_name(4))
        # the survivor actually restores
        restored = checkpoint.restore_train_state(picked, make_state(0))
        assert int(restored.step) == 4

    def test_newest_valid_none_when_all_torn(self, tmp_path):
        store = str(tmp_path / "store")
        checkpoint.save_versioned(store, make_state(4), keep=3)
        path = os.path.join(store, checkpoint.versioned_name(4))
        with open(path, "wb") as f:
            f.write(b"xx")
        assert checkpoint.newest_valid_checkpoint(store) is None
        assert checkpoint.newest_valid_checkpoint(str(tmp_path / "absent")) is None

    def test_manifestless_dir_falls_back_to_decode_validation(self, tmp_path):
        store = str(tmp_path / "store")
        checkpoint.save_versioned(store, make_state(4), keep=3)
        checkpoint.save_versioned(store, make_state(8), keep=3)
        os.remove(os.path.join(store, checkpoint.MANIFEST_NAME))
        with open(os.path.join(store, checkpoint.versioned_name(8)), "wb") as f:
            f.write(b"torn")
        assert checkpoint.newest_valid_checkpoint(store) == os.path.join(
            store, checkpoint.versioned_name(4))

    def test_torn_fault_is_caught_by_manifest_scan(self, monkeypatch, tmp_path):
        """End-to-end inside one process: an armed torn fault corrupts the write,
        but the manifest checksum (computed pre-write) refuses it on scan."""
        store = str(tmp_path / "store")
        checkpoint.save_versioned(store, make_state(4), keep=3)
        monkeypatch.setenv(faults.ENV_VAR, "torn:match=ckpt_00000008")
        checkpoint.save_versioned(store, make_state(8), keep=3)
        monkeypatch.delenv(faults.ENV_VAR)
        assert [e["step"] for e in checkpoint.load_manifest(store)["entries"]] \
            == [4, 8]
        assert checkpoint.newest_valid_checkpoint(store) == os.path.join(
            store, checkpoint.versioned_name(4))


# =========================================================================================
# checkpoint corruption + resume edges (satellites)
# =========================================================================================


class TestCheckpointEdges:
    def test_restore_corrupt_full_checkpoint_is_crisp(self, tmp_path):
        path = str(tmp_path / "model.ckpt")
        checkpoint.save_train_state(path, make_state(4))
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:len(data) // 2])
        with pytest.raises(checkpoint.CheckpointCorrupt, match="model.ckpt"):
            checkpoint.restore_train_state(path, make_state(0))

    def test_restore_corrupt_sharded_checkpoint_is_crisp(self, tmp_path):
        import jax
        d = str(tmp_path / "sharded")
        state = TrainState(params={"w": jax.numpy.arange(4, dtype=np.float32)},
                           velocity={"w": jax.numpy.zeros(4)},
                           step=jax.numpy.int32(4), ema=None)
        checkpoint.save_train_state_sharded(d, state)
        shard = os.path.join(d, "shards_p0.msgpack")
        data = open(shard, "rb").read()
        with open(shard, "wb") as f:
            f.write(data[:len(data) // 2])
        with pytest.raises(checkpoint.CheckpointCorrupt, match="shards_p0"):
            checkpoint.restore_train_state_sharded(d, state)

    def test_restore_for_resume_mid_epoch_warning(self, tmp_path):
        path = str(tmp_path / "model.ckpt")
        checkpoint.save_train_state(path, make_state(5))
        state, start_epoch, warning = checkpoint.restore_for_resume(
            path, make_state(0), process_index=0, process_count=1,
            steps_per_epoch=4)
        assert int(state.step) == 5 and start_epoch == 1
        assert warning is not None and "mid-epoch" in warning
        # whole-epoch checkpoints resume silently
        checkpoint.save_train_state(path, make_state(8))
        _, start_epoch, warning = checkpoint.restore_for_resume(
            path, make_state(0), process_index=0, process_count=1,
            steps_per_epoch=4)
        assert start_epoch == 2 and warning is None

    def test_box_subtract_degenerate_and_overlap(self):
        bs = checkpoint._box_subtract
        assert bs((), ()) == []                        # 0-d scalar: any cut removes
        box = ((0, 4), (0, 4))
        assert bs(box, ((4, 8), (0, 4))) == [box]      # disjoint: survives whole
        assert bs(box, ((0, 4), (0, 4))) == []         # exact cover
        assert bs(box, ((0, 4), (2, 2))) == [box]      # empty cut: no-op
        pieces = bs(box, ((1, 3), (1, 3)))             # interior cut: ring of 4
        assert len(pieces) == 4
        covered = np.zeros((4, 4), bool)
        for p in pieces:
            region = tuple(slice(lo, hi) for lo, hi in p)
            assert not covered[region].any()           # disjointness
            covered[region] = True
        covered[1:3, 1:3] = True
        assert covered.all()                           # exact complement

    def test_overlapping_cuts_do_not_double_remove(self):
        bs = checkpoint._box_subtract
        boxes = [((0, 8),)]
        for cut in [((0, 5),), ((3, 8),)]:             # overlapping cuts
            boxes = [p for b in boxes for p in bs(b, cut)]
        assert boxes == []                             # covered exactly once-ish


# =========================================================================================
# supervisor: classify + restart against tiny jax-free children
# =========================================================================================


def _read_events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestSupervisor:
    def test_restarts_until_success(self, tmp_path):
        cnt = tmp_path / "attempts"
        script = (f"import os, sys; p = {str(cnt)!r}\n"
                  "n = int(open(p).read()) if os.path.exists(p) else 0\n"
                  "open(p, 'w').write(str(n + 1))\n"
                  "sys.exit(0 if n >= 2 else 7)\n")
        cfg = sup.SupervisorConfig(num_processes=1, max_restarts=5, backoff_s=0.0,
                                   poll_s=0.01,
                                   telemetry=str(tmp_path / "sup.jsonl"))
        res = sup.supervise(["-c", script], cfg)
        assert (res.status, res.exit_code) == ("ok", 0)
        assert res.attempts == 3 and res.restarts == 2
        events = _read_events(tmp_path / "sup.jsonl")
        restarts = [e for e in events if e["event"] == "restart"]
        assert len(restarts) == 2
        assert all(e["reason"] == "crash" and e["exit_code"] == 7 for e in restarts)
        assert events[-1]["event"] == "supervise_summary"
        assert events[-1]["status"] == "ok"

    def test_all_workers_crashing_is_never_ok(self, tmp_path):
        """Both workers dying (even between supervisor polls) must classify as a
        crash, not slip through the drained-fleet path as success."""
        cfg = sup.SupervisorConfig(num_processes=2, max_restarts=1, backoff_s=0.0,
                                   poll_s=0.01)
        res = sup.supervise(["-c", "import sys; sys.exit(7)"], cfg)
        assert (res.status, res.exit_code) == ("failed", 7)
        assert res.attempts == 2

    def test_retry_budget_exhausted(self, tmp_path):
        cfg = sup.SupervisorConfig(num_processes=1, max_restarts=1, backoff_s=0.0,
                                   poll_s=0.01)
        res = sup.supervise(["-c", "import sys; sys.exit(5)"], cfg)
        assert (res.status, res.exit_code) == ("failed", 5)
        assert res.attempts == 2 and res.restarts == 1

    def test_preempted_child_is_resumable_not_failed(self, tmp_path):
        cfg = sup.SupervisorConfig(num_processes=1, max_restarts=3, backoff_s=0.0,
                                   poll_s=0.01)
        res = sup.supervise(
            ["-c", f"import sys; sys.exit({preemption.EXIT_PREEMPTED})"], cfg)
        assert (res.status, res.exit_code) == ("preempted", 75)
        assert res.restarts == 0                      # no retry burned

    def test_hung_fleet_detected_by_heartbeat_staleness(self, tmp_path):
        hb_dir = tmp_path / "hb"
        cfg = sup.SupervisorConfig(num_processes=1, max_restarts=1, backoff_s=0.0,
                                   poll_s=0.05, heartbeat_dir=str(hb_dir),
                                   heartbeat_timeout_s=1.0,
                                   telemetry=str(tmp_path / "sup.jsonl"))
        t0 = time.monotonic()
        res = sup.supervise(["-c", "import time; time.sleep(120)"], cfg)
        assert res.status == "failed"
        assert res.exit_code == sup.EXIT_TORN_DOWN
        assert time.monotonic() - t0 < 60             # detected, not waited out
        restarts = [e for e in _read_events(tmp_path / "sup.jsonl")
                    if e["event"] == "restart"]
        assert len(restarts) == 1 and restarts[0]["reason"] == "hung"

    def test_resumes_from_newest_valid_checkpoint(self, tmp_path):
        store = tmp_path / "store"
        checkpoint.save_versioned(str(store), make_state(4), keep=3)
        checkpoint.save_versioned(str(store), make_state(8), keep=3)
        newest = store / checkpoint.versioned_name(8)
        data = newest.read_bytes()
        newest.write_bytes(data[:len(data) // 2])     # torn: must be skipped
        out = tmp_path / "argv.json"
        script = (f"import json, sys; json.dump(sys.argv[1:], open({str(out)!r}, 'w'))")
        cfg = sup.SupervisorConfig(num_processes=1, max_restarts=0,
                                   checkpoint_dir=str(store), poll_s=0.01)
        res = sup.supervise(["-c", script], cfg)
        assert res.status == "ok"
        argv = json.load(open(out))
        assert argv[-2:] == ["--resume-from",
                             str(store / checkpoint.versioned_name(4))]
        assert res.resume_history == [str(store / checkpoint.versioned_name(4))]


# =========================================================================================
# launcher: fail-fast flag (satellite) + CLI smokes (satellite)
# =========================================================================================


class TestFailFast:
    CMD = ["-c",
           "import os, sys, time\n"
           "sys.exit(3) if os.environ['JAX_PROCESS_ID'] == '0' else time.sleep(120)\n"]

    def test_fail_fast_tears_down_peers_promptly(self):
        t0 = time.monotonic()
        assert launch(self.CMD, num_processes=2, timeout=60) == 3
        assert time.monotonic() - t0 < 30

    def test_no_fail_fast_waits_for_all(self):
        cmd = ["-c",
               "import os, sys, time\n"
               "if os.environ['JAX_PROCESS_ID'] == '0':\n"
               "    sys.exit(3)\n"
               "time.sleep(1.0)\n"]
        t0 = time.monotonic()
        assert launch(cmd, num_processes=2, timeout=60, fail_fast=False) == 3
        assert time.monotonic() - t0 >= 1.0           # peer ran to its own exit

    def test_cli_flag_passthrough(self, monkeypatch):
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            launch as L,
        )
        seen = {}
        monkeypatch.setattr(L, "launch",
                            lambda command, **kw: seen.update(kw) or 0)
        L.main(["--num-processes", "2", "--no-fail-fast", "--", "-m", "x"])
        assert seen["fail_fast"] is False
        L.main(["--num-processes", "2", "--", "-m", "x"])
        assert seen["fail_fast"] is True


def test_cli_help_smokes():
    """train.launch --help and tools/fleet_supervise.py --help exit 0 (satellite)."""
    for cmd in ([sys.executable, "-m", f"{PKG}.train.launch", "--help"],
                [sys.executable, os.path.join(REPO, "tools", "fleet_supervise.py"),
                 "--help"]):
        p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           timeout=120)
        assert p.returncode == 0, p.stderr
        assert "usage" in p.stdout.lower()


def test_report_renders_resilience_events(tmp_path, capsys):
    """telemetry_report summarizes checkpoint/restart/preempt events (satellite)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    rows = [
        {"event": "checkpoint", "op": "save", "path": "a", "kind": "full",
         "bytes": 1000, "wall_s": 0.01, "step": 4, "coalesced": 2,
         "background": True},
        {"event": "checkpoint", "op": "restore", "path": "a", "kind": "full",
         "bytes": 1000, "wall_s": 0.02, "step": 4},
        {"event": "restart", "attempt": 1, "reason": "crash", "exit_code": 41,
         "resume_from": "a", "backoff_s": 0.0},
        {"event": "preempt", "epoch": 1, "step": 8, "checkpoint": "a"},
    ]
    path = tmp_path / "t.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    s = telemetry_report.summarize(str(path))
    assert s["ckpt_saves"] == 1 and s["ckpt_coalesced"] == 2
    assert s["ckpt_restores"] == 1
    assert s["restarts"] == 1 and s["restart_reasons"] == ["crash"]
    assert s["preempted_step"] == 8
    telemetry_report.print_summary(s)
    out = capsys.readouterr().out
    assert "restarts: 1 (crash)" in out and "preempted at step 8" in out
