"""Tensor parallelism: TP-sharded training pinned equal to the single-device step.

Contract (``parallel/tensor_parallel.py``): sharding transformer weights over a ``model``
mesh axis — alone, with a ``data`` axis, or in the full 3-axis data × seq × model
composition with ring attention — changes WHERE the math runs, never what it computes.
All collectives are compiler-inserted; the oracle is the unsharded jitted step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    TransformerClassifier,
)
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    make_mesh,
    make_ring_attention_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    tensor_parallel as tp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state,
    make_train_step,
)


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.normal(size=(n, 28, 28, 1)).astype(np.float32)),
            jnp.asarray((np.arange(n) % 10).astype(np.int32)))


@pytest.fixture(scope="module")
def model():
    return TransformerClassifier(dropout_rate=0.0)


@pytest.fixture(scope="module")
def reference(model):
    """Single-device one-step oracle."""
    state = create_train_state(model, jax.random.PRNGKey(0))
    step = make_train_step(model, learning_rate=0.05, momentum=0.5)
    x, y = _batch()
    new_state, loss = jax.jit(step)(state, x, y, jax.random.PRNGKey(1))
    return new_state, float(loss)


def _assert_params_match(actual, expected, atol=1e-6):
    flat_a = jax.tree_util.tree_leaves(jax.device_get(actual))
    flat_e = jax.tree_util.tree_leaves(jax.device_get(expected))
    for a, e in zip(flat_a, flat_e):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-5, atol=atol)


def test_partition_specs_classify_transformer_params(model):
    params = create_train_state(model, jax.random.PRNGKey(0)).params
    specs = tp.param_partition_specs(params)
    attn = specs["block_0"]["attn"]
    assert attn["qkv_kernel"] == P(None, "model")
    assert attn["qkv_bias"] == P("model")
    assert attn["out_kernel"] == P("model", None)
    assert attn["out_bias"] == P()
    blk = specs["block_0"]
    assert blk["mlp_up_kernel"] == P(None, "model")
    assert blk["mlp_down_kernel"] == P("model", None)
    assert specs["embed_kernel"] == P()
    assert specs["pos_embed"] == P()


def test_cnn_params_all_replicate():
    """The rules degrade to plain DP for models with nothing to shard."""
    params = create_train_state(Net(), jax.random.PRNGKey(0)).params
    specs = tp.param_partition_specs(params)
    assert all(s == P() for s in jax.tree_util.tree_leaves(specs))


def test_shard_train_state_actually_shards(model):
    mesh = make_mesh(4, axis_names=("model",))
    state = tp.shard_train_state(mesh, create_train_state(model, jax.random.PRNGKey(0)))
    qkv = state.params["block_0"]["attn"]["qkv_kernel"]
    assert qkv.shape == (64, 192)
    assert qkv.addressable_shards[0].data.shape == (64, 48)  # 192/4 per device
    vel = state.velocity["block_0"]["attn"]["qkv_kernel"]
    assert vel.addressable_shards[0].data.shape == (64, 48)  # ZeRO-style opt state


def test_pure_tp_step_matches_single_device(model, reference):
    ref_state, ref_loss = reference
    mesh = make_mesh(4, axis_names=("model",))
    state = tp.shard_train_state(mesh, create_train_state(model, jax.random.PRNGKey(0)))
    step = tp.compile_step_tp(make_train_step(model, learning_rate=0.05, momentum=0.5),
                              mesh, data_axis=None)
    x, y = _batch()
    new_state, loss = step(state, x, y, jax.random.PRNGKey(1))
    assert abs(float(loss) - ref_loss) < 1e-5
    _assert_params_match(new_state.params, ref_state.params)


def test_dp_tp_step_matches_single_device(model, reference):
    ref_state, ref_loss = reference
    mesh = make_mesh(8, axis_names=("data", "model"), axis_shape=(2, 4))
    state = tp.shard_train_state(mesh, create_train_state(model, jax.random.PRNGKey(0)))
    step = tp.compile_step_tp(make_train_step(model, learning_rate=0.05, momentum=0.5),
                              mesh)
    x, y = _batch()
    new_state, loss = step(state, x, y, jax.random.PRNGKey(1))
    assert abs(float(loss) - ref_loss) < 1e-5
    _assert_params_match(new_state.params, ref_state.params)


@pytest.mark.slow
def test_three_axis_dp_sp_tp_matches_single_device(reference):
    """The headline composition: batch over 'data', sequence ring over 'seq', weights
    over 'model' — one mesh, one jitted step, same numbers."""
    ref_state, ref_loss = reference
    mesh = make_mesh(8, axis_names=("data", "seq", "model"), axis_shape=(2, 2, 2))
    ring_model = TransformerClassifier(
        dropout_rate=0.0, attention_fn=make_ring_attention_fn(mesh))
    state = tp.shard_train_state(
        mesh, create_train_state(ring_model, jax.random.PRNGKey(0)))
    step = tp.compile_step_tp(
        make_train_step(ring_model, learning_rate=0.05, momentum=0.5), mesh)
    x, y = _batch()
    new_state, loss = step(state, x, y, jax.random.PRNGKey(1))
    assert abs(float(loss) - ref_loss) < 1e-5
    _assert_params_match(new_state.params, ref_state.params)


def test_multi_step_tp_trajectory_matches(model):
    """Five consecutive donated-buffer TP steps track the single-device trajectory."""
    x, y = _batch(seed=2)
    ref_state = create_train_state(model, jax.random.PRNGKey(0))
    ref_step = jax.jit(make_train_step(model, learning_rate=0.05, momentum=0.5))
    mesh = make_mesh(4, axis_names=("model",))
    state = tp.shard_train_state(mesh, create_train_state(model, jax.random.PRNGKey(0)))
    step = tp.compile_step_tp(make_train_step(model, learning_rate=0.05, momentum=0.5),
                              mesh, data_axis=None)
    for _ in range(5):
        ref_state, ref_loss = ref_step(ref_state, x, y, jax.random.PRNGKey(1))
        state, loss = step(state, x, y, jax.random.PRNGKey(1))
    assert abs(float(loss) - float(ref_loss)) < 1e-5
    _assert_params_match(state.params, ref_state.params, atol=1e-5)


def test_filter_to_mesh_drops_absent_axes():
    """Specs naming axes the mesh lacks are filtered to replication on that dim, so one
    rule set serves every mesh declaration."""
    mesh = make_mesh(8)  # ('data',) only
    specs = {"a": P(None, "model"), "b": P("expert", None, None), "c": P("data")}
    out = tp._filter_to_mesh(specs, mesh)
    assert out["a"] == P(None, None)
    assert out["b"] == P(None, None, None)
    assert out["c"] == P("data")
