"""obs/ observability layer: histograms, SLO attainment, goodput accounting.

Tier-1 coverage of the run-level observability PR:

- ``obs/hist.py`` sketches pinned against the repo's nearest-rank ORACLE
  (``utils.jsonl.percentiles``) within the configured relative error, on
  multiple latency-shaped distributions; merge = union; JSON round-trip.
- ``obs/slo.py`` spec parsing/semantics and windowed attainment.
- ``obs/goodput.py`` edge cases the issue pins: a clean run's restart badput
  is 0.0 EXACTLY, replayed-epoch time is charged to badput (not compute), a
  torn final JSONL line never blocks the join, and the exclusive segments sum
  to the run's wall time.
- ``utils.telemetry.TelemetryWriter`` non-stream history preservation — the
  property the multi-attempt goodput join stands on.
- ``tools/fleet_top.py`` one-frame rendering from a router stream (jax-free).

All synthetic-stream tests are pure host work (no jax), built on hand-written
JSONL in the writers' exact schemas.
"""

import json
import math
import os
import subprocess
import sys

import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.obs.goodput import (
    decompose,
    goodput_event,
    read_streams,
)
from csed_514_project_distributed_training_using_pytorch_tpu.obs.hist import (
    LogHistogram,
)
from csed_514_project_distributed_training_using_pytorch_tpu.obs.slo import (
    AttainmentTracker,
    SLOSpec,
    slo_event,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
    percentiles,
)

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)


# ------------------------------------------------------------------ histograms


def _series_cases():
    """Three latency-shaped series (the acceptance criterion asks for >= 3):
    lognormal TTFT-ish, exponential queue-wait-ish with zeros, and a bimodal
    cache-hit/miss mixture."""
    import numpy as np

    rng = np.random.default_rng(42)
    return {
        "ttft_lognormal": np.exp(rng.normal(-3.0, 1.0, size=2000)).tolist(),
        "queue_exponential": ([0.0] * 25
                              + rng.exponential(0.05, size=1500).tolist()),
        "bimodal_hit_miss": (rng.normal(0.002, 0.0002, size=700).clip(1e-6)
                             .tolist()
                             + rng.normal(0.2, 0.02, size=300).clip(1e-6)
                             .tolist()),
    }


@pytest.mark.parametrize("rel_err", [0.01, 0.05])
def test_hist_quantiles_within_relative_error_of_nearest_rank(rel_err):
    """The tentpole bound: sketch p50/p95/p99 vs the nearest-rank oracle,
    within the configured relative error, on every series."""
    for name, xs in _series_cases().items():
        h = LogHistogram(rel_err)
        h.extend(xs)
        exact = percentiles(xs, qs=(50, 95, 99))
        sketched = h.percentiles((50, 95, 99))
        for q in ("p50", "p95", "p99"):
            assert sketched[q] == pytest.approx(exact[q], rel=rel_err), \
                f"{name} {q}: sketch {sketched[q]} vs exact {exact[q]}"


def test_hist_merge_equals_union_and_json_round_trips():
    """Merging per-replica sketches == one sketch over the concatenation
    (bucket-count addition is lossless), including across a JSON hop — the
    replica -> router stats path."""
    cases = _series_cases()
    xs, ys = cases["ttft_lognormal"], cases["bimodal_hit_miss"]
    ha, hb, union = LogHistogram(0.01), LogHistogram(0.01), LogHistogram(0.01)
    ha.extend(xs)
    hb.extend(ys)
    union.extend(xs + ys)
    merged = LogHistogram(0.01)
    merged.merge(json.loads(json.dumps(ha.to_json())))      # the wire hop
    merged.merge(hb)
    assert merged.count == union.count == len(xs) + len(ys)
    assert merged.sum == pytest.approx(union.sum)
    for q in (50, 95, 99):
        assert merged.quantile(q) == union.quantile(q)
    # Memory stays O(buckets): far below the sample count.
    assert merged.num_buckets < 300 < merged.count


def test_hist_edges_zeros_negatives_empty_and_mismatched_merge():
    h = LogHistogram(0.02)
    assert h.percentiles() is None and h.quantile(50) is None
    h.add(None)                      # skipped, the percentiles() convention
    assert h.count == 0
    h.extend([0.0, 0.0, 1.0])
    assert h.quantile(50) == 0.0     # zeros are exact, not bucketed
    assert h.min == 0.0 and h.max == 1.0 and h.count == 3
    with pytest.raises(ValueError):
        h.add(-0.1)
    with pytest.raises(ValueError):
        h.merge(LogHistogram(0.01))  # different bound: refuse, never degrade
    with pytest.raises(ValueError):
        LogHistogram(0.0)


# ------------------------------------------------------------------------- slo


def test_slo_spec_parse_and_meets():
    spec = SLOSpec.parse("ttft=0.5,e2e=2.0,window=10")
    assert spec == SLOSpec(ttft_s=0.5, e2e_s=2.0, window_s=10.0)
    assert SLOSpec.parse("") is None and SLOSpec.parse("off") is None
    with pytest.raises(ValueError):
        SLOSpec.parse("bogus=1")
    with pytest.raises(ValueError):
        SLOSpec(window_s=5.0)        # a promise with no targets
    assert spec.meets(ttft_s=0.4, e2e_s=1.9)
    assert not spec.meets(ttft_s=0.6, e2e_s=1.0)      # one target missed
    assert not spec.meets(ttft_s=None, e2e_s=1.0)     # named but unmeasured
    assert not spec.meets(ok=False, ttft_s=0.1, e2e_s=0.1)   # timeouts miss
    assert spec.meets(ttft_s=0.4, e2e_s=1.0, tpot_s=99.0)    # unnamed ignored


def test_slo_attainment_run_level_and_sliding_window():
    spec = SLOSpec(ttft_s=0.5, window_s=10.0)
    tr = AttainmentTracker(spec)
    assert tr.attainment() is None
    for t, ttft in [(0.0, 0.1), (1.0, 0.9), (2.0, 0.2), (3.0, 0.3)]:
        tr.observe(t, ttft_s=ttft)
    assert tr.attainment() == pytest.approx(0.75)
    assert tr.window(3.0) == {"attainment": pytest.approx(0.75), "requests": 4}
    # Later the early observations fall off the window (horizon 11.5-10 =
    # 1.5: only t=2, t=3 remain, both hits); run-level is unchanged.
    win = tr.window(11.5)
    assert win == {"attainment": pytest.approx(1.0), "requests": 2}
    assert tr.attainment() == pytest.approx(0.75)
    ev = slo_event(tr, source="router", window=win)
    assert ev["event"] == "slo" and ev["source"] == "router"
    assert ev["met"] == 3 and ev["requests"] == 4
    assert ev["spec"]["ttft_s"] == 0.5 and ev["window"] == win


# ----------------------------------------------------------- goodput synthetic


def _epoch(epoch, t_s, *, wall=10.0, execute=8.0, ev=1.0, data=0.5, steps=4):
    return {"event": "epoch", "epoch": epoch, "steps": steps, "wall_s": wall,
            "execute_s": execute, "eval_s": ev, "data_s": data, "t_s": t_s}


def _write(path, rows, torn_tail: str = ""):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        if torn_tail:
            f.write(torn_tail)       # a killed writer's mid-line tear
    return str(path)


def _clean_run(tmp_path, *, torn=False):
    """One attempt, two epochs, two synchronous saves, anchored at unix 1000."""
    rows = [
        {"event": "manifest", "unix_time": 1000.0, "t_s": 0.0},
        {"event": "compile", "lower_s": 1.0, "compile_s": 3.0, "t_s": 5.0},
        _epoch(0, 15.0),
        {"event": "checkpoint", "op": "save", "wall_s": 1.0, "t_s": 16.0},
        _epoch(1, 26.0),
        {"event": "checkpoint", "op": "save", "wall_s": 1.0, "t_s": 27.0},
    ]
    return _write(tmp_path / "run.jsonl", rows,
                  torn_tail='{"event": "epo' if torn else "")


def test_goodput_clean_run_zero_badput_and_exact_sum(tmp_path):
    """Zero restarts => restart_badput == 0.0 EXACTLY (not epsilon), and the
    exclusive segments sum to the wall."""
    path = _clean_run(tmp_path)
    r = decompose([path])
    assert r["attempts"] == 1 and r["restarts"] == 0
    assert r["segments"]["restart_badput_s"] == 0.0
    assert r["epochs_replayed"] == 0 and r["replayed_steps"] == 0
    assert r["wall_s"] == pytest.approx(27.0)
    assert sum(r["segments"].values()) == pytest.approx(r["wall_s"], rel=0.01)
    # init/compile = attempt start -> first epoch start (covers the AOT
    # compile); compute = execute + eval of both epochs.
    assert r["segments"]["init_compile_s"] == pytest.approx(5.0)
    assert r["segments"]["compute_s"] == pytest.approx(18.0)
    assert r["segments"]["data_wait_s"] == pytest.approx(1.0)
    assert r["segments"]["checkpoint_stall_s"] == pytest.approx(2.0)
    assert r["goodput_frac"] == pytest.approx(18.0 / 27.0)
    assert r["unaccounted_s"] == 0.0


def test_goodput_tolerates_torn_final_line(tmp_path):
    """The guarded-reader contract extends to the join: a run killed mid-emit
    decomposes from everything before the tear."""
    torn = decompose([_clean_run(tmp_path, torn=True)])
    clean = decompose([_clean_run(tmp_path)])
    assert torn["segments"] == clean["segments"]


def _faulted_run(tmp_path):
    """Two attempts in ONE telemetry file (the preserved-history layout):
    attempt 1 runs epochs 0-1 then crashes; attempt 2 resumes from the
    epoch-0 checkpoint, REPLAYS epoch 1, and finishes epoch 2. Plus the
    supervisor's restart stream anchored on the same unix clock."""
    tele = [
        {"event": "manifest", "unix_time": 1000.0, "t_s": 0.0},
        _epoch(0, 15.0),
        {"event": "checkpoint", "op": "save", "wall_s": 1.0, "t_s": 16.0},
        _epoch(1, 26.0, execute=7.0),
        {"event": "checkpoint", "op": "save", "wall_s": 1.0, "t_s": 27.0},
        # -- crash; supervisor restarts; attempt 2 appends after attempt 1 --
        {"event": "manifest", "unix_time": 1040.0, "t_s": 0.0},
        {"event": "checkpoint", "op": "restore", "wall_s": 0.5, "t_s": 2.0},
        _epoch(1, 19.0),              # the REPLAY: epoch 1 again
        {"event": "checkpoint", "op": "save", "wall_s": 1.0, "t_s": 20.0},
        _epoch(2, 30.0),
        {"event": "checkpoint", "op": "save", "wall_s": 1.0, "t_s": 31.0},
    ]
    sup = [
        {"event": "restart", "attempt": 1, "restart": 1, "reason": "crash",
         "exit_code": 41, "backoff_s": 1.0, "unix_time": 1030.0, "t_s": 31.0},
        {"event": "supervise_summary", "status": "ok", "attempts": 2,
         "restarts": 1, "unix_time": 1073.0, "t_s": 74.0},
    ]
    run = tmp_path / "faulted"
    run.mkdir()
    _write(run / "run.jsonl", tele)
    _write(run / "supervisor.jsonl", sup)
    return str(run)


def test_goodput_faulted_run_charges_replay_to_badput(tmp_path):
    """The issue's replay rule: a resumed attempt's re-executed epoch lands in
    restart_badput (its whole wall), NOT in compute — and the decomposition
    still sums to the run's wall time within 1%."""
    r = decompose([_faulted_run(tmp_path)])
    assert r["attempts"] == 2 and r["restarts"] == 1
    assert r["epochs_replayed"] == 1 and r["replayed_steps"] == 4
    # Compute = first executions only: epoch 0 (8+1), attempt-1 epoch 1
    # (7+1), epoch 2 (8+1).
    assert r["segments"]["compute_s"] == pytest.approx(26.0)
    # Badput = crash->respawn gap (attempt-1's last event 1027 -> attempt-2
    # anchor 1040 = 13) + attempt-2 init window (9s: restore + recompile up
    # to the replay's start) + the replayed epoch's wall (10).
    assert r["segments"]["restart_badput_s"] == pytest.approx(32.0)
    assert r["segments"]["restart_badput_s"] > 0.0
    # Supervisor stream bounds the run: anchor 999 -> summary 1073.
    assert r["wall_s"] == pytest.approx(74.0)
    assert sum(r["segments"].values()) == pytest.approx(r["wall_s"], rel=0.01)
    ev = goodput_event(r)
    assert ev["event"] == "goodput"
    assert ev["restart_badput_s"] == pytest.approx(32.0)
    assert ev["goodput_frac"] == pytest.approx(26.0 / 74.0)


def test_goodput_stream_classification_and_errors(tmp_path):
    run = tmp_path / "mix"
    run.mkdir()
    _write(run / "t.jsonl", [
        {"event": "manifest", "unix_time": 50.0, "t_s": 0.0},
        _epoch(0, 12.0),
        {"event": "restart", "reason": "crash", "unix_time": 70.0,
         "t_s": 21.0},
        {"event": "span", "trace_id": "x", "name": "client", "ts": 75.0,
         "dur_s": 2.0},
    ])
    streams = read_streams([str(run)])
    assert len(streams["attempts"]) == 1
    assert len(streams["supervisor"]) == 1 and len(streams["spans"]) == 1
    r = decompose([str(run)])
    # The span's end (77) extends the joined run past the trainer's last
    # event — trace streams participate in the wall-clock join.
    assert r["end_unix"] == pytest.approx(77.0)
    with pytest.raises(ValueError, match="no trainer epochs"):
        decompose([_write(tmp_path / "empty.jsonl",
                          [{"event": "manifest", "unix_time": 1.0,
                            "t_s": 0.0}])])


def test_goodput_report_cli_renders_and_emits(tmp_path):
    """tools/telemetry_report.py --goodput: faulted-vs-clean A-vs-B rows plus
    --emit's registered 'goodput' event line."""
    faulted = _faulted_run(tmp_path)
    clean = _clean_run(tmp_path)
    out_path = str(tmp_path / "goodput.jsonl")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "telemetry_report.py"),
         "--goodput", "--emit", out_path, faulted, clean],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "restart badput s" in proc.stdout and "goodput frac" in proc.stdout
    assert "B/A" in proc.stdout      # the two-run comparison table
    rows = [json.loads(l) for l in open(out_path) if l.strip()]
    assert [r["event"] for r in rows] == ["goodput", "goodput"]
    assert rows[0]["restart_badput_s"] > 0.0 and \
        rows[1]["restart_badput_s"] == 0.0


def test_goodput_rejoin_skips_its_own_emitted_ledger(tmp_path):
    """--emit drops the ledger NEXT TO the run's streams (the documented
    flow); a later join of the same directory must skip the derived line
    instead of mistaking it for an unanchored trainer attempt."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
        JsonlWriter,
    )

    run = _faulted_run(tmp_path)
    before = decompose([run])
    w = JsonlWriter(os.path.join(run, "goodput.jsonl"))
    w.emit(goodput_event(before))
    w.emit({"event": "bench_guard", "metric": "decode_tick_s",
            "median_s": 1.0, "pass": True})
    w.close()
    after = decompose([run])
    assert after["segments"] == before["segments"]
    assert after["attempts"] == before["attempts"]


# ------------------------------------------------- telemetry history preserved


def test_telemetry_writer_preserves_history_only_when_resuming(tmp_path):
    """The non-stream writer's restart contract: with ``preserve=True`` (the
    trainers pass ``bool(config.resume_from)``) a NEW writer on the SAME path
    appends its attempt after the old events instead of truncating them —
    including past a torn final line. A FRESH run (preserve off, the
    default) keeps the historical truncate-and-rewrite semantics, so two
    unrelated runs never blend into a fake multi-attempt history."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    path = str(tmp_path / "run.jsonl")
    w1 = T.TelemetryWriter(path)
    w1.emit({"event": "manifest", "attempt": 1})
    w1.emit({"event": "epoch", "epoch": 0})
    with open(path, "a") as f:
        f.write('{"event": "epo')          # the crash tears the final line
    w2 = T.TelemetryWriter(path, preserve=True)
    w2.emit({"event": "manifest", "attempt": 2})
    w2.emit({"event": "epoch", "epoch": 1})
    rows = [json.loads(l) for l in open(path) if l.strip()]
    assert [r["event"] for r in rows] == ["manifest", "epoch", "manifest",
                                          "epoch"]
    assert [r.get("attempt") for r in rows if r["event"] == "manifest"] \
        == [1, 2]
    # Default (no resume): the old behavior — a fresh run truncates.
    w3 = T.TelemetryWriter(path)
    w3.emit({"event": "manifest", "attempt": 3})
    rows = [json.loads(l) for l in open(path) if l.strip()]
    assert [r.get("attempt") for r in rows] == [3]


# ------------------------------------------------------- summary event plumbing


def test_serve_summary_event_accepts_histograms_and_slo():
    """serve_summary_event's latency series take LogHistogram sketches (the
    server's new store) and raw lists interchangeably; the slo dict rides
    through."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        telemetry as T,
    )

    xs = [0.01 * (i + 1) for i in range(100)]
    h = LogHistogram(0.01)
    h.extend(xs)
    tr = AttainmentTracker(SLOSpec(ttft_s=0.5))
    tr.observe(0.0, ttft_s=0.1)
    ev = T.serve_summary_event(
        requests=100, ok=100, timeout=0, new_tokens=500, wall_s=2.0,
        slo=tr.summary(), ttft_s=h, e2e_s=xs)
    exact = percentiles(xs)
    for q in ("p50", "p95", "p99"):
        assert ev["ttft_s"][q] == pytest.approx(exact[q], rel=0.01)
        assert ev["e2e_s"][q] == exact[q]           # raw list: oracle, exact
    assert ev["slo"]["attainment"] == 1.0
    assert ev["tpot_s"] is None                     # empty series stays None


# ---------------------------------------------------------------- fleet_top


def test_fleet_top_renders_snapshot_and_slo(tmp_path):
    """A --once frame from a hand-built router stream: per-replica table, SLO
    attainment, queue state. Subprocess = also proves the tool runs jax-free
    from a bare interpreter (graftlint pins the import graph; this pins the
    runtime)."""
    rows = [
        {"event": "router_config", "replicas": 2, "affinity": True},
        {"event": "scale", "action": "up", "replica": 2, "target": 3,
         "t_s": 4.0},
        {"event": "fleet_snapshot", "t_s": 5.0,
         "queue": {"depth": 3, "oldest_age_s": 0.4},
         "utilization": 0.5, "inflight": 4, "capacity_up": 8,
         "target": 3, "replicas_ready": 2, "requests": 11, "ok": 10,
         "redispatches": 1, "restarts": 0,
         "slo": {"attainment": 0.9, "requests": 10},
         "per_replica": [
             {"replica": 0, "state": "ready", "inflight": 2, "capacity": 4,
              "occupancy": 0.5, "restarts": 0, "completed": 6,
              "slo": {"attainment": 1.0, "requests": 6}},
             {"replica": 1, "state": "ready", "inflight": 2, "capacity": 4,
              "occupancy": 0.5, "restarts": 0, "completed": 4,
              "slo": {"attainment": 0.75, "requests": 4}}]},
    ]
    path = tmp_path / "router.jsonl"
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write('{"event": "fleet_sn')       # live tail: torn line in flight
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "fleet_top.py"),
         str(path), "--once"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "target 3" in out and "ready 2" in out
    assert "queue depth 3" in out
    assert "SLO window" in out and "0.900" in out
    assert "scale up -> target 3" in out
    for frag in ("0.750", "1.000"):          # per-replica attainment column
        assert frag in out
    # Backend purity at runtime: no jax in the tool's import closure.
    probe = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); import tools.fleet_top; "
         "assert 'jax' not in sys.modules, 'fleet_top imported jax'"
         % _REPO],
        capture_output=True, text=True, timeout=60)
    assert probe.returncode == 0, probe.stderr
