"""Model-layer tests: shape/param-count oracles from SURVEY.md §3.4, op semantics, dropout
modes. The reference has no tests (SURVEY.md §4); these encode its model contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net, param_count


@pytest.fixture(scope="module")
def net_and_params():
    net = Net()
    params = net.init({"params": jax.random.PRNGKey(0)}, jnp.zeros((2, 28, 28, 1)))
    return net, params


def test_param_count_matches_reference(net_and_params):
    # conv1 260 + conv2 5020 + fc1 16050 + fc2 510 (reference src/model.py:9-13)
    _, params = net_and_params
    assert param_count(params["params"]) == 21_840


def test_param_shapes(net_and_params):
    _, params = net_and_params
    shapes = {k: v.shape for k, v in params["params"].items()}
    assert shapes == {
        "conv1_kernel": (5, 5, 1, 10), "conv1_bias": (10,),
        "conv2_kernel": (5, 5, 10, 20), "conv2_bias": (20,),
        "fc1_kernel": (320, 50), "fc1_bias": (50,),
        "fc2_kernel": (50, 10), "fc2_bias": (10,),
    }


def test_forward_shape_and_log_probs(net_and_params):
    net, params = net_and_params
    x = jax.random.normal(jax.random.PRNGKey(1), (7, 28, 28, 1))
    out = net.apply(params, x)
    assert out.shape == (7, 10)
    # log_softmax output: rows exp-sum to 1 (reference src/model.py:22)
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), np.ones(7), rtol=1e-5)


def test_eval_mode_deterministic(net_and_params):
    net, params = net_and_params
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 28, 28, 1))
    np.testing.assert_array_equal(net.apply(params, x), net.apply(params, x))


def test_train_mode_applies_dropout(net_and_params):
    net, params = net_and_params
    x = jax.random.normal(jax.random.PRNGKey(3), (3, 28, 28, 1))
    a = net.apply(params, x, deterministic=False, rngs={"dropout": jax.random.PRNGKey(4)})
    b = net.apply(params, x, deterministic=False, rngs={"dropout": jax.random.PRNGKey(5)})
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_forward_jits_once_per_mode(net_and_params):
    net, params = net_and_params
    fwd = jax.jit(lambda p, x: net.apply(p, x))
    x = jnp.zeros((4, 28, 28, 1))
    out1 = fwd(params, x)
    out2 = fwd(params, x + 1.0)
    assert out1.shape == out2.shape == (4, 10)


def test_intermediate_shapes():
    """The layer-by-layer shape trace of SURVEY.md §3.4 (model.py:16-21)."""
    x = jnp.zeros((2, 28, 28, 1))
    w1 = jnp.zeros((5, 5, 1, 10))
    h = ops.conv2d(x, w1)
    assert h.shape == (2, 24, 24, 10)
    h = ops.max_pool2d(h, 2)
    assert h.shape == (2, 12, 12, 10)
    w2 = jnp.zeros((5, 5, 10, 20))
    h = ops.conv2d(h, w2)
    assert h.shape == (2, 8, 8, 20)
    h = ops.max_pool2d(h, 2)
    assert h.shape == (2, 4, 4, 20)
    assert h.reshape(2, -1).shape == (2, 320)
