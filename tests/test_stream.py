"""Streaming corpus pipeline (DESIGN.md §26, the data half): corpus build
determinism against the committed fixture, epoch-plan purity in ``(seed,
epoch)``, the durable cursor's bitwise resume contract (kill mid-epoch,
resume from the manifest cursor, remaining stream identical), cursor-drift
detection (corpus changed under a checkpoint must RAISE, never reshuffle),
shard integrity hashing, and the loader-stall instrumentation both loaders
feed into the goodput ``data_wait`` segment."""

import importlib.util
import os
import time

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    BatchLoader, Dataset,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data.stream import (
    CorpusError,
    StreamLoader,
    eval_tokens,
    load_meta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "corpus_tiny")


def _load_build_corpus():
    spec = importlib.util.spec_from_file_location(
        "build_corpus", os.path.join(REPO, "tools", "build_corpus.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -----------------------------------------------------------------------------------------
# Corpus build + fixture integrity
# -----------------------------------------------------------------------------------------


def test_build_corpus_reproduces_committed_fixture(tmp_path):
    """The committed fixture is exact ``tools/build_corpus.py`` output: the
    same synthetic flags rebuild it bitwise (shards AND manifest hashes).
    If this fails, someone edited the fixture by hand or the builder's
    determinism broke — both corrupt every cursor pinned against it."""
    bc = _load_build_corpus()
    out = str(tmp_path / "corpus")
    rc = bc.main(["--out", out, "--seq-len", "64", "--shard-sequences", "48",
                  "--eval-frac", "0.2", "--synthetic-chars", "12000",
                  "--synthetic-seed", "7"])
    assert rc == 0
    ref, new = load_meta(FIXTURE), load_meta(out)
    assert [s["sha256"] for s in new["shards"]] == \
        [s["sha256"] for s in ref["shards"]]
    assert new.get("eval", {}).get("sha256") == ref.get("eval", {}).get("sha256")
    for entry in ref["shards"]:
        with open(os.path.join(FIXTURE, entry["file"]), "rb") as fa, \
                open(os.path.join(out, entry["file"]), "rb") as fb:
            assert fa.read() == fb.read()


def test_fixture_shape_contract():
    meta = load_meta(FIXTURE)
    assert meta["seq_len"] == 64 and meta["vocab"] == 256
    ev = eval_tokens(FIXTURE)
    assert ev is not None and ev.shape[1] == 64 and ev.dtype == np.int32
    loader = StreamLoader(FIXTURE, 16, seed=1)
    assert loader.num_sequences == sum(
        s["sequences"] for s in meta["shards"])
    assert loader.batches_per_epoch == loader.num_sequences // 16


# -----------------------------------------------------------------------------------------
# Epoch-plan purity + stream determinism
# -----------------------------------------------------------------------------------------


def test_epoch_plan_pure_in_seed_and_epoch():
    a = StreamLoader(FIXTURE, 16, seed=3)
    b = StreamLoader(FIXTURE, 16, seed=3)
    assert a.epoch_plan(2)["crc"] == b.epoch_plan(2)["crc"]
    assert a.epoch_plan(2)["crc"] != a.epoch_plan(3)["crc"]
    assert (StreamLoader(FIXTURE, 16, seed=4).epoch_plan(2)["crc"]
            != a.epoch_plan(2)["crc"])


def test_stream_batches_shape_and_determinism():
    a = StreamLoader(FIXTURE, 16, seed=1)
    batches = list(a.iter_batches(0))
    assert len(batches) == a.batches_per_epoch
    assert all(b.shape == (16, a.seq_len) and b.dtype == np.int32
               for b in batches)
    b = StreamLoader(FIXTURE, 16, seed=1)
    np.testing.assert_array_equal(a.epoch_tokens(0), b.epoch_tokens(0))
    assert not np.array_equal(a.epoch_tokens(0), a.epoch_tokens(1))


# -----------------------------------------------------------------------------------------
# The cursor: bitwise resume + drift detection
# -----------------------------------------------------------------------------------------


@pytest.mark.parametrize("resume_batch", [1, 4, 8])
def test_cursor_resume_bitwise_identical(resume_batch):
    """Kill mid-epoch, resume from the manifest cursor in a FRESH loader:
    the remaining batch stream is bitwise identical to the uninterrupted
    one — the tentpole's deterministic-resume contract at loader level
    (tools/train_serve_loop.py proves the same through a full trainer)."""
    epoch = 2
    full = StreamLoader(FIXTURE, 16, seed=1)
    uninterrupted = full.epoch_tokens(epoch)
    cursor = full.cursor(epoch, resume_batch)
    resumed = StreamLoader(FIXTURE, 16, seed=1)     # a new process
    e, b = resumed.verify_cursor(cursor)
    assert (e, b) == (epoch, resume_batch)
    np.testing.assert_array_equal(
        resumed.epoch_tokens(e, start_batch=b),
        uninterrupted[resume_batch * 16:])
    assert (resumed.stream_digest(e, start_batch=b)
            == StreamLoader(FIXTURE, 16, seed=1).stream_digest(
                epoch, start_batch=resume_batch))


def test_cursor_drift_raises():
    loader = StreamLoader(FIXTURE, 16, seed=1)
    good = loader.cursor(1, 3)
    with pytest.raises(CorpusError, match="seed"):
        loader.verify_cursor({**good, "seed": 99})
    with pytest.raises(CorpusError, match="plan_crc"):
        loader.verify_cursor({**good, "plan_crc": good["plan_crc"] ^ 1})
    with pytest.raises(CorpusError, match="offset"):
        loader.verify_cursor({**good, "offset": good["offset"] + 1})
    with pytest.raises(CorpusError, match="version"):
        loader.verify_cursor({**good, "version": 999})
    with pytest.raises(CorpusError, match="stream cursor"):
        loader.verify_cursor({"kind": "epoch"})


def test_shard_corruption_detected(tmp_path):
    """A corpus edited under its manifest is an error, not a reshuffle."""
    import shutil
    out = tmp_path / "corrupt"
    shutil.copytree(FIXTURE, out)
    meta = load_meta(str(out))
    victim = out / meta["shards"][0]["file"]
    raw = bytearray(victim.read_bytes())
    raw[-1] ^= 0xFF
    victim.write_bytes(bytes(raw))
    loader = StreamLoader(str(out), 16, seed=1)
    with pytest.raises(CorpusError, match="sha256 mismatch"):
        loader.epoch_tokens(0)


# -----------------------------------------------------------------------------------------
# Stall instrumentation: the goodput data_wait input
# -----------------------------------------------------------------------------------------


def test_stream_loader_throttle_charges_wait():
    """The regression this instrumentation exists for: a stalled loader must
    show up in ``wait_s`` (the trainers charge it to the epoch event's
    ``data_s``, goodput's ``data_wait`` segment) — not hide inside idle."""
    loader = StreamLoader(FIXTURE, 16, seed=1, throttle_s=0.01)
    n = sum(1 for _ in loader.iter_batches(0))
    assert n == loader.batches_per_epoch
    # Lower bound only: sleep() can overshoot but never undershoot.
    accrued = loader.wait_s
    assert accrued >= n * 0.01 * 0.9
    assert loader.pop_wait_s() == accrued
    assert loader.wait_s == 0.0 and loader.pop_wait_s() == 0.0


class _SlowImages(np.ndarray):
    """An image array whose gathers stall — the throttled-loader stand-in."""

    DELAY_S = 0.004

    def __getitem__(self, idx):
        if isinstance(idx, np.ndarray):
            time.sleep(self.DELAY_S)
        return super().__getitem__(idx)


def test_batchloader_stall_charges_wait(monkeypatch):
    """BatchLoader's consumer-blocked accounting: a slow gather per batch
    lands in ``wait_s``; ``pop_wait_s`` drains it."""
    from csed_514_project_distributed_training_using_pytorch_tpu.data import (
        native,
    )
    monkeypatch.setattr(native, "available", lambda: False)
    rng = np.random.default_rng(0)
    images = rng.normal(size=(64, 28, 28, 1)).astype(np.float32) \
        .view(_SlowImages)
    ds = Dataset(images, rng.integers(0, 10, 64).astype(np.int32), "test")
    loader = BatchLoader(ds, 16, shuffle=True, seed=1)
    batches = list(loader)
    assert len(batches) == 4
    assert loader.wait_s >= 4 * _SlowImages.DELAY_S * 0.9
    assert loader.pop_wait_s() > 0.0
    assert loader.wait_s == 0.0
