"""Fault-injection integration tests: real multi-process CPU fleets under the
supervisor (tier-1 by design — these are the acceptance gates of the resilience
layer, not heavyweight equivalence sweeps).

- a 2-process fleet with a worker hard-killed mid-run is torn down, restarted from
  the newest VALID checkpoint (the torn write the fault produced is skipped), and
  completes with the same final step as an uninterrupted run;
- a preemption signal makes the fleet stop cooperatively at the next epoch boundary,
  exit with the distinct "preempted" status (75), and leave a checkpoint that a
  fresh run resumes to completion;
- the resilience flags are behaviorally zero-cost: flag-on training is bitwise
  identical to flag-off (the hooks are host-side only — same discipline as
  ``--health-stats``).
"""

import json
import os
import signal

import numpy as np
import pytest
from flax import serialization

from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
    Dataset, _normalize, _synthesize_split,
)
from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
    heartbeat, preemption, supervisor as sup,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.launch import launch
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "csed_514_project_distributed_training_using_pytorch_tpu"

# 256 examples / 2 replicas / per-replica batch 32 -> 4 steps per epoch; 3 epochs
# -> an uninterrupted run ends at step 12 with versioned checkpoints at 4, 8, 12.
STEPS_PER_EPOCH, EPOCHS = 4, 3
TRAIN = [
    "-m", f"{PKG}.train.distributed",
    "--epochs", str(EPOCHS), "--global-batch-size", "64",
    "--batch-size-test", "256",
    "--max-train-examples", "256", "--max-test-examples", "256",
    "--keep-checkpoints", "3", "--handle-preemption",
]


@pytest.fixture(autouse=True)
def _child_pythonpath(monkeypatch):
    """Children must find the package no matter their cwd."""
    existing = os.environ.get("PYTHONPATH", "")
    monkeypatch.setenv("PYTHONPATH", f"{REPO}:{existing}" if existing else REPO)


def _step_of(ckpt_path: str) -> int:
    with open(ckpt_path, "rb") as f:
        return int(serialization.msgpack_restore(f.read())["step"])


def test_supervisor_restarts_killed_fleet_skipping_torn_checkpoint(tmp_path,
                                                                   monkeypatch):
    """Kill worker 1 at the epoch-2 tick AND tear the epoch-1 checkpoint write: the
    supervisor must fall back to the epoch-0 checkpoint (never the torn one),
    restart the fleet, and finish with an uninterrupted run's final step.

    Doubles as the goodput acceptance gate (obs/goodput.py): the joined
    telemetry + supervisor streams of this faulted run must decompose into
    exclusive segments that sum to the run's wall time (±1%) with restart
    badput > 0, while the uninterrupted reference run decomposes with badput
    exactly 0."""
    work = tmp_path / "supervised"
    work.mkdir()
    monkeypatch.chdir(work)
    store = str(work / "results" / "checkpoints")
    flags = tmp_path / "flags"
    flags.mkdir()
    monkeypatch.setenv("RESILIENCE_FAULTS",
                       f"torn:match=ckpt_00000008,flag={flags / 'torn'};"
                       f"kill:proc=1,step=8,exit=41,flag={flags / 'kill'}")
    cfg = sup.SupervisorConfig(num_processes=2, platform="cpu",
                               devices_per_process=1, max_restarts=2,
                               backoff_s=0.0, checkpoint_dir=store,
                               attempt_timeout_s=300,
                               telemetry=str(work / "supervisor.jsonl"))
    # --telemetry is cwd-relative: both supervised attempts write (and the
    # restarted one PRESERVES) one history at work/run.jsonl.
    res = sup.supervise(TRAIN + ["--telemetry", "run.jsonl"], cfg)
    assert (res.status, res.exit_code) == ("ok", 0)
    assert res.attempts == 2 and res.restarts == 1
    ckpt4 = os.path.join(store, checkpoint.versioned_name(4))
    # The torn step-8 checkpoint was never selected: attempt 2 resumed from step 4.
    assert res.resume_history == [None, ckpt4]
    with open(work / "supervisor.jsonl") as f:
        events = [json.loads(line) for line in f if line.strip()]
    restarts = [e for e in events if e["event"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["reason"] == "crash" and restarts[0]["exit_code"] == 41
    assert restarts[0]["resume_from"] == ckpt4

    # Uninterrupted reference run: same command, no faults, plain launch.
    monkeypatch.delenv("RESILIENCE_FAULTS")
    ref = tmp_path / "uninterrupted"
    ref.mkdir()
    monkeypatch.chdir(ref)
    assert launch(TRAIN + ["--telemetry", "run.jsonl"], num_processes=2,
                  platform="cpu", devices_per_process=1, timeout=300) == 0
    ref_store = str(ref / "results" / "checkpoints")
    ref_final = checkpoint.newest_valid_checkpoint(ref_store)
    supervised_final = checkpoint.newest_valid_checkpoint(store)
    assert _step_of(supervised_final) == _step_of(ref_final) \
        == EPOCHS * STEPS_PER_EPOCH

    # -- goodput accounting over the streams both runs just wrote ------------
    from csed_514_project_distributed_training_using_pytorch_tpu.obs import (
        goodput,
    )

    faulted = goodput.decompose([str(work / "run.jsonl"),
                                 str(work / "supervisor.jsonl")])
    # The preserved multi-attempt telemetry history: both attempts present.
    assert faulted["attempts"] == 2 and faulted["restarts"] == 1
    # Attempt 2 resumed from step 4 (epoch 0) and re-ran epoch 1: replayed
    # work is charged to restart badput, never to compute.
    assert faulted["epochs_replayed"] >= 1
    assert faulted["replayed_steps"] >= STEPS_PER_EPOCH
    assert faulted["segments"]["restart_badput_s"] > 0.0
    assert faulted["segments"]["compute_s"] > 0.0
    assert sum(faulted["segments"].values()) == pytest.approx(
        faulted["wall_s"], rel=0.01)
    assert faulted["unaccounted_s"] <= 0.01 * faulted["wall_s"]

    clean = goodput.decompose([str(ref / "run.jsonl")])
    assert clean["attempts"] == 1 and clean["restarts"] == 0
    assert clean["segments"]["restart_badput_s"] == 0.0       # exactly
    assert clean["epochs_replayed"] == 0
    assert sum(clean["segments"].values()) == pytest.approx(
        clean["wall_s"], rel=0.01)
    # The faulted run burned MORE wall for the same final step — and the
    # ledger knows where it went.
    assert faulted["wall_s"] > clean["wall_s"]
    assert faulted["goodput_frac"] < clean["goodput_frac"]


def test_preempted_fleet_exits_75_with_resumable_checkpoint(tmp_path, monkeypatch):
    """A SIGTERM'd (fault-delivered, so deterministic) fleet finishes its epoch,
    checkpoints, emits the preempt event, and exits 75; a fresh run resumes the
    checkpoint to the full step count."""
    monkeypatch.chdir(tmp_path)
    hb_dir = str(tmp_path / "hb")
    args = TRAIN + ["--heartbeat-dir", hb_dir,
                    "--telemetry", str(tmp_path / "run.jsonl")]
    # Both processes SIGTERM themselves at the epoch-1 tick (step 4): the run must
    # complete epoch 1, checkpoint at step 8, and stop cooperatively.
    monkeypatch.setenv("RESILIENCE_FAULTS", "preempt:step=4")
    code = launch(args, num_processes=2, platform="cpu", devices_per_process=1,
                  timeout=300)
    assert code == preemption.EXIT_PREEMPTED
    ckpt = tmp_path / "results" / "model_dist.ckpt"
    assert ckpt.exists() and _step_of(str(ckpt)) == 2 * STEPS_PER_EPOCH
    with open(tmp_path / "run.jsonl") as f:
        events = [json.loads(line) for line in f if line.strip()]
    preempts = [e for e in events if e["event"] == "preempt"]
    assert len(preempts) == 1
    assert preempts[0]["step"] == 2 * STEPS_PER_EPOCH
    assert preempts[0]["checkpoint"].endswith("model_dist.ckpt")
    beats = heartbeat.read_heartbeats(hb_dir)
    assert beats and all(b["status"] == heartbeat.STATUS_PREEMPTED
                         for b in beats.values())

    # The preempted checkpoint resumes to completion once capacity returns.
    monkeypatch.delenv("RESILIENCE_FAULTS")
    assert launch(args + ["--resume-from", str(ckpt)], num_processes=2,
                  platform="cpu", devices_per_process=1, timeout=300) == 0
    assert _step_of(str(ckpt)) == EPOCHS * STEPS_PER_EPOCH


@pytest.fixture()
def tiny_datasets():
    xs, ys = _synthesize_split(256, seed=300)
    train = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    xs, ys = _synthesize_split(100, seed=301)
    test = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    return train, test


def test_resilience_flags_are_bitwise_zero_cost(tmp_path, tiny_datasets):
    """Heartbeat + preemption wiring on (but unsignalled) trains bitwise-identically
    to flags off — the hooks are host-side only, the compiled program is untouched
    (the --health-stats discipline, acceptance criterion)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.train import single
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
        SingleProcessConfig,
    )

    results = {}
    try:
        for name, extra in [("off", {}),
                            ("on", {"heartbeat_dir": str(tmp_path / "hb"),
                                    "handle_preemption": True,
                                    "keep_checkpoints": 2})]:
            cfg = SingleProcessConfig(
                n_epochs=1, batch_size_train=64, batch_size_test=100,
                results_dir=str(tmp_path / name / "results"),
                images_dir=str(tmp_path / name / "images"), **extra)
            state, _ = single.main(cfg, datasets=tiny_datasets)
            results[name] = state
    finally:
        # single.main installs the SIGTERM/SIGINT latch in-process; restore.
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGINT, signal.default_int_handler)

    import jax
    leaves_off = jax.tree_util.tree_leaves(results["off"].params)
    leaves_on = jax.tree_util.tree_leaves(results["on"].params)
    for a, b in zip(leaves_off, leaves_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ...and the flag-on run actually produced its artifacts.
    beats = heartbeat.read_heartbeats(str(tmp_path / "hb"))
    assert beats[0]["epoch"] == 1
    store = str(tmp_path / "on" / "results" / "checkpoints")
    assert checkpoint.newest_valid_checkpoint(store) is not None


def test_single_trainer_preempts_cooperatively_in_process(tmp_path, monkeypatch,
                                                          tiny_datasets):
    """In-process flavor of the preemption contract: the fault-delivered SIGTERM
    surfaces as Preempted at the epoch boundary with the checkpoint durable."""
    from csed_514_project_distributed_training_using_pytorch_tpu import resilience
    from csed_514_project_distributed_training_using_pytorch_tpu.train import single
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
        SingleProcessConfig,
    )

    monkeypatch.setenv("RESILIENCE_FAULTS", "preempt:epoch=1")
    cfg = SingleProcessConfig(
        n_epochs=3, batch_size_train=64, batch_size_test=100,
        handle_preemption=True, heartbeat_dir=str(tmp_path / "hb"),
        results_dir=str(tmp_path / "results"), images_dir=str(tmp_path / "images"))
    try:
        with pytest.raises(resilience.Preempted) as ei:
            single.main(cfg, datasets=tiny_datasets)
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGINT, signal.default_int_handler)
    ckpt = tmp_path / "results" / "model.ckpt"
    assert ckpt.exists()
    assert ei.value.step == _step_of(str(ckpt)) > 0
    beats = heartbeat.read_heartbeats(str(tmp_path / "hb"))
    assert beats[0]["status"] == heartbeat.STATUS_PREEMPTED
