"""Flash-attention Pallas kernels vs the dense oracle.

Interpret-mode (CPU) tests pin exact numerics of the forward and the two-kernel
recompute backward against ``ops.full_attention``; the TPU-gated test re-checks parity
compiled through Mosaic on hardware (looser tolerance: TPU matmuls run f32 via bf16
passes in both paths, so they differ from each other at ~1e-3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.ops.attention import (
    full_attention,
)
from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
    BLOCK,
    flash_attention,
)


def _qkv(b=2, s=256, h=2, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
                 for _ in range(3))


def _tol(tight_rtol, tight_atol):
    """Interpret mode (CPU) is exact to f32 round-off; on hardware both paths run f32
    matmuls as bf16 MXU passes and differ from each other at ~1e-3."""
    if jax.default_backend() == "tpu":
        return dict(rtol=2e-2, atol=2e-2)
    return dict(rtol=tight_rtol, atol=tight_atol)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal)),
        np.asarray(full_attention(q, k, v, causal=causal)),
        **_tol(1e-5, 1e-5))


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(seed=1)

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v, causal=causal)))

    g_ref = jax.grad(loss(full_attention), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(1e-4, 2e-5))


def test_multi_block_sequence():
    """S spanning several 128-blocks exercises the online-softmax accumulation and the
    causal block-skip bounds."""
    q, k, v = _qkv(b=1, s=512, h=1, d=64, seed=2)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=True)),
        np.asarray(full_attention(q, k, v, causal=True)),
        **_tol(1e-5, 1e-5))


@pytest.mark.parametrize("causal", [False, True])
def test_block_size_is_numerics_invariant(causal):
    """``block`` is a pure performance knob (r3 tuning surface): a 256-row block over
    a 512-sequence — forward AND gradients — equals both the dense oracle and the
    default-block kernel."""
    q, k, v = _qkv(b=1, s=512, h=2, d=64, seed=4)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal, block=256)),
        np.asarray(full_attention(q, k, v, causal=causal)),
        **_tol(1e-5, 1e-5))

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v, causal=causal)))

    g_ref = jax.grad(loss(full_attention), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(lambda q, k, v, causal: flash_attention(
        q, k, v, causal=causal, block=256)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(1e-4, 2e-5))


def test_dense_window_matches_naive_mask():
    """full_attention(window=W) equals an explicit numpy band mask — the windowed
    semantics oracle (distance < W; causal restricts to the past side)."""
    q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=6)
    w = 10
    for causal in (False, True):
        ref = np.asarray(full_attention(q, k, v, causal=causal, window=w))
        i = np.arange(64)[:, None]
        j = np.arange(64)[None, :]
        mask = (np.abs(i - j) < w) & ((i >= j) if causal else True)
        scores = np.einsum("bqhd,bkhd->bhqk", np.asarray(q),
                           np.asarray(k)) / np.sqrt(16.0)
        scores = np.where(mask[None, None], scores, -1e30)
        weights = np.exp(scores - scores.max(-1, keepdims=True))
        weights /= weights.sum(-1, keepdims=True)
        naive = np.einsum("bhqk,bkhd->bqhd", weights, np.asarray(v))
        # The oracle is numpy f32; on hardware the jax side runs its matmuls as
        # bf16 MXU passes, so the comparison needs the hardware tolerance.
        np.testing.assert_allclose(ref, naive, **_tol(1e-5, 1e-6),
                                   err_msg=f"causal={causal}")


@pytest.mark.parametrize("causal,s,w", [
    # s=512, w=160: causal runs the band-compressed grid (reach+1 = 3 < 4 blocks);
    # non-causal falls back to the full grid (2·reach+1 = 5 ≥ 4) — both paths covered.
    (False, 512, 160), (True, 512, 160),
    # s=1024 activates the band-compressed grid for the BIDIRECTIONAL walk too
    # (5 < 8 blocks) — offsets clamp at both sequence edges.
    (False, 1024, 160), (True, 1024, 160),
])
def test_flash_window_matches_dense(causal, s, w):
    """Banded flash (band-compressed grid + in-kernel band mask) equals dense windowed
    attention — forward AND gradients. window=160 straddles block boundaries (not a
    multiple of 128), exercising partial-band blocks on both sides."""
    q, k, v = _qkv(b=1, s=s, h=2, d=64, seed=7)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal, window=w)),
        np.asarray(full_attention(q, k, v, causal=causal, window=w)),
        **_tol(1e-5, 1e-5))

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(loss(lambda q, k, v: full_attention(
        q, k, v, causal=causal, window=w)), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, window=w)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(1e-4, 2e-5))


def test_window_validation():
    q, k, v = _qkv(b=1, s=256, h=1, d=64, seed=8)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, window=0)
    with pytest.raises(ValueError, match="window"):
        full_attention(q, k, v, window=-1)


def test_block_validation():
    q, k, v = _qkv(b=1, s=256, h=1, d=64, seed=5)
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, k, v, block=64)
    with pytest.raises(ValueError, match="divisible by block"):
        flash_attention(q, k, v, block=384)


def test_indivisible_sequence_rejected():
    q, k, v = _qkv(s=200)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_bf16_forward_and_gradients_match_f32_dense(causal):
    """The r4 kernels keep matmul operands in the INPUT dtype (bf16 on the MXU's
    native path) with f32 accumulation — so the bf16 path must be pinned against
    the f32 dense oracle at bf16-resolution tolerance, not just exercised as the
    identity-astype f32 case the other tests cover."""
    q, k, v = _qkv(seed=11)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = full_attention(qb.astype(jnp.float32), kb.astype(jnp.float32),
                         vb.astype(jnp.float32), causal=causal)
    out = flash_attention(qb, kb, vb, causal=causal)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.03)

    def loss(attn, cast):
        return lambda q, k, v: jnp.sum(
            jnp.sin(attn(cast(q), cast(k), cast(v), causal=causal)
                    .astype(jnp.float32)))

    g_ref = jax.grad(loss(full_attention, lambda x: x.astype(jnp.float32)),
                     argnums=(0, 1, 2))(qb, kb, vb)
    g_flash = jax.grad(loss(flash_attention, lambda x: x),
                       argnums=(0, 1, 2))(qb, kb, vb)
    for name, a, b in zip("qkv", g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b, np.float32),
                                   np.asarray(a, np.float32),
                                   err_msg=name, rtol=0.1, atol=0.05)


@pytest.mark.parametrize("q_offset", [256, -256])
@pytest.mark.parametrize("window", [100, 300])
def test_q_offset_block_pair_matches_manual(q_offset, window):
    """The ring hop building block: a q-block set attending a k-block set whose
    global positions differ by a static q_offset must equal the manually-masked
    dense computation on the same band (rows with no visible key normalize to 0 —
    the ring merge never consumes them). Exercises the offset-shifted band masks
    and the banded grid's shifted center in one shot."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        flash_forward_with_lse,
    )

    bh, s, d = 2, 256, 32
    rng = np.random.default_rng(23)
    q3, k3, v3 = (jnp.asarray(rng.normal(size=(bh, s, d)).astype(np.float32))
                  for _ in range(3))
    out, _ = flash_forward_with_lse(q3, k3, v3, causal=False, window=window,
                                    q_offset=q_offset)

    rel = (q_offset + np.arange(s))[:, None] - np.arange(s)[None, :]
    visible = np.abs(rel) < window
    scores = np.einsum("bqd,bkd->bqk", np.asarray(q3),
                       np.asarray(k3)) / np.sqrt(d)
    scores = np.where(visible, scores, -np.inf)
    with np.errstate(invalid="ignore", over="ignore"):
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = np.nan_to_num(p, nan=0.0)
        denom = p.sum(-1, keepdims=True)
        ref = np.einsum("bqk,bkd->bqd", p / np.where(denom == 0, 1, denom),
                        np.asarray(v3))
    # _tol: hardware matmuls run bf16-multiply default precision vs numpy's exact
    # reference, so the TPU-gated pass needs the module's loose tolerance.
    np.testing.assert_allclose(np.asarray(out), ref, **_tol(1e-5, 1e-5))


def test_q_offset_validation():
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        flash_forward_with_lse,
    )

    q3 = jnp.zeros((1, 256, 32))
    with pytest.raises(ValueError, match="multiple of block"):
        flash_forward_with_lse(q3, q3, q3, window=64, q_offset=100)


def test_auto_block_selection():
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        auto_block,
    )

    assert auto_block(256) == 256
    assert auto_block(1024) == 1024
    assert auto_block(8192) == 1024      # capped at the measured sweet spot
    assert auto_block(1280) == 256       # largest divisor under the cap
    # Windowed cap is W-dependent (r5 hw sweeps): narrow bands keep the 512
    # windowed cap; wide bands (W >= WIDE_WINDOW) amortize like the full walk.
    assert auto_block(8192, window=256) == 512
    assert auto_block(8192, window=4096) == 1024
    with pytest.raises(ValueError, match="divisible by 128"):
        auto_block(200)


def test_dispatch_attention_routes_by_crossover(monkeypatch):
    """Below FLASH_MIN_SEQ (and for unaligned S) dispatch is exactly the dense
    path; at and above it, the flash kernels (checked by matching each impl's own
    output bit-for-bit, which also pins the routing)."""
    import csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention as pa

    q, k, v = _qkv(s=256, seed=7)
    np.testing.assert_array_equal(
        np.asarray(pa.dispatch_attention(q, k, v, causal=True)),
        np.asarray(full_attention(q, k, v, causal=True)))
    qo, ko, vo = _qkv(s=200, seed=8)     # unaligned: must fall to dense, not raise
    np.testing.assert_array_equal(
        np.asarray(pa.dispatch_attention(qo, ko, vo)),
        np.asarray(full_attention(qo, ko, vo)))
    monkeypatch.setattr(pa, "FLASH_MIN_SEQ", 256)
    np.testing.assert_array_equal(
        np.asarray(pa.dispatch_attention(q, k, v, causal=True)),
        np.asarray(flash_attention(q, k, v, causal=True)))


@pytest.mark.slow
def test_as_transformer_attention_core():
    """flash_attention plugs into the transformer family as attention_fn; one optimizer
    step from shared init matches the dense-core step."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state, make_train_step,
    )

    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.normal(size=(8, BLOCK, 8)).astype(np.float32))
    labels = jnp.asarray((np.arange(8) % 10).astype(np.int32))

    kwargs = dict(seq_len=BLOCK, embed_dim=32, num_layers=1, num_heads=2,
                  dropout_rate=0.0)
    dense_model = TransformerClassifier(**kwargs)
    flash_model = TransformerClassifier(attention_fn=flash_attention, **kwargs)
    state0 = create_train_state(dense_model, jax.random.PRNGKey(0),
                                sample_input_shape=(1, BLOCK, 8))

    results = []
    for m in (dense_model, flash_model):
        step = jax.jit(make_train_step(m, learning_rate=0.05, momentum=0.5))
        s1, loss = step(state0, tokens, labels, jax.random.PRNGKey(1))
        results.append((s1, float(loss)))
    (sa, la), (sb, lb) = results
    assert abs(la - lb) < (1e-2 if jax.default_backend() == "tpu" else 1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(sa.params),
                    jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   **_tol(1e-4, 1e-5))


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="hardware Mosaic-compile smoke (FRAMEWORK_TEST_PLATFORM=tpu)")
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("native", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_on_tpu_matches_dense(causal, native, dtype):
    """Compiled-through-Mosaic parity on a real chip — BOTH layouts × BOTH
    dtypes: the native-flat lane slices and rank-5 lse are constructs only the
    chip exercises, and the dtype axis is load-bearing — the r5 per-head
    SUBLANE-slice design compiled for f32 but crashed the Mosaic compiler for
    bf16 (slice feeding an MXU dot), a break an f32-only smoke cannot see.
    Tolerance 2e-2: on the MXU the f32 paths run their matmuls as bf16 passes
    and differ from the dense oracle at ~1e-3; the bf16 paths carry bf16
    operands end-to-end."""
    q, k, v = (x.astype(dtype) for x in _qkv(seed=4))
    ref = full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), causal=causal)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal,
                                   native_layout=native)).astype(np.float32),
        np.asarray(ref), rtol=2e-2, atol=2e-2)
    loss = lambda attn: lambda q, k, v: jnp.sum(
        jnp.sin(attn(q, k, v).astype(jnp.float32)))
    g_flash = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, native_layout=native)),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(lambda q, k, v: full_attention(q, k, v, causal=causal)),
                     argnums=(0, 1, 2))(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    # bf16 atol 5e-2: the measured on-chip worst-case |Δgrad| vs the f32 dense
    # oracle at these shapes is 0.018 (bf16 operand rounding through the sin
    # chain); 5e-2 pins with ~3× margin without being vacuous for O(1) grads.
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(b).astype(np.float32),
                                   np.asarray(a), rtol=2e-2, atol=5e-2 if
                                   dtype == "bfloat16" else 2e-2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "window",
    [None,
     # The windowed variant re-runs the full fwd+grad pinning with the band
     # masks (~20 s of interpret work); the slow tier also covers banded
     # native via test_native_layout_banded_grid_matches_dense.
     pytest.param(160, marks=pytest.mark.slow)])
def test_native_layout_is_numerics_invariant(causal, window):
    """``native_layout=True`` feeds the kernels [B, S, H, D] directly (no
    transpose repacks — r5, the repack copies were 11% of the r4 large
    transformer step): forward AND gradients equal the packed path's and the
    dense oracle's."""
    q, k, v = _qkv(b=2, s=256, h=4, d=64, seed=11)
    ref = full_attention(q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal, window=window,
                                   block=128, native_layout=True)),
        np.asarray(ref), **_tol(2e-5, 2e-5))

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(loss(lambda q, k, v: full_attention(
        q, k, v, causal=causal, window=window)), argnums=(0, 1, 2))(q, k, v)
    g_nat = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, window=window, block=128,
        native_layout=True)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_nat):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(2e-4, 2e-5))


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="hardware Mosaic-compile smoke (FRAMEWORK_TEST_PLATFORM=tpu)")
def test_native_strided_on_tpu_matches_dense():
    """Compiled-through-Mosaic parity for the STRIDED native form at the
    trainer geometry (D=128, bf16): lane-block index maps (g//H, walk, g%H)
    over the flat operands are chip-only constructs, and bf16 is the dtype
    whose layout bugs interpret mode has twice failed to catch."""
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(b=1, s=512, h=4, d=128,
                                                    seed=17))
    ref = full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=True,
                                   native_layout=True)).astype(np.float32),
        np.asarray(ref), rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
        q, k, v, causal=True, native_layout=True).astype(jnp.float32))),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(full_attention(
        q, k, v, causal=True))), argnums=(0, 1, 2))(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    for a, b in zip(g_ref, g):
        np.testing.assert_allclose(np.asarray(b).astype(np.float32),
                                   np.asarray(a), rtol=2e-2, atol=5e-2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("window", [None, 160])
def test_native_strided_mode_matches_dense(causal, window, monkeypatch):
    """At D % 128 == 0 the native layout takes the STRIDED form — packed grid,
    D-wide lane blocks over the flat [B, S, H·D] operands, no head unroll
    (``native_mode``): forward AND gradients equal the dense oracle's, the
    banded (windowed) walk index maps compose with the strided decomposition,
    and the mode predicate picks the form exactly when the head width
    permits."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        native_mode,
    )

    # Self-contained against the documented measurement knob: a stray
    # FLASH_NATIVE_MODE=unroll in the shell must not flip which form this pins.
    monkeypatch.delenv("FLASH_NATIVE_MODE", raising=False)
    assert native_mode(128) == "strided"
    assert native_mode(64) == "unroll"
    q, k, v = _qkv(b=2, s=256, h=3, d=128, seed=13)
    ref = full_attention(q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal, window=window,
                                   block=128, native_layout=True)),
        np.asarray(ref), **_tol(2e-5, 2e-5))

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(loss(lambda q, k, v: full_attention(
        q, k, v, causal=causal, window=window)), argnums=(0, 1, 2))(q, k, v)
    g_nat = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, window=window, block=128, native_layout=True)),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_nat):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(2e-4, 2e-5))


@pytest.mark.slow
@pytest.mark.parametrize("q_offset", [0, 256, -256])
def test_dyn_offset_banded_grid_matches_static(q_offset):
    """r5: a TRACED hop offset steers the banded walk through scalar-prefetch
    index maps — at sizes where banding engages (nq > 2*reach+1), the dynamic
    path's forward AND blockwise backward must equal the static-offset banded
    path exactly (same math, different grid steering)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        _band_reach, _banded, flash_backward_blocks, flash_forward_with_lse,
    )

    bh, s, d, window = 2, 1024, 32, 160
    assert _banded(window, False, s // 128, 128)   # the banded path is engaged
    rng = np.random.default_rng(31)
    q3, k3, v3, g = (jnp.asarray(rng.normal(size=(bh, s, d)).astype(np.float32))
                     for _ in range(4))

    out_s, lse_s = flash_forward_with_lse(q3, k3, v3, causal=False,
                                          window=window, q_offset=q_offset)
    out_d, lse_d = jax.jit(lambda off: flash_forward_with_lse(
        q3, k3, v3, causal=False, window=window, q_offset_dyn=off))(
        jnp.int32(q_offset))
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_s),
                               **_tol(1e-6, 1e-6))
    np.testing.assert_allclose(np.asarray(lse_d), np.asarray(lse_s),
                               **_tol(1e-6, 1e-6))

    delta = jnp.sum(g * out_s, axis=-1).reshape(bh, s // 128, 1, 128)
    grads_s = flash_backward_blocks(q3, k3, v3, g, lse_s, delta, causal=False,
                                    window=window, q_offset=q_offset)
    grads_d = jax.jit(lambda off: flash_backward_blocks(
        q3, k3, v3, g, lse_s, delta, causal=False, window=window,
        q_offset_dyn=off))(jnp.int32(q_offset))
    for name, a, b in zip("q k v".split(), grads_s, grads_d):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(1e-6, 1e-6))


@pytest.mark.slow
def test_dyn_offset_needs_no_block_quantization():
    """Unlike the static q_offset (rejected unless a block multiple), a TRACED
    offset may be arbitrary: the dynamic band is one block wider to absorb the
    sub-block remainder the floor-division steering discards. Pinned against the
    manual numpy band oracle at off=+100/-100 with banding engaged."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        _dyn_banded, flash_forward_with_lse,
    )

    bh, s, d, window = 2, 1024, 32, 160
    assert _dyn_banded(window, s // 128, 128)
    rng = np.random.default_rng(37)
    q3, k3, v3 = (jnp.asarray(rng.normal(size=(bh, s, d)).astype(np.float32))
                  for _ in range(3))
    for q_offset in (100, -100):
        out, _ = jax.jit(lambda off: flash_forward_with_lse(
            q3, k3, v3, causal=False, window=window, q_offset_dyn=off))(
            jnp.int32(q_offset))
        rel = (q_offset + np.arange(s))[:, None] - np.arange(s)[None, :]
        visible = np.abs(rel) < window
        scores = np.einsum("bqd,bkd->bqk", np.asarray(q3),
                           np.asarray(k3)) / np.sqrt(d)
        scores = np.where(visible, scores, -np.inf)
        with np.errstate(invalid="ignore", over="ignore"):
            p = np.exp(scores - scores.max(-1, keepdims=True))
            p = np.nan_to_num(p, nan=0.0)
            denom = p.sum(-1, keepdims=True)
            ref = np.einsum("bqk,bkd->bqd", p / np.where(denom == 0, 1, denom),
                            np.asarray(v3))
        np.testing.assert_allclose(np.asarray(out), ref, err_msg=str(q_offset),
                                   **_tol(1e-5, 1e-5))


@pytest.mark.slow
def test_dyn_offset_native_layout_forward():
    """The native-flat specs compose with scalar prefetch too: a traced offset
    over the [B, S, H·D] view (``heads=h``) equals the packed dynamic path."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        _flash_forward,
    )

    b, s, h, d, window = 2, 1024, 2, 32, 160
    rng = np.random.default_rng(41)
    q4, k4, v4 = (jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
                  for _ in range(3))
    pack = lambda x: jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    flat = lambda x: x.reshape(b, s, h * d)
    outf, lse5 = jax.jit(lambda off: _flash_forward(
        flat(q4), flat(k4), flat(v4), causal=False, window=window,
        q_offset_dyn=off, heads=h))(jnp.int32(256))
    out3, lse4 = jax.jit(lambda off: _flash_forward(
        pack(q4), pack(k4), pack(v4), causal=False, window=window,
        q_offset_dyn=off))(jnp.int32(256))
    np.testing.assert_allclose(
        np.asarray(pack(outf.reshape(b, s, h, d))), np.asarray(out3),
        **_tol(1e-6, 1e-6))
    np.testing.assert_allclose(
        np.asarray(lse5.reshape(b * h, *lse4.shape[1:])), np.asarray(lse4),
        **_tol(1e-6, 1e-6))


def test_dyn_offset_native_strided_forward():
    """The native-STRIDED form (``per_head_grid=True``: packed ``(B·H, nq,
    steps)`` grid, D-wide lane blocks over the flat operands) composes with
    scalar prefetch too — the strided dyn-offset index maps ``(g//H, idx(i, j,
    off), g%H)`` equal the packed dynamic path. Mirrors
    ``test_dyn_offset_native_layout_forward`` at a register-width head dim
    (D % 128 == 0, the shape that selects this form)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        _flash_forward,
    )

    b, s, h, d, window = 2, 1024, 2, 128, 160
    rng = np.random.default_rng(42)
    q4, k4, v4 = (jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
                  for _ in range(3))
    pack = lambda x: jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    flat = lambda x: x.reshape(b, s, h * d)
    outf, lse_strided = jax.jit(lambda off: _flash_forward(
        flat(q4), flat(k4), flat(v4), causal=False, window=window,
        q_offset_dyn=off, heads=h, per_head_grid=True))(jnp.int32(256))
    out3, lse4 = jax.jit(lambda off: _flash_forward(
        pack(q4), pack(k4), pack(v4), causal=False, window=window,
        q_offset_dyn=off))(jnp.int32(256))
    np.testing.assert_allclose(
        np.asarray(pack(outf.reshape(b, s, h, d))), np.asarray(out3),
        **_tol(1e-6, 1e-6))
    # The strided form keeps the packed lse shape — directly comparable.
    np.testing.assert_allclose(np.asarray(lse_strided), np.asarray(lse4),
                               **_tol(1e-6, 1e-6))


def test_native_unroll_auto_block_envelope_falls_back_to_packed():
    """A geometry whose smallest legal native-unroll block (128·H·D) exceeds
    the VMEM envelope must not die at trace time when the block is AUTO-chosen:
    ``flash_attention`` warns and falls back to the packed layout (same math);
    an EXPLICIT block keeps the hard error — the user asked for something the
    chip cannot compile."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        NATIVE_BLOCK_ELEMS,
    )

    b, s, h, d = 1, 128, 32, 80            # D % 128 != 0 -> unroll form
    assert 128 * h * d > NATIVE_BLOCK_ELEMS
    q, k, v = _qkv(b=b, s=s, h=h, d=d, seed=7)
    with pytest.warns(UserWarning, match="falling back to the packed layout"):
        out = flash_attention(q, k, v, native_layout=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(full_attention(q, k, v)),
                               **_tol(1e-5, 1e-5))
    with pytest.raises(ValueError, match="block\\*heads\\*head_dim"):
        flash_attention(q, k, v, native_layout=True, block=128)


def test_native_mode_rejects_unknown_env(monkeypatch):
    """``FLASH_NATIVE_MODE`` is a measurement knob: a typo'd value silently
    timing the default form would poison the comparison it exists for —
    validate against {'', 'unroll'} and raise on anything else."""
    from csed_514_project_distributed_training_using_pytorch_tpu.ops.pallas_attention import (
        native_mode,
    )

    monkeypatch.setenv("FLASH_NATIVE_MODE", "unroll")
    assert native_mode(128) == "unroll"
    monkeypatch.setenv("FLASH_NATIVE_MODE", "")
    assert native_mode(128) == "strided"
    assert native_mode(64) == "unroll"
    monkeypatch.setenv("FLASH_NATIVE_MODE", "strided")   # not a valid FORCE
    with pytest.raises(ValueError, match="FLASH_NATIVE_MODE"):
        native_mode(128)
    monkeypatch.setenv("FLASH_NATIVE_MODE", "unrol")
    with pytest.raises(ValueError, match="got 'unrol'"):
        native_mode(64)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_native_layout_banded_grid_matches_dense(causal):
    """Native [B,S,H,D] layout × the band-compressed grid (s large enough that
    banding engages) — the 4-d walk specs' banded index maps, fwd + grads."""
    q, k, v = _qkv(b=1, s=1024, h=2, d=64, seed=43)
    w = 160
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=causal, window=w,
                                   native_layout=True)),
        np.asarray(full_attention(q, k, v, causal=causal, window=w)),
        **_tol(1e-5, 1e-5))

    def loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    g_ref = jax.grad(loss(lambda q, k, v: full_attention(
        q, k, v, causal=causal, window=w)), argnums=(0, 1, 2))(q, k, v)
    g_nat = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, window=w, native_layout=True)),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_nat):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   err_msg=name, **_tol(1e-4, 2e-5))
