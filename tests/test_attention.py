"""ops.attention + the new transformer-support ops (layer_norm, gelu).

These are beyond-parity ops (the reference has no attention or normalization anywhere —
its only model is the conv/fc CNN, reference ``src/model.py:4-22``); the oracle here is
direct numpy math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops


def _numpy_attention(q, k, v, causal=False):
    b, s, h, d = q.shape
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        scores = np.where(mask[None, None], scores, -np.inf)
    scores -= scores.max(-1, keepdims=True)
    w = np.exp(scores)
    w /= w.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", w, v)


@pytest.mark.parametrize("causal", [False, True])
def test_full_attention_matches_numpy(causal):
    rng = np.random.default_rng(0)
    q, k, v = (rng.normal(size=(2, 6, 3, 4)).astype(np.float32) for _ in range(3))
    out = ops.full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=causal)
    np.testing.assert_allclose(np.asarray(out), _numpy_attention(q, k, v, causal),
                               rtol=1e-5, atol=1e-6)


def test_causal_first_token_attends_only_to_itself():
    rng = np.random.default_rng(1)
    q, k = (rng.normal(size=(1, 5, 1, 4)).astype(np.float32) for _ in range(2))
    v = rng.normal(size=(1, 5, 1, 4)).astype(np.float32)
    out = ops.full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=True)
    # Query 0 sees only key 0 → its output IS v[0] exactly (softmax over one entry).
    np.testing.assert_allclose(np.asarray(out)[0, 0, 0], v[0, 0, 0],
                               rtol=1e-6, atol=1e-6)


def test_layer_norm_matches_numpy():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 7, 16)).astype(np.float32) * 3 + 1
    gamma = rng.normal(size=(16,)).astype(np.float32)
    beta = rng.normal(size=(16,)).astype(np.float32)
    out = ops.layer_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    expected = (x - mean) / np.sqrt(var + 1e-5) * gamma + beta
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)


def test_layer_norm_output_standardized():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32) * 10 + 5)
    out = ops.layer_norm(x, jnp.ones(32), jnp.zeros(32))
    np.testing.assert_allclose(np.asarray(out).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out).std(-1), 1.0, atol=1e-3)


def test_gelu_basic_properties():
    x = jnp.linspace(-5, 5, 101)
    y = ops.gelu(x)
    assert bool(jnp.all(jnp.isfinite(y)))
    # gelu(0)=0; positive tail ≈ identity, negative tail ≈ 0
    np.testing.assert_allclose(float(ops.gelu(jnp.zeros(()))), 0.0, atol=1e-7)
    np.testing.assert_allclose(float(y[-1]), 5.0, atol=1e-3)
    np.testing.assert_allclose(float(y[0]), 0.0, atol=1e-3)


def test_full_attention_is_jittable_and_differentiable():
    rng = np.random.default_rng(4)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 4, 2, 4)).astype(np.float32))
               for _ in range(3))

    @jax.jit
    def loss(q, k, v):
        return jnp.sum(jnp.square(ops.full_attention(q, k, v, causal=True)))

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in grads)
