"""Native (C++) data-loader runtime: bit-exact parity with the pure-numpy paths.

The native library (``data/_native/loader.cc`` via ``data/native.py``) re-creates the C++
substrate the reference's input path leans on (torchvision cache reader + DataLoader worker
pool, reference ``src/train.py:26-31``, ``src/train_dist.py:43-45``). These tests assert that
every native entry point produces exactly what the numpy fallback produces, so the two paths
are interchangeable.
"""

import gzip
import os
import struct

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    BatchLoader, load_mnist, mnist, native,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native loader library not built (no toolchain)")


@pytest.fixture(scope="module")
def imgs_u8():
    return np.random.default_rng(7).integers(0, 256, size=(64, 28, 28), dtype=np.uint8)


@pytest.fixture(scope="module")
def dataset():
    train, _ = load_mnist("/nonexistent-data-dir", synthetic_seed=99)
    return train


def _write_idx(path: str, arr: np.ndarray, gz: bool = False) -> str:
    header = struct.pack(">I", 0x0800 | arr.ndim) + struct.pack(
        f">{arr.ndim}I", *arr.shape)
    opener = gzip.open if gz else open
    with opener(path, "wb") as f:
        f.write(header + arr.tobytes())
    return path


class TestIdxParsing:
    def test_images_plain_and_gz(self, tmp_path, imgs_u8):
        plain = _write_idx(str(tmp_path / "imgs"), imgs_u8)
        gzed = _write_idx(str(tmp_path / "imgs.gz"), imgs_u8, gz=True)
        np.testing.assert_array_equal(native.load_idx(plain), imgs_u8)
        np.testing.assert_array_equal(native.load_idx(gzed), imgs_u8)
        np.testing.assert_array_equal(native.load_idx(plain), mnist._read_idx(plain))

    def test_labels_1d(self, tmp_path):
        labels = np.arange(100, dtype=np.uint8) % 10
        path = _write_idx(str(tmp_path / "labels"), labels)
        np.testing.assert_array_equal(native.load_idx(path), labels)

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(ValueError):
            native.load_idx(str(tmp_path / "nope"))

    def test_bad_magic_raises(self, tmp_path):
        path = tmp_path / "bad"
        path.write_bytes(b"\x00\x00\x07\x03" + b"\x00" * 32)
        with pytest.raises(ValueError):
            native.load_idx(str(path))


class TestNormalize:
    def test_bit_exact_vs_numpy(self, imgs_u8):
        got = native.normalize(imgs_u8, mnist.MNIST_MEAN, mnist.MNIST_STD)
        want = mnist._normalize(imgs_u8)
        assert got.shape == want.shape == (64, 28, 28, 1)
        assert got.dtype == np.float32
        np.testing.assert_array_equal(got, want)

    def test_multithreaded_matches_single(self, imgs_u8):
        a = native.normalize(imgs_u8, mnist.MNIST_MEAN, mnist.MNIST_STD, num_threads=1)
        b = native.normalize(imgs_u8, mnist.MNIST_MEAN, mnist.MNIST_STD, num_threads=8)
        np.testing.assert_array_equal(a, b)


class TestGather:
    def test_matches_fancy_index(self, dataset):
        idx = np.random.default_rng(3).permutation(len(dataset))[:128].astype(np.int32)
        gi, gl = native.gather(dataset.images, dataset.labels, idx)
        np.testing.assert_array_equal(gi, dataset.images[idx])
        np.testing.assert_array_equal(gl, dataset.labels[idx])

    def test_out_of_range_raises(self, dataset):
        with pytest.raises(IndexError):
            native.gather(dataset.images, dataset.labels,
                          np.array([0, len(dataset)], dtype=np.int32))


class TestPrefetcher:
    def test_order_and_content(self, dataset):
        rng = np.random.default_rng(11)
        plan = rng.integers(0, len(dataset), size=(23, 32)).astype(np.int32)
        with native.Prefetcher(dataset.images, dataset.labels, plan,
                               num_workers=3, capacity=4) as pf:
            steps = 0
            for s, (bi, bl) in enumerate(pf):
                np.testing.assert_array_equal(bi, dataset.images[plan[s]])
                np.testing.assert_array_equal(bl, dataset.labels[plan[s]])
                steps += 1
        assert steps == 23

    def test_capacity_smaller_than_steps(self, dataset):
        plan = np.arange(40 * 8, dtype=np.int32).reshape(40, 8)
        with native.Prefetcher(dataset.images, dataset.labels, plan,
                               num_workers=2, capacity=2) as pf:
            got = [bl.copy() for _, bl in pf]
        assert len(got) == 40
        for s, bl in enumerate(got):
            np.testing.assert_array_equal(bl, dataset.labels[plan[s]])

    def test_early_close_does_not_hang(self, dataset):
        plan = np.arange(100 * 16, dtype=np.int32).reshape(100, 16) % len(dataset)
        pf = native.Prefetcher(dataset.images, dataset.labels, plan,
                               num_workers=4, capacity=2)
        it = iter(pf)
        next(it)
        pf.close()  # workers blocked on a full ring must exit cleanly
        with pytest.raises(ValueError, match="closed"):
            next(it)  # iterating a closed prefetcher must raise, not segfault

    def test_bad_plan_index_reported(self, dataset):
        plan = np.full((3, 4), len(dataset), dtype=np.int32)  # every index out of range
        with native.Prefetcher(dataset.images, dataset.labels, plan) as pf:
            with pytest.raises(IndexError):
                list(pf)


class TestNormalizeInProductPath:
    def test_load_mnist_routes_through_native_normalize(self, tmp_path, monkeypatch):
        """load_mnist must actually call native.normalize when the library is available,
        and its output must equal the pure-numpy pipeline bit-for-bit. Exercised end to end
        with real IDX files so both the native IDX read and normalize wiring run."""
        rng = np.random.default_rng(2)
        train_x = rng.integers(0, 256, (20, 28, 28), dtype=np.uint8)
        test_x = rng.integers(0, 256, (8, 28, 28), dtype=np.uint8)
        train_y = (np.arange(20) % 10).astype(np.uint8)
        test_y = (np.arange(8) % 10).astype(np.uint8)
        _write_idx(str(tmp_path / "train-images-idx3-ubyte"), train_x)
        _write_idx(str(tmp_path / "train-labels-idx1-ubyte"), train_y)
        _write_idx(str(tmp_path / "t10k-images-idx3-ubyte"), test_x)
        _write_idx(str(tmp_path / "t10k-labels-idx1-ubyte"), test_y)

        calls = []
        real_normalize = native.normalize

        def recording_normalize(*args, **kwargs):
            calls.append(args[0].shape)
            return real_normalize(*args, **kwargs)

        monkeypatch.setattr(native, "normalize", recording_normalize)
        train, test = load_mnist(str(tmp_path), allow_synthetic=False)

        assert train.source == "idx"
        assert calls == [(20, 28, 28), (8, 28, 28)]
        np.testing.assert_array_equal(train.images, mnist._normalize(train_x))
        np.testing.assert_array_equal(test.images, mnist._normalize(test_x))
        np.testing.assert_array_equal(train.labels, train_y.astype(np.int32))
        np.testing.assert_array_equal(test.labels, test_y.astype(np.int32))


class TestBatchLoaderIntegration:
    def test_iter_uses_native_and_matches_numpy(self, dataset):
        loader = BatchLoader(dataset, 64, shuffle=True, seed=5)
        loader.set_epoch(2)
        indices = loader.sampler.epoch_indices(2)
        for i, (bi, bl) in enumerate(loader):
            idx = indices[i * 64:(i + 1) * 64]
            np.testing.assert_array_equal(bi, dataset.images[idx])
            np.testing.assert_array_equal(bl, dataset.labels[idx])
            if i >= 3:
                break

    def test_prefetch_iter_matches_index_matrix(self, dataset):
        loader = BatchLoader(dataset, 128, shuffle=True, seed=6)
        loader.set_epoch(1)
        plan = loader.epoch_index_matrix(1)
        for s, (bi, bl) in enumerate(loader.prefetch_iter(1)):
            np.testing.assert_array_equal(bi, dataset.images[plan[s]])
            np.testing.assert_array_equal(bl, dataset.labels[plan[s]])
        assert s == plan.shape[0] - 1

    def test_iter_plan_batches_on_noncontiguous_column_slice(self, dataset):
        """The distributed host-local feed passes a column slice of the global plan
        (non-contiguous view) — native-path batches must equal a plain gather of the
        same rows (the numpy-fallback leg is covered unconditionally in
        test_data.py::test_iter_plan_batches_numpy_fallback)."""
        from csed_514_project_distributed_training_using_pytorch_tpu.data.loader import (
            iter_plan_batches,
        )
        rng = np.random.default_rng(13)
        full = rng.integers(0, len(dataset), size=(9, 32)).astype(np.int32)
        local = full[:, 8:24]            # a process's column block, as in _host_local_columns
        steps = 0
        for s, (bi, bl) in enumerate(iter_plan_batches(dataset, local)):
            np.testing.assert_array_equal(bi, dataset.images[local[s]])
            np.testing.assert_array_equal(bl, dataset.labels[local[s]])
            steps += 1
        assert steps == 9
