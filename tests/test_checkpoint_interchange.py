"""Checkpoint interchange across sharding layouts.

One property the whole parallelism surface hangs on: a TrainState checkpoint is layout-
free. The same init trained one step under every execution layout (single device, DP,
TP, FSDP, 3-axis composed) produces the same full TrainState — params AND optimizer
velocity — to f32 round-off (cross-layout reduction orders differ), the save/restore
round-trip itself is bit-exact, and any sharded state's checkpoint restores into the
plain unsharded template.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    TransformerClassifier,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    fsdp,
    make_mesh,
    make_ring_attention_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    tensor_parallel as tp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state,
    make_train_step,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint

# Heavyweight end-to-end/equivalence tests: full-suite runs only; deselect with
# -m "not slow" for the fast single-core signal (README).
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    return (jnp.asarray(rng.normal(size=(16, 28, 28, 1)).astype(np.float32)),
            jnp.asarray((np.arange(16) % 10).astype(np.int32)))


def test_every_layout_checkpoints_to_the_same_state(tmp_path, batch):
    x, y = batch
    model = TransformerClassifier(dropout_rate=0.0)
    rng = jax.random.PRNGKey(1)

    def fresh():
        return create_train_state(model, jax.random.PRNGKey(0))

    step_fn = lambda m: make_train_step(m, learning_rate=0.05, momentum=0.5)

    # Reference: plain single-device jit.
    ref_state, ref_loss = jax.jit(step_fn(model))(fresh(), x, y, rng)

    trained = {}
    from jax.sharding import PartitionSpec as P

    mesh_dp = make_mesh(8)
    trained["dp"] = dp.compile_step(step_fn(model), mesh_dp)(
        jax.device_put(fresh(), dp.replicated(mesh_dp)),
        dp.put_global(mesh_dp, np.asarray(x), P("data")),
        dp.put_global(mesh_dp, np.asarray(y), P("data")), rng)[0]

    mesh_tp = make_mesh(4, axis_names=("model",))
    trained["tp"] = tp.compile_step_tp(step_fn(model), mesh_tp, data_axis=None)(
        tp.shard_train_state(mesh_tp, fresh()), x, y, rng)[0]

    trained["fsdp"] = fsdp.compile_step_fsdp(step_fn(model), mesh_dp)(
        fsdp.shard_train_state(mesh_dp, fresh()), x, y, rng)[0]

    mesh_3d = make_mesh(8, axis_names=("data", "seq", "model"), axis_shape=(2, 2, 2))
    ring_model = TransformerClassifier(dropout_rate=0.0,
                                       attention_fn=make_ring_attention_fn(mesh_3d))
    trained["composed"] = tp.compile_step_tp(step_fn(ring_model), mesh_3d)(
        tp.shard_train_state(mesh_3d, fresh()), x, y, rng)[0]

    template = fresh()
    ref_param_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_state.params))
    ref_vel_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_state.velocity))
    for name, state in trained.items():
        host_state = jax.device_get(state)
        path = str(tmp_path / f"{name}.ckpt")
        checkpoint.save_train_state(path, host_state)
        restored = checkpoint.restore_train_state(path, template)
        assert int(restored.step) == 1
        # save/restore round-trip is bit-exact vs what was saved
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(host_state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"roundtrip {name}")
        # and the full TrainState matches the single-device result to f32 round-off
        for a, b in zip(jax.tree_util.tree_leaves(restored.params), ref_param_leaves):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"params {name}")
        for a, b in zip(jax.tree_util.tree_leaves(restored.velocity), ref_vel_leaves):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"velocity {name}")


class TestShardedCheckpoint:
    """Per-process distributed checkpoints: every process writes only the shards it
    addresses, restore re-assembles from ANY source layout (and can re-shard onto the
    current mesh) — the multi-host-scalable path beside the process-0 full-state
    writer."""

    def _trained_fsdp(self, batch):
        x, y = batch
        model = TransformerClassifier(dropout_rate=0.0)
        mesh = make_mesh(8)
        state = fsdp.shard_train_state(
            mesh, create_train_state(model, jax.random.PRNGKey(0)))
        step = fsdp.compile_step_fsdp(
            make_train_step(model, learning_rate=0.05, momentum=0.5), mesh)
        state, _ = step(state, x, y, jax.random.PRNGKey(1))
        return model, mesh, state

    def test_fsdp_round_trip_and_reshard_to_tp(self, tmp_path, batch):
        model, mesh, state = self._trained_fsdp(batch)
        d = str(tmp_path / "sharded.ckpt")
        checkpoint.save_train_state_sharded(d, state)
        import os

        assert os.path.exists(os.path.join(d, "meta.msgpack"))
        assert os.path.exists(os.path.join(d, "shards_p0.msgpack"))

        template = create_train_state(model, jax.random.PRNGKey(9))
        restored = checkpoint.restore_train_state_sharded(d, template)
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(restored)),
                        jax.tree_util.tree_leaves(jax.device_get(state))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # Re-shard the FSDP-written checkpoint straight onto a TP mesh.
        mesh_tp = make_mesh(8, axis_names=("model",))
        tp_sh = tp.state_shardings(mesh_tp,
                                   create_train_state(model, jax.random.PRNGKey(9)))
        resharded = checkpoint.restore_train_state_sharded(d, template,
                                                           shardings=tp_sh)
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(resharded)),
                        jax.tree_util.tree_leaves(jax.device_get(state))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_ema_none_and_scalar_step_round_trip(self, tmp_path):
        model = TransformerClassifier(dropout_rate=0.0)
        state = create_train_state(model, jax.random.PRNGKey(0), ema=True)
        state = state._replace(step=jnp.asarray(17, jnp.int32))
        d = str(tmp_path / "ema.ckpt")
        checkpoint.save_train_state_sharded(d, state)
        restored = checkpoint.restore_train_state_sharded(
            d, create_train_state(model, jax.random.PRNGKey(3), ema=True))
        assert int(restored.step) == 17
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(restored.ema)[0]),
            np.asarray(jax.tree_util.tree_leaves(state.ema)[0]))
        # ema=None round-trips as absent.
        plain = create_train_state(model, jax.random.PRNGKey(0))
        d2 = str(tmp_path / "plain.ckpt")
        checkpoint.save_train_state_sharded(d2, plain)
        r2 = checkpoint.restore_train_state_sharded(
            d2, create_train_state(model, jax.random.PRNGKey(3)))
        assert r2.ema is None
        # Cross-flag interchange (mirrors restore_train_state): a pre-EMA sharded
        # checkpoint seeds an EMA-enabled reference's tree from its params...
        r3 = checkpoint.restore_train_state_sharded(
            d2, create_train_state(model, jax.random.PRNGKey(3), ema=True))
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(r3.ema)[0]),
            np.asarray(jax.tree_util.tree_leaves(plain.params)[0]))
        # ...and an EMA sharded checkpoint restores into a plain reference by
        # dropping the tree.
        r4 = checkpoint.restore_train_state_sharded(
            d, create_train_state(model, jax.random.PRNGKey(3)))
        assert r4.ema is None

    def test_stale_larger_fleet_shards_are_not_merged(self, tmp_path):
        import os
        import shutil

        model = TransformerClassifier(dropout_rate=0.0)
        state = create_train_state(model, jax.random.PRNGKey(0))
        d = str(tmp_path / "s.ckpt")
        checkpoint.save_train_state_sharded(d, state)
        # Simulate leftovers from an older, larger fleet in the same directory:
        # restore must read exactly process_count files and ignore the stale one,
        # and a fresh save must clean it up.
        stale = os.path.join(d, "shards_p7.msgpack")
        shutil.copy(os.path.join(d, "shards_p0.msgpack"), stale)
        restored = checkpoint.restore_train_state_sharded(
            d, create_train_state(model, jax.random.PRNGKey(3)))
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(restored.params)[0]),
            np.asarray(jax.tree_util.tree_leaves(state.params)[0]))
        checkpoint.save_train_state_sharded(d, state)
        assert not os.path.exists(stale)

    def test_missing_blocks_detected(self, tmp_path, batch):
        from flax import serialization as ser

        _, _, state = self._trained_fsdp(batch)
        d = str(tmp_path / "broken.ckpt")
        checkpoint.save_train_state_sharded(d, state)
        import os

        p = os.path.join(d, "shards_p0.msgpack")
        shards = ser.msgpack_restore(open(p, "rb").read())
        dropped = next(k for k in shards if "pos_embed" in k)
        del shards[dropped]
        open(p, "wb").write(ser.msgpack_serialize(shards))
        with pytest.raises(ValueError, match="missing blocks"):
            checkpoint.restore_train_state_sharded(
                d, create_train_state(TransformerClassifier(dropout_rate=0.0),
                                      jax.random.PRNGKey(9)))
        with pytest.raises(FileNotFoundError):
            checkpoint.restore_train_state_sharded(
                str(tmp_path / "empty"), state)

    def test_overlapping_blocks_do_not_mask_missing_region(self, tmp_path, batch):
        """Coverage is checked per element, not by volume: a duplicated block whose
        element count equals the hole it leaves (a writer bug, a hand-edited
        checkpoint) must still fail restore rather than silently yield zeros."""
        from flax import serialization as ser

        _, _, state = self._trained_fsdp(batch)
        d = str(tmp_path / "overlap.ckpt")
        checkpoint.save_train_state_sharded(d, state)
        import os

        p = os.path.join(d, "shards_p0.msgpack")
        shards = ser.msgpack_restore(open(p, "rb").read())
        key, blocks = next((k, b) for k, b in shards.items()
                           if b and b[0]["data"].ndim
                           and b[0]["data"].shape[0] % 2 == 0)
        blk = blocks[0]
        half = np.asarray(blk["data"])[: blk["data"].shape[0] // 2]
        dup = {"start": blk["start"], "data": half}
        shards[key] = [dup, dict(dup)] + list(blocks[1:])
        open(p, "wb").write(ser.msgpack_serialize(shards))
        with pytest.raises(ValueError, match="missing blocks"):
            checkpoint.restore_train_state_sharded(
                d, create_train_state(TransformerClassifier(dropout_rate=0.0),
                                      jax.random.PRNGKey(9)))


def test_box_subtract_matches_mask_oracle():
    """The O(#blocks) coverage arithmetic must agree exactly with the per-element
    bool-mask oracle it replaced (r4 advisor finding), including overlaps, exact
    fits, disjoint cuts, and scalars."""
    rng = np.random.default_rng(7)
    for _ in range(200):
        ndim = int(rng.integers(0, 4))
        shape = tuple(int(n) for n in rng.integers(1, 7, size=ndim))
        remaining = [tuple((0, n) for n in shape)]
        mask = np.zeros(shape, bool)
        for _ in range(int(rng.integers(1, 6))):
            lo = [int(rng.integers(0, n + 1)) for n in shape]
            hi = [int(rng.integers(l, n + 1)) for l, n in zip(lo, shape)]
            cut = tuple(zip(lo, hi))
            remaining = [p for box in remaining
                         for p in checkpoint._box_subtract(box, cut)]
            mask[tuple(slice(l, h) for l, h in cut)] = True
        # Rebuild a mask from the remaining boxes: complement must match exactly.
        rebuilt = np.ones(shape, bool)
        for box in remaining:
            rebuilt[tuple(slice(lo, hi) for lo, hi in box)] = False
        np.testing.assert_array_equal(rebuilt, mask)
