"""Checkpoint interchange across sharding layouts.

One property the whole parallelism surface hangs on: a TrainState checkpoint is layout-
free. The same init trained one step under every execution layout (single device, DP,
TP, FSDP, 3-axis composed) produces the same full TrainState — params AND optimizer
velocity — to f32 round-off (cross-layout reduction orders differ), the save/restore
round-trip itself is bit-exact, and any sharded state's checkpoint restores into the
plain unsharded template.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models import (
    TransformerClassifier,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    fsdp,
    make_mesh,
    make_ring_attention_fn,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    tensor_parallel as tp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state,
    make_train_step,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils import checkpoint


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    return (jnp.asarray(rng.normal(size=(16, 28, 28, 1)).astype(np.float32)),
            jnp.asarray((np.arange(16) % 10).astype(np.int32)))


def test_every_layout_checkpoints_to_the_same_state(tmp_path, batch):
    x, y = batch
    model = TransformerClassifier(dropout_rate=0.0)
    rng = jax.random.PRNGKey(1)

    def fresh():
        return create_train_state(model, jax.random.PRNGKey(0))

    step_fn = lambda m: make_train_step(m, learning_rate=0.05, momentum=0.5)

    # Reference: plain single-device jit.
    ref_state, ref_loss = jax.jit(step_fn(model))(fresh(), x, y, rng)

    trained = {}
    from jax.sharding import PartitionSpec as P

    mesh_dp = make_mesh(8)
    trained["dp"] = dp.compile_step(step_fn(model), mesh_dp)(
        jax.device_put(fresh(), dp.replicated(mesh_dp)),
        dp.put_global(mesh_dp, np.asarray(x), P("data")),
        dp.put_global(mesh_dp, np.asarray(y), P("data")), rng)[0]

    mesh_tp = make_mesh(4, axis_names=("model",))
    trained["tp"] = tp.compile_step_tp(step_fn(model), mesh_tp, data_axis=None)(
        tp.shard_train_state(mesh_tp, fresh()), x, y, rng)[0]

    trained["fsdp"] = fsdp.compile_step_fsdp(step_fn(model), mesh_dp)(
        fsdp.shard_train_state(mesh_dp, fresh()), x, y, rng)[0]

    mesh_3d = make_mesh(8, axis_names=("data", "seq", "model"), axis_shape=(2, 2, 2))
    ring_model = TransformerClassifier(dropout_rate=0.0,
                                       attention_fn=make_ring_attention_fn(mesh_3d))
    trained["composed"] = tp.compile_step_tp(step_fn(ring_model), mesh_3d)(
        tp.shard_train_state(mesh_3d, fresh()), x, y, rng)[0]

    template = fresh()
    ref_param_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_state.params))
    ref_vel_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_state.velocity))
    for name, state in trained.items():
        host_state = jax.device_get(state)
        path = str(tmp_path / f"{name}.ckpt")
        checkpoint.save_train_state(path, host_state)
        restored = checkpoint.restore_train_state(path, template)
        assert int(restored.step) == 1
        # save/restore round-trip is bit-exact vs what was saved
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(host_state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"roundtrip {name}")
        # and the full TrainState matches the single-device result to f32 round-off
        for a, b in zip(jax.tree_util.tree_leaves(restored.params), ref_param_leaves):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"params {name}")
        for a, b in zip(jax.tree_util.tree_leaves(restored.velocity), ref_vel_leaves):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"velocity {name}")
