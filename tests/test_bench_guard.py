"""tools/bench_guard.py: the perf-regression gate's contract.

The acceptance criteria of the gate itself: it exits 0 against a freshly
seeded baseline, nonzero (exit 3) on an injected synthetic regression, and
its artifacts (run JSON + bench_guard telemetry lines) carry the per-metric
medians and ratios. Subprocess-driven like the other tool tests — the gate
must work from a bare ``python tools/bench_guard.py``, which is exactly how
the CI job invokes it.

The suite is restricted to ``decode_tick_s`` here: one metric exercises the
whole measure/gate/artifact pipeline, and tier-1 should not pay four model
compiles per assertion. The full four-metric suite runs in the (non-blocking)
``bench-guard`` CI job and seeds ``bench_results/guard_baseline.json``.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)
_TOOL = os.path.join(_REPO, "tools", "bench_guard.py")
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def _run(*args):
    return subprocess.run([sys.executable, _TOOL, *args],
                          capture_output=True, text=True, timeout=300,
                          env=_ENV, cwd=_REPO)


def test_bench_guard_gate_passes_then_trips_on_injected_regression(tmp_path):
    baseline = str(tmp_path / "baseline.json")
    common = ["--baseline", baseline, "--suite", "decode_tick_s", "--runs", "2"]

    # No baseline yet: a distinct exit code that tells "unseeded" from
    # "regressed".
    proc = _run(*common)
    assert proc.returncode == 2, proc.stderr

    proc = _run(*common, "--update-baseline")
    assert proc.returncode == 0, proc.stderr
    doc = json.load(open(baseline))
    assert doc["metrics"]["decode_tick_s"]["median_s"] > 0
    assert doc["metrics"]["decode_tick_s"]["tolerance"] == 0.6
    assert doc["host"]["platform"] == "cpu"

    # Same machine, same suite: the gate holds (median-of-N absorbs noise
    # far below the 1.6x allowance).
    out_json = str(tmp_path / "run.json")
    tele = str(tmp_path / "guard.jsonl")
    proc = _run(*common, "--out", out_json, "--telemetry", tele)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    artifact = json.load(open(out_json))
    row = artifact["metrics"]["decode_tick_s"]
    assert row["pass"] is True and row["ratio"] is not None
    assert len(row["samples"]) == 2
    assert artifact["pass"] is True and artifact["host_matches_baseline"]
    events = [json.loads(l) for l in open(tele) if l.strip()]
    assert [e["event"] for e in events] == ["bench_guard"]
    assert events[0]["metric"] == "decode_tick_s" and events[0]["pass"]

    # The injected synthetic regression MUST trip the gate (exit 3) and the
    # artifact must say why.
    proc = _run(*common, "--out", out_json, "--inject-regression",
                "decode_tick_s=10")
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout
    artifact = json.load(open(out_json))
    assert artifact["pass"] is False
    assert artifact["metrics"]["decode_tick_s"]["ratio"] > 1.6
    assert any("decode_tick_s" in f for f in artifact["failures"])


def test_bench_guard_rejects_unknown_suite_and_holes(tmp_path):
    baseline = str(tmp_path / "baseline.json")
    proc = _run("--suite", "not_a_metric", "--baseline", baseline)
    assert proc.returncode == 2 and "unknown suite metric" in proc.stderr

    # A baseline metric the run skipped is a HOLE in the gate, not a pass:
    # seed with decode_tick_s, then gate... nothing.
    proc = _run("--baseline", baseline, "--suite", "decode_tick_s",
                "--runs", "1", "--update-baseline")
    assert proc.returncode == 0, proc.stderr
    doc = json.load(open(baseline))
    doc["metrics"]["phantom_metric_s"] = {"median_s": 1.0, "tolerance": 0.5}
    json.dump(doc, open(baseline, "w"))
    proc = _run("--baseline", baseline, "--suite", "decode_tick_s",
                "--runs", "1")
    assert proc.returncode == 3
    assert "in baseline but not measured" in proc.stderr
