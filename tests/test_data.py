"""Data-pipeline tests: IDX parsing against hand-built files, normalization constants,
synthetic-fallback determinism/learnability shape contract, loader batching semantics
(reference src/train.py:25-41, src/train_dist.py:15-47)."""

import gzip
import os
import struct

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.data import (
    BatchLoader, Dataset, MNIST_MEAN, MNIST_STD, load_mnist,
)
from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import _read_idx
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)


def _write_idx_images(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">3I", *arr.shape))
        f.write(arr.tobytes())


def _write_idx_labels(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", arr.shape[0]))
        f.write(arr.tobytes())


def test_idx_roundtrip(tmp_path):
    imgs = np.random.default_rng(0).integers(0, 256, (5, 28, 28), dtype=np.uint8)
    p = tmp_path / "imgs"
    _write_idx_images(p, imgs)
    np.testing.assert_array_equal(_read_idx(str(p)), imgs)


def test_idx_gzip(tmp_path):
    labels = np.asarray([3, 1, 4], dtype=np.uint8)
    p = tmp_path / "labels.gz"
    with gzip.open(p, "wb") as f:
        f.write(struct.pack(">I", 0x00000801) + struct.pack(">I", 3) + labels.tobytes())
    np.testing.assert_array_equal(_read_idx(str(p)), labels)


def test_idx_unsupported_dtype_raises(tmp_path):
    p = tmp_path / "bad_dtype"
    with open(p, "wb") as f:
        f.write(struct.pack(">I", 0x00000D03))   # dtype 0x0D (float), not MNIST's 0x08
        f.write(struct.pack(">3I", 1, 2, 2))
        f.write(b"\x00" * 4)
    with pytest.raises(ValueError, match="unsupported IDX dtype"):
        _read_idx(str(p))


def test_idx_truncated_payload_raises(tmp_path):
    imgs = np.random.default_rng(1).integers(0, 256, (4, 28, 28), dtype=np.uint8)
    p = tmp_path / "truncated"
    _write_idx_images(p, imgs)
    with open(p, "r+b") as f:
        f.truncate(16 + imgs.nbytes - 100)       # drop the last 100 payload bytes
    with pytest.raises(ValueError, match="payload size mismatch"):
        _read_idx(str(p))


def test_load_real_idx_layout(tmp_path):
    """torchvision's MNIST/raw cache layout is found and parsed (src/train.py:26-31)."""
    raw = tmp_path / "MNIST" / "raw"
    os.makedirs(raw)
    rng = np.random.default_rng(1)
    _write_idx_images(raw / "train-images-idx3-ubyte",
                      rng.integers(0, 256, (20, 28, 28), dtype=np.uint8))
    _write_idx_labels(raw / "train-labels-idx1-ubyte",
                      rng.integers(0, 10, 20).astype(np.uint8))
    _write_idx_images(raw / "t10k-images-idx3-ubyte",
                      rng.integers(0, 256, (10, 28, 28), dtype=np.uint8))
    _write_idx_labels(raw / "t10k-labels-idx1-ubyte",
                      rng.integers(0, 10, 10).astype(np.uint8))
    train, test = load_mnist(str(tmp_path))
    assert train.source == "idx" and test.source == "idx"
    assert train.images.shape == (20, 28, 28, 1) and test.images.shape == (10, 28, 28, 1)


def test_normalization_applied(tmp_path):
    raw = tmp_path
    imgs = np.full((2, 28, 28), 255, dtype=np.uint8)
    _write_idx_images(raw / "train-images-idx3-ubyte", imgs)
    _write_idx_labels(raw / "train-labels-idx1-ubyte", np.zeros(2, dtype=np.uint8))
    _write_idx_images(raw / "t10k-images-idx3-ubyte", imgs)
    _write_idx_labels(raw / "t10k-labels-idx1-ubyte", np.zeros(2, dtype=np.uint8))
    train, _ = load_mnist(str(tmp_path))
    np.testing.assert_allclose(train.images, (1.0 - MNIST_MEAN) / MNIST_STD, rtol=1e-5)


def test_synthetic_fallback_shapes_and_determinism(tmp_path):
    t1, e1 = load_mnist(str(tmp_path / "nothing_here"))
    assert t1.source == "synthetic"
    assert t1.images.shape == (60_000, 28, 28, 1) and e1.images.shape == (10_000, 28, 28, 1)
    assert t1.images.dtype == np.float32 and t1.labels.dtype == np.int32
    assert set(np.unique(t1.labels)) == set(range(10))
    t2, _ = load_mnist(str(tmp_path / "nothing_here"))
    np.testing.assert_array_equal(t1.images[:100], t2.images[:100])


def test_synthetic_disabled_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_mnist(str(tmp_path / "absent"), allow_synthetic=False)


def _tiny_dataset(n=100):
    rng = np.random.default_rng(0)
    return Dataset(rng.normal(size=(n, 28, 28, 1)).astype(np.float32),
                   rng.integers(0, 10, n).astype(np.int32), "test")


def test_loader_batch_shapes_and_last_partial():
    ds = _tiny_dataset(100)
    loader = BatchLoader(ds, 64, shuffle=True, seed=1)
    batches = list(loader)
    assert len(batches) == len(loader) == 2
    assert batches[0][0].shape == (64, 28, 28, 1)
    assert batches[1][0].shape == (36, 28, 28, 1)  # drop_last=False, torch default


def test_loader_drop_last():
    loader = BatchLoader(_tiny_dataset(100), 64, drop_last=True)
    assert len(list(loader)) == len(loader) == 1


def test_loader_epoch_reshuffle_covers_dataset():
    ds = _tiny_dataset(100)
    loader = BatchLoader(ds, 10, shuffle=True, seed=7)
    loader.set_epoch(0)
    first = np.concatenate([b[1] for b in loader])
    loader.set_epoch(1)
    second = np.concatenate([b[1] for b in loader])
    assert sorted(first.tolist()) == sorted(ds.labels.tolist())
    assert not np.array_equal(first, second)


def test_loader_with_sampler_rejects_shuffle():
    with pytest.raises(ValueError):
        BatchLoader(_tiny_dataset(), 10,
                    sampler=ShardedSampler(100, num_replicas=2, rank=0), shuffle=True)


def test_epoch_index_matrix():
    loader = BatchLoader(_tiny_dataset(100), 8, shuffle=True, seed=3)
    mat = loader.epoch_index_matrix(0, steps_multiple=5)
    assert mat.shape == (10, 8)  # 12 full batches -> truncated to multiple of 5


def test_prefetch_iter_tiny_dataset_yields_nothing():
    """A split smaller than one batch has zero full batches: prefetch_iter must yield
    nothing (leaving the ragged tail to the caller, like the scan fast path) instead of
    raising — advisor finding r1 on the host-pipeline trainer."""
    loader = BatchLoader(_tiny_dataset(40), 64, shuffle=True, seed=1)
    assert list(loader.prefetch_iter(1)) == []


def test_iter_plan_batches_numpy_fallback(monkeypatch):
    """The pure-numpy leg of iter_plan_batches (used when the C++ library isn't built)
    must match a plain gather — forced here so it stays covered even on machines where
    the native path is available (test_native.py skips entirely when it isn't)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.data import native
    from csed_514_project_distributed_training_using_pytorch_tpu.data.loader import (
        iter_plan_batches,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
        Dataset, _normalize, _synthesize_split,
    )

    xs, ys = _synthesize_split(256, seed=77)
    ds = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    plan = np.random.default_rng(3).integers(0, 256, size=(5, 16)).astype(np.int32)
    monkeypatch.setattr(native, "available", lambda: False)
    batches = list(iter_plan_batches(ds, plan))
    assert len(batches) == 5
    for s, (bi, bl) in enumerate(batches):
        np.testing.assert_array_equal(bi, ds.images[plan[s]])
        np.testing.assert_array_equal(bl, ds.labels[plan[s]])
    assert list(iter_plan_batches(ds, plan[:0])) == []


# -----------------------------------------------------------------------------------------
# Double-buffered device prefetch (loader `prefetch=` flag)
# -----------------------------------------------------------------------------------------


def test_loader_prefetch_preserves_order_and_values():
    """The prefetch pipeline changes residency and overlap, never content: the
    device-put batch stream is element-identical to the plain host iterator."""
    import jax

    ds = _tiny_dataset(100)
    plain = list(BatchLoader(ds, 32, shuffle=True, seed=3))
    pre = list(BatchLoader(ds, 32, shuffle=True, seed=3, prefetch=2))
    assert len(plain) == len(pre) == 4
    for (hi, hl), (di, dl) in zip(plain, pre):
        assert isinstance(di, jax.Array) and isinstance(dl, jax.Array)
        np.testing.assert_array_equal(hi, np.asarray(di))
        np.testing.assert_array_equal(hl, np.asarray(dl))


def test_loader_prefetch_epoch_reshuffle_and_early_abandon():
    ds = _tiny_dataset(64)
    loader = BatchLoader(ds, 16, shuffle=True, seed=5, prefetch=2)
    loader.set_epoch(0)
    e0 = [np.asarray(b[0]) for b in loader]
    loader.set_epoch(1)
    e1 = [np.asarray(b[0]) for b in loader]
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))  # reshuffled
    # Abandoning mid-iteration must not wedge the worker thread.
    it = iter(BatchLoader(ds, 16, prefetch=1))
    next(it)
    it.close()


def test_loader_prefetch_validates_and_defaults_off():
    ds = _tiny_dataset(32)
    with pytest.raises(ValueError):
        BatchLoader(ds, 16, prefetch=-1)
    batch = next(iter(BatchLoader(ds, 16)))
    assert isinstance(batch[0], np.ndarray)        # prefetch off: host numpy batches
