"""Pipeline parallelism: the GPipe microbatch schedule pinned to the sequential stack.

Contract (``parallel/pipeline.py``): stage-sharding a homogeneous layer stack and
streaming microbatches through the ring computes exactly what applying the layers in
sequence computes — forward and gradients — for any microbatch count ≥ 1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
    TransformerBlock,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import make_mesh
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (

    pipeline as pp,
)

# Heavyweight end-to-end/equivalence tests: full-suite runs only; deselect with
# -m "not slow" for the fast single-core signal (README).
pytestmark = pytest.mark.slow

NUM_STAGES = 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(NUM_STAGES, axis_names=("stage",))


@pytest.fixture(scope="module")
def block():
    return TransformerBlock(num_heads=4, dropout_rate=0.0)


@pytest.fixture(scope="module")
def stage_params(block):
    x0 = jnp.zeros((1, 8, 64), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(0), NUM_STAGES)
    return [block.init({"params": k}, x0)["params"] for k in keys]


def _stage_fn(block):
    return lambda params, x: block.apply({"params": params}, x)


def _sequential(block, stage_params, x):
    y = x
    for p in stage_params:
        y = _stage_fn(block)(p, y)
    return y


def _x(b=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(b, 8, 64)).astype(np.float32))


@pytest.mark.parametrize("num_micro", [1, 4, 8])
def test_pipeline_forward_matches_sequential(mesh, block, stage_params, num_micro):
    x = _x()
    stacked = pp.stack_stage_params(stage_params)
    f = pp.make_pipelined_blocks_fn(mesh, _stage_fn(block), num_microbatches=num_micro)
    np.testing.assert_allclose(np.asarray(f(stacked, x)),
                               np.asarray(_sequential(block, stage_params, x)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential(mesh, block, stage_params):
    x = _x(seed=1)
    stacked = pp.stack_stage_params(stage_params)
    f = pp.make_pipelined_blocks_fn(mesh, _stage_fn(block), num_microbatches=8)

    g_pipe = jax.grad(lambda sp: jnp.sum(jnp.sin(f(sp, x))))(stacked)
    g_seq = jax.grad(
        lambda ps: jnp.sum(jnp.sin(_sequential(block, ps, x))))(stage_params)
    g_seq_stacked = pp.stack_stage_params(g_seq)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_pipeline_under_jit_with_stage_sharded_params(mesh, block, stage_params):
    """Params placed with their real P('stage') sharding (each device holds one stage's
    weights), the whole schedule jitted — the deployment shape."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = _x(seed=2)
    stacked = jax.device_put(
        pp.stack_stage_params(stage_params),
        NamedSharding(mesh, P("stage")))
    leaf = jax.tree_util.tree_leaves(stacked)[0]
    assert leaf.addressable_shards[0].data.shape[0] == 1  # one stage per device
    f = jax.jit(pp.make_pipelined_blocks_fn(mesh, _stage_fn(block),
                                            num_microbatches=8))
    np.testing.assert_allclose(np.asarray(f(stacked, x)),
                               np.asarray(_sequential(block, stage_params, x)),
                               rtol=1e-5, atol=1e-5)


def test_stacked_dim_must_match_mesh(mesh, block, stage_params):
    stacked = pp.stack_stage_params(stage_params[:2])  # 2 stages on a 4-way mesh
    with pytest.raises(ValueError, match="mesh axis"):
        pp.pipeline_apply(mesh, _stage_fn(block), stacked,
                          _x().reshape(4, 4, 8, 64))


def test_indivisible_microbatching_rejected(mesh, block, stage_params):
    f = pp.make_pipelined_blocks_fn(mesh, _stage_fn(block), num_microbatches=5)
    with pytest.raises(ValueError, match="not divisible"):
        f(pp.stack_stage_params(stage_params), _x(b=16))


def test_transformer_checkpoint_bridges_to_pipeline(mesh):
    """A classifier checkpoint's per-name block subtrees stack into the pipeline layout,
    the pipelined blocks compute exactly what the classifier's block stack computes, and
    the layout round-trips bit-for-bit."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )

    model = TransformerClassifier(num_layers=NUM_STAGES, dropout_rate=0.0)
    params = create_train_state(model, jax.random.PRNGKey(3)).params
    stacked, rest = pp.stack_transformer_blocks(params, NUM_STAGES)
    assert "embed_kernel" in rest and not any(k.startswith("block_") for k in rest)

    rebuilt = pp.unstack_transformer_blocks(stacked, rest)
    for a, b in zip(jax.tree_util.tree_leaves(rebuilt),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    blk = TransformerBlock(num_heads=model.num_heads, dropout_rate=0.0)
    x = _x(b=8, seed=4)[:, :, :64]
    f = pp.make_pipelined_blocks_fn(mesh, lambda p, a: blk.apply({"params": p}, a),
                                    num_microbatches=4)
    y_pipe = f(stacked, x)
    y_seq = x
    for i in range(NUM_STAGES):
        y_seq = blk.apply({"params": params[f"block_{i}"]}, y_seq)
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_seq),
                               rtol=1e-5, atol=1e-5)


def test_pipelined_classifier_matches_model(mesh):
    """``PipelinedClassifier`` (the composed trainer's stage engine) computes exactly
    ``TransformerClassifier.apply`` on the bridged stacked layout — including the
    embed/head math it mirrors and multi-layer-per-stage sub-stacks."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )

    # 2·NUM_STAGES layers → 2 layers per stage (exercises the sub-stack scan).
    model = TransformerClassifier(num_layers=2 * NUM_STAGES, dropout_rate=0.0)
    params = create_train_state(model, jax.random.PRNGKey(5)).params
    stacked, rest = pp.stack_transformer_blocks(params, model.num_layers)
    engine = pp.PipelinedClassifier(model, mesh, num_microbatches=4)

    images = jnp.asarray(
        np.random.default_rng(6).normal(size=(8, 28, 28, 1)).astype(np.float32))
    ref = model.apply({"params": params}, images)
    out = engine.apply({"params": {"blocks": stacked, "rest": rest}}, images)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipelined_classifier_guards(mesh):
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )

    with pytest.raises(ValueError, match="not divisible by stage axis"):
        pp.PipelinedClassifier(TransformerClassifier(num_layers=NUM_STAGES + 1), mesh)
    with pytest.raises(ValueError, match="MoE"):
        pp.PipelinedClassifier(
            TransformerClassifier(num_layers=NUM_STAGES, num_experts=2), mesh)
    with pytest.raises(ValueError, match="dropout_rate == 0"):
        pp.PipelinedClassifier(
            TransformerClassifier(num_layers=NUM_STAGES, dropout_rate=0.1), mesh)


def test_stack_transformer_blocks_missing_block_rejected():
    with pytest.raises(ValueError, match="lacks block"):
        pp.stack_transformer_blocks({"block_0": {}, "embed_kernel": 1}, 2)


def test_stack_transformer_blocks_extra_block_rejected():
    with pytest.raises(ValueError, match="beyond num_layers"):
        pp.stack_transformer_blocks(
            {"block_0": {}, "block_1": {}, "block_2": {}, "embed_kernel": 1}, 2)


@pytest.mark.parametrize("num_micro", [4, 8])
def test_1f1b_matches_sequential_and_gpipe(mesh, block, stage_params, num_micro):
    """The 1F1B schedule (custom-VJP reverse ring, stage-input-only residuals with
    in-tick remat) reproduces the sequential oracle's forward AND gradients — and
    therefore GPipe's, which is pinned to the same oracle above."""
    x = _x(seed=5)
    stacked = pp.stack_stage_params(stage_params)
    f = pp.make_pipelined_blocks_fn(mesh, _stage_fn(block),
                                    num_microbatches=num_micro, schedule="1f1b")

    np.testing.assert_allclose(np.asarray(f(stacked, x)),
                               np.asarray(_sequential(block, stage_params, x)),
                               rtol=1e-5, atol=1e-5)

    g_pipe, gx_pipe = jax.grad(
        lambda sp_x: jnp.sum(jnp.sin(f(*sp_x))))((stacked, x))
    g_seq, gx_seq = jax.grad(
        lambda ps_x: jnp.sum(jnp.sin(_sequential(block, *ps_x))))(
            (stage_params, x))
    g_seq_stacked = pp.stack_stage_params(g_seq)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gx_pipe), np.asarray(gx_seq),
                               rtol=1e-3, atol=1e-4)


def test_unknown_schedule_rejected(mesh, block, stage_params):
    stacked = pp.stack_stage_params(stage_params)
    with pytest.raises(ValueError, match="schedule"):
        pp.pipeline_apply(mesh, _stage_fn(block), stacked,
                          _x().reshape(4, 4, 8, 64), schedule="2f2b")


def test_pipeline_composes_with_auto_model_axis():
    """PP x TP in one program (r4 verdict item 4): on a stage x model mesh the
    pipeline keeps only 'stage' manual and the Megatron-sharded stacked params
    (stacked_state_shardings' column/row rules) ride the AUTO model axis — forward
    and gradients must still match the sequential oracle bit-close."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh2 = make_mesh(8, axis_names=("stage", "model"), axis_shape=(4, 2))
    block = TransformerBlock(num_heads=4, dropout_rate=0.0)
    x0 = jnp.zeros((1, 8, 64), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(11), NUM_STAGES)
    stage_params = [block.init({"params": k}, x0)["params"] for k in keys]
    stacked = pp.stack_stage_params(stage_params)

    # Megatron placement, one dim right of the stack dim (as stacked_state_shardings
    # computes it) — column kernels [S, E, F] over (stage, -, model), row kernels
    # over (stage, model, -).
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        tensor_parallel as tp,
    )

    def place(path, leaf):
        name = tp._leaf_name(path)
        if name in tp._COLUMN_PARALLEL and leaf.ndim == 3:
            return jax.device_put(leaf, NamedSharding(mesh2, P("stage", None, "model")))
        if name in tp._ROW_PARALLEL and leaf.ndim == 3:
            return jax.device_put(leaf, NamedSharding(mesh2, P("stage", "model", None)))
        if name in tp._COLUMN_PARALLEL_BIAS and leaf.ndim == 2:
            return jax.device_put(leaf, NamedSharding(mesh2, P("stage", "model")))
        return jax.device_put(leaf, NamedSharding(mesh2, P("stage")))

    stacked_tp = jax.tree_util.tree_map_with_path(place, stacked)
    x = _x(seed=7)
    f = jax.jit(pp.make_pipelined_blocks_fn(mesh2, _stage_fn(block),
                                            num_microbatches=4))
    np.testing.assert_allclose(np.asarray(f(stacked_tp, x)),
                               np.asarray(_sequential(block, stage_params, x)),
                               rtol=1e-5, atol=1e-5)

    g_pipe = jax.grad(lambda sp: jnp.sum(jnp.sin(f(sp, x))))(stacked_tp)
    g_seq = pp.stack_stage_params(jax.grad(
        lambda ps: jnp.sum(jnp.sin(_sequential(block, ps, x))))(stage_params))
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_kernel_traces_inside_pipeline_body(mesh):
    """The flash pallas kernel PROPER (not the crossover dispatcher, which picks
    dense at short S) runs inside the pipeline's shard_map body and matches the
    same model evaluated sequentially — the kernel-level half of r4 verdict item 4's
    flash-in-stage ask."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
        pallas_attention as pa,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )

    model = TransformerClassifier(num_layers=NUM_STAGES, dropout_rate=0.0,
                                  seq_len=256, attention_fn=pa.flash_attention)
    params = create_train_state(model, jax.random.PRNGKey(13)).params
    stacked, rest = pp.stack_transformer_blocks(params, model.num_layers)
    engine = pp.PipelinedClassifier(model, mesh, num_microbatches=4)

    images = jnp.asarray(
        np.random.default_rng(14).normal(size=(8, 28, 28, 1)).astype(np.float32))
    ref = model.apply({"params": params}, images)
    out = engine.apply({"params": {"blocks": stacked, "rest": rest}}, images)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_backward_differentiates_inside_pipeline(mesh):
    """Training with the flash kernel PROPER inside a stage (what a user gets past
    the dispatch crossover) exercises the flash custom-VJP backward inside the
    pipeline's shard_map — gradients must match the same model differentiated
    sequentially (review finding: the composition's backward was previously
    untested anywhere)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        TransformerClassifier,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
        pallas_attention as pa,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )

    model = TransformerClassifier(num_layers=NUM_STAGES, dropout_rate=0.0,
                                  seq_len=256, attention_fn=pa.flash_attention)
    params = create_train_state(model, jax.random.PRNGKey(15)).params
    stacked, rest = pp.stack_transformer_blocks(params, model.num_layers)
    engine = pp.PipelinedClassifier(model, mesh, num_microbatches=4)

    images = jnp.asarray(
        np.random.default_rng(16).normal(size=(8, 28, 28, 1)).astype(np.float32))
    labels = jnp.asarray(np.arange(8) % 10)

    def nll(logprobs):
        return -jnp.mean(logprobs[jnp.arange(8), labels])

    g_pipe = jax.grad(lambda p: nll(engine.apply({"params": p}, images)))(
        {"blocks": stacked, "rest": rest})
    g_seq = jax.grad(lambda p: nll(model.apply({"params": p}, images)))(params)
    g_seq_stacked, g_seq_rest = pp.stack_transformer_blocks(
        g_seq, model.num_layers)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe["blocks"]),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe["rest"]),
                    jax.tree_util.tree_leaves(g_seq_rest)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)
