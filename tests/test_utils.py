"""Utility-layer tests: torch-default initializer parity and the profiler flag."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.utils.profiling import (
    maybe_profile,
)


class TestTorchDefaultInit:
    """The reference trains from torch's default inits (it never sets any — SURVEY.md §2a #1);
    our initializers must reproduce those distributions so loss trajectories are comparable."""

    def test_conv_kernel_bound_and_moments(self):
        # fan_in for a 5x5x10-in kernel = 250 → U(±1/sqrt(250))
        shape, fan_in = (5, 5, 10, 20), 250
        w = np.asarray(ops.torch_kaiming_uniform(jax.random.PRNGKey(0), shape))
        bound = 1.0 / np.sqrt(fan_in)
        assert np.abs(w).max() <= bound
        assert np.abs(w).max() > 0.95 * bound          # actually fills the support
        assert abs(w.mean()) < 0.1 * bound
        np.testing.assert_allclose(w.var(), bound**2 / 3, rtol=0.1)  # uniform variance

    def test_bound_matches_torch_formula(self):
        """torch kaiming_uniform_(a=sqrt(5)): bound = sqrt(6 / ((1+a^2) * fan_in))
        = 1/sqrt(fan_in) — cross-checked against a real torch layer's observed support."""
        torch = pytest.importorskip("torch")
        conv = torch.nn.Conv2d(10, 20, kernel_size=5)
        observed = conv.weight.detach().abs().max().item()
        bound = 1.0 / np.sqrt(250)
        assert observed <= bound
        assert observed > 0.9 * bound
        lin = torch.nn.Linear(320, 50)
        lin_observed = lin.weight.detach().abs().max().item()
        lin_bound = 1.0 / np.sqrt(320)
        assert lin_observed <= lin_bound
        assert lin_observed > 0.9 * lin_bound

    def test_bias_uses_weight_fan_in(self):
        b = np.asarray(ops.torch_fan_in_uniform(320)(jax.random.PRNGKey(1), (50,)))
        assert np.abs(b).max() <= 1.0 / np.sqrt(320)


def test_restore_for_resume_warns_on_step_mismatch(tmp_path):
    """The shared resume prologue flags a checkpoint whose step count is not a whole
    number of THIS config's epochs — the tell-tale of a mid-epoch checkpoint or a
    different batch size (previously a silent wrong-epoch resume)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        create_train_state,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    state = create_train_state(Net(), jax.random.PRNGKey(0))
    state = state._replace(step=jnp.asarray(62, jnp.int32))
    path = str(tmp_path / "ckpt.msgpack")
    checkpoint.save_train_state(path, state)
    template = create_train_state(Net(), jax.random.PRNGKey(1))

    restored, start_epoch, warning = checkpoint.restore_for_resume(
        path, template, process_index=0, process_count=1, steps_per_epoch=31)
    assert int(restored.step) == 62 and start_epoch == 2 and warning is None

    restored, start_epoch, warning = checkpoint.restore_for_resume(
        path, template, process_index=0, process_count=1, steps_per_epoch=16)
    assert start_epoch == 3
    assert warning is not None and "different batch size" in warning


def test_maybe_profile_writes_trace(tmp_path):
    log_dir = str(tmp_path / "trace")
    with maybe_profile(True, log_dir):
        jax.block_until_ready(jax.jit(lambda x: x @ x)(jnp.ones((64, 64))))
    found = [os.path.join(r, f) for r, _, fs in os.walk(log_dir) for f in fs]
    assert found, "profiler trace directory is empty"


def test_maybe_profile_disabled_is_noop(tmp_path):
    log_dir = str(tmp_path / "trace")
    with maybe_profile(False, log_dir):
        pass
    assert not os.path.exists(log_dir)


def test_maybe_profile_creates_log_dir_and_logs_path(tmp_path, capsys):
    """The flag must work on a fresh results tree (log_dir created if missing) and
    say where the trace went (metrics.log line)."""
    log_dir = str(tmp_path / "fresh" / "nested" / "trace")
    with maybe_profile(True, log_dir):
        jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.ones(8)))
    assert os.path.isdir(log_dir)
    assert f"Saved profiler trace to {log_dir}" in capsys.readouterr().out


def test_maybe_profile_gates_to_process_zero(tmp_path, monkeypatch):
    """Every process tracing would write world-size duplicate traces; non-zero
    processes must no-op (internal gating — call sites pass the bare flag)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    monkeypatch.setattr(M, "is_logging_process", lambda: False)
    log_dir = str(tmp_path / "trace")
    with maybe_profile(True, log_dir):
        pass
    assert not os.path.exists(log_dir)


class TestReplicaSyncCheck:
    """utils/determinism.py — the desync 'race detector' the reference lacks. The happy
    path runs in every 2-process fleet test; the failure branch is faked here (a real
    desynced fleet would have to be built broken on purpose)."""

    def test_fingerprint_is_order_independent(self):
        # List pytrees preserve leaf order (dicts would sort keys and prove nothing),
        # so swapping elements genuinely permutes the leaf sequence.
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            determinism as D,
        )
        w, b = jnp.arange(6.0).reshape(2, 3), jnp.ones(3)
        assert D.param_fingerprint([w, b]) == D.param_fingerprint([b, w])

    def test_single_process_is_noop(self):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            determinism as D,
        )
        assert jax.process_count() == 1
        D.assert_replicas_synced({"w": jnp.ones(3)})   # must not raise, no collective

    def test_desync_raises_and_sync_passes(self, monkeypatch):
        from jax.experimental import multihost_utils

        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            determinism as D,
        )
        params = {"w": jnp.ones(3)}
        mine = D.param_fingerprint(params)
        monkeypatch.setattr(D.jax, "process_count", lambda: 2)

        monkeypatch.setattr(multihost_utils, "process_allgather",
                            lambda x: np.asarray([[mine], [mine + 0.5]]))
        with pytest.raises(RuntimeError, match="desync"):
            D.assert_replicas_synced(params)

        monkeypatch.setattr(multihost_utils, "process_allgather",
                            lambda x: np.asarray([[mine], [mine]]))
        D.assert_replicas_synced(params)               # identical fingerprints: fine


class TestEmaCheckpointReconciliation:
    """``restore_train_state`` bridges checkpoints across the ``--ema-decay`` flag:
    pre-EMA checkpoints seed the EMA tree from their params; EMA checkpoints restore
    into plain references by dropping the tree."""

    def _state(self, ema: bool):
        from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import (
            Net,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
            create_train_state,
        )

        return create_train_state(Net(), jax.random.PRNGKey(3), ema=ema)

    def test_round_trip_with_ema(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        state = self._state(ema=True)
        path = str(tmp_path / "s.ckpt")
        checkpoint.save_train_state(path, state)
        restored = checkpoint.restore_train_state(path, self._state(ema=True))
        for a, b in zip(jax.tree_util.tree_leaves(restored.ema),
                        jax.tree_util.tree_leaves(state.ema)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_plain_checkpoint_into_ema_reference_seeds_from_params(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        plain = self._state(ema=False)
        path = str(tmp_path / "s.ckpt")
        checkpoint.save_train_state(path, plain)
        restored = checkpoint.restore_train_state(path, self._state(ema=True))
        assert restored.ema is not None
        for e, p in zip(jax.tree_util.tree_leaves(restored.ema),
                        jax.tree_util.tree_leaves(plain.params)):
            np.testing.assert_array_equal(np.asarray(e), np.asarray(p))

    def test_ema_checkpoint_into_plain_reference_drops_tree(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        state = self._state(ema=True)
        path = str(tmp_path / "s.ckpt")
        checkpoint.save_train_state(path, state)
        restored = checkpoint.restore_train_state(path, self._state(ema=False))
        assert restored.ema is None
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestAsyncCheckpointer:
    def _state(self):
        from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import (
            Net,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
            create_train_state,
        )

        return create_train_state(Net(), jax.random.PRNGKey(5))

    def test_async_write_matches_sync_bytes(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        state = self._state()
        sync_path = str(tmp_path / "sync.ckpt")
        async_path = str(tmp_path / "async.ckpt")
        checkpoint.save_train_state(sync_path, state)
        with checkpoint.AsyncCheckpointer() as ck:
            ck.save_train_state(async_path, state)
        assert open(async_path, "rb").read() == open(sync_path, "rb").read()

    def test_overwrites_coalesce_to_newest(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        state = self._state()
        path = str(tmp_path / "s.ckpt")
        with checkpoint.AsyncCheckpointer() as ck:
            for i in range(20):
                ck.save_train_state(path, state._replace(
                    step=jnp.asarray(i, jnp.int32)))
        restored = checkpoint.restore_train_state(path, self._state())
        assert int(restored.step) == 19

    def test_flush_reraises_background_error(self, tmp_path):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        ck = checkpoint.AsyncCheckpointer()
        # A directory path makes the atomic rename fail in the worker.
        bad = str(tmp_path / "dir.ckpt")
        os.makedirs(bad)
        ck.save_train_state(bad, self._state())
        with pytest.raises(OSError):
            ck.flush()
        # The checkpointer is reusable after an error surfaced.
        good = str(tmp_path / "ok.ckpt")
        ck.save_train_state(good, self._state())
        ck.flush()
        assert os.path.exists(good)


class _FakeTty:
    def __init__(self):
        self.buf = []

    def isatty(self):
        return True

    def write(self, s):
        self.buf.append(s)

    def flush(self):
        pass


def test_progress_bar_renders_on_tty():
    """The tqdm-analog bar (reference src/train_dist.py:76,96): in-place \\r line
    with counts and rate, final state full, close() terminates the line."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    stream = _FakeTty()
    bar = M.ProgressBar(4, desc="ep1 ", stream=stream, min_interval_s=0.0)
    for _ in range(4):
        bar.update(1, loss=1.25)
    bar.close()
    text = "".join(stream.buf)
    assert "\r" in text and "ep1 [" in text
    assert "4/4" in text and "loss=1.2500" in text
    assert text.endswith("\n")


def test_progress_bar_silent_when_not_a_tty():
    """Piped/CI output must stay byte-stable: a non-tty stream gets nothing."""
    import io

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    stream = io.StringIO()          # isatty() -> False
    bar = M.ProgressBar(4, stream=stream, min_interval_s=0.0)
    bar.update(4, loss=0.5)
    bar.close()
    assert stream.getvalue() == ""


def test_progress_bar_silent_on_non_zero_process(monkeypatch):
    """Only process 0 renders — a fleet must not draw world-size duplicate bars."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    monkeypatch.setattr(M, "is_logging_process", lambda: False)
    stream = _FakeTty()
    bar = M.ProgressBar(4, stream=stream, min_interval_s=0.0)
    bar.update(4, loss=0.5)
    bar.close()
    assert stream.buf == []


def test_progress_bar_rate_limits_renders():
    """Intermediate updates inside min_interval_s are dropped; the first update and
    the final (n == total) one always render — the bar can never finish stale."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    stream = _FakeTty()
    bar = M.ProgressBar(100, stream=stream, min_interval_s=3600.0)
    for _ in range(99):
        bar.update(1)
    assert len(stream.buf) == 1          # only the first update rendered
    assert "1/100" in stream.buf[0]
    bar.update(1)                        # n == total bypasses the rate limit
    assert len(stream.buf) == 2
    assert "100/100" in stream.buf[1]
    bar.close()


def test_progress_bar_pads_stale_tail():
    """A shrinking line (loss dropping off, rate settling) must overwrite the
    previous render completely: each \\r frame is padded to the prior length."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        metrics as M,
    )

    stream = _FakeTty()
    bar = M.ProgressBar(3, stream=stream, min_interval_s=0.0)
    bar.update(1, loss=123456.75)        # long line
    bar.update(1)                        # shorter line: no loss field
    bar.update(1)
    frames = [f for f in stream.buf if f.startswith("\r")]
    assert len(frames) == 3
    assert "loss=123456.7500" in frames[0]
    # The shorter second frame is padded out to the first frame's full width, so
    # the stale loss tail is blanked rather than left behind on the tty.
    assert len(frames[1]) == len(frames[0])
    assert frames[1].endswith(" ")
    assert "loss" not in frames[1]
