"""Sequence-parallel ring attention: parity against the dense oracle on a virtual mesh.

The contract (``parallel/ring_attention.py``): attention over a sequence sharded across a
mesh axis equals ``ops.full_attention`` to float32 round-off — forward AND reverse-mode —
for both full and causal masking. Runs on the 8-virtual-CPU-device platform (conftest),
the same SPMD program a TPU slice executes with ppermute hops on ICI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu import ops
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (

    make_mesh,
    make_ring_attention_fn,
    ring_attention,
)

# Heavyweight end-to-end/equivalence tests: full-suite runs only; deselect with
# -m "not slow" for the fast single-core signal (README).
pytestmark = pytest.mark.slow


def _qkv(b=2, s=32, h=3, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
                 for _ in range(3))


@pytest.fixture(scope="module")
def seq_mesh(request):
    return make_mesh(8, axis_names=("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense_forward(seq_mesh, causal):
    q, k, v = _qkv()
    ref = ops.full_attention(q, k, v, causal=causal)
    out = ring_attention(seq_mesh, q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense_gradients(seq_mesh, causal):
    q, k, v = _qkv(seed=1)

    def make_loss(attn):
        # sin keeps the cotangent non-trivial in every element.
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v, causal=causal)))

    ref_grads = jax.grad(make_loss(ops.full_attention), argnums=(0, 1, 2))(q, k, v)
    ring = make_ring_attention_fn(seq_mesh)
    ring_grads = jax.grad(make_loss(ring), argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_ring in zip(ref_grads, ring_grads):
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


def test_ring_under_jit(seq_mesh):
    q, k, v = _qkv(seed=2)

    @jax.jit
    def f(q, k, v):
        return ring_attention(seq_mesh, q, k, v, causal=True)

    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(ops.full_attention(q, k, v, causal=True)),
                               rtol=1e-5, atol=1e-5)


def test_ring_on_smaller_mesh():
    mesh4 = make_mesh(4, axis_names=("seq",))
    q, k, v = _qkv(s=12, seed=3)
    np.testing.assert_allclose(
        np.asarray(ring_attention(mesh4, q, k, v, causal=True)),
        np.asarray(ops.full_attention(q, k, v, causal=True)),
        rtol=1e-5, atol=1e-5)


def test_indivisible_sequence_rejected(seq_mesh):
    q, k, v = _qkv(s=30, seed=4)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(seq_mesh, q, k, v)


def test_ring_respects_sequence_sharding(seq_mesh):
    """The output of the shard_map program carries the seq-sharded layout (no silent
    all-gather back to replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(seed=5)
    spec = P(None, "seq", None, None)
    q = jax.device_put(q, NamedSharding(seq_mesh, spec))
    k = jax.device_put(k, NamedSharding(seq_mesh, spec))
    v = jax.device_put(v, NamedSharding(seq_mesh, spec))
    out = ring_attention(seq_mesh, q, k, v)
    assert out.sharding.spec == spec


@pytest.mark.parametrize("causal", [False, True])
def test_ring_of_flash_matches_dense(seq_mesh, causal):
    """Ring-of-flash (ring across shards, Pallas flash kernel within each hop, exact
    lse-weighted merge) equals dense attention — the two-level long-context composition.
    Causal hops decompose into past/diagonal/future cases (r3: previously
    non-causal-only)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        ring_flash_attention,
    )

    q, k, v = _qkv(b=1, s=1024, h=2, d=64, seed=6)
    out = ring_flash_attention(seq_mesh, q, k, v, causal=causal)
    ref = ops.full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_of_flash_matches_dense_gradients(seq_mesh, causal):
    """Ring-of-flash TRAINS (r3; previously forward-only): the custom VJP — flash
    backward kernels per hop against the merged global lse, dk/dv riding the ring home
    — matches the dense-attention gradient oracle at S=1024 over 8 shards."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        ring_flash_attention,
    )

    q, k, v = _qkv(b=1, s=1024, h=2, d=64, seed=8)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v, causal=causal)))

    ref_grads = jax.grad(make_loss(ops.full_attention), argnums=(0, 1, 2))(q, k, v)
    ring = lambda q, k, v, *, causal: ring_flash_attention(
        seq_mesh, q, k, v, causal=causal)
    ring_grads = jax.grad(make_loss(ring), argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_ring in zip(ref_grads, ring_grads):
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


def test_ring_specs_shard_batch_and_heads_on_composed_mesh():
    """On a data×seq×model mesh the ring's shard_map specs co-shard the batch dim over
    'data' and the head dim over 'model' (advisor r2: previously replicated, so every
    (data, model) coordinate redundantly recomputed the full batch and all heads)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        _qkv_spec,
    )
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(8, axis_names=("data", "seq", "model"), axis_shape=(2, 2, 2))
    assert _qkv_spec(mesh, (4, 32, 2, 8), "seq") == P("data", "seq", "model", None)
    # Indivisible dims fall back to replicated rather than erroring.
    assert _qkv_spec(mesh, (3, 32, 3, 8), "seq") == P(None, "seq", None, None)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense_on_composed_mesh(causal):
    """Numerics are unchanged by the data/model co-sharding (forward + grads)."""
    mesh = make_mesh(8, axis_names=("data", "seq", "model"), axis_shape=(2, 2, 2))
    q, k, v = _qkv(b=4, s=32, h=2, d=8, seed=9)

    out = ring_attention(mesh, q, k, v, causal=causal)
    ref = ops.full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v, causal=causal)))

    ring = make_ring_attention_fn(mesh)
    ref_grads = jax.grad(make_loss(ops.full_attention), argnums=(0, 1, 2))(q, k, v)
    ring_grads = jax.grad(make_loss(ring), argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_ring in zip(ref_grads, ring_grads):
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


def test_zigzag_matches_dense_causal(seq_mesh):
    """Zig-zag causal ring (load-balanced chunk pairing) equals the dense causal
    oracle — forward and gradients — through the permute/ring/inverse-permute path."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        zigzag_ring_attention,
    )

    q, k, v = _qkv(s=64, seed=10)
    out = zigzag_ring_attention(seq_mesh, q, k, v)
    ref = ops.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    ref_grads = jax.grad(make_loss(
        lambda q, k, v: ops.full_attention(q, k, v, causal=True)),
        argnums=(0, 1, 2))(q, k, v)
    zz_grads = jax.grad(make_loss(
        lambda q, k, v: zigzag_ring_attention(seq_mesh, q, k, v)),
        argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_zz in zip(ref_grads, zz_grads):
        np.testing.assert_allclose(np.asarray(g_zz), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


def test_ring_of_flash_bf16_inputs(seq_mesh):
    """bfloat16 q/k/v (the --bf16 --flash-attention path): the ring promotes to f32
    once at kernel-layout entry, merges partials in f32, and returns the input dtype
    — so the result matches the f32 reference to bf16 resolution."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        ring_flash_attention,
    )

    q, k, v = _qkv(b=1, s=1024, h=2, d=64, seed=14)
    ref = ops.full_attention(q, k, v)
    out = ring_flash_attention(seq_mesh, q.astype(jnp.bfloat16),
                               k.astype(jnp.bfloat16), v.astype(jnp.bfloat16))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_zigzag_ring_of_flash_matches_dense_causal(seq_mesh):
    """Zig-zag ring-OF-FLASH (load-balanced causal schedule + Pallas flash kernels on
    every live chunk pair + custom VJP) equals the dense causal oracle, forward and
    gradients — the complete long-context causal training composition."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        zigzag_ring_flash_attention,
    )

    q, k, v = _qkv(b=1, s=2048, h=1, d=32, seed=12)
    out = zigzag_ring_flash_attention(seq_mesh, q, k, v)
    ref = ops.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    ref_grads = jax.grad(make_loss(
        lambda q, k, v: ops.full_attention(q, k, v, causal=True)),
        argnums=(0, 1, 2))(q, k, v)
    zz_grads = jax.grad(make_loss(
        lambda q, k, v: zigzag_ring_flash_attention(seq_mesh, q, k, v)),
        argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_zz in zip(ref_grads, zz_grads):
        np.testing.assert_allclose(np.asarray(g_zz), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


def test_zigzag_flash_divisibility_enforced(seq_mesh):
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        zigzag_ring_flash_attention,
    )

    q, k, v = _qkv(b=1, s=1024, h=1, d=32, seed=13)  # 1024 % (2·8·128) != 0
    with pytest.raises(ValueError, match="2·shards·BLOCK"):
        zigzag_ring_flash_attention(seq_mesh, q, k, v)


def test_zigzag_divisibility_enforced(seq_mesh):
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        zigzag_ring_attention,
    )

    q, k, v = _qkv(s=40, seed=11)  # 40 % 16 != 0
    with pytest.raises(ValueError, match="2·shards"):
        zigzag_ring_attention(seq_mesh, q, k, v)


def test_ring_of_flash_block_divisibility_enforced(seq_mesh):
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.ring_attention import (
        ring_flash_attention,
    )

    q, k, v = _qkv(b=1, s=512, h=1, d=64, seed=7)  # 512 / 8 shards = 64 < BLOCK
    with pytest.raises(ValueError, match="shards"):
        ring_flash_attention(seq_mesh, q, k, v)


@pytest.mark.parametrize("causal,window", [(False, 5), (True, 5),
                                           (False, 11), (True, 11)])
def test_windowed_ring_matches_dense(seq_mesh, causal, window):
    """Windowed context parallelism (r3): the einsum ring with a sliding band equals
    the dense windowed oracle — forward AND gradients. s=32 over 8 shards gives
    chunk=4: window=5 spans block boundaries (partial bands on live hops) and
    window=11 keeps ~3 hops live per side, so both the hop-skip predicate and the
    in-band masks are exercised."""
    q, k, v = _qkv(seed=9)
    ref = ops.full_attention(q, k, v, causal=causal, window=window)
    out = ring_attention(seq_mesh, q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v, causal=causal)))

    ref_grads = jax.grad(make_loss(lambda q, k, v, *, causal: ops.full_attention(
        q, k, v, causal=causal, window=window)), argnums=(0, 1, 2))(q, k, v)
    ring = make_ring_attention_fn(seq_mesh, window=window)
    ring_grads = jax.grad(make_loss(ring), argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_ring in zip(ref_grads, ring_grads):
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


def test_windowed_ring_guards(seq_mesh):
    q, k, v = _qkv(seed=9)
    with pytest.raises(ValueError, match="window"):
        ring_attention(seq_mesh, q, k, v, window=-1)


@pytest.mark.parametrize("window", [100, 400])
def test_windowed_zigzag_ring_of_flash_matches_dense(window):
    """Windowed flash zig-zag (r4 — the final cell of the schedule × masking
    matrix): device-dependent chunk-pair offsets ride into the flash kernels as
    traced SMEM scalars (``q_offset_dyn``), band-dead pairs skip — forward AND
    gradients equal the dense windowed causal oracle."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        zigzag_ring_flash_attention,
    )

    mesh = make_mesh(4, axis_names=("seq",))
    q, k, v = _qkv(s=2 * 4 * 128, h=2, d=8, seed=29)
    ref = ops.full_attention(q, k, v, causal=True, window=window)
    out = zigzag_ring_flash_attention(mesh, q, k, v, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    ref_grads = jax.grad(make_loss(lambda q, k, v: ops.full_attention(
        q, k, v, causal=True, window=window)), argnums=(0, 1, 2))(q, k, v)
    got_grads = jax.grad(make_loss(lambda q, k, v: zigzag_ring_flash_attention(
        mesh, q, k, v, window=window)), argnums=(0, 1, 2))(q, k, v)
    for name, g_ref, g_got in zip("qkv", ref_grads, got_grads):
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                                   err_msg=name, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("window", [100, 300])
def test_windowed_ring_of_flash_matches_dense(causal, window):
    """Windowed ring-of-flash (r4): each hop's static shard offset rides into the
    flash kernels' band masks (``q_offset``) and the ring truncates to the band's
    hop reach (bidirectional when non-causal) — forward AND gradients equal the
    dense windowed oracle. s=512 over 4 shards → chunk=128: window=100 keeps only
    neighbor hops live (the truncation path), window=300 spans several hops with
    partial bands (offset masks cutting inside blocks)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        ring_flash_attention,
    )

    mesh = make_mesh(4, axis_names=("seq",))
    q, k, v = _qkv(s=4 * 128, h=2, d=8, seed=13)
    ref = ops.full_attention(q, k, v, causal=causal, window=window)
    out = ring_flash_attention(mesh, q, k, v, causal=causal, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    ref_grads = jax.grad(make_loss(lambda q, k, v: ops.full_attention(
        q, k, v, causal=causal, window=window)), argnums=(0, 1, 2))(q, k, v)
    got_grads = jax.grad(make_loss(lambda q, k, v: ring_flash_attention(
        mesh, q, k, v, causal=causal, window=window)), argnums=(0, 1, 2))(q, k, v)
    for name, g_ref, g_got in zip("qkv", ref_grads, got_grads):
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                                   err_msg=name, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("window", [3, 9, 21])
def test_windowed_zigzag_matches_dense(seq_mesh, window):
    """Windowed einsum zig-zag (r4): chunk-pair band masks from global positions
    plus band-liveness skipping equal the dense windowed causal oracle — forward
    AND gradients. s=32 over 8 shards → chunk pairs of 2: window=3 exercises
    band-dead pairs, 9 partial bands, 21 nearly-full visibility."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        zigzag_ring_attention,
    )

    q, k, v = _qkv(seed=17)
    ref = ops.full_attention(q, k, v, causal=True, window=window)
    out = zigzag_ring_attention(seq_mesh, q, k, v, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def make_loss(attn):
        return lambda q, k, v: jnp.sum(jnp.sin(attn(q, k, v)))

    ref_grads = jax.grad(make_loss(lambda q, k, v: ops.full_attention(
        q, k, v, causal=True, window=window)), argnums=(0, 1, 2))(q, k, v)
    got_grads = jax.grad(make_loss(lambda q, k, v: zigzag_ring_attention(
        seq_mesh, q, k, v, window=window)), argnums=(0, 1, 2))(q, k, v)
    for name, g_ref, g_got in zip("qkv", ref_grads, got_grads):
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                                   err_msg=name, rtol=1e-4, atol=1e-5)


def test_windowed_attention_fn_routes_all_schedules(seq_mesh):
    """make_ring_attention_fn(window=W) returns a working attention_fn for the
    einsum ring, the ring-of-flash, and the einsum zig-zag — all matching the same
    dense windowed oracle (the trainer's flag-combination surface)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        make_mesh as mk,
    )

    mesh = mk(4, axis_names=("seq",))
    q, k, v = _qkv(s=4 * 128, h=2, d=8, seed=19)
    ref = ops.full_attention(q, k, v, causal=True, window=200)
    for kwargs in ({}, {"use_flash": True}, {"use_zigzag": True}):
        fn = make_ring_attention_fn(mesh, window=200, **kwargs)
        np.testing.assert_allclose(
            np.asarray(fn(q, k, v, causal=True)), np.asarray(ref),
            rtol=1e-5, atol=1e-5, err_msg=str(kwargs))
