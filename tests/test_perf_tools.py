"""Functional coverage of the r5 measurement tools (tools/bench_pipeline_bubble.py,
tools/bench_decode_analysis.py): tiny shapes, one JSON document each, the fields the
committed artifacts are read by. Timing values are only sanity-bounded — these are
measurement tools, not benchmarks, under test."""

import json
import os
import subprocess
import sys

import pytest

# Heavyweight end-to-end runs: full-suite only.
pytestmark = pytest.mark.slow

_REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _run_tool(script, *args):
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", script), *args],
        capture_output=True, text=True, env=env, timeout=560, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_pipeline_bubble_tool(tmp_path):
    doc = _run_tool("bench_pipeline_bubble.py",
                    "--microbatch-counts", "2", "8",
                    "--out", str(tmp_path / "bubble.json"))
    assert doc["stages"] == 4 and doc["schedule"] == "gpipe"
    assert doc["per_tick_s"] > 0
    rows = doc["rows"]
    assert [r["microbatches"] for r in rows] == [2, 8]
    for r in rows:
        assert r["ticks"] == r["microbatches"] + 3
        assert r["predicted_bubble_fraction"] == pytest.approx(
            3 / r["ticks"], abs=1e-3)
        assert 0 < r["measured_bubble_fraction"] < 1
    assert (tmp_path / "bubble.json").exists()


def test_pipeline_bubble_tool_rejects_single_count():
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "bench_pipeline_bubble.py"),
         "--microbatch-counts", "8"],
        capture_output=True, text=True, env=env, timeout=120, cwd=_REPO)
    assert out.returncode != 0 and "distinct" in out.stderr


def test_telemetry_report_on_real_trainer_output(tmp_path):
    """End-to-end: a real single-trainer --telemetry file (produced in-process on a
    tiny synthetic split) renders through the report CLI with the headline fields.
    Schema-level coverage is tier-1 (tests/test_telemetry.py); this pins the tool
    against ACTUAL trainer output, not a hand-written fixture."""
    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
        Dataset, _normalize, _synthesize_split,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train import single
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
        SingleProcessConfig,
    )

    xs, ys = _synthesize_split(256, seed=500)
    train = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    xs, ys = _synthesize_split(100, seed=501)
    test = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
    path = str(tmp_path / "run.jsonl")
    cfg = SingleProcessConfig(
        n_epochs=1, batch_size_train=64, batch_size_test=100, log_interval=2,
        telemetry=path, health_stats=True,
        results_dir=str(tmp_path / "results"), images_dir=str(tmp_path / "images"))
    single.main(cfg, datasets=(train, test))

    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "telemetry_report.py"),
         path, path],
        capture_output=True, text=True, env=env, timeout=180, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "single run on" in out.stdout
    assert "grad_norm" in out.stdout
    assert "B/A" in out.stdout          # two files -> the comparison table renders


def test_decode_analysis_tool(tmp_path):
    doc = _run_tool("bench_decode_analysis.py",
                    "--d-model", "64", "--layers", "2", "--heads", "4",
                    "--seq", "256", "--gen-batch", "2",
                    "--out", str(tmp_path / "decode.json"))
    assert doc["ops_per_token"] > 0
    assert doc["op_kinds"] and sum(doc["op_kinds"].values()) == doc["ops_per_token"]
    assert doc["t_token_s"] > 0 and doc["tokens_per_s"] > 0
    # CPU run: no HBM roofline — the decomposition fields stay explicit nulls.
    assert doc["t_roofline_s"] is None and doc["per_op_overhead_us"] is None
    assert (tmp_path / "decode.json").exists()
