"""Distributed tracing (utils/trace.py + tools/trace_report.py): unit tier.

The span plane's contracts, jax-free:

- the :class:`Tracer` schema (anchored timestamps, ``*_ts`` attr anchoring,
  None-attr dropping) and its disabled-mode zero-cost guarantee;
- the ONE guarded line parse (``utils.jsonl.read_jsonl``): torn-final-line
  tolerance for router/trace files, corrupt-mid-file rejection — the satellite
  pin that the trace reader and ``load_metrics_jsonl`` share one owner;
- critical-path accounting: segments are exclusive and sum (with overhead) to
  the trace's end-to-end span; redispatch hops and causes surface; span-derived
  TTFT comes from the attempt that actually resolved;
- the wire-protocol pin: a submit line for an untraced request is byte-identical
  to the pre-tracing protocol (tracing off changes NOTHING on the wire);
- the Chrome trace-event export and its validator (the CI trace-smoke gate).

The cross-process fleet tier (2-replica echo fleet, kill mid-flight, span-tree
assertions) lives in ``tests/test_router_fleet.py`` next to the other fleet
acceptance tests.
"""

import concurrent.futures
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.utils import trace
from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
    read_jsonl,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (
    load_metrics_jsonl,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -----------------------------------------------------------------------------------------
# Tracer: emission schema + anchoring
# -----------------------------------------------------------------------------------------


def test_tracer_disabled_is_total_noop(tmp_path):
    t = trace.Tracer("", proc="router")
    assert not t.enabled
    t.span("queue_wait", "abc", time.monotonic())   # no file, no error
    t.close()
    assert list(tmp_path.iterdir()) == []


def test_tracer_span_schema_and_anchoring(tmp_path):
    path = str(tmp_path / "router.jsonl")
    t = trace.Tracer(path, proc="router")
    assert t.enabled
    t0 = time.monotonic()
    t1 = t0 + 0.25
    t.span("dispatch", "tid-1", t0, t1, replica=2, outcome="ok",
           none_attr=None, first_token_ts=t0 + 0.1)
    t.span("redispatch", "tid-1", t1, cause="crash")      # point span
    t.span("decode", None, t0, t1)                        # untraced: dropped
    t.close()
    rows = read_jsonl(path)
    assert len(rows) == 2
    ev = rows[0]
    assert ev["event"] == "span" and ev["name"] == "dispatch"
    assert ev["trace_id"] == "tid-1" and ev["proc"] == "router"
    assert ev["dur_s"] == pytest.approx(0.25, abs=1e-6)
    # Anchored: the monotonic stamp became wall-comparable absolute seconds.
    assert abs(ev["ts"] - time.time()) < 60
    # *_ts attrs are anchored onto the same clock; others ride verbatim.
    assert ev["first_token_ts"] == pytest.approx(ev["ts"] + 0.1, abs=1e-4)
    assert ev["replica"] == 2 and ev["outcome"] == "ok"
    assert "none_attr" not in ev
    assert rows[1]["dur_s"] == 0.0 and rows[1]["cause"] == "crash"


def test_new_trace_id_unique():
    ids = {trace.new_trace_id() for _ in range(2000)}
    assert len(ids) == 2000


# -----------------------------------------------------------------------------------------
# Torn/corrupt files: the shared guarded reader (satellite pin)
# -----------------------------------------------------------------------------------------


def _torn(path):
    with open(path, "a") as f:
        f.write('{"event": "span", "trace_id": "x", "na')   # killed mid-line


def test_trace_file_torn_final_line_tolerated(tmp_path):
    path = str(tmp_path / "replica0.jsonl")
    t = trace.Tracer(path, proc="replica0")
    now = time.monotonic()
    t.span("decode", "tid-a", now, now + 0.1)
    t.span("resolve", "tid-a", now + 0.1, now + 0.2)
    t.close()
    _torn(path)
    spans, other = trace.read_spans([str(tmp_path)])
    assert [s["name"] for s in spans] == ["decode", "resolve"]
    assert other == []


def test_router_telemetry_torn_final_line_tolerated(tmp_path):
    """The router's JsonlWriter telemetry (route/fleet_snapshot lines) gets the
    identical tolerance — one guard, one owner (utils.jsonl.read_jsonl), shared
    by load_metrics_jsonl and the trace reader."""
    path = str(tmp_path / "router.jsonl")
    with open(path, "w") as f:
        f.write('{"event": "route", "request_id": 0}\n')
        f.write('{"event": "fleet_snapshot", "inflight": 1}\n')
        f.write('{"event": "router_summary", "ok": 1')      # torn tail
    for reader in (read_jsonl, load_metrics_jsonl):
        rows = reader(path)
        assert [r["event"] for r in rows] == ["route", "fleet_snapshot"]


def test_corrupt_midfile_line_still_raises(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    with open(path, "w") as f:
        f.write('{"event": "span", "trace_id": "x", "name": "decode"}\n')
        f.write("NOT JSON\n")
        f.write('{"event": "span", "trace_id": "x", "name": "resolve"}\n')
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(path)
    with pytest.raises(json.JSONDecodeError):
        trace.read_spans([path])


# -----------------------------------------------------------------------------------------
# Critical-path accounting
# -----------------------------------------------------------------------------------------


def _span(name, ts, dur, proc="router", tid="t1", **attrs):
    return {"event": "span", "trace_id": tid, "name": name, "proc": proc,
            "ts": ts, "dur_s": dur, **attrs}


def _redispatched_trace(tid="t1", base=1000.0):
    """A synthetic two-hop trace: dispatch to replica 1 dies (crash), replay
    lands on replica 0 and resolves. Layout (seconds after ``base``):

    0.00-0.01  queue_wait (router)        0.21-0.25  queue_wait (replica0)
    0.01       route -> replica 1         0.25-0.30  prefill
    0.01-0.20  dispatch DRAINED           0.30-0.50  decode (first at +0.05)
    0.20       redispatch cause=crash     0.50-0.52  resolve
    0.20-0.21  queue_wait (router, hop 2)
    0.21       route -> replica 0
    0.21-0.51  dispatch ok (overlaps the replica's own spans)

    Replica 1 flushed its own queue_wait + prefill spans before dying (the
    real kill-mid-decode shape): they sit INSIDE the drained window, charged
    once as failed_dispatch, never double-counted into their segments.
    """
    return [
        _span("queue_wait", base, 0.01, tid=tid, hop=0),
        _span("route", base + 0.01, 0.0, tid=tid, replica=1,
              affinity_hit=False, spilled=False),
        _span("dispatch", base + 0.01, 0.19, tid=tid, replica=1,
              outcome="drained", hop=0),
        _span("queue_wait", base + 0.02, 0.01, proc="replica1", tid=tid),
        _span("prefill", base + 0.03, 0.04, proc="replica1", tid=tid,
              chunk=32, cache_hit_len=0),
        _span("decode", base + 0.07, 0.10, proc="replica1", tid=tid,
              first_token_s=0.02, first_token_ts=base + 0.09, finish="ok"),
        _span("redispatch", base + 0.20, 0.0, tid=tid, replica=1,
              cause="crash", hop=1),
        _span("queue_wait", base + 0.20, 0.01, tid=tid, hop=1),
        _span("route", base + 0.21, 0.0, tid=tid, replica=0,
              affinity_hit=False, spilled=True),
        _span("dispatch", base + 0.21, 0.30, tid=tid, replica=0,
              outcome="ok", hop=1),
        _span("queue_wait", base + 0.21, 0.04, proc="replica0", tid=tid),
        _span("prefill", base + 0.25, 0.05, proc="replica0", tid=tid,
              chunk=32, cache_hit_len=0),
        _span("decode", base + 0.30, 0.20, proc="replica0", tid=tid,
              first_token_s=0.05, first_token_ts=base + 0.35, finish="ok"),
        _span("resolve", base + 0.50, 0.02, tid=tid, finish="ok"),
    ]


def test_breakdown_segments_sum_to_e2e_with_hops():
    spans = _redispatched_trace()
    down = trace.trace_breakdown(spans)
    seg = down["segments"]
    assert down["e2e_s"] == pytest.approx(0.52, abs=1e-9)
    assert seg["router_queue_wait"] == pytest.approx(0.02)
    # The dead replica's own spans (queue_wait 0.01, prefill 0.04, decode 0.10
    # inside the drained window) are NOT double-counted into their segments —
    # failed_dispatch charges that interval once, in full.
    assert seg["replica_queue_wait"] == pytest.approx(0.04)
    assert seg["failed_dispatch"] == pytest.approx(0.19)     # only the drained hop
    assert seg["prefill"] == pytest.approx(0.05)
    assert seg["decode_first"] == pytest.approx(0.05)
    assert seg["decode_tail"] == pytest.approx(0.15)
    assert seg["resolve"] == pytest.approx(0.02)
    # Exclusive accounting: segments + overhead == e2e exactly.
    assert sum(seg.values()) == pytest.approx(down["e2e_s"], abs=1e-9)
    assert down["hops"] == 2 and down["redispatch_causes"] == ["crash"]
    assert down["resolved"] is True
    # Span-derived TTFT: origin -> the resolving attempt's first token.
    assert down["ttft_s"] == pytest.approx(0.35, abs=1e-9)
    assert down["finish"] == "ok"


def test_summarize_counts_orphans_and_redispatched():
    spans = _redispatched_trace(tid="good")
    # An orphan: spans but no terminal resolve/client (a stranded future).
    spans += [_span("queue_wait", 2000.0, 0.01, tid="lost"),
              _span("dispatch", 2000.01, 0.05, tid="lost", outcome="drained")]
    summ = trace.summarize_traces(spans)
    assert summ["traces"] == 2 and summ["orphans"] == 1
    assert summ["orphan_ids"] == ["lost"]
    # Redispatch accounting follows the explicit hop-marker spans ("good" has
    # one); a drained dispatch alone ("lost" — the router died before the
    # marker) is an orphan, not a counted redispatch.
    assert summ["redispatched"] == 1
    assert summ["ttft_s"]["p50"] == pytest.approx(0.35)
    assert list(summ["by_trace"]) == ["good", "lost"]   # slowest-first


def test_reconcile_ttft_prefers_route_events():
    summ = trace.summarize_traces(_redispatched_trace())
    routes = [{"event": "route", "ttft_s": 0.35}]
    serves = [{"event": "serve", "ttft_s": 99.0}]
    rec = trace.reconcile_ttft(summ, routes + serves)
    assert rec["source"] == "route"
    assert rec["p50_ratio"] == pytest.approx(1.0, abs=1e-6)
    rec = trace.reconcile_ttft(summ, serves)
    assert rec["source"] == "serve"
    assert trace.reconcile_ttft(summ, []) is None


# -----------------------------------------------------------------------------------------
# Wire-protocol pin: tracing off is byte-identical
# -----------------------------------------------------------------------------------------


def test_submit_msg_untraced_is_byte_identical_to_pre_tracing_protocol():
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.router import (
        Router,
        RouterRequest,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.scheduler import (
        SamplingParams,
    )

    req = RouterRequest(prompt=np.asarray([3, 1, 4], np.int32),
                        max_new_tokens=7, sampling=SamplingParams(),
                        request_id=42,
                        future=concurrent.futures.Future(), arrival_s=0.0)
    msg = Router._submit_msg(req, now=0.0)
    # The EXACT pre-tracing line — field set AND order (json.dumps preserves
    # insertion order, so this pins the bytes on the wire).
    assert json.dumps(msg) == json.dumps({
        "op": "submit", "id": 42, "prompt": [3, 1, 4], "max_new_tokens": 7,
        "temperature": 0.0, "top_k": 0, "top_p": 1.0, "timeout_s": None})
    # A traced request adds exactly one field, after all existing ones.
    req.trace_id = "tid-9"
    traced = Router._submit_msg(req, now=0.0)
    assert list(traced) == list(msg) + ["trace_id"]
    assert traced["trace_id"] == "tid-9"


# -----------------------------------------------------------------------------------------
# Chrome trace-event export + validator (the CI trace-smoke gate)
# -----------------------------------------------------------------------------------------


def test_chrome_export_valid_schema_tracks_and_lanes():
    spans = (_redispatched_trace(tid="t1")
             + _redispatched_trace(tid="t2", base=1100.0))
    doc = trace.chrome_trace(spans)
    assert trace.validate_chrome(doc) == []
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == len(spans)
    # One pid track per process, named; router sorted first.
    names = {m["args"]["name"]: m["pid"] for m in metas
             if m["name"] == "process_name"}
    assert set(names) == {"router", "replica0", "replica1"}
    sort_idx = {m["pid"]: m["args"]["sort_index"] for m in metas
                if m["name"] == "process_sort_index"}
    assert sort_idx[names["router"]] < sort_idx[names["replica0"]]
    # One tid lane per trace, so concurrent requests never nest into nonsense.
    assert {e["tid"] for e in xs} == {1, 2}
    # Timestamps are relative micros, attrs preserved under args.
    assert min(e["ts"] for e in xs) == 0.0
    assert all(e["args"]["trace_id"] in ("t1", "t2") for e in xs)
    assert all(e["dur"] >= 1.0 for e in xs)    # point spans visible, not lost


def test_chrome_validator_catches_broken_events():
    spans = _redispatched_trace()
    doc = trace.chrome_trace(spans)
    doc["traceEvents"][-1]["ts"] = float("nan")
    del doc["traceEvents"][-2]["args"]["trace_id"]
    doc["traceEvents"].append({"name": "stray", "cat": "serve", "ph": "X",
                               "pid": 999, "tid": 1, "ts": 1.0, "dur": 1.0,
                               "args": {"trace_id": "t1"}})
    problems = trace.validate_chrome(doc)
    assert any("bad ts" in p for p in problems)
    assert any("no trace_id" in p for p in problems)
    assert any("no process_name" in p for p in problems)
    assert trace.validate_chrome({"traceEvents": None}) == \
        ["traceEvents is not a list"]


# -----------------------------------------------------------------------------------------
# trace_report CLI
# -----------------------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_cli_renders_and_validates(tmp_path, capsys):
    tracer = trace.Tracer(str(tmp_path / "router.jsonl"), proc="router")
    for s in _redispatched_trace():
        # Re-emit the synthetic trace through a real Tracer so the file is the
        # production byte format (anchor shifts every ts consistently).
        tracer.span(s["name"], s["trace_id"], s["ts"],
                    s["ts"] + s["dur_s"] if s["dur_s"] else None,
                    **{k: v for k, v in s.items()
                       if k not in ("event", "trace_id", "name", "proc",
                                    "ts", "dur_s")})
    tracer.close()
    with open(tmp_path / "telemetry.jsonl", "w") as f:
        f.write('{"event": "route", "ttft_s": 0.35}\n')
    report = _load_tool("trace_report")
    chrome = tmp_path / "chrome.json"
    rc = report.main([str(tmp_path / "router.jsonl"),
                      str(tmp_path / "telemetry.jsonl"),
                      "--slowest", "1", "--chrome", str(chrome), "--validate"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 traces" in out and "1 redispatched" in out and "0 orphan" in out
    assert "failed_dispatch" in out and "decode_first" in out
    assert "redispatch" in out and "cause=crash" in out
    assert "ttft reconciliation" in out and "route" in out
    doc = json.loads(chrome.read_text())
    assert trace.validate_chrome(doc) == []

    # An orphan trace under --validate is a nonzero exit (the CI gate).
    orphan = trace.Tracer(str(tmp_path / "orphan.jsonl"), proc="router")
    orphan.span("queue_wait", "stranded", 1.0, 2.0)
    orphan.close()
    assert report.main([str(tmp_path / "orphan.jsonl"), "--validate"]) == 1
