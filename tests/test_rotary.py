"""Rotary position embeddings: the relative-position property, model wiring, and the
LM decode-parity invariant under RoPE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.ops.rotary import (
    apply_rotary,
)


def test_relative_position_invariance():
    """THE RoPE property: ⟨R(p)q, R(p')k⟩ depends only on p − p' — shifting both
    positions by the same offset leaves every q·k score unchanged."""
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 8, 2, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 8, 2, 16)).astype(np.float32))

    def scores(shift):
        pos = jnp.arange(8) + shift
        qr, kr = apply_rotary(q, pos), apply_rotary(k, pos)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(np.asarray(scores(0)), np.asarray(scores(100)),
                               rtol=1e-4, atol=1e-4)


def test_scalar_position_matches_indexed_row():
    """Decode-style scalar-position rotation equals the corresponding row of the
    full-sequence rotation (the forward/decode consistency RoPE decode relies on)."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 8, 4, 16)).astype(np.float32))
    full = apply_rotary(x, jnp.arange(8))
    for t in (0, 3, 7):
        row = apply_rotary(x[:, t], jnp.asarray(t, jnp.int32))
        np.testing.assert_allclose(np.asarray(row), np.asarray(full[:, t]),
                                   rtol=1e-6, atol=1e-6)


def test_odd_head_dim_rejected():
    with pytest.raises(ValueError, match="even head dim"):
        apply_rotary(jnp.zeros((1, 4, 2, 15)), jnp.arange(4))


def test_rope_changes_classifier_output_same_params():
    """rope=True is a pure q/k transform: identical parameter tree, different
    function — the wiring sanity check."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        build_model,
    )

    plain = build_model("transformer")
    roped = build_model("transformer", rope=True)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 28, 28, 1)).astype(np.float32))
    params = plain.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    out_plain = plain.apply({"params": params}, x)
    out_roped = roped.apply({"params": params}, x)
    assert not np.allclose(np.asarray(out_plain), np.asarray(out_roped))


@pytest.mark.slow  # ~13 s: full train + KV-cache decode; the fast tier keeps
                   # the rotation-math and cache-parity unit pins
def test_lm_rope_decode_matches_full_forward():
    """The decode-parity invariant under RoPE (+GQA): the KV-cache path rotates its
    single position by the same formula as the teacher-forced forward."""
    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm

    model = lm.TransformerLM(vocab_size=9, seq_len=16, embed_dim=32, num_layers=2,
                             num_heads=4, num_kv_heads=2, rope=True)
    ids0 = jnp.zeros((1, 16), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(3)}, ids0)["params"]
    assert "pos_embed" not in params            # RoPE owns position
    rng = np.random.default_rng(4)
    targets = jnp.asarray(rng.integers(0, 8, size=(2, 16)).astype(np.int32))
    inputs = model.shift_right(targets)
    ref = model.apply({"params": params}, inputs)

    cache = lm.init_cache(model, batch=2)
    for t in range(model.seq_len):
        cache, log_probs = lm.decode_step(model, params, cache, inputs[:, t],
                                          jnp.asarray(t, jnp.int32))
        np.testing.assert_allclose(np.asarray(log_probs), np.asarray(ref[:, t]),
                                   rtol=1e-5, atol=1e-5, err_msg=f"position {t}")
