"""Paged KV cache (DESIGN.md §27): engine identity, allocator discipline, COW.

The paged store's whole contract, pinned at tier-1 sizes:

1. **Token identity** — a ``kv_layout="paged"`` engine is token-IDENTICAL to
   the contiguous oracle on the same workload, across MHA/GQA/window/RoPE,
   int8 planes, prefix-cache sharing, and speculative decoding: the adapters
   gather the table-mapped view and run the SAME attention program, so this is
   bitwise by construction — any drift is a page-mapping bug.
2. **One program per family** — paging adds page tables as DATA, never shape:
   ``trace_count`` pins hold, plus exactly one COW program
   (``cow_trace_count``) no matter how many boundary pages get copied.
3. **Reservation-at-admission** — exhaustion is a typed ``KVPagesExhausted``
   refusal carrying who got in and who must requeue, never a partial bind or
   a mid-decode failure; a drain frees pages and the refused re-admit.
4. **No leaks** — park/resume/expire/prefix-share all settle through page
   refcounts; after everything finishes and the prefix cache clears, the pool
   is byte-for-byte empty (``in_use == 0``).

Plus the satellite pins: PrefixCache's MEASURED byte budget (an int8 engine
fits ~3x the fp32 entry count in the same bytes), the ``kv_pages`` telemetry
surface end to end through the server, and the planner's paged residency.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
    ContinuousBatchingEngine,
    Request,
    Server,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
    KVPagesExhausted,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.pagepool import (
    PagePool,
    PagePoolExhausted,
    pages_for,
)
from csed_514_project_distributed_training_using_pytorch_tpu.serving.prefix_cache import (
    PrefixCache,
    _tree_nbytes,
)

SMALL = dict(vocab_size=9, seq_len=16, embed_dim=32, num_layers=2, num_heads=4)


def _model(**kw):
    return lm.TransformerLM(**{**SMALL, **kw})


def _params(model, seed=0):
    ids = jnp.zeros((1, model.seq_len), jnp.int32)
    return model.init({"params": jax.random.PRNGKey(seed)}, ids)["params"]


def _mixed_requests(model, n, seed=0):
    rng = np.random.default_rng(seed)
    return [Request(
        prompt=rng.integers(0, model.vocab_size - 1,
                            size=int(rng.integers(0, model.seq_len // 2))
                            ).astype(np.int32),
        max_new_tokens=int(rng.integers(1, model.seq_len)), request_id=i)
        for i in range(n)]


def _run_pair(model, params, reqs, *, paged_kw=None, **common):
    """The same workload through contiguous and paged engines; returns both
    engines plus their {request_id: tokens} maps."""
    a = ContinuousBatchingEngine(model, params, **common)
    ta = {c.request.request_id: c.tokens for c in a.run(list(reqs))}
    b = ContinuousBatchingEngine(model, params, kv_layout="paged",
                                 **{**common, **(paged_kw or {})})
    tb = {c.request.request_id: c.tokens for c in b.run(list(reqs))}
    return a, b, ta, tb


# -----------------------------------------------------------------------------------------
# Token identity + trace pins
# -----------------------------------------------------------------------------------------


@pytest.mark.parametrize("cfg", [
    dict(), dict(num_kv_heads=2), dict(attention_window=5), dict(rope=True),
], ids=["mha", "gqa", "window", "rope"])
def test_paged_identical_to_contiguous_with_prefix_cache(cfg):
    """The tentpole pin: paged == contiguous token-for-token on a mixed
    workload through fewer slots than requests, prefix cache on, with the
    decode/prefill one-program pins intact on the paged side."""
    model = _model(**cfg)
    params = _params(model)
    reqs = _mixed_requests(model, 6, seed=7)
    a, b, ta, tb = _run_pair(model, params, reqs, num_slots=3,
                             prefix_cache_entries=4,
                             paged_kw=dict(page_size=4))
    for i in ta:
        np.testing.assert_array_equal(ta[i], tb[i])
    assert b.trace_count == 1
    assert all(v <= 1 for v in b.prefill_trace_counts.values())
    # Everything drained and nothing parked: only prefix-cache entries may
    # still hold pages.
    stats = b.page_stats()
    assert stats["slot_pages_held"] == 0
    b.prefix_cache.clear()
    assert b.page_stats()["in_use"] == 0


def test_paged_identical_int8_planes():
    """Quantize-on-write planes ride the paged pools (codes + scale pools):
    int8 paged == int8 contiguous exactly."""
    model = _model()
    params = _params(model)
    reqs = _mixed_requests(model, 5, seed=11)
    a, b, ta, tb = _run_pair(model, params, reqs, num_slots=3,
                             kv_dtype="int8", paged_kw=dict(page_size=4))
    for i in ta:
        np.testing.assert_array_equal(ta[i], tb[i])
    assert b.plane_layout.startswith("paged:4:")
    assert b.plane_layout != a.plane_layout


def test_paged_identical_under_speculation():
    """Spec mode (ngram draft + batched verify): the paged verify program is
    the one that runs — the decode program legitimately never traces
    (``trace_count == 0`` on BOTH sides), the verify pin carries the
    one-program contract."""
    model = _model()
    params = _params(model)
    rng = np.random.default_rng(5)
    reqs = []
    for i in range(4):
        prompt = np.tile(np.arange(1, 4, dtype=np.int32), 3)
        reqs.append(Request(prompt=prompt,
                            max_new_tokens=int(rng.integers(3, 8)),
                            request_id=i))
    a, b, ta, tb = _run_pair(model, params, reqs, num_slots=2,
                             spec="ngram", spec_k=3,
                             paged_kw=dict(page_size=4))
    for i in ta:
        np.testing.assert_array_equal(ta[i], tb[i])
    assert b.trace_count == a.trace_count
    assert dict(b.verify_trace_counts) == dict(a.verify_trace_counts)
    assert all(v <= 1 for v in b.verify_trace_counts.values())


def test_paged_prefix_sharing_cow_single_program():
    """A partial prefix hit whose length is not page-aligned shares the full
    pages by refcount and copies exactly the boundary page (COW) — tokens
    identical to the contiguous engine, one compiled COW program no matter
    how many copies run."""
    model = _model()
    params = _params(model)
    base = np.asarray([1, 2, 3, 4, 5, 6, 7], np.int32)
    first = [Request(prompt=base.copy(), max_new_tokens=2, request_id=0)]
    later = [Request(prompt=np.concatenate([base[:6], [8]]).astype(np.int32),
                     max_new_tokens=4, request_id=1),
             Request(prompt=base.copy(), max_new_tokens=4, request_id=2)]

    def run(engine):
        out = {c.request.request_id: c.tokens for c in engine.run(list(first))}
        out.update({c.request.request_id: c.tokens
                    for c in engine.run(list(later))})
        return out

    a = ContinuousBatchingEngine(model, params, num_slots=2,
                                 prefix_cache_entries=4,
                                 prefill_chunk_sizes=(4, 8))
    b = ContinuousBatchingEngine(model, params, num_slots=2, kv_layout="paged",
                                 page_size=4, prefix_cache_entries=4,
                                 prefill_chunk_sizes=(4, 8))
    ta, tb = run(a), run(b)
    for i in ta:
        np.testing.assert_array_equal(ta[i], tb[i])
    assert b.prefix_cache.hits >= 1
    assert b.cow_copies >= 1
    assert b.cow_trace_count == 1
    pool = b.page_stats()
    assert pool["shared"] >= 1                 # full pages genuinely refcounted


# -----------------------------------------------------------------------------------------
# Exhaustion -> typed refusal -> drain recovers
# -----------------------------------------------------------------------------------------


def test_pool_exhaustion_typed_refusal_then_drain_recovers():
    """Over-admitting full-context requests on an undersized pool raises
    KVPagesExhausted AFTER binding what fit: the admitted decode normally, the
    refused carry their original Request objects, and after a drain the same
    requests admit cleanly — backpressure, never OOM."""
    model = _model()
    params = _params(model)
    eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                   kv_layout="paged", page_size=4, num_pages=9)
    reqs = [Request(prompt=(np.arange(1, 8) % 8).astype(np.int32),
                    max_new_tokens=16, request_id=i) for i in range(4)]
    with pytest.raises(KVPagesExhausted) as exc_info:
        eng.admit_many(list(zip(eng.free_slots(), reqs)))
    exc = exc_info.value
    assert len(exc.admitted) == 2 and len(exc.refused) == 2
    assert exc.refused == reqs[2:]             # FIFO order, original objects
    assert exc.needed > exc.free
    while eng.num_active:
        eng.step()
    # The drain returned every page: the refused now admit without incident.
    eng.admit_many(list(zip(eng.free_slots(), exc.refused)))
    while eng.num_active:
        eng.step()
    stats = eng.page_stats()
    assert stats["in_use"] == 0
    assert stats["refusals"] >= 1


def test_run_requeues_refusals_and_stays_identical():
    """engine.run() under pool pressure: refusals are requeued and retried as
    decode frees pages — the final streams are identical to the contiguous
    engine's, pressure only reorders WHEN work starts."""
    model = _model()
    params = _params(model)
    reqs = _mixed_requests(model, 10, seed=3)
    a, b, ta, tb = _run_pair(model, params, reqs, num_slots=4,
                             paged_kw=dict(page_size=4, num_pages=9))
    for i in ta:
        np.testing.assert_array_equal(ta[i], tb[i])
    assert b.page_stats()["in_use"] == 0


def test_park_resume_expire_returns_every_page():
    """The preemption lifecycle settles through refcounts: park moves the
    slot's pages into the prefix-cache entry, resume re-shares them, expiry
    plus a cache clear returns the pool to empty."""
    model = _model()
    params = _params(model)
    eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                   kv_layout="paged", page_size=4,
                                   prefix_cache_entries=4)
    req = Request(prompt=np.asarray([1, 2, 3, 4, 5], np.int32),
                  max_new_tokens=8, request_id=0, preemptible=True)
    eng.admit(0, req)
    for _ in range(4):
        eng.step()
    parked = eng.park(0)
    assert eng.page_stats()["in_use"] > 0      # the entry owns the pages
    eng.admit(0, parked)
    for _ in range(2):
        eng.step()
    req.deadline_s = time.monotonic() - 1.0
    comps = eng.expire()
    assert len(comps) == 1 and comps[0].finish == "timeout"
    eng.prefix_cache.clear()
    assert eng.page_stats()["in_use"] == 0


def test_server_loop_requeues_page_refusals():
    """End to end through the Server: more concurrent submissions than the
    pool can hold all complete ok — the loop catches KVPagesExhausted and
    requeues, callers only ever see their futures resolve."""
    model = _model()
    params = _params(model)
    eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                   kv_layout="paged", page_size=4, num_pages=9)
    server = Server(eng).start()
    futs = [server.submit((np.arange(1, 7) % 8).astype(np.int32),
                          max_new_tokens=8) for _ in range(8)]
    comps = [f.result(timeout=60) for f in futs]
    server.stop()
    assert all(c.ok for c in comps)
    assert eng.page_stats()["refusals"] >= 0   # pressure is workload-timing
    assert eng.page_stats()["in_use"] == 0


# -----------------------------------------------------------------------------------------
# Byte accounting + telemetry surface
# -----------------------------------------------------------------------------------------


def test_paged_byte_accounting_and_page_stats():
    model = _model()
    params = _params(model)
    eng = ContinuousBatchingEngine(model, params, num_slots=3,
                                   kv_layout="paged", page_size=4)
    doc = eng.byte_accounting()
    assert doc["kv_layout"] == "paged"
    assert doc["page_size"] == 4
    assert doc["num_pages"] == eng._pagepool.num_pages
    assert doc["page_bytes"] * doc["num_pages"] == doc["kv_bytes_resident"]
    contiguous = ContinuousBatchingEngine(model, params, num_slots=3)
    assert contiguous.byte_accounting()["kv_layout"] == "contiguous"
    assert contiguous.page_stats() is None
    stats = eng.page_stats()
    assert stats["free"] == stats["usable"] and stats["in_use"] == 0


def test_serve_summary_and_kv_pages_event(tmp_path):
    """The telemetry chain: a paged server run emits a standalone kv_pages
    event and a serve_summary whose kv_pages field carries the same ledger;
    a contiguous run emits neither (field null, no event)."""
    model = _model()
    params = _params(model)

    def drain(eng):
        path = tmp_path / f"t_{id(eng)}.jsonl"
        server = Server(eng, telemetry=str(path)).start()
        futs = [server.submit([1, 2, 3], max_new_tokens=4) for _ in range(3)]
        for f in futs:
            f.result(timeout=60)
        server.stop()
        return [json.loads(line) for line in path.read_text().splitlines()]

    paged = drain(ContinuousBatchingEngine(model, params, num_slots=2,
                                           kv_layout="paged", page_size=4))
    kinds = [e["event"] for e in paged]
    assert "kv_pages" in kinds
    summary = next(e for e in paged if e["event"] == "serve_summary")
    event = next(e for e in paged if e["event"] == "kv_pages")
    assert summary["kv_pages"]["page_size"] == 4
    assert event["page_size"] == 4
    assert summary["bytes"]["kv_layout"] == "paged"

    flat = drain(ContinuousBatchingEngine(model, params, num_slots=2))
    assert "kv_pages" not in [e["event"] for e in flat]
    summary = next(e for e in flat if e["event"] == "serve_summary")
    assert summary["kv_pages"] is None


# -----------------------------------------------------------------------------------------
# PrefixCache: measured bytes, on_evict, the int8 regression
# -----------------------------------------------------------------------------------------


def test_prefix_cache_measured_bytes_and_on_evict_all_paths():
    evicted = []
    cache = PrefixCache(8, capacity_bytes=64, on_evict=evicted.append)
    mk = lambda v, n: {"k": np.full(n, v, np.int8)}
    cache.insert([1, 2], mk(1, 24))
    assert cache.bytes == 24
    cache.insert([3, 4], mk(2, 24))
    cache.insert([5, 6], mk(3, 24))                # byte pressure: entry 1 out
    assert len(cache) == 2 and cache.bytes == 48
    assert [p["k"][0] for p in evicted] == [1]
    cache.insert([3, 4, 9], mk(4, 8))              # covered-drop fires it too
    assert [p["k"][0] for p in evicted] == [1, 2]
    cache.clear()                                  # and clear, per entry
    assert [p["k"][0] for p in evicted] == [1, 2, 3, 4]
    assert cache.bytes == 0 and len(cache) == 0
    # Explicit nbytes (the paged engine's page-span charge) overrides measure.
    cache.insert([7], mk(5, 2), nbytes=1000)
    assert cache.bytes == 1000
    # The byte budget never evicts the LAST entry (an oversized single entry
    # is resident-until-displaced, not a permanently empty cache).
    assert len(cache) == 1


def test_prefix_cache_nbytes_counts_scale_planes():
    planes = {"k": np.zeros((4, 2), np.int8), "k_scale": np.zeros(4, np.float32),
              "nested": {"v": np.zeros(3, np.float64)}}
    assert _tree_nbytes(planes) == 8 + 16 + 24


def test_int8_engine_fits_3x_entries_in_same_byte_budget():
    """THE satellite regression: with capacity counted in MEASURED bytes, an
    int8 engine's prefix entries (int8 codes + f32 scales) fit >= 3x the
    fp32 entry count in the same budget — before, capacity-in-entries charged
    both layouts identically and the int8 engine wasted its savings."""
    model = _model()
    params = _params(model)

    def fill(kv_dtype, budget):
        eng = ContinuousBatchingEngine(model, params, num_slots=2,
                                       kv_dtype=kv_dtype,
                                       prefix_cache_bytes=budget)
        rng = np.random.default_rng(1)
        for i in range(16):
            prompt = np.concatenate([
                [i % (model.vocab_size - 1)],
                rng.integers(0, model.vocab_size - 1, size=5)]
            ).astype(np.int32)
            eng.run([Request(prompt=prompt, max_new_tokens=1, request_id=i)])
        return eng.prefix_cache

    probe = fill("model", 1 << 40)
    entry_bytes = probe.bytes // max(len(probe), 1)
    budget = int(3.5 * entry_bytes)
    fp32 = fill("model", budget)
    int8 = fill("int8", budget)
    assert fp32.bytes <= budget and int8.bytes <= budget
    assert len(int8) >= 3 * len(fp32)


# -----------------------------------------------------------------------------------------
# Allocator property tests (engine-free)
# -----------------------------------------------------------------------------------------


def test_pagepool_random_walk_conserves_pages():
    """Random alloc/ref/unref walk: refcounts and free lists stay consistent,
    and releasing everything returns the pool to fully free."""
    rng = np.random.default_rng(0)
    pool = PagePool(32, page_size=4, groups=2)
    held: list[list[int]] = []
    for _ in range(300):
        op = rng.integers(0, 3)
        if op == 0:
            try:
                held.append(pool.alloc(int(rng.integers(1, 4)),
                                       group=int(rng.integers(0, 2))))
            except PagePoolExhausted:
                pass
        elif op == 1 and held:
            span = held[int(rng.integers(0, len(held)))]
            pool.ref(span)
            held.append(list(span))
        elif op == 2 and held:
            pool.unref(held.pop(int(rng.integers(0, len(held)))))
        total_refs = sum(pool.refcount(p) for p in range(pool.num_pages))
        assert total_refs == pool.groups + sum(len(s) for s in held)
        assert pool.free_pages() == pool.usable_pages - len(
            {p for s in held for p in s})
    for span in held:
        pool.unref(span)
    assert pool.free_pages() == pool.usable_pages


def test_pages_for_matches_reservation_arithmetic():
    assert pages_for(0, 4) == 0
    assert pages_for(1, 4) == 1
    assert pages_for(4, 4) == 1
    assert pages_for(5, 4) == 2
    with pytest.raises(ValueError):
        pages_for(-1, 4)


# -----------------------------------------------------------------------------------------
# Planner: paged residency pricing
# -----------------------------------------------------------------------------------------


def test_predict_serve_paged_prices_page_residency():
    from csed_514_project_distributed_training_using_pytorch_tpu.plan.costs import (
        ServeStats,
        Topology,
        predict_serve,
    )

    stats = ServeStats(name="fixture", param_bytes=1e6,
                       kv_bytes_per_slot=1024 * 64.0, seq_len=1024,
                       flops_per_token=1e6, num_layers=2, embed_dim=64)
    topo = Topology(num_devices=1, device_kind="cpu", hbm_bytes=int(16e6))
    kw = dict(tp=1, dp=1, num_slots=8, prompt_len=128)
    flat = predict_serve(stats, topo, **kw)
    # The contiguous default is bitwise-unchanged by the new kwargs.
    assert predict_serve(stats, topo, **kw,
                         kv_layout="contiguous").to_dict() == flat.to_dict()
    # Full-context paged (the conservative pin) rounds UP to page multiples:
    # never cheaper than contiguous per slot, here equal (1024 % 64 == 0).
    full = predict_serve(stats, topo, **kw, kv_layout="paged", page_size=64)
    assert full.kv_bytes_per_chip == flat.kv_bytes_per_chip
    # A short-context mix shrinks residency by the measured page span, and
    # the freed bytes buy admissible slots.
    short = predict_serve(stats, topo, **kw, kv_layout="paged", page_size=64,
                          context_tokens=128)
    assert short.kv_bytes_per_chip < flat.kv_bytes_per_chip
    assert short.slots_at_budget > flat.slots_at_budget
    with pytest.raises(ValueError):
        predict_serve(stats, topo, **kw, kv_layout="ragged")
