"""Parallelism-layer tests on the 8-device virtual CPU mesh (SURVEY.md §4): mesh construction,
ppermute ring (the p2p smoke analog of reference src/run1.py), explicit all-reduce, and the
DDP-equivalence oracle — the mesh-compiled SPMD step must reproduce the single-device step on
the same global batch, since XLA's auto-inserted gradient all-reduce is the DDP Reducer analog
(reference src/train_dist.py:63,83)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    all_reduce_sum, make_mesh, ring_pass,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import data_parallel as dp
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state, make_epoch_fn, make_eval_fn, make_train_step,
)


@pytest.fixture(scope="module")
def mesh8(devices8):
    return make_mesh(8)


@pytest.fixture
def model_and_states():
    # function-scoped: donated steps consume state buffers (device_put may alias the
    # device-0 shard), so each test needs a fresh state
    model = Net()
    return model, create_train_state(model, jax.random.PRNGKey(0))


def test_make_mesh_shapes(devices8):
    assert make_mesh(8).shape == {"data": 8}
    assert make_mesh(4).shape == {"data": 4}
    m = make_mesh(8, axis_names=("data", "model"), axis_shape=(4, 2))
    assert m.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(8, axis_names=("data", "model"), axis_shape=(3, 2))


def test_ring_pass_rotates(mesh8):
    """Device i's value lands on device i+1 (mod 8) — the send/recv smoke-test analog
    (reference src/run1.py:8-17, where rank 0's tensor arrives at rank 1)."""
    vals = jnp.arange(8.0)
    out = np.asarray(ring_pass(mesh8, vals))
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_ring_pass_full_cycle_identity(mesh8):
    x = jnp.arange(8.0)
    for _ in range(8):
        x = ring_pass(mesh8, x)
    np.testing.assert_array_equal(np.asarray(x), np.arange(8.0))


def test_all_reduce_sum(mesh8):
    vals = jnp.arange(16.0).reshape(8, 2)  # 2 elements per device
    out = np.asarray(all_reduce_sum(mesh8, vals))
    np.testing.assert_allclose(out, np.arange(16.0).reshape(8, 2).sum(0))


def test_dp_step_equals_single_device(mesh8, model_and_states):
    """THE oracle (SURVEY.md §7 build step 3): N-chip SPMD step == 1-chip step on the same
    global batch, i.e. 'psum grad == sequential grad on the concatenated batch'."""
    model, state0 = model_and_states
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 10)
    rng = jax.random.PRNGKey(3)
    step = make_train_step(model, learning_rate=0.02, momentum=0.5)

    single = jax.jit(step)
    state_s = state0
    for _ in range(3):
        state_s, loss_s = single(state_s, x, y, rng)

    sharded = dp.compile_step(step, mesh8)
    state_d = jax.device_put(state0, dp.replicated(mesh8))
    xd = jax.device_put(x, dp.batch_sharding(mesh8))
    yd = jax.device_put(y, dp.batch_sharding(mesh8))
    for _ in range(3):
        state_d, loss_d = sharded(state_d, xd, yd, rng)

    np.testing.assert_allclose(float(loss_s), float(loss_d), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state_s.params),
                    jax.tree_util.tree_leaves(state_d.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_dp_epoch_equals_single_device(mesh8, model_and_states):
    """Same oracle for the scanned-epoch fast path with a sharded index plan."""
    model, state0 = model_and_states
    images = jax.random.normal(jax.random.PRNGKey(4), (128, 28, 28, 1))
    labels = jax.random.randint(jax.random.PRNGKey(5), (128,), 0, 10)
    idx = jnp.arange(128).reshape(4, 32)
    rng = jax.random.PRNGKey(6)
    epoch = make_epoch_fn(model, learning_rate=0.01, momentum=0.5)

    state_s, losses_s = jax.jit(epoch)(state0, images, labels, idx, rng)

    ep_d = dp.compile_epoch(epoch, mesh8)
    state_d = jax.device_put(state0, dp.replicated(mesh8))
    img_d, lab_d = dp.device_put_dataset(mesh8, np.asarray(images), np.asarray(labels))
    idx_d = jax.device_put(idx, jax.sharding.NamedSharding(
        mesh8, jax.sharding.PartitionSpec(None, "data")))
    state_d, losses_d = ep_d(state_d, img_d, lab_d, idx_d, rng)

    np.testing.assert_allclose(np.asarray(losses_s), np.asarray(losses_d),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(state_s.params),
                    jax.tree_util.tree_leaves(state_d.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("shard", [False, True])
def test_eval_modes_agree(mesh8, model_and_states, shard):
    """Replicated eval (the reference's every-rank-full-test-set behavior, §2d.7) and
    sharded+psum eval (the fixed version) must produce identical numbers."""
    model, state = model_and_states
    x = jax.random.normal(jax.random.PRNGKey(7), (80, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(8), (80,), 0, 10)
    ev = make_eval_fn(model, batch_size=10)
    want_nll, want_correct = jax.jit(ev)(state.params, x, y)

    ev_c = dp.compile_eval(ev, mesh8, shard=shard)
    params_d = jax.device_put(state.params, dp.replicated(mesh8))
    sh = dp.batch_sharding(mesh8) if shard else dp.replicated(mesh8)
    got_nll, got_correct = ev_c(params_d, jax.device_put(x, sh), jax.device_put(y, sh))
    np.testing.assert_allclose(float(got_nll), float(want_nll), rtol=1e-4)
    assert int(got_correct) == int(want_correct)


def test_global_batch_from_host_local(mesh8):
    """Single-process degenerate case: the host-local slice is the whole global batch."""
    x = np.arange(32.0).reshape(16, 2)
    y = np.arange(16)
    gx, gy = dp.global_batch_from_host_local(mesh8, x, y)
    assert gx.shape == (16, 2) and gy.shape == (16,)
    np.testing.assert_array_equal(np.asarray(gx), x)


class TestHybridMesh:
    """Multi-slice ICI×DCN mesh arrangement: the dcn axis's leading factor strides
    across slice granules (slice-major), every other axis stays within a granule."""

    def _devices(self):
        return jax.devices()[:8]

    def test_data_axis_slice_major(self):
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            make_hybrid_mesh,
        )

        devs = self._devices()
        mesh = make_hybrid_mesh(("data",), (8,), num_slices=2, devices=devs)
        ids = [d.id for d in mesh.devices.reshape(-1)]
        # Virtual granules are contiguous in topology order: slice 0 = devices 0-3.
        assert ids == [d.id for d in devs]
        # First half of the data axis is entirely granule 0.
        assert ids[:4] == [d.id for d in devs[:4]]

    def test_inner_axes_stay_within_slice(self):
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            make_hybrid_mesh,
        )

        devs = self._devices()
        mesh = make_hybrid_mesh(("data", "model"), (4, 2), num_slices=2,
                                devices=devs)
        arr = mesh.devices                       # [data=4, model=2]
        granule = {d.id: i // 4 for i, d in enumerate(devs)}
        # data coordinates 0-1 (slice 0's rows) hold only granule-0 devices; their
        # model neighbors are in the same granule (TP rides ICI).
        for di in range(4):
            expected = 0 if di < 2 else 1
            for mi in range(2):
                assert granule[arr[di, mi].id] == expected, (di, mi)

    def test_validation(self):
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
            make_hybrid_mesh,
        )

        devs = self._devices()
        with pytest.raises(ValueError, match="not in axis_names"):
            make_hybrid_mesh(("model",), (8,), num_slices=2, devices=devs)
        with pytest.raises(ValueError, match="must divide"):
            make_hybrid_mesh(("data", "model"), (2, 4), num_slices=4, devices=devs)
        with pytest.raises(ValueError, match="divide"):
            make_hybrid_mesh(("data",), (8,), num_slices=3, devices=devs)
        with pytest.raises(ValueError, match="divide"):
            make_hybrid_mesh(("data",), (8,), num_slices=16, devices=devs)
        with pytest.raises(ValueError, match=">= 1"):
            make_hybrid_mesh(("data",), (8,), num_slices=-1, devices=devs)
        with pytest.raises(ValueError, match="pass num_slices"):
            make_hybrid_mesh(("data",), (8,), devices=devs)

    def test_super_granule_merge_of_host_granules(self):
        """num_slices < the platform's natural host granules is valid when it
        divides them: contiguous hosts merge into DCN super-granules (hosts-per-
        slice > 1 without the multi-slice slice_index attribute)."""
        from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
            _slice_granules,
        )

        class Dev:
            def __init__(self, i, p):
                self.id, self.process_index = i, p

        devs = [Dev(i, i // 2) for i in range(8)]        # 4 hosts × 2 devices
        g = _slice_granules(devs, 2)                     # 2 slices of 2 hosts each
        assert sorted(g) == [0, 1]
        assert [d.id for d in g[0]] == [0, 1, 2, 3]
        assert [d.id for d in g[1]] == [4, 5, 6, 7]
        # The natural count itself still works, and a non-divisor still errors.
        assert sorted(len(v) for v in _slice_granules(devs, 4).values()) == [2] * 4
        with pytest.raises(ValueError, match="topology wins"):
            _slice_granules(devs, 3)

    @pytest.mark.slow
    def test_composed_trainer_dcn_data_matches_flat_mesh(self, tmp_path):
        """--dcn-data is placement-only: same trajectory as the flat mesh."""
        from csed_514_project_distributed_training_using_pytorch_tpu.data.mnist import (
            Dataset, _normalize, _synthesize_split,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.train import (
            composed,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
            ComposedConfig,
        )

        xs, ys = _synthesize_split(512, seed=100)
        train = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
        xs, ys = _synthesize_split(200, seed=101)
        test = Dataset(_normalize(xs), ys.astype(np.int32), "synthetic")
        common = dict(mesh="data=4,model=2", epochs=1, batch_size=64,
                      batch_size_test=100)
        _, hist_flat = composed.main(
            ComposedConfig(results_dir=str(tmp_path / "flat"), **common),
            datasets=(train, test))
        _, hist_dcn = composed.main(
            ComposedConfig(results_dir=str(tmp_path / "dcn"), dcn_data=2,
                           **common),
            datasets=(train, test))
        np.testing.assert_allclose(hist_dcn.train_losses, hist_flat.train_losses,
                                   rtol=1e-5, atol=1e-6)
