"""Benchmark: MNIST 1-epoch wall-clock on TPU — the reference's headline metric.

The reference's published result is time-to-train-one-epoch vs machine count: ≈17.5 on one
e2-standard-8 CPU machine and ≈7.6 on four machines with DDP/gloo, unit unlabeled on the chart
(BASELINE.md). ``vs_baseline`` reported here is the speedup over the reference's best
(4-machine, 7.6) figure under the *most conservative* reading of its unlabeled y-axis —
seconds. Anything >1 beats the whole reference cluster with this framework.

Measurement protocol (warmup + median of 3 timed epochs, each closed by a host fetch of the
epoch's final loss scalar — not ``block_until_ready``, which can resolve at enqueue-ack on
tunnelled PJRT backends): ``utils/benchmarks.py``.

Prints exactly ONE JSON line on stdout.
"""

import json

import jax
import numpy as np

from csed_514_project_distributed_training_using_pytorch_tpu.data import load_mnist
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import make_mesh
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import make_eval_fn
from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
    GLOBAL_BATCH, LEARNING_RATE, MOMENTUM, time_epochs,
)

BASELINE_BEST = 7.6          # reference 4-machine DDP/gloo epoch time (BASELINE.md)


def run() -> dict:
    mesh = make_mesh()
    train_ds, test_ds = load_mnist("files")

    result = time_epochs(mesh, train_ds, global_batch=GLOBAL_BATCH,
                         learning_rate=LEARNING_RATE, momentum=MOMENTUM,
                         seed=1, timed_epochs=3)

    eval_fn = dp.compile_eval(make_eval_fn(Net(), batch_size=1000), mesh)
    test_x = dp.put_global(mesh, test_ds.images, jax.sharding.PartitionSpec())
    test_y = dp.put_global(mesh, test_ds.labels, jax.sharding.PartitionSpec())
    sum_nll, correct = jax.device_get(
        eval_fn(result.final_state.params, test_x, test_y))

    return {
        "metric": "MNIST 1-epoch wall-clock (60k examples, global batch 64)",
        "value": round(result.median_seconds, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_BEST / result.median_seconds, 2),
        "devices": result.devices,
        "platform": jax.devices()[0].platform,
        "steps_per_epoch": result.steps_per_epoch,
        "final_train_loss": round(result.final_train_loss, 4),
        "test_nll_after_4_epochs": round(float(sum_nll) / len(test_ds), 4),
        "test_accuracy_after_4_epochs": round(float(correct) / len(test_ds), 4),
        "data_source": train_ds.source,
    }


if __name__ == "__main__":
    print(json.dumps(run()))
