"""Benchmark: MNIST 1-epoch wall-clock on TPU — the reference's headline metric.

The reference's published result is time-to-train-one-epoch vs machine count: ≈17.5 on one
e2-standard-8 CPU machine and ≈7.6 on four machines with DDP/gloo, unit unlabeled on the chart
(BASELINE.md). ``vs_baseline`` reported here is the speedup over the reference's best
(4-machine, 7.6) figure under the *most conservative* reading of its unlabeled y-axis —
seconds. Anything >1 beats the whole reference cluster with this framework.

Protocol: full training epoch (60,000 examples, global batch 64 — reference
``src/train.py:12-13`` scale) as one jit-compiled scanned program over the device mesh; one
warmup epoch to compile and fault in data, then the median of 3 timed epochs, each closed by
a host fetch of the epoch's final loss scalar. The fetch — not ``block_until_ready`` — is the
sync point on purpose: on tunnelled/experimental PJRT backends (this image's axon TPU),
``block_until_ready`` can resolve at enqueue-ack rather than device completion and
under-reports by orders of magnitude (measured: 0.0016 s "epoch"); a device→host transfer of
a value data-dependent on the whole epoch cannot lie (honest async-dispatch timing,
SURVEY.md §7 hard part (c)).

Prints exactly ONE JSON line on stdout.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from csed_514_project_distributed_training_using_pytorch_tpu.data import load_mnist
from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
    data_parallel as dp,
)
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import make_mesh
from csed_514_project_distributed_training_using_pytorch_tpu.parallel.sampler import (
    ShardedSampler,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.distributed import (
    epoch_index_plan,
)
from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
    create_train_state, make_epoch_fn, make_eval_fn,
)

BASELINE_BEST = 7.6          # reference 4-machine DDP/gloo epoch time (BASELINE.md)
GLOBAL_BATCH = 64            # reference src/train.py:13
LEARNING_RATE = 0.01         # reference src/train.py:15
MOMENTUM = 0.5               # reference src/train.py:16


def run() -> dict:
    mesh = make_mesh()
    world = mesh.shape["data"]
    if GLOBAL_BATCH % world:
        raise ValueError(f"global batch {GLOBAL_BATCH} not divisible by device count "
                         f"{world} — the reported protocol would be wrong (same check as "
                         f"train.distributed.main)")
    train_ds, test_ds = load_mnist("files")

    model = Net()
    state = jax.device_put(create_train_state(model, jax.random.PRNGKey(1)),
                           dp.replicated(mesh))
    rng = jax.random.PRNGKey(2)

    train_x = dp.put_global(mesh, train_ds.images, P())
    train_y = dp.put_global(mesh, train_ds.labels, P())

    epoch_fn = dp.compile_epoch(
        make_epoch_fn(model, learning_rate=LEARNING_RATE, momentum=MOMENTUM), mesh)
    eval_fn = dp.compile_eval(make_eval_fn(model, batch_size=1000), mesh)

    samplers = [ShardedSampler(len(train_ds), num_replicas=world, rank=r, seed=42)
                for r in range(world)]

    def one_epoch(state, epoch):
        plan = epoch_index_plan(samplers, epoch, GLOBAL_BATCH // world)
        plan_d = dp.put_global(mesh, plan, P(None, "data"))
        state, losses = epoch_fn(state, train_x, train_y, plan_d, rng)
        # Sync by fetching the last per-step loss scalar: data-dependent on (almost) every
        # step of the epoch, so the transfer completing proves the device finished it.
        float(jax.device_get(losses[-1]))
        return state, losses

    state, _ = one_epoch(state, 0)  # warmup: compile + fault-in

    times = []
    for epoch in range(1, 4):
        t0 = time.perf_counter()
        state, losses = one_epoch(state, epoch)
        times.append(time.perf_counter() - t0)

    test_x = dp.put_global(mesh, test_ds.images, P())
    test_y = dp.put_global(mesh, test_ds.labels, P())
    sum_nll, correct = jax.device_get(eval_fn(state.params, test_x, test_y))

    epoch_s = float(np.median(times))
    return {
        "metric": "MNIST 1-epoch wall-clock (60k examples, global batch 64)",
        "value": round(epoch_s, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_BEST / epoch_s, 2),
        "devices": world,
        "platform": jax.devices()[0].platform,
        "steps_per_epoch": 60_000 // GLOBAL_BATCH,
        "final_train_loss": round(float(np.asarray(losses)[-1]), 4),
        "test_accuracy_after_4_epochs": round(float(correct) / len(test_ds), 4),
        "data_source": train_ds.source,
    }


if __name__ == "__main__":
    print(json.dumps(run()))
