"""Benchmark: MNIST 1-epoch wall-clock on TPU — the reference's headline metric.

The reference's published result is time-to-train-one-epoch vs machine count: ≈17.5 on one
e2-standard-8 CPU machine and ≈7.6 on four machines with DDP/gloo, unit unlabeled on the chart
(BASELINE.md). ``vs_baseline`` reported here is the speedup over the reference's best
(4-machine, 7.6) figure under the *most conservative* reading of its unlabeled y-axis —
seconds. Anything >1 beats the whole reference cluster with this framework.

Robustness (r1 verdict item 1): the round-1 bench died with rc=1 on a transient
``UNAVAILABLE: TPU backend setup/compile error`` — and a backend-init failure is cached
in-process by jax, while a wedged TPU claim can make init *hang* rather than fail. So the
measurement runs in a CHILD process driven by a parent retry loop: each attempt gets a fresh
interpreter and a hard deadline (graceful SIGTERM first — SIGKILL on a process holding the
TPU claim wedges the lease). r2 hardening: every measurement attempt is preceded by a cheap
chip-claim PROBE child (seconds when healthy, ~90 s cap when wedged), so a wedged lease
burns probes, not 600-s attempts; the child enables a persistent XLA compilation cache under
``bench_results/.jax_cache`` so a claim that succeeds after priming costs seconds, not a
full compile. r5 hardening (after r4's 9/9 probe timeouts against a stale claim): two
consecutive probe TIMEOUTS are treated as the stale-lease signature, after which the loop
queues ONE PATIENT probe for the rest of the budget instead of probe-and-abandon cycling —
the relay grants the claim to whoever is queued when the stale lease expires, so a single
long-lived claimant converts any mid-round lease expiry into a measurement, where the old
cadence could only win if expiry landed between probes. On exhausting the retry budget (``BENCH_TPU_RETRY_SECONDS``, default 900) the
parent re-runs the child on the CPU backend so the round still records a real, parseable
measurement — clearly labeled ``"platform": "cpu"`` with the TPU failure in
``fallback_reason`` and the newest committed hardware capture embedded as
``last_hardware_capture`` — instead of a stack trace.

Throughput/MFU (r1 verdict item 3): alongside epoch seconds the JSON carries steps/s,
examples/s, achieved model FLOP/s, and an MFU estimate against the chip's bf16 peak (the
model runs f32, so the estimate is conservative). Model FLOPs/step are computed statically
from the flagship architecture (SURVEY.md §3.4).

Measurement protocol (warmup + median of 7 timed epochs — r4: in the r3 captures the
first timed epoch ran ~40-50% slow, and 3-sample medians straddling it made those
captures diverge; min and all samples now ride beside the median — each epoch closed by a host fetch of a scalar
data-dependent on its final *parameter update*, not ``block_until_ready``, which can
resolve at enqueue-ack on tunnelled PJRT backends): ``utils/benchmarks.py``;
``BENCH_TIMED_EPOCHS`` overrides the count.

Prints exactly ONE JSON line on stdout.
"""

import json
import os
import signal
import subprocess
import sys
import time

BASELINE_BEST = 7.6          # reference 4-machine DDP/gloo epoch time (BASELINE.md)


def measure() -> dict:
    """The actual measurement — runs in the child process (``bench.py --inner``)."""
    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        enable_compile_cache,
    )

    # Persistent compilation cache (r2 verdict item 1a): priming during any hardware
    # window makes later claims cost seconds. Harmless on CPU fallback (cache entries
    # are keyed by platform).
    enable_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_results", ".jax_cache"))

    from csed_514_project_distributed_training_using_pytorch_tpu.data import load_mnist
    from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        data_parallel as dp,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
        make_mesh,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        make_eval_fn,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        GLOBAL_BATCH, LEARNING_RATE, MOMENTUM, TRAIN_FLOPS_PER_EXAMPLE, peak_flops,
        time_epochs,
    )

    from csed_514_project_distributed_training_using_pytorch_tpu.data import mnist

    mesh = make_mesh()
    train_ds, test_ds = load_mnist("files")
    # Functional-test knob only — the published protocol is the full 60k split (0).
    truncated_to = int(os.environ.get("BENCH_MAX_TRAIN_EXAMPLES", "0"))
    full_split = truncated_to <= 0 or truncated_to >= len(train_ds)
    train_ds = mnist.truncate(train_ds, truncated_to)
    # Scan-body unroll factor (semantics-preserving, equivalence-tested); >1 amortizes
    # per-iteration control overhead, which can rival compute on a model this small.
    # Default 8: the round-2 hardware sweep (bench_results/bench_r2_tpu_knob_sweep/)
    # measured unroll=8 + pregather as the best stable configuration on a v5e chip
    # (0.171-0.176 s/epoch vs 0.194 at unroll=1 without pregather).
    unroll = int(os.environ.get("BENCH_UNROLL", "8"))
    # Gather the epoch's batches once before the scan instead of per step (semantics-
    # preserving, equivalence-tested); trades one epoch-sized HBM copy for gather latency.
    pregather = (os.environ.get("BENCH_PREGATHER", "on").strip().lower()
                 in ("1", "true", "yes", "on"))

    # 7 timed epochs (r4): in the r3 captures the first timed epoch ran ~40-50%
    # slow (residual warm-up the single warmup epoch didn't absorb), and the r3
    # driver/builder captures diverged (0.1973 vs 0.2516 s) purely on 3-sample
    # medians straddling it; a 7-sample median sits firmly in the steady state, and
    # min/median are both reported so the spread is visible in the artifact.
    timed = max(1, int(os.environ.get("BENCH_TIMED_EPOCHS", "7")))
    result = time_epochs(mesh, train_ds, global_batch=GLOBAL_BATCH,
                         learning_rate=LEARNING_RATE, momentum=MOMENTUM,
                         seed=1, timed_epochs=timed, unroll=unroll,
                         pregather=pregather)

    eval_fn = dp.compile_eval(make_eval_fn(Net(), batch_size=1000), mesh)
    test_x = dp.put_global(mesh, test_ds.images, jax.sharding.PartitionSpec())
    test_y = dp.put_global(mesh, test_ds.labels, jax.sharding.PartitionSpec())
    sum_nll, correct = jax.device_get(
        eval_fn(result.final_state.params, test_x, test_y))

    dev = jax.devices()[0]
    examples_per_epoch = result.steps_per_epoch * GLOBAL_BATCH
    examples_per_s = examples_per_epoch / result.median_seconds
    achieved_flops = examples_per_s * TRAIN_FLOPS_PER_EXAMPLE
    peak = peak_flops(getattr(dev, "device_kind", "")) if dev.platform == "tpu" else None

    return {
        # Telemetry event typing: the bench artifact is one "bench" event in the
        # utils/telemetry.py schema, so tools/telemetry_report.py compares bench
        # runs against training runs through the same reader.
        "event": "bench",
        # A truncated functional run is labeled as such and never compared against the
        # reference's FULL-epoch time — a 16-step "epoch" beating 7.6 s means nothing.
        "metric": ("MNIST 1-epoch wall-clock (60k examples, global batch 64)"
                   if full_split else
                   f"MNIST truncated-epoch wall-clock ({len(train_ds)} examples, "
                   f"global batch 64) — FUNCTIONAL TEST, not the published protocol"),
        "value": round(result.median_seconds, 4),
        "unit": "s",
        "vs_baseline": (round(BASELINE_BEST / result.median_seconds, 2)
                        if full_split else None),
        "devices": result.devices,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "steps_per_epoch": result.steps_per_epoch,
        "train_examples": len(train_ds),
        "scan_unroll": unroll,
        "pregather": pregather,
        "steps_per_s": round(result.steps_per_epoch / result.median_seconds, 1),
        "examples_per_s": round(examples_per_s, 1),
        "model_train_flops_per_example": TRAIN_FLOPS_PER_EXAMPLE,
        "achieved_model_flops_per_s": round(achieved_flops),
        "mfu_vs_bf16_peak": (round(achieved_flops / (peak * result.devices), 8)
                             if peak else None),
        "epoch_seconds_all": [round(t, 4) for t in result.epoch_seconds],
        "min_epoch_seconds": round(min(result.epoch_seconds), 4),
        "final_train_loss": round(result.final_train_loss, 4),
        "epochs_trained": 1 + timed,        # warmup + timed, all real training
        "test_nll_after_run": round(float(sum_nll) / len(test_ds), 4),
        "test_accuracy_after_run": round(float(correct) / len(test_ds), 4),
        "data_source": train_ds.source,
    }


def _sanitize_json(obj):
    """Strict-JSONL rule (utils/telemetry.py's, duplicated because this parent
    entry point stays jax-import-free): non-finite floats become None."""
    import math

    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize_json(v) for v in obj]
    return obj


def _emit(payload: dict, telemetry_path: str | None) -> None:
    """Print the one bench JSON line and (``--telemetry PATH``) append it as a
    telemetry event — the same ``"event": "bench"`` schema the trainers' telemetry
    files use, so ``tools/telemetry_report.py`` compares bench and training runs.
    A diverged run's NaN serializes as null (strict JSONL), never a bare NaN token."""
    payload.setdefault("event", "bench")
    line = json.dumps(_sanitize_json(payload), allow_nan=False)
    print(line)
    if telemetry_path:
        os.makedirs(os.path.dirname(telemetry_path) or ".", exist_ok=True)
        with open(telemetry_path, "a") as f:
            f.write(line + "\n")


def _telemetry_path() -> str | None:
    """The optional ``--telemetry PATH`` argv pair (parsed by hand: this parent
    entry point deliberately stays argparse- and jax-import-free)."""
    argv = sys.argv
    if "--telemetry" in argv:
        i = argv.index("--telemetry")
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def _parse_child_json(out: str) -> dict | None:
    """Last stdout line of a child as a JSON object, or None if it isn't one."""
    try:
        payload = json.loads(out.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return None
    return payload if isinstance(payload, dict) else None


_ABANDONED: list = []   # hung children we deliberately do NOT SIGKILL (see _run_child)


def _run_child(env_overrides: dict, timeout_s: float,
               argv: list | None = None) -> tuple[int | None, str, str]:
    """One child in a fresh interpreter (default: this file with ``--inner``).
    Returns (rc, stdout, stderr); rc=None on timeout. Termination is graceful
    (SIGTERM, then a grace period). A child still alive after the grace is ABANDONED,
    not SIGKILLed: a child hung *post-claim* in backend init is a holder, and a
    SIGKILLed holder of the tunnelled TPU claim wedges the lease for hours. An
    abandoned probe merely lists devices and exits on its own once unblocked."""
    env = dict(os.environ, **env_overrides)
    proc = subprocess.Popen(
        argv or [sys.executable, os.path.abspath(__file__), "--inner"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            for pipe in (proc.stdout, proc.stderr):
                if pipe is not None:
                    pipe.close()
            _ABANDONED.append(proc)
            out, err = "", ""
        return None, out or "", err or ""


def _probe_chip(timeout_s: float) -> tuple[str, str]:
    """Cheap chip-claim probe in a fresh interpreter (r2 verdict item 1b).

    A wedged TPU lease (a previously-killed holder — see SETUP.md) makes backend init
    *hang*, so committing a full 600-s measurement attempt to find that out wastes most
    of the retry budget. This child only claims the backend, prints the platform, and
    exits cleanly — detectable in seconds when healthy, and cheap to give up on when
    not. Returns (status, detail) with status one of:
      'tpu'     — chip claimed, measure now;
      'other'   — backend init SUCCEEDED but resolved to a non-TPU platform — a
                  deterministic condition (no plugin / JAX_PLATFORMS override), so the
                  caller should fall back immediately instead of burning the budget;
      'timeout' — the probe child HUNG past its deadline (the stale-lease wedge
                  signature — a distinct status, not a substring of the detail text,
                  so a fast-failing error that merely *mentions* a timeout can't
                  masquerade as one);
      'retry'   — transient/unknown failure worth ordinary retry cadence."""
    code = ("import jax, json; d = jax.devices(); "
            "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))")
    rc, out, err = _run_child({}, timeout_s, argv=[sys.executable, "-c", code])
    if rc is None:
        return "timeout", f"probe timed out after {timeout_s:.0f}s (claim likely wedged)"
    info = _parse_child_json(out or "")
    if rc == 0 and info and info.get("platform") == "tpu":
        return "tpu", f"tpu x{info.get('n')}"
    if rc == 0 and info:
        return "other", f"backend is {info.get('platform')!r}, not tpu"
    tail = (err or out or "").strip().splitlines()
    return "retry", tail[-1] if tail else f"probe exited rc={rc}"


def _latest_hardware_capture() -> dict | None:
    """Newest committed TPU capture under bench_results/ (r2 verdict item 1c), so the
    driver artifact carries hardware evidence even when the chip is wedged all round."""
    import glob
    import re
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_results")
    candidates = [p for p in (glob.glob(os.path.join(root, "bench_r*_tpu*.json"))
                              + glob.glob(os.path.join(root, "hw_r*",
                                                       "bench_defaults*.json")))
                  if os.path.isfile(p)]
    if not candidates:
        return None

    # Newest by ROUND NUMBER in the path, not mtime — on a fresh clone every file
    # shares the checkout mtime. Within a round, prefer the curated "*best*"/plain
    # defaults capture over numbered retries.
    def rank(p: str) -> tuple:
        # Match within bench_results/ only — a clone path containing 'hw_rN'
        # must not corrupt the round ranking.
        m = re.search(r"(?:bench|hw)_r(\d+)", os.path.relpath(p, root))
        name = os.path.basename(p)
        return (int(m.group(1)) if m else -1,
                "best" in name or name == "bench_defaults.json")

    path = max(candidates, key=rank)
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return {
        "file": os.path.relpath(path, os.path.dirname(root)),
        "selected_by": "highest round number in filename, preferring '*best*'",
        "provenance": ("builder-side capture during a live TPU window; committed to "
                       "bench_results/ with the measurement protocol in RESULTS.md"),
        "payload": payload,
    }


def main() -> int:
    telemetry_path = _telemetry_path()
    retry_budget = float(os.environ.get("BENCH_TPU_RETRY_SECONDS", "900"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_SECONDS", "600"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_SECONDS", "90"))
    # Consecutive probe TIMEOUTS before the loop treats the claim as stale-wedged
    # and commits its one patient probe (r4 verdict item 1).
    wedge_quick_probes = int(os.environ.get("BENCH_WEDGE_QUICK_PROBES", "2"))
    deadline = time.monotonic() + retry_budget

    # Probe-first (r2 verdict item 1b): only commit a full measurement attempt after a
    # cheap probe child proves the chip claim is obtainable. A wedged claim burns a
    # ~90-s probe instead of a 600-s attempt, leaving budget for many retries.
    #
    # Stale-lease handling (r4 verdict item 1): in r4 all 9 quick probes timed out
    # against an exclusive claim some long-dead client still held — the retry loop's
    # cadence could only win if the stale lease happened to expire *between* probes.
    # The relay grants the claim to whoever is queued when the lease finally expires,
    # and an abandoned probe child (SIGTERM lands only after the C++ claim wait
    # returns) stays in that queue — so every extra quick probe lengthens the
    # grant cascade the eventual winner must wait behind. After
    # ``wedge_quick_probes`` consecutive timeouts the loop therefore stops
    # probing-and-abandoning and commits ONE PATIENT probe that stays queued for
    # the rest of the budget (minus a reserve for the measurement attempt): if the
    # lease TTLs out any time in that window, the patient claimant is granted
    # within seconds of expiry and the measurement still runs this round.
    attempts, probes, last_error = 0, 0, ""
    wedge_timeouts = 0
    probe_log: list = []     # [deadline_s, status] per probe — diagnosis artifact
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        is_patient = wedge_timeouts >= wedge_quick_probes
        if is_patient:
            # Clamped to the remaining budget: a wedge signature that trips late
            # must not queue a probe that outlives the configured deadline. The
            # reserve splits what's left evenly with the measurement attempt (capped
            # at the attempt's own timeout) — a patient win near the end of its
            # window must still leave the attempt a usable share of the budget.
            attempt_reserve = max(60.0, min(attempt_timeout, remaining / 2))
            this_probe = min(remaining, max(probe_timeout,
                                            remaining - attempt_reserve))
            print(f"bench: wedge signature ({wedge_timeouts} consecutive probe "
                  f"timeouts); queueing one patient probe for {this_probe:.0f}s",
                  file=sys.stderr)
        else:
            this_probe = min(probe_timeout, max(10.0, remaining))
        probes += 1
        status, detail = _probe_chip(this_probe)
        probe_log.append([round(this_probe), status])
        if is_patient and status == "timeout":
            # The one patient claimant was abandoned at its deadline; anything left
            # of the budget is shorter than what patience just failed to win — go
            # straight to the fallback (no backoff sleep: it buys no retry).
            last_error = detail
            print(f"bench probe {probes} failed: {detail}", file=sys.stderr)
            break
        if status == "other":
            # Deterministic: this interpreter will never see a TPU. Don't burn the
            # retry budget re-discovering it — go straight to the labeled fallback.
            last_error = detail
            print(f"bench probe {probes}: {detail}; skipping TPU retries",
                  file=sys.stderr)
            break
        if status != "tpu":
            last_error = detail
            # Only a hang is the wedge signature; a probe that exits quickly with
            # an error is a transient init failure worth ordinary retries (and a
            # fast-failing PATIENT probe resets the signature too — the claim
            # answered, so the lease isn't stale, and patience stays available for
            # a genuine wedge later in the budget).
            wedge_timeouts = wedge_timeouts + 1 if status == "timeout" else 0
            print(f"bench probe {probes} failed: {detail}", file=sys.stderr)
            time.sleep(min(20.0, max(1.0, deadline - time.monotonic())))
            continue
        wedge_timeouts = 0
        print(f"bench probe {probes}: chip alive ({detail}); measuring",
              file=sys.stderr)
        attempts += 1
        this_timeout = min(attempt_timeout,
                           max(60.0, deadline - time.monotonic()))
        abandoned_before = len(_ABANDONED)
        rc, out, err = _run_child({}, this_timeout)
        if rc == 0 and out.strip():
            payload = _parse_child_json(out)
            if payload is None:
                last_error = f"unparseable child stdout: {out[-300:]!r}"
            else:
                payload["attempts"] = attempts
                payload["probes"] = probes
                payload["probe_log"] = probe_log
                _emit(payload, telemetry_path)
                return 0
        else:
            tail = (err or out).strip().splitlines()
            last_error = (f"attempt timed out after {this_timeout:.0f}s"
                          if rc is None else
                          (tail[-1] if tail else f"child exited rc={rc}"))
        print(f"bench attempt {attempts} failed: {last_error}", file=sys.stderr)
        if rc is None and len(_ABANDONED) > abandoned_before:
            # THIS attempt's hung child was just abandoned and now holds (or queues
            # on) the exclusive TPU claim; every further probe is doomed to time out
            # against it. Skip straight to the CPU fallback instead of burning the
            # rest of the budget. (An earlier abandoned *probe* doesn't trigger this —
            # it may have exited by now, so later probes stay worth trying.)
            print("bench: hung attempt child abandoned; no further TPU retries "
                  "possible this run", file=sys.stderr)
            break
        time.sleep(min(30.0, 5.0 * attempts,
                       max(1.0, deadline - time.monotonic())))

    # Retry budget exhausted — fall back to a labeled CPU measurement so the round still
    # records a real number instead of a stack trace (r1: BENCH_r01.json was rc=1).
    print(f"bench: TPU unavailable after {attempts} attempts; falling back to CPU",
          file=sys.stderr)
    # Drop only the sitecustomize dir that force-registers the tunnelled TPU plugin
    # (a failing/hung plugin is the very thing we're falling back from); keep every
    # other PYTHONPATH entry the user set, with the repo dir prepended.
    keep = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon_site" not in p]
    fallback_timeout = max(attempt_timeout, 1800.0)
    rc, out, err = _run_child(
        {"JAX_PLATFORMS": "cpu",
         "PYTHONPATH": os.pathsep.join(
             [os.path.dirname(os.path.abspath(__file__))] + keep)},
        fallback_timeout)
    if rc is None and not (err or out):
        err = f"cpu fallback timed out after {fallback_timeout:.0f}s"
    capture = _latest_hardware_capture()
    if rc == 0 and out.strip():
        payload = _parse_child_json(out)
        if payload is not None:
            payload["attempts"] = attempts
            payload["probes"] = probes
            payload["probe_log"] = probe_log
            payload["fallback_reason"] = f"tpu unavailable: {last_error}"
            if capture is not None:
                payload["last_hardware_capture"] = capture
            _emit(payload, telemetry_path)
            return 0
        err = f"unparseable CPU-fallback stdout: {out[-300:]!r}"

    # Even the CPU fallback failed: emit a structured, parseable error line.
    _emit({
        "event": "bench",
        "metric": "MNIST 1-epoch wall-clock (60k examples, global batch 64)",
        "value": None, "unit": "s", "vs_baseline": None,
        "error": last_error,
        "cpu_fallback_error": (err or out).strip().splitlines()[-1:],
        "attempts": attempts, "probes": probes, "probe_log": probe_log,
        **({"last_hardware_capture": capture} if capture is not None else {}),
    }, telemetry_path)
    return 1


if __name__ == "__main__":
    if "--inner" in sys.argv:
        print(json.dumps(measure()))
    else:
        sys.exit(main())
