"""Benchmark: MNIST 1-epoch wall-clock on TPU — the reference's headline metric.

The reference's published result is time-to-train-one-epoch vs machine count: ≈17.5 on one
e2-standard-8 CPU machine and ≈7.6 on four machines with DDP/gloo, unit unlabeled on the chart
(BASELINE.md). ``vs_baseline`` reported here is the speedup over the reference's best
(4-machine, 7.6) figure under the *most conservative* reading of its unlabeled y-axis —
seconds. Anything >1 beats the whole reference cluster with this framework.

Robustness (r1 verdict item 1): the round-1 bench died with rc=1 on a transient
``UNAVAILABLE: TPU backend setup/compile error`` — and a backend-init failure is cached
in-process by jax, while a wedged TPU claim can make init *hang* rather than fail. So the
measurement runs in a CHILD process driven by a parent retry loop: each attempt gets a fresh
interpreter and a hard deadline (graceful SIGTERM first — SIGKILL on a process holding the
TPU claim wedges the lease); on exhausting the retry budget (``BENCH_TPU_RETRY_SECONDS``,
default 900) the parent re-runs the child on the CPU backend so the round still records a
real, parseable measurement — clearly labeled ``"platform": "cpu"`` with the TPU failure in
``fallback_reason`` — instead of a stack trace.

Throughput/MFU (r1 verdict item 3): alongside epoch seconds the JSON carries steps/s,
examples/s, achieved model FLOP/s, and an MFU estimate against the chip's bf16 peak (the
model runs f32, so the estimate is conservative). Model FLOPs/step are computed statically
from the flagship architecture (SURVEY.md §3.4).

Measurement protocol (warmup + median of 3 timed epochs, each closed by a host fetch of a
scalar data-dependent on the epoch's final *parameter update* — not ``block_until_ready``,
which can resolve at enqueue-ack on tunnelled PJRT backends): ``utils/benchmarks.py``.

Prints exactly ONE JSON line on stdout.
"""

import json
import os
import signal
import subprocess
import sys
import time

BASELINE_BEST = 7.6          # reference 4-machine DDP/gloo epoch time (BASELINE.md)


def measure() -> dict:
    """The actual measurement — runs in the child process (``bench.py --inner``)."""
    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.data import load_mnist
    from csed_514_project_distributed_training_using_pytorch_tpu.models.cnn import Net
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        data_parallel as dp,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel.mesh import (
        make_mesh,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.step import (
        make_eval_fn,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        GLOBAL_BATCH, LEARNING_RATE, MOMENTUM, TRAIN_FLOPS_PER_EXAMPLE, peak_flops,
        time_epochs,
    )

    from csed_514_project_distributed_training_using_pytorch_tpu.data import mnist

    mesh = make_mesh()
    train_ds, test_ds = load_mnist("files")
    # Functional-test knob only — the published protocol is the full 60k split (0).
    truncated_to = int(os.environ.get("BENCH_MAX_TRAIN_EXAMPLES", "0"))
    full_split = truncated_to <= 0 or truncated_to >= len(train_ds)
    train_ds = mnist.truncate(train_ds, truncated_to)
    # Scan-body unroll factor (semantics-preserving, equivalence-tested); >1 amortizes
    # per-iteration control overhead, which can rival compute on a model this small.
    # Default 8: the round-2 hardware sweep (bench_results/bench_r2_tpu_knob_sweep/)
    # measured unroll=8 + pregather as the best stable configuration on a v5e chip
    # (0.171-0.176 s/epoch vs 0.194 at unroll=1 without pregather).
    unroll = int(os.environ.get("BENCH_UNROLL", "8"))
    # Gather the epoch's batches once before the scan instead of per step (semantics-
    # preserving, equivalence-tested); trades one epoch-sized HBM copy for gather latency.
    pregather = (os.environ.get("BENCH_PREGATHER", "on").strip().lower()
                 in ("1", "true", "yes", "on"))

    result = time_epochs(mesh, train_ds, global_batch=GLOBAL_BATCH,
                         learning_rate=LEARNING_RATE, momentum=MOMENTUM,
                         seed=1, timed_epochs=3, unroll=unroll, pregather=pregather)

    eval_fn = dp.compile_eval(make_eval_fn(Net(), batch_size=1000), mesh)
    test_x = dp.put_global(mesh, test_ds.images, jax.sharding.PartitionSpec())
    test_y = dp.put_global(mesh, test_ds.labels, jax.sharding.PartitionSpec())
    sum_nll, correct = jax.device_get(
        eval_fn(result.final_state.params, test_x, test_y))

    dev = jax.devices()[0]
    examples_per_epoch = result.steps_per_epoch * GLOBAL_BATCH
    examples_per_s = examples_per_epoch / result.median_seconds
    achieved_flops = examples_per_s * TRAIN_FLOPS_PER_EXAMPLE
    peak = peak_flops(getattr(dev, "device_kind", "")) if dev.platform == "tpu" else None

    return {
        # A truncated functional run is labeled as such and never compared against the
        # reference's FULL-epoch time — a 16-step "epoch" beating 7.6 s means nothing.
        "metric": ("MNIST 1-epoch wall-clock (60k examples, global batch 64)"
                   if full_split else
                   f"MNIST truncated-epoch wall-clock ({len(train_ds)} examples, "
                   f"global batch 64) — FUNCTIONAL TEST, not the published protocol"),
        "value": round(result.median_seconds, 4),
        "unit": "s",
        "vs_baseline": (round(BASELINE_BEST / result.median_seconds, 2)
                        if full_split else None),
        "devices": result.devices,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "steps_per_epoch": result.steps_per_epoch,
        "train_examples": len(train_ds),
        "scan_unroll": unroll,
        "pregather": pregather,
        "steps_per_s": round(result.steps_per_epoch / result.median_seconds, 1),
        "examples_per_s": round(examples_per_s, 1),
        "model_train_flops_per_example": TRAIN_FLOPS_PER_EXAMPLE,
        "achieved_model_flops_per_s": round(achieved_flops),
        "mfu_vs_bf16_peak": (round(achieved_flops / (peak * result.devices), 8)
                             if peak else None),
        "epoch_seconds_all": [round(t, 4) for t in result.epoch_seconds],
        "final_train_loss": round(result.final_train_loss, 4),
        "test_nll_after_4_epochs": round(float(sum_nll) / len(test_ds), 4),
        "test_accuracy_after_4_epochs": round(float(correct) / len(test_ds), 4),
        "data_source": train_ds.source,
    }


def _parse_child_json(out: str) -> dict | None:
    """Last stdout line of a child as a JSON object, or None if it isn't one."""
    try:
        payload = json.loads(out.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return None
    return payload if isinstance(payload, dict) else None


def _run_child(env_overrides: dict, timeout_s: float) -> tuple[int | None, str, str]:
    """One measurement attempt in a fresh interpreter. Returns (rc, stdout, stderr);
    rc=None on timeout. Termination is graceful (SIGTERM, then a grace period) — a
    SIGKILLed holder of the tunnelled TPU claim wedges the lease for later attempts."""
    env = dict(os.environ, **env_overrides)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__), "--inner"],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env)
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        return None, out or "", err or ""


def main() -> int:
    retry_budget = float(os.environ.get("BENCH_TPU_RETRY_SECONDS", "900"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_SECONDS", "600"))
    deadline = time.monotonic() + retry_budget

    attempts, last_error = 0, ""
    while True:
        attempts += 1
        rc, out, err = _run_child({}, attempt_timeout)
        if rc == 0 and out.strip():
            payload = _parse_child_json(out)
            if payload is None:
                last_error = f"unparseable child stdout: {out[-300:]!r}"
            else:
                payload["attempts"] = attempts
                print(json.dumps(payload))
                return 0
        else:
            tail = (err or out).strip().splitlines()
            last_error = (f"attempt timed out after {attempt_timeout:.0f}s"
                          if rc is None else
                          (tail[-1] if tail else f"child exited rc={rc}"))
        print(f"bench attempt {attempts} failed: {last_error}", file=sys.stderr)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        time.sleep(min(30.0, 5.0 * attempts, max(1.0, remaining)))

    # Retry budget exhausted — fall back to a labeled CPU measurement so the round still
    # records a real number instead of a stack trace (r1: BENCH_r01.json was rc=1).
    print(f"bench: TPU unavailable after {attempts} attempts; falling back to CPU",
          file=sys.stderr)
    # Drop only the sitecustomize dir that force-registers the tunnelled TPU plugin
    # (a failing/hung plugin is the very thing we're falling back from); keep every
    # other PYTHONPATH entry the user set, with the repo dir prepended.
    keep = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon_site" not in p]
    rc, out, err = _run_child(
        {"JAX_PLATFORMS": "cpu",
         "PYTHONPATH": os.pathsep.join(
             [os.path.dirname(os.path.abspath(__file__))] + keep)},
        max(attempt_timeout, 1800.0))
    if rc == 0 and out.strip():
        payload = _parse_child_json(out)
        if payload is not None:
            payload["attempts"] = attempts
            payload["fallback_reason"] = f"tpu unavailable: {last_error}"
            print(json.dumps(payload))
            return 0
        err = f"unparseable CPU-fallback stdout: {out[-300:]!r}"

    # Even the CPU fallback failed: emit a structured, parseable error line.
    print(json.dumps({
        "metric": "MNIST 1-epoch wall-clock (60k examples, global batch 64)",
        "value": None, "unit": "s", "vs_baseline": None,
        "error": last_error,
        "cpu_fallback_error": (err or out).strip().splitlines()[-1:],
        "attempts": attempts,
    }))
    return 1


if __name__ == "__main__":
    if "--inner" in sys.argv:
        print(json.dumps(measure()))
    else:
        sys.exit(main())
