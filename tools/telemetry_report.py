"""Render telemetry JSONL (utils/telemetry.py) as a run summary or A-vs-B comparison.

Input files are whatever the trainers' ``--telemetry PATH`` wrote (manifest /
compile / epoch / health / mfu / checkpoint / preempt events), ``bench*.py
--telemetry`` output (bench events), serving logs from ``serving/server.py`` /
``tools/serve_loadgen.py`` (serve / prefill / serve_summary events — rendered as
a TTFT/TPOT/e2e latency-percentile table plus aggregate decode AND prefill
tokens/s with prefix-cache hit rates), fleet-router logs from
``serving/router.py`` (route / replica / router_summary events — rendered as a
per-replica request/token table with affinity hit rate, redispatch and restart
counts; ``affinity hit rate``/``redispatches`` become A-vs-B rows for the
affinity on/off comparison), supervisor logs
from ``tools/fleet_supervise.py`` (restart events — rendered as a restart count
with reasons), or the loss-curve ``metrics.jsonl`` companions
(``kind`` rows) — all read through the one shared reader,
``utils.metrics.load_metrics_jsonl``, which passes unknown event types through.

Usage::

    python tools/telemetry_report.py results/run.jsonl            # one-run summary
    python tools/telemetry_report.py a.jsonl b.jsonl              # A-vs-B table
    python tools/telemetry_report.py --goodput results/           # wall-time ledger
    python tools/telemetry_report.py --goodput faulted/ clean/    # badput A-vs-B

One run prints its manifest line, phase-timing/throughput summary, grad-norm
trajectory, and any bench rows; two or more runs additionally print a side-by-side
comparison table (compile_s, execute_s/epoch, examples/s, MFU, final losses) with
the ratio of the last run against the first.
"""

from __future__ import annotations

import argparse
import os
import sys

# Script-mode import path: ``python tools/telemetry_report.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (  # noqa: E402
    load_metrics_jsonl,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.telemetry import (  # noqa: E402
    percentiles as _percentiles,
)

# Every event kind this reporter understands (or deliberately passes over,
# like per-span trace lines — those render via tools/trace_report.py). Anything
# outside this set is counted and surfaced in a footer: schema drift between a
# writer and this reporter must be visible, not silently dropped. DERIVED from
# the one registry every emitter is statically checked against
# (utils/telemetry_events.py, enforced by tools/graftlint's telemetry-schema
# checker) — this reporter can no longer disagree with the writers.
from csed_514_project_distributed_training_using_pytorch_tpu.utils.telemetry_events import (  # noqa: E402
    KNOWN_EVENTS,
)

SERVE_SERIES = ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s")
SERVE_QS = (50, 95, 99)


def _median(xs: list) -> float | None:
    xs = sorted(x for x in xs if x is not None)
    if not xs:
        return None
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def _fmt(x, digits: int = 4) -> str:
    if x is None:
        return "-"
    if isinstance(x, float):
        if x != 0 and (abs(x) >= 10000 or abs(x) < 0.001):
            return f"{x:.3g}"
        return f"{x:.{digits}g}" if abs(x) >= 1 else f"{x:.4f}"
    return str(x)


def summarize(path: str) -> dict:
    """Reduce one telemetry/metrics JSONL file to the report's summary fields."""
    rows = load_metrics_jsonl(path)
    by_event: dict[str, list] = {}
    for r in rows:
        by_event.setdefault(r.get("event", r.get("kind", "?")), []).append(r)

    s: dict = {"path": path, "label": os.path.basename(path), "events": len(rows)}
    unknown = {k: len(v) for k, v in by_event.items() if k not in KNOWN_EVENTS}
    if unknown:
        s["unknown_events"] = sum(unknown.values())
        s["unknown_kinds"] = sorted(unknown)

    man = (by_event.get("manifest") or [None])[0]
    if man:
        mesh = man.get("mesh")
        s["run"] = man.get("run_type") or "?"
        s["device"] = f"{man.get('device_kind')} x{man.get('device_count')}"
        s["processes"] = man.get("process_count")
        s["mesh"] = (",".join(f"{k}={v}" for k, v in mesh["shape"].items())
                     if mesh else None)
        s["jax"] = man.get("jax_version")

    epochs = by_event.get("epoch", [])
    if epochs:
        s["epochs"] = len(epochs)
        s["compile_s"] = next((e.get("compile_s") for e in epochs
                               if e.get("compile_s") is not None), None)
        s["execute_s_per_epoch"] = _median([e.get("execute_s") for e in epochs])
        s["examples_per_s"] = _median([e.get("examples_per_s") for e in epochs])
        s["flops_per_step"] = next((e.get("flops_per_step") for e in epochs
                                    if e.get("flops_per_step") is not None), None)
        s["final_train_loss"] = epochs[-1].get("train_loss")
        s["final_val_loss"] = epochs[-1].get("val_loss")
    compiles = by_event.get("compile", [])
    if compiles and s.get("compile_s") is None:
        c = compiles[0]
        if c.get("lower_s") is not None and c.get("compile_s") is not None:
            s["compile_s"] = c["lower_s"] + c["compile_s"]
        s.setdefault("flops_per_step", c.get("flops_per_step"))

    mfus = by_event.get("mfu", [])
    s["mfu"] = next((m.get("mfu") for m in reversed(mfus)
                     if m.get("mfu") is not None),
                    next((e.get("mfu") for e in reversed(epochs)
                          if e.get("mfu") is not None), None))

    health = by_event.get("health", [])
    if health:
        s["grad_norm_trajectory"] = [h.get("grad_norm") for h in health]
        s["grad_norm_max"] = max((h.get("grad_norm_max") for h in health
                                  if h.get("grad_norm_max") is not None),
                                 default=None)
        s["param_norm"] = health[-1].get("param_norm")

    s["bench"] = [{"metric": b.get("metric"), "value": b.get("value"),
                   "unit": b.get("unit"), "examples_per_s": b.get("examples_per_s"),
                   "mfu": b.get("mfu_vs_bf16_peak")}
                  for b in by_event.get("bench", [])]

    # Serving runs: per-request percentiles from the raw serve lines; aggregate
    # throughput/occupancy from the drain-time summary when present (a truncated
    # log still renders from whatever serve lines survived).
    serves = by_event.get("serve", [])
    summary = (by_event.get("serve_summary") or [None])[-1]
    if serves:
        s["serve_requests"] = len(serves)
        s["serve_ok"] = sum(r.get("finish") == "ok" for r in serves)
        s["serve_timeout"] = sum(r.get("finish") == "timeout" for r in serves)
        for name in SERVE_SERIES:
            # The one estimator (utils.telemetry.percentiles): report-side
            # percentiles from raw serve lines agree with the summary event's.
            pcts = _percentiles([r.get(name) for r in serves], qs=SERVE_QS) or {}
            for q in SERVE_QS:
                s[f"serve_{name}_p{q}"] = pcts.get(f"p{q}")
    # Chunked-prefill telemetry: per-prompt "prefill" events (chunks, tokens,
    # cache_hit_len, wall_s) aggregated; the serve_summary's engine-level
    # counters fill any gaps (e.g. a truncated per-event stream).
    prefills = by_event.get("prefill", [])
    if prefills:
        s["prefill_prompts"] = len(prefills)
        s["prefill_tokens"] = sum(r.get("tokens") or 0 for r in prefills)
        s["prefill_chunks"] = sum(r.get("chunks") or 0 for r in prefills)
        wall = sum(r.get("wall_s") or 0 for r in prefills)
        s["prefill_tokens_per_s"] = (s["prefill_tokens"] / wall
                                     if s["prefill_tokens"] and wall else None)
        hits = [r for r in prefills if (r.get("cache_hit_len") or 0) > 0]
        s["prefix_hits"] = len(hits)
        s["prefix_hit_tokens"] = sum(r.get("cache_hit_len") or 0
                                     for r in prefills)
        s["prefix_hit_rate"] = len(hits) / len(prefills)
    # Speculative-decoding accept stats: per-step "spec" events aggregated;
    # the serve_summary's engine-level spec ledger (below) overrides where it
    # exists so both sides of an A-vs-B row use the engine's own definitions.
    specs = by_event.get("spec", [])
    if specs:
        s["spec_steps"] = len(specs)
        proposed = sum(r.get("proposed") or 0 for r in specs)
        accepted = sum(r.get("accepted") or 0 for r in specs)
        slot_draws = sum(r.get("active") or 0 for r in specs)
        emitted = sum(r.get("emitted") or 0 for r in specs)
        s["spec_acceptance_rate"] = accepted / proposed if proposed else None
        s["accepted_tokens_per_step"] = (emitted / slot_draws
                                         if slot_draws else None)
    if summary:
        s.setdefault("serve_requests", summary.get("requests"))
        s.setdefault("serve_ok", summary.get("ok"))
        s.setdefault("serve_timeout", summary.get("timeout"))
        s["serve_tokens_per_s"] = summary.get("tokens_per_s")
        s["serve_occupancy"] = summary.get("slot_occupancy")
        # Program invocations vs generated tokens (separate counters since
        # speculative decoding made them diverge from 1:1 per slot).
        if summary.get("decode_invocations") is not None:
            s["decode_invocations"] = summary.get("decode_invocations")
            s["generated_tokens"] = summary.get("generated_tokens")
        sp = summary.get("spec") or {}
        if sp:
            s["spec_mode"] = sp.get("mode")
            s["spec_k"] = sp.get("k")
            s["spec_acceptance_rate"] = sp.get("acceptance_rate")
            s["accepted_tokens_per_step"] = sp.get("accepted_tokens_per_step")
        # The drain-time summary is the ENGINE's ledger (it also counts prompts
        # expired mid-prefill, which never emit a "prefill" event), so where it
        # exists it OVERRIDES the per-event estimates — both sides of an A-vs-B
        # row then use the same definitions (hit rate = hits / queries).
        for key in ("prefill_tokens", "prefill_chunks", "prefill_tokens_per_s"):
            if summary.get(key) is not None:
                s[key] = summary[key]
        pc = summary.get("prefix_cache") or {}
        if pc.get("queries"):
            s["prefix_hits"] = pc.get("hits")
            s["prefix_hit_tokens"] = pc.get("hit_tokens")
            s["prefix_hit_rate"] = pc["hits"] / pc["queries"]
        # Byte-true quantization ledger (engine.byte_accounting()): the A-vs-B
        # rows that prove a kv-dtype change moved fewer bytes and bought slots.
        by = summary.get("bytes") or {}
        if by:
            s["kv_dtype"] = by.get("kv_dtype")
            s["quant_policy"] = by.get("quant_policy")
            s["decode_bytes_per_token"] = by.get("decode_bytes_per_token")
            s["kv_bytes_per_slot"] = by.get("kv_bytes_per_slot")
            s["slots_at_budget"] = by.get("slots_at_budget")
            s["kv_layout"] = by.get("kv_layout")
        # Paged-KV pool ledger (paged engines only — the summary field and the
        # standalone kv_pages line carry the same page_stats() dict; prefer
        # the summary, fall back to the last standalone line on a killed run).
        kp = summary.get("kv_pages") \
            or (by_event.get("kv_pages") or [None])[-1] or {}
        if kp:
            s["kv_page_size"] = kp.get("page_size")
            s["kv_pages_in_use"] = kp.get("in_use")
            s["kv_pages_free"] = kp.get("free")
            s["kv_pages_shared"] = kp.get("shared")
            s["kv_page_refusals"] = kp.get("refusals")
            s["kv_page_fragmentation"] = kp.get("fragmentation")
            s["kv_cow_copies"] = kp.get("cow_copies")
        for name in SERVE_SERIES:          # summary percentiles fill any gaps
            pcts = summary.get(name) or {}
            for q in SERVE_QS:
                s.setdefault(f"serve_{name}_p{q}", pcts.get(f"p{q}"))
    elif serves:
        # No summary (killed run): aggregate tokens/s over the serve lines' span.
        toks = sum(r.get("new_tokens") or 0 for r in serves)
        ts = [r.get("t_s") for r in serves if r.get("t_s") is not None]
        starts = [r["t_s"] - r["e2e_s"] for r in serves
                  if r.get("t_s") is not None and r.get("e2e_s") is not None]
        span = max(ts) - min(starts) if ts and starts else None
        s["serve_tokens_per_s"] = toks / span if toks and span else None

    # Fleet-router runs (serving/router.py): per-request "route" lines give the
    # latency percentiles (reusing the serve table), the drain-time
    # router_summary the per-replica table, affinity hit rate, and redispatch/
    # restart counts; replica lifecycle events fill restart reasons when the
    # summary is missing (killed run).
    routes = by_event.get("route", [])
    rsum = (by_event.get("router_summary") or [None])[-1]
    if routes:
        s.setdefault("serve_requests", len(routes))
        s.setdefault("serve_ok", sum(r.get("finish") == "ok" for r in routes))
        s.setdefault("serve_timeout",
                     sum(r.get("finish") == "timeout" for r in routes))
        s["redispatches"] = sum(r.get("redispatches") or 0 for r in routes)
        hits = sum(bool(r.get("affinity_hit")) for r in routes)
        s["affinity_rate"] = hits / len(routes)
        for name in SERVE_SERIES:
            pcts = _percentiles([r.get(name) for r in routes], qs=SERVE_QS) or {}
            for q in SERVE_QS:
                s.setdefault(f"serve_{name}_p{q}", pcts.get(f"p{q}"))
    replica_evs = by_event.get("replica", [])
    if replica_evs:
        fails = [r for r in replica_evs if r.get("action") in ("fail", "dead")]
        s["replica_restarts"] = sum(r.get("action") == "restart"
                                    for r in replica_evs)
        s["replica_fail_reasons"] = [r.get("reason") for r in fails]
    if rsum:
        s.setdefault("serve_requests", rsum.get("requests"))
        s.setdefault("serve_ok", rsum.get("ok"))
        s.setdefault("serve_timeout", rsum.get("timeout"))
        s["serve_tokens_per_s"] = rsum.get("tokens_per_s")
        s["router_replicas"] = rsum.get("replicas")
        s["router_target"] = rsum.get("target")
        if rsum.get("scale_events") is not None:
            s.setdefault("scale_events", rsum.get("scale_events"))
        if rsum.get("replicas_ready_p50") is not None:
            s.setdefault("replicas_p50", rsum.get("replicas_ready_p50"))
            s.setdefault("replicas_max", rsum.get("replicas_ready_max"))
            s.setdefault("replicas_min", rsum.get("replicas_ready_min"))
        s["affinity_rate"] = rsum.get("affinity_rate")
        s["redispatches"] = rsum.get("redispatches")
        s["duplicate_completions"] = rsum.get("duplicates")
        s["replica_restarts"] = rsum.get("replica_restarts")
        s["replica_table"] = [
            {"replica": r.get("replica"), "state": r.get("state"),
             "restarts": r.get("restarts"), "dispatched": r.get("dispatched"),
             "completed": r.get("completed"), "tier": r.get("tier"),
             "handoffs": r.get("handoffs")}
            for r in rsum.get("per_replica") or []]
        pc = rsum.get("prefix_cache") or {}
        if pc.get("queries"):
            s["prefix_hits"] = pc.get("hits")
            s["prefix_hit_tokens"] = pc.get("hit_tokens")
            s["prefix_hit_rate"] = pc["hits"] / pc["queries"]
        sp = rsum.get("spec") or {}
        if sp:
            s["spec_mode"] = sp.get("mode")
            s["spec_k"] = sp.get("k")
            s["spec_acceptance_rate"] = sp.get("acceptance_rate")
            s["accepted_tokens_per_step"] = sp.get("accepted_tokens_per_step")
            s.setdefault("decode_invocations", sp.get("steps"))
            s.setdefault("generated_tokens", sp.get("generated_tokens"))
        for name in SERVE_SERIES:
            pcts = rsum.get(name) or {}
            for q in SERVE_QS:
                s.setdefault(f"serve_{name}_p{q}", pcts.get(f"p{q}"))

    # Metrics-timeline snapshots (serving/router.py --snapshot-interval-s): the
    # elasticity load signal. Reduce to the ranges a scale-up/down decision
    # reads — queue depth/age peaks vs fleet utilization.
    snaps = by_event.get("fleet_snapshot", [])
    if snaps:
        s["snapshots"] = len(snaps)
        depths = [(sn.get("queue") or {}).get("depth") or 0 for sn in snaps]
        ages = [(sn.get("queue") or {}).get("oldest_age_s") or 0 for sn in snaps]
        utils_ = [sn.get("utilization") for sn in snaps
                  if sn.get("utilization") is not None]
        s["snapshot_queue_depth_max"] = max(depths)
        s["snapshot_oldest_age_max_s"] = max(ages)
        s["snapshot_utilization_mean"] = (sum(utils_) / len(utils_)
                                          if utils_ else None)
        s["snapshot_utilization_max"] = max(utils_) if utils_ else None
        ready = [sn.get("replicas_ready") for sn in snaps
                 if sn.get("replicas_ready") is not None]
        if ready:
            s["replicas_p50"] = _median(ready)
            s["replicas_max"] = max(ready)
            s["replicas_min"] = min(ready)

    # Scale timeline (serving/router.py scale_up/scale_down/reload events):
    # each action joined against the nearest preceding fleet_snapshot, so the
    # rendered timeline shows WHAT the autoscaler saw when it acted.
    # Only realized transitions count (up/down/reload) — the stream also
    # carries reload_drain bookkeeping lines, and counting those would make
    # this disagree with router_summary's ups+downs+reloads in A-vs-B rows.
    scales = [e for e in by_event.get("scale", [])
              if e.get("action") in ("up", "down", "reload")]
    if scales:
        s["scale_events"] = len(scales)
        s["scale_ups"] = sum(e.get("action") == "up" for e in scales)
        s["scale_downs"] = sum(e.get("action") == "down" for e in scales)
        s["scale_reloads"] = sum(e.get("action") == "reload" for e in scales)
        timeline = []
        for e in scales:
            t = e.get("t_s")
            before = [sn for sn in snaps
                      if sn.get("t_s") is not None and t is not None
                      and sn["t_s"] <= t]
            sn = before[-1] if before else None
            timeline.append({
                "t_s": t, "action": e.get("action"),
                "replica": e.get("replica"), "target": e.get("target"),
                "reason": e.get("reason"),
                "queue_depth": ((sn.get("queue") or {}).get("depth")
                                if sn else None),
                "utilization": sn.get("utilization") if sn else None,
                "replicas_ready": sn.get("replicas_ready") if sn else None,
            })
        s["scale_timeline"] = timeline

    # Gray-failure tolerance (DESIGN.md §23): straggler ejections + probe
    # recoveries, hedged dispatches + win rate, typed wire-corruption events,
    # and the chaos harness's injected-fault ledger. The router_summary
    # counters win; the event stream fills in for a killed run.
    ejects = by_event.get("eject", [])
    if ejects:
        s["ejections"] = sum(e.get("action") == "eject" for e in ejects)
        s["probe_recoveries"] = sum(e.get("action") == "probe" for e in ejects)
    hedge_evs = by_event.get("hedge", [])
    if hedge_evs:
        s["hedges"] = len(hedge_evs)
    if rsum:
        for key in ("ejections", "probes", "hedges", "hedge_wins",
                    "hedge_win_rate", "wire_corrupt"):
            if rsum.get(key) is not None:
                s[key] = rsum[key]
    chaos_evs = by_event.get("chaos", [])
    if chaos_evs:
        s["chaos_faults"] = len(chaos_evs)
        by_kind: dict = {}
        for ev in chaos_evs:
            by_kind[ev.get("kind")] = by_kind.get(ev.get("kind"), 0) + 1
        s["chaos_by_kind"] = by_kind

    # Disaggregated serving (DESIGN.md §25): tier membership + the prefill→
    # decode KV handoff ledger. Per-event "kv_handoff" lines give the wall/TTFT
    # medians (the summary only carries counts); router_summary counters win
    # for the totals so both sides of an A-vs-B row use the router's ledger.
    tier_evs = by_event.get("tier", [])
    if tier_evs:
        tiers: dict = {}
        for ev in tier_evs:
            if ev.get("tier"):
                tiers[ev["tier"]] = tiers.get(ev["tier"], 0) + 1
        s["tier_replicas"] = tiers
    handoff_evs = by_event.get("kv_handoff", [])
    if handoff_evs:
        oks = [e for e in handoff_evs if e.get("ok")]
        s["handoffs"] = len(oks)
        s["handoff_failures"] = len(handoff_evs) - len(oks)
        s["handoff_bytes"] = sum(e.get("bytes") or 0 for e in oks)
        s["handoff_wall_s"] = _median([e.get("wall_s") for e in oks])
        s["tier_ttft_s"] = _median([e.get("prefill_ttft_s") for e in oks])
    if rsum:
        for key in ("handoffs", "handoff_bytes", "handoff_failures"):
            if rsum.get(key) is not None:
                s[key] = rsum[key]

    # Checkpoint traffic (utils/checkpoint.py savers + restores): how much resume
    # insurance the run paid for, and what it cost in wall time.
    ckpts = by_event.get("checkpoint", [])
    saves = [c for c in ckpts if c.get("op") == "save"]
    if saves:
        s["ckpt_saves"] = len(saves)
        s["ckpt_save_s"] = _median([c.get("wall_s") for c in saves])
        s["ckpt_bytes"] = next((c.get("bytes") for c in reversed(saves)
                                if c.get("bytes")), None)
        s["ckpt_coalesced"] = sum(c.get("coalesced") or 0 for c in saves)
    restores = [c for c in ckpts if c.get("op") == "restore"]
    if restores:
        s["ckpt_restores"] = len(restores)
        s["ckpt_restore_s"] = _median([c.get("wall_s") for c in restores])

    # SLO attainment (obs/slo.py): the drain-time "slo" events and/or the
    # summaries' embedded attainment dicts. Router (client-facing) wins over
    # server (replica-local) when a run carries both.
    slos = by_event.get("slo", [])
    slo = (next((e for e in reversed(slos) if e.get("source") == "router"),
                None) or (slos[-1] if slos else None))
    for doc in ((rsum or {}).get("slo"), (summary or {}).get("slo"), slo):
        if doc and doc.get("attainment") is not None:
            s["slo_attainment"] = doc.get("attainment")
            s["slo_met"] = doc.get("met")
            s["slo_requests"] = doc.get("requests")
            s["slo_spec"] = doc.get("spec")
            break

    # Multi-tenant serving (DESIGN.md §22): per-tenant drain ledgers (the
    # router's client-facing rows win over a replica's local ones), shed
    # decisions by reason, and fleet preemption counters.
    tsums = by_event.get("tenant_summary", [])
    tenants = {}
    for ev in tsums:                       # later rows (router) overwrite
        if ev.get("tenant"):
            tenants[ev["tenant"]] = ev
    if tenants:
        s["tenants"] = tenants
    sheds = by_event.get("shed", [])
    if sheds:
        s["shed_events"] = len(sheds)
        by_reason: dict = {}
        for ev in sheds:
            by_reason[ev.get("reason")] = by_reason.get(ev.get("reason"), 0) + 1
        s["shed_by_reason"] = by_reason
    for doc in (rsum, summary):
        if doc and (doc.get("preemptions") or doc.get("shed")):
            s["preemptions"] = doc.get("preemptions")
            s["resumes"] = doc.get("resumes")
            s["shed"] = doc.get("shed")
            break

    # Goodput ledger lines (obs/goodput.py via --goodput --emit): read the
    # decomposition back without re-joining the streams.
    gp = (by_event.get("goodput") or [None])[-1]
    if gp:
        s["goodput_frac"] = gp.get("goodput_frac")
        s["badput_frac"] = gp.get("badput_frac")
        s["compute_s"] = gp.get("compute_s")
        s["restart_badput_s"] = gp.get("restart_badput_s")
        s["rollback_badput_s"] = gp.get("rollback_badput_s")
        s["goodput_wall_s"] = gp.get("wall_s")
        s["epochs_replayed"] = gp.get("epochs_replayed")

    # Perf-gate lines (tools/bench_guard.py --telemetry): the bench
    # trajectory's per-metric medians, comparable across runs like any bench.
    guards = by_event.get("bench_guard", [])
    if guards:
        s["bench_guard"] = [
            {"metric": g.get("metric"), "median_s": g.get("median_s"),
             "ratio": g.get("ratio"), "pass": g.get("pass")}
            for g in guards]
        for g in guards:
            if g.get("metric"):
                s[f"guard_{g['metric']}"] = g.get("median_s")

    # Numerical-immune-system verdicts (train/step.py --guard): the last
    # anomaly event carries the attempt's cumulative counters; rollbacks are
    # the poisoned/desync restarts the supervisor performed in response.
    anomaly_evs = by_event.get("anomaly", [])
    if anomaly_evs:
        last = anomaly_evs[-1]
        s["anomalies"] = last.get("anomalies")
        s["anomaly_nonfinite"] = last.get("nonfinite")
        s["anomaly_spikes"] = last.get("spikes")
        s["skipped_steps"] = last.get("skipped")
        s["anomaly_fingerprint"] = last.get("fingerprint")
        s["anomaly_skip_windows"] = last.get("skip") or None

    # Resilience events: supervisor restarts (resilience/supervisor.py telemetry)
    # and cooperative preemption stops.
    restarts = by_event.get("restart", [])
    if restarts:
        s["restarts"] = len(restarts)
        s["restart_reasons"] = [r.get("reason") for r in restarts]
        rollbacks = sum(r.get("reason") in ("poisoned", "desync")
                        for r in restarts)
        if rollbacks:
            s["rollbacks"] = rollbacks
    preempts = by_event.get("preempt", [])
    if preempts:
        s["preempted_step"] = preempts[-1].get("step")
        s["preempted_ckpt"] = preempts[-1].get("checkpoint")

    # Streaming-loader ledger (data/stream.py per-epoch "data" events): how
    # many sequences the run consumed, the consumer's total stall behind the
    # prefetcher (the goodput ``data_wait`` input), and the per-epoch stream
    # CRCs the deterministic-resume tests pin across a kill/resume boundary.
    data_evs = by_event.get("data", [])
    if data_evs:
        s["data_epochs"] = len(data_evs)
        s["data_sequences"] = sum(e.get("sequences") or 0 for e in data_evs)
        s["data_wait_s"] = sum(e.get("wait_s") or 0.0 for e in data_evs)
        s["data_throttle_s"] = max((e.get("throttle_s") or 0.0)
                                   for e in data_evs)
        digests = [e.get("stream_digest") for e in data_evs]
        if any(d is not None for d in digests):
            s["stream_digests"] = digests

    # Continuous-deployment lifecycle (deploy/promoter.py "promote"/"canary"
    # events): verdict counts plus the ordered timeline — who was seen, who
    # failed which gate by what measured margin, who canaried on which
    # replica against what fleet evidence, and what the fleet rolled to.
    promos = by_event.get("promote", [])
    canary_evs = by_event.get("canary", [])
    if promos or canary_evs:
        by_action: dict = {}
        for ev in promos:
            by_action[ev.get("action")] = by_action.get(ev.get("action"), 0) + 1
        s["promote_actions"] = by_action
        s["promotions"] = by_action.get("promoted", 0)
        s["promote_rollbacks"] = by_action.get("rolled_back", 0)
        timeline = [
            {"t_s": ev.get("t_s"), "kind": "promote",
             "action": ev.get("action"),
             "candidate": os.path.basename(ev.get("candidate") or "?"),
             "reason": ev.get("reason"),
             "nll": ev.get("nll"), "incumbent_nll": ev.get("incumbent_nll")}
            for ev in promos
        ] + [
            {"t_s": ev.get("t_s"), "kind": "canary",
             "action": f"canary_{ev.get('verdict')}",
             "candidate": os.path.basename(ev.get("candidate") or "?"),
             "replica": ev.get("replica"), "reason": ev.get("reason"),
             "canary_attainment": ev.get("canary_attainment"),
             "fleet_attainment": ev.get("fleet_attainment"),
             "canary_nll": ev.get("canary_nll"),
             "fleet_nll": ev.get("fleet_nll")}
            for ev in canary_evs
        ]
        timeline.sort(key=lambda r: (r["t_s"] is None, r["t_s"] or 0.0))
        s["promotion_timeline"] = timeline

    # Loss-curve metrics.jsonl rows (the companion artifact) — final losses.
    for kind, key in (("train", "final_train_loss"), ("test", "final_val_loss")):
        pts = [r for r in by_event.get(kind, []) if "loss" in r]
        if pts and s.get(key) is None:
            s[key] = pts[-1]["loss"]
    return s


def print_summary(s: dict) -> None:
    print(f"== {s['label']} ({s['events']} events)")
    if s.get("run"):
        mesh = f", mesh {s['mesh']}" if s.get("mesh") else ""
        print(f"   {s['run']} run on {s['device']}{mesh}, "
              f"{s['processes']} process(es), jax {s['jax']}")
    if s.get("epochs"):
        print(f"   epochs {s['epochs']}  compile_s {_fmt(s.get('compile_s'))}  "
              f"execute_s/epoch {_fmt(s.get('execute_s_per_epoch'))}  "
              f"examples/s {_fmt(s.get('examples_per_s'))}")
        print(f"   flops/step {_fmt(s.get('flops_per_step'))}  "
              f"mfu {_fmt(s.get('mfu'))}  "
              f"train_loss {_fmt(s.get('final_train_loss'))}  "
              f"val_loss {_fmt(s.get('final_val_loss'))}")
    traj = s.get("grad_norm_trajectory")
    if traj:
        shown = " -> ".join(_fmt(g) for g in (traj if len(traj) <= 6
                                              else traj[:3] + traj[-3:]))
        print(f"   grad_norm {shown}  (max {_fmt(s.get('grad_norm_max'))}, "
              f"param_norm {_fmt(s.get('param_norm'))})")
    if s.get("ckpt_saves") or s.get("ckpt_restores"):
        parts = []
        if s.get("ckpt_saves"):
            co = (f", {s['ckpt_coalesced']} coalesced" if s.get("ckpt_coalesced")
                  else "")
            parts.append(f"{s['ckpt_saves']} save(s) "
                         f"(median {_fmt(s.get('ckpt_save_s'))}s, "
                         f"{_fmt(s.get('ckpt_bytes'))} bytes{co})")
        if s.get("ckpt_restores"):
            parts.append(f"{s['ckpt_restores']} restore(s) "
                         f"(median {_fmt(s.get('ckpt_restore_s'))}s)")
        print(f"   checkpoint: {', '.join(parts)}")
    if s.get("anomalies") is not None:
        # The immune-system line: what the guard saw, what it refused to
        # apply, and the replay windows in force.
        skip = (f"  skip windows {s['anomaly_skip_windows']}"
                if s.get("anomaly_skip_windows") else "")
        print(f"   anomaly guard: {_fmt(s['anomalies'])} anomalies "
              f"({_fmt(s.get('anomaly_nonfinite'))} nonfinite, "
              f"{_fmt(s.get('anomaly_spikes'))} spikes)  "
              f"{_fmt(s.get('skipped_steps'))} skipped step(s)  "
              f"fingerprint {_fmt(s.get('anomaly_fingerprint'))}{skip}")
    if s.get("restarts"):
        rb = (f", {s['rollbacks']} rollback(s)" if s.get("rollbacks") else "")
        print(f"   restarts: {s['restarts']} "
              f"({', '.join(s['restart_reasons'])}{rb})")
    if s.get("preempted_step") is not None:
        ck = f" -> {s['preempted_ckpt']}" if s.get("preempted_ckpt") else ""
        print(f"   preempted at step {s['preempted_step']}{ck}")
    if s.get("data_epochs"):
        thr = (f"  (throttled {_fmt(s['data_throttle_s'])}s/batch)"
               if s.get("data_throttle_s") else "")
        dig = ""
        if s.get("stream_digests"):
            shown = [d for d in s["stream_digests"] if d is not None]
            dig = (f"  digests {' '.join(f'{d:08x}' for d in shown[:4])}"
                   + (" ..." if len(shown) > 4 else ""))
        print(f"   data: {s['data_epochs']} streamed epoch(s), "
              f"{_fmt(s['data_sequences'])} sequences  "
              f"loader wait {_fmt(s['data_wait_s'])}s{thr}{dig}")
    if s.get("promotion_timeline"):
        acts = s.get("promote_actions") or {}
        print(f"   promotion: {s.get('promotions', 0)} promoted, "
              f"{acts.get('gate_fail', 0)} gate failure(s), "
              f"{s.get('promote_rollbacks', 0)} rollback(s)")
        for e in s["promotion_timeline"]:
            t = "-" if e["t_s"] is None else f"+{e['t_s']:.2f}s"
            if e["kind"] == "canary":
                ctx = (f"  replica {_fmt(e.get('replica'))}  attainment "
                       f"{_fmt(e.get('canary_attainment'))} vs fleet "
                       f"{_fmt(e.get('fleet_attainment'))}  nll "
                       f"{_fmt(e.get('canary_nll'))} vs fleet "
                       f"{_fmt(e.get('fleet_nll'))}")
            else:
                ctx = ("" if e.get("nll") is None else
                       f"  nll {_fmt(e['nll'])} vs incumbent "
                       f"{_fmt(e.get('incumbent_nll'))}")
            print(f"     {t.rjust(9)}  {(e['action'] or '?').ljust(14)} "
                  f"{e['candidate']}"
                  + (f" [{e['reason']}]" if e.get("reason") else "") + ctx)
    for b in s.get("bench", []):
        extra = "".join(f"  {k} {_fmt(b[k])}" for k in ("examples_per_s", "mfu")
                        if b.get(k) is not None)
        print(f"   bench: {b['metric']}: {_fmt(b['value'])} {b['unit'] or ''}{extra}")
    if s.get("serve_requests"):
        occ = (f"  occupancy {_fmt(s['serve_occupancy'])}"
               if s.get("serve_occupancy") is not None else "")
        print(f"   serve: {s['serve_requests']} requests "
              f"({_fmt(s.get('serve_ok'))} ok, {_fmt(s.get('serve_timeout'))} "
              f"timeout)  tokens/s {_fmt(s.get('serve_tokens_per_s'))}{occ}")
        if s.get("router_replicas"):
            reasons = s.get("replica_fail_reasons") or []
            print(f"   router: {s['router_replicas']} replicas  "
                  f"affinity rate {_fmt(s.get('affinity_rate'))}  "
                  f"redispatches {_fmt(s.get('redispatches'))}  "
                  f"restarts {_fmt(s.get('replica_restarts'))}"
                  + (f" ({', '.join(reasons)})" if reasons else ""))
            for r in s.get("replica_table") or []:
                tier = (f" [{r['tier']}, {_fmt(r.get('handoffs'))} handoffs]"
                        if r.get("tier") else "")
                print(f"     replica {r['replica']}: "
                      f"{_fmt(r.get('dispatched'))} dispatched, "
                      f"{_fmt(r.get('completed'))} completed, "
                      f"{_fmt(r.get('restarts'))} restart(s), "
                      f"{r.get('state')}{tier}")
            if (s.get("ejections") or s.get("hedges")
                    or s.get("wire_corrupt") or s.get("chaos_faults")):
                kinds = ", ".join(f"{k}: {v}" for k, v in
                                  sorted((s.get("chaos_by_kind") or {})
                                         .items()))
                probes = s.get("probe_recoveries") or s.get("probes") or 0
                print(f"   gray failures: {_fmt(s.get('ejections') or 0)} "
                      f"ejection(s) ({_fmt(probes)} probe recoveries)  "
                      f"hedges {_fmt(s.get('hedges') or 0)} "
                      f"(win rate {_fmt(s.get('hedge_win_rate'))})  "
                      f"wire corrupt {_fmt(s.get('wire_corrupt') or 0)}"
                      + (f"  chaos {s['chaos_faults']} ({kinds})"
                         if s.get("chaos_faults") else ""))
        if s.get("handoffs") is not None or s.get("handoff_failures"):
            tiers = ", ".join(f"{k}: {v}" for k, v in
                              sorted((s.get("tier_replicas") or {}).items()))
            print(f"   tiers: {_fmt(s.get('handoffs') or 0)} handoff(s) "
                  f"({_fmt(s.get('handoff_bytes') or 0)} bytes, "
                  f"{_fmt(s.get('handoff_failures') or 0)} failed)  "
                  f"handoff wall p50 {_fmt(s.get('handoff_wall_s'))}s  "
                  f"tier ttft p50 {_fmt(s.get('tier_ttft_s'))}s"
                  + (f"  [{tiers}]" if tiers else ""))
        if s.get("prefill_tokens") is not None:
            hit = ""
            if s.get("prefix_hit_rate") is not None:
                hit = (f"  prefix hits {_fmt(s.get('prefix_hits'))} "
                       f"(rate {_fmt(s['prefix_hit_rate'])}, "
                       f"{_fmt(s.get('prefix_hit_tokens'))} tokens reused)")
            print(f"   prefill: {_fmt(s['prefill_tokens'])} tokens in "
                  f"{_fmt(s.get('prefill_chunks'))} chunks  "
                  f"tokens/s {_fmt(s.get('prefill_tokens_per_s'))}{hit}")
        if s.get("spec_mode") or s.get("accepted_tokens_per_step") is not None:
            inv = ""
            if s.get("decode_invocations") is not None:
                inv = (f"  {_fmt(s.get('generated_tokens'))} tokens in "
                       f"{_fmt(s['decode_invocations'])} program invocations")
            print(f"   spec: {s.get('spec_mode') or '?'}"
                  + (f" k={s['spec_k']}" if s.get("spec_k") else "")
                  + f"  accepted tok/step {_fmt(s.get('accepted_tokens_per_step'))}"
                  + f"  acceptance rate {_fmt(s.get('spec_acceptance_rate'))}"
                  + inv)
        if s.get("decode_bytes_per_token") is not None:
            print(f"   bytes: kv {s.get('kv_dtype')} / weights "
                  f"{s.get('quant_policy')}  "
                  f"decode/token {_fmt(s['decode_bytes_per_token'])}  "
                  f"kv/slot {_fmt(s.get('kv_bytes_per_slot'))}  "
                  f"slots@budget {_fmt(s.get('slots_at_budget'))}")
        if s.get("kv_pages_in_use") is not None:
            print(f"   kv pages: {_fmt(s['kv_pages_in_use'])} in use / "
                  f"{_fmt(s.get('kv_pages_free'))} free "
                  f"(size {_fmt(s.get('kv_page_size'))} tok)  "
                  f"shared {_fmt(s.get('kv_pages_shared'))}  "
                  f"cow {_fmt(s.get('kv_cow_copies'))}  "
                  f"refusals {_fmt(s.get('kv_page_refusals'))}  "
                  f"frag {_fmt(s.get('kv_page_fragmentation'))}")
        head = "   " + "".ljust(14) + "".join(f"p{q}".rjust(12) for q in SERVE_QS)
        print(head)
        for name in SERVE_SERIES:
            vals = [s.get(f"serve_{name}_p{q}") for q in SERVE_QS]
            if all(v is None for v in vals):
                continue
            print("   " + name.ljust(14)
                  + "".join(_fmt(v).rjust(12) for v in vals))
    if s.get("snapshots"):
        reps = ""
        if s.get("replicas_p50") is not None:
            reps = (f"  replicas ready p50 {_fmt(s['replicas_p50'])} / "
                    f"min {_fmt(s.get('replicas_min'))} / "
                    f"max {_fmt(s.get('replicas_max'))}")
        print(f"   timeline: {s['snapshots']} fleet snapshots  "
              f"queue depth max {_fmt(s.get('snapshot_queue_depth_max'))}  "
              f"oldest age max {_fmt(s.get('snapshot_oldest_age_max_s'))}s  "
              f"utilization mean {_fmt(s.get('snapshot_utilization_mean'))} "
              f"/ max {_fmt(s.get('snapshot_utilization_max'))}{reps}")
    if s.get("scale_timeline"):
        print(f"   scale timeline: {s.get('scale_ups', 0)} up, "
              f"{s.get('scale_downs', 0)} down, "
              f"{s.get('scale_reloads', 0)} reload")
        for e in s["scale_timeline"]:
            t = "-" if e["t_s"] is None else f"+{e['t_s']:.2f}s"
            ctx = ""
            if e.get("queue_depth") is not None:
                ctx = (f"  (saw queue depth {e['queue_depth']}, "
                       f"util {_fmt(e.get('utilization'))}, "
                       f"{_fmt(e.get('replicas_ready'))} ready)")
            print(f"     {t.rjust(9)}  {(e['action'] or '?').ljust(12)} "
                  f"replica {e['replica']} -> target {e['target']}"
                  + (f" [{e['reason']}]" if e.get("reason") else "") + ctx)
    if s.get("slo_attainment") is not None:
        spec = s.get("slo_spec") or {}
        targets = ", ".join(f"{k}<={v}" for k, v in spec.items()
                            if k != "window_s" and v is not None)
        print(f"   slo: attainment {_fmt(s['slo_attainment'])} "
              f"({_fmt(s.get('slo_met'))}/{_fmt(s.get('slo_requests'))} met"
              + (f"; {targets}" if targets else "") + ")")
    if s.get("tenants"):
        # The multi-tenant ledger: one row per service class — who got
        # served, who absorbed the squeeze (shed/preemptions), and whether
        # each class kept its own promise.
        print(f"   {'tenant':<10} {'req':>5} {'ok':>5} {'shed':>5} "
              f"{'preempt':>7} {'ttft p95':>9} {'e2e p95':>9} {'slo':>7}")
        for name in sorted(s["tenants"]):
            row = s["tenants"][name]
            att = (row.get("slo") or {}).get("attainment")
            print(f"   {name:<10} {_fmt(row.get('requests')):>5} "
                  f"{_fmt(row.get('ok')):>5} {_fmt(row.get('shed')):>5} "
                  f"{_fmt(row.get('preemptions')):>7} "
                  f"{_fmt((row.get('ttft_s') or {}).get('p95')):>9} "
                  f"{_fmt((row.get('e2e_s') or {}).get('p95')):>9} "
                  f"{_fmt(att):>7}")
    if s.get("shed_events"):
        reasons = ", ".join(f"{k}: {v}" for k, v in
                            sorted((s.get("shed_by_reason") or {}).items()))
        print(f"   shed: {s['shed_events']} decision(s) ({reasons})")
    if s.get("preemptions"):
        print(f"   preemption: {_fmt(s['preemptions'])} park(s), "
              f"{_fmt(s.get('resumes'))} resume(s)")
    if s.get("goodput_frac") is not None:
        print(f"   goodput: {_fmt(s['goodput_frac'])} of "
              f"{_fmt(s.get('goodput_wall_s'))}s wall "
              f"(compute {_fmt(s.get('compute_s'))}s, restart badput "
              f"{_fmt(s.get('restart_badput_s'))}s, "
              f"{_fmt(s.get('epochs_replayed'))} epoch(s) replayed)")
    for g in s.get("bench_guard", []):
        verdict = "" if g.get("pass") is None else \
            ("  ok" if g["pass"] else "  REGRESSION")
        print(f"   bench_guard: {g['metric']}: {_fmt(g.get('median_s'))}s"
              + (f"  ratio {_fmt(g['ratio'])}x" if g.get("ratio") is not None
                 else "") + verdict)
    if s.get("unknown_events"):
        print(f"   {s['unknown_events']} unrecognized events "
              f"(kinds: {', '.join(s['unknown_kinds'])}) — writer/reporter "
              f"schema drift?")
    print()


COMPARE_ROWS = [
    ("compile_s", "compile_s"),
    ("execute_s/epoch", "execute_s_per_epoch"),
    ("examples/s", "examples_per_s"),
    ("flops/step", "flops_per_step"),
    ("mfu", "mfu"),
    ("train_loss", "final_train_loss"),
    ("val_loss", "final_val_loss"),
    ("ckpt_save_s", "ckpt_save_s"),
    ("restarts", "restarts"),
    ("anomalies", "anomalies"),
    ("skipped steps", "skipped_steps"),
    ("rollbacks", "rollbacks"),
    ("data wait s", "data_wait_s"),
    ("promotions", "promotions"),
    ("promote rollbacks", "promote_rollbacks"),
    ("goodput frac", "goodput_frac"),
    ("restart badput s", "restart_badput_s"),
    ("rollback badput s", "rollback_badput_s"),
    ("slo attainment", "slo_attainment"),
    ("shed", "shed"),
    ("preemptions", "preemptions"),
    ("serve tokens/s", "serve_tokens_per_s"),
    ("accepted tok/step", "accepted_tokens_per_step"),
    ("acceptance rate", "spec_acceptance_rate"),
    ("decode invocations", "decode_invocations"),
    ("prefill tok/s", "prefill_tokens_per_s"),
    ("decode bytes/tok", "decode_bytes_per_token"),
    ("kv bytes/slot", "kv_bytes_per_slot"),
    ("slots @ budget", "slots_at_budget"),
    ("kv pages in use", "kv_pages_in_use"),
    ("kv pages shared", "kv_pages_shared"),
    ("kv page refusals", "kv_page_refusals"),
    ("kv cow copies", "kv_cow_copies"),
    ("kv page frag", "kv_page_fragmentation"),
    ("prefix hit rate", "prefix_hit_rate"),
    ("affinity hit rate", "affinity_rate"),
    ("redispatches", "redispatches"),
    ("handoffs", "handoffs"),
    ("handoff bytes", "handoff_bytes"),
    ("handoff wall", "handoff_wall_s"),
    ("tier TTFT", "tier_ttft_s"),
    ("ejections", "ejections"),
    ("hedges", "hedges"),
    ("hedge win rate", "hedge_win_rate"),
    ("wire corrupt", "wire_corrupt"),
    ("replica restarts", "replica_restarts"),
    ("replicas p50", "replicas_p50"),
    ("replicas max", "replicas_max"),
    ("scale events", "scale_events"),
    ("ttft_s p50", "serve_ttft_s_p50"),
    ("ttft_s p99", "serve_ttft_s_p99"),
    ("tpot_s p50", "serve_tpot_s_p50"),
    ("e2e_s p95", "serve_e2e_s_p95"),
    ("queue_wait p95", "serve_queue_wait_s_p95"),
]


# ----------------------------------------------------------------- goodput mode

# The A-vs-B rows of a --goodput comparison (label, key into the flattened
# report) — the faulted-vs-clean run table the resilience story is judged by.
GOODPUT_ROWS = [
    ("wall_s", "wall_s"),
    ("init/compile s", "init_compile_s"),
    ("compute s", "compute_s"),
    ("data wait s", "data_wait_s"),
    ("ckpt stall s", "checkpoint_stall_s"),
    ("restart badput s", "restart_badput_s"),
    ("rollback badput s", "rollback_badput_s"),
    ("idle s", "idle_s"),
    ("goodput frac", "goodput_frac"),
    ("badput frac", "badput_frac"),
    ("attempts", "attempts"),
    ("restarts", "restarts"),
    ("rollbacks", "rollbacks"),
    ("epochs replayed", "epochs_replayed"),
    ("replayed steps", "replayed_steps"),
]


def _flat_goodput(report: dict, label: str) -> dict:
    return {"label": label, **report["segments"],
            **{k: v for k, v in report.items() if k != "segments"}}


def print_goodput(report: dict, label: str) -> None:
    """One run's decomposition: segments as seconds AND fractions of wall —
    the exclusive ledger sums to wall by construction, so the fractions sum
    to 1 (modulo the surfaced unaccounted residue)."""
    wall = report["wall_s"]
    print(f"== {label}  (goodput ledger over {_fmt(wall)}s wall)")
    print(f"   attempts {report['attempts']}  restarts {report['restarts']}"
          + (f" ({report['rollbacks']} rollback(s))"
             if report.get("rollbacks") else "")
          + f"  epochs {report['epochs']} "
          f"({report['epochs_replayed']} replayed, "
          f"{report['replayed_steps']} replayed step(s))"
          + ("  [preempted]" if report.get("preempted") else ""))
    for key, value in report["segments"].items():
        frac = value / wall if wall else None
        name = key[:-2].replace("_", " ")        # init_compile_s -> init compile
        print(f"   {name.ljust(16)} {_fmt(value).rjust(10)}s"
              f"  {_fmt(frac).rjust(8)}")
    print(f"   {'goodput frac'.ljust(16)} {''.rjust(10)} "
          f"{_fmt(report['goodput_frac']).rjust(8)}")
    if report.get("unaccounted_s"):
        print(f"   unaccounted residue: {_fmt(report['unaccounted_s'])}s "
              f"(clock skew / overlapping windows)")
    ck = report.get("checkpoint") or {}
    if ck.get("saves") or ck.get("restores"):
        print(f"   checkpoints: {ck.get('saves', 0)} save(s), "
              f"{ck.get('restores', 0)} restore(s) "
              f"({_fmt(ck.get('restore_s'))}s restoring)")
    st = report.get("streams") or {}
    print(f"   joined {st.get('files', '?')} file(s): {st.get('events', '?')} "
          f"events, {st.get('supervisor_events', 0)} supervisor, "
          f"{st.get('spans', 0)} span(s)")
    print()


def print_goodput_comparison(flats: list[dict]) -> None:
    labels = [f["label"] for f in flats]
    width = max(12, *(len(l) for l in labels)) + 2
    head = "metric".ljust(18) + "".join(l.rjust(width) for l in labels)
    ratio = len(flats) == 2
    if ratio:
        head += "B/A".rjust(10)
    print(head)
    print("-" * len(head))
    for name, key in GOODPUT_ROWS:
        vals = [f.get(key) for f in flats]
        if all(v is None for v in vals):
            continue
        line = name.ljust(18) + "".join(_fmt(v).rjust(width) for v in vals)
        if ratio and vals[0] and vals[1] is not None:
            line += f"{vals[1] / vals[0]:.3f}x".rjust(10)
        print(line)


def run_goodput(args) -> int:
    """--goodput: each positional arg is ONE RUN — a telemetry JSONL, or a
    directory whose *.jsonl files are joined (trainer telemetry + supervisor
    stream + trace spans self-classify by event kind)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.obs.goodput import (  # noqa: E402
        decompose,
        goodput_event,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (  # noqa: E402
        JsonlWriter,
    )

    flats = []
    for path in args.files:
        report = decompose([path])
        label = os.path.basename(os.path.normpath(path))
        print_goodput(report, label)
        flats.append(_flat_goodput(report, label))
        if args.emit:
            w = JsonlWriter(args.emit)
            w.emit(goodput_event(report))
            w.close()
    if len(flats) > 1:
        print_goodput_comparison(flats)
    return 0


def print_comparison(summaries: list[dict]) -> None:
    labels = [s["label"] for s in summaries]
    width = max(12, *(len(l) for l in labels)) + 2
    head = "metric".ljust(18) + "".join(l.rjust(width) for l in labels)
    ratio = len(summaries) == 2
    if ratio:
        head += "B/A".rjust(10)
    print(head)
    print("-" * len(head))
    for name, key in COMPARE_ROWS:
        vals = [s.get(key) for s in summaries]
        if all(v is None for v in vals):
            continue
        line = name.ljust(18) + "".join(_fmt(v).rjust(width) for v in vals)
        if ratio and vals[0] and vals[1] is not None:
            line += f"{vals[1] / vals[0]:.3f}x".rjust(10)
        print(line)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("files", nargs="+",
                   help="telemetry/metrics JSONL file(s); with --goodput, "
                        "one RUN each (a file, or a directory of JSONL "
                        "streams joined by obs/goodput.py)")
    p.add_argument("--goodput", action="store_true",
                   help="render each run's exclusive wall-time decomposition "
                        "(obs/goodput.py) instead of the event summary; two+ "
                        "runs add the faulted-vs-clean A-vs-B table")
    p.add_argument("--emit", default="",
                   help="--goodput only: append each run's ledger as a "
                        "{'event': 'goodput'} line to this JSONL")
    args = p.parse_args(argv)

    if args.goodput:
        return run_goodput(args)

    summaries = [summarize(f) for f in args.files]
    for s in summaries:
        print_summary(s)
    if len(summaries) > 1:
        print_comparison(summaries)
    return 0


if __name__ == "__main__":
    sys.exit(main())
