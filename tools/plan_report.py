"""Render a parallelism-plan artifact (plan/) as candidate tables + deltas.

Input is the JSON a ``--plan auto|tune`` run saved (``plan_<run_type>.json``
next to its checkpoints, or anything ``plan.Plan.save`` wrote). Prints the
chosen layout, the topology it was priced against, and the ranked candidate
table — predicted step time, per-chip memory, feasibility, and (tune mode) the
measured step time with its predicted-vs-measured delta, so the cost model is
auditable at a glance.

Usage::

    python tools/plan_report.py results/plan_composed.json
    python tools/plan_report.py results/plan_composed.json --telemetry run.jsonl

``--telemetry`` joins the plan against a training run's telemetry JSONL
(``--telemetry`` on the trainer): the run's best measured step seconds (epoch
events) lands next to the plan's prediction, and any ``autotune`` trial lines
are folded into the table — the predicted-vs-measured loop the planner's
credibility rests on.
"""

from __future__ import annotations

import argparse
import os
import sys

# Script-mode import path: ``python tools/plan_report.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csed_514_project_distributed_training_using_pytorch_tpu.plan import (  # noqa: E402
    Plan,
)
from csed_514_project_distributed_training_using_pytorch_tpu.utils.metrics import (  # noqa: E402
    load_metrics_jsonl,
)


def _fmt_ms(x) -> str:
    return f"{x * 1e3:.3f}" if isinstance(x, (int, float)) else "-"


def _fmt_gib(x) -> str:
    return f"{x / 2**30:.3f}" if isinstance(x, (int, float)) else "-"


def _delta(pred, meas) -> str:
    if not isinstance(pred, (int, float)) or not isinstance(meas, (int, float)) \
            or not pred:
        return "-"
    return f"{(meas - pred) / pred * 100:+.0f}%"


def _cand_label(c: dict) -> str:
    label = ",".join(f"{k}={v}" for k, v in c.get("axes", {}).items()
                     if v > 1) or "data=1"
    if c.get("fsdp"):
        label += "+fsdp"
    return label


def measured_step_from_telemetry(rows: list[dict]) -> float | None:
    """Best measured step seconds of a run: min over epoch events of
    ``execute_s / steps`` — the same steady-state quantity the ``mfu`` event
    uses, recomputed here so partial logs still report."""
    best = None
    for r in rows:
        if r.get("event") == "epoch" and r.get("execute_s") and r.get("steps"):
            s = r["execute_s"] / r["steps"]
            best = s if best is None else min(best, s)
    return best


def render(plan: Plan, telemetry_rows: list[dict] | None = None,
           out=sys.stdout) -> None:
    w = lambda line="": print(line, file=out)
    topo = plan.topology or {}
    w(f"# plan: {plan.run_type} · source={plan.source} · "
      f"{plan.device_count} devices · global batch {plan.global_batch}")
    if topo:
        w(f"  topology: {topo.get('device_kind', '?')} · "
          f"hbm {_fmt_gib(topo.get('hbm_bytes'))} GiB/chip "
          f"({topo.get('hbm_source', '?')}) · "
          f"ici {topo.get('ici_bytes', 0) / 1e9:.0f} GB/s · "
          f"dcn {topo.get('dcn_bytes', 0) / 1e9:.2f} GB/s · "
          f"{topo.get('num_slices', 1)} granule(s)")
    pred = plan.predicted or {}
    w(f"  chosen: mesh {plan.mesh}" + (" +fsdp" if plan.fsdp else "")
      + f" · grad_accum {plan.grad_accum}"
      + (f" · microbatches {plan.pipeline_microbatches}"
         if plan.axes.get("stage", 1) > 1 else ""))
    w(f"  predicted: step {_fmt_ms(pred.get('step_s'))} ms · "
      f"{_fmt_gib(pred.get('total_bytes_per_chip'))} GiB/chip"
      + (f" · measured (tune) {_fmt_ms(plan.measured_step_s)} ms "
         f"[{_delta(pred.get('step_s'), plan.measured_step_s)}]"
         if plan.measured_step_s is not None else ""))

    # Autotune lines from telemetry augment rows the plan didn't carry.
    tuned = {}
    run_measured = None
    if telemetry_rows:
        for r in telemetry_rows:
            if r.get("event") == "autotune" and r.get("measured_step_s"):
                key = (r.get("mesh"), bool(r.get("fsdp")),
                       int(r.get("grad_accum") or 1),
                       int(r.get("microbatches") or 1))
                tuned[key] = r["measured_step_s"]
        run_measured = measured_step_from_telemetry(telemetry_rows)

    if plan.candidates:
        w()
        w("  rank  layout                    accum  micro  pred_ms  meas_ms  "
          "delta  GiB/chip  fits")
        for i, row in enumerate(plan.candidates):
            c, costs = row.get("candidate", {}), row.get("costs", {})
            cand_axes = {"data": c.get("data", 1), "model": c.get("model", 1),
                         "stage": c.get("stage", 1)}
            label = _cand_label({"axes": cand_axes, "fsdp": c.get("fsdp")})
            meas = row.get("measured_step_s")
            if meas is None:
                mesh_str = ",".join(
                    [f"data={c.get('data', 1)}"]
                    + [f"{k}={v}" for k, v in (("model", c.get("model", 1)),
                                               ("stage", c.get("stage", 1)))
                       if v > 1])
                meas = tuned.get((mesh_str, bool(c.get("fsdp")),
                                  int(c.get("grad_accum") or 1),
                                  int(c.get("microbatches") or 1)))
            w(f"  {i:>4}  {label:<24}  {c.get('grad_accum', 1):>5}  "
              f"{c.get('microbatches', 1):>5}  "
              f"{_fmt_ms(costs.get('step_s')):>7}  {_fmt_ms(meas):>7}  "
              f"{_delta(costs.get('step_s'), meas):>5}  "
              f"{_fmt_gib(costs.get('total_bytes_per_chip')):>8}  "
              f"{'yes' if costs.get('fits') else 'NO'}")

    if run_measured is not None:
        w()
        w(f"  run measured (telemetry): best step {_fmt_ms(run_measured)} ms vs "
          f"predicted {_fmt_ms(pred.get('step_s'))} ms "
          f"[{_delta(pred.get('step_s'), run_measured)}]")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n", 1)[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("plan", help="plan JSON artifact (plan.Plan.save output)")
    parser.add_argument("--telemetry", default="",
                        help="telemetry JSONL of a run to compare measured step "
                             "time (epoch/autotune events) against the plan")
    args = parser.parse_args(argv)
    plan = Plan.load(args.plan)
    rows = load_metrics_jsonl(args.telemetry) if args.telemetry else None
    render(plan, rows)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
