"""Train→serve promotion loop bench — the committed artifact (DESIGN.md §26).

One command closes the loop: a corpus LM trainer publishes health-stamped
versioned checkpoints while a replica fleet serves live traffic; the promoter
(``deploy/promoter.py``) gate-qualifies each candidate (health stamp →
``decode_nll`` accuracy budget → perf tolerance), canaries survivors on ONE
replica via the router's rolling-reload path, and promotes fleet-wide or
auto-rolls-back on regression. Four legs, each with exit-code gates:

- **promote** — trainer + fleet run concurrently under closed-loop traffic;
  at least one candidate qualifies, canaries, and promotes fleet-wide with
  ZERO lost requests across every rolling reload.
- **rollback** — a deliberately param-corrupted candidate (clean health
  stamp, so only measurement can catch it) is rejected at the NLL gate; a
  second one rides a loosened gate into the canary, where the sampled-token
  NLL under the last-good scorer catches it and the fleet auto-rolls-back to
  the incumbent.
- **resume** — the deterministic-resume invariant: kill-free split training
  (k epochs, then resume from the manifest cursor) produces a final model
  BITWISE identical to the uninterrupted run, epoch stream digests included.
- **data_wait** — a throttled streaming loader shows up in the goodput
  ledger: ``data_wait_s > 0`` and the exclusive segments sum to wall ±1%.

Produces ``--out-dir`` (default ``bench_results/promote_loop_cpu/``) with
``summary.json`` (the gates), ``promotion_ledger.jsonl``,
``promote_telemetry.jsonl`` (promote/canary events — render with
``tools/telemetry_report.py``), ``router.jsonl`` (fleet stream incl. canary
snapshots — watch live with ``tools/fleet_top.py``), and ``goodput.json``.
``--quick`` shrinks everything for the CI smoke job.

Usage::

    python tools/train_serve_loop.py --out-dir bench_results/promote_loop_cpu
    python tools/train_serve_loop.py --quick --out-dir /tmp/psl --work-dir /tmp/pslw
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "csed_514_project_distributed_training_using_pytorch_tpu"
_CORPUS = os.path.join(_REPO, "tests", "fixtures", "corpus_tiny")


def _child_env() -> dict:
    env = dict(os.environ)
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = f"{_REPO}:{existing}" if existing else _REPO
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def train_argv(args, *, epochs, results_dir, telemetry="", resume_from="",
               throttle=0.0, keep=8, guard=True, seed=1) -> list[str]:
    cmd = [sys.executable, "-m", f"{PKG}.train.lm",
           "--corpus", args.corpus, "--epochs", str(epochs),
           "--batch-size", str(args.batch_size),
           "--embed-dim", str(args.embed_dim),
           "--num-layers", str(args.num_layers),
           "--num-heads", str(args.num_heads),
           "--results-dir", results_dir,
           "--images-dir", os.path.join(results_dir, "images"),
           "--seed", str(seed),
           "--keep-checkpoints", str(keep)]
    if guard:
        cmd += ["--guard"]
    if telemetry:
        cmd += ["--telemetry", telemetry]
    if resume_from:
        cmd += ["--resume-from", resume_from]
    if throttle:
        cmd += ["--data-throttle-s", str(throttle)]
    return cmd


def run_train(cmd: list[str], *, cwd: str) -> None:
    os.makedirs(cwd, exist_ok=True)
    r = subprocess.run(cmd, cwd=cwd, env=_child_env(),
                       capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout[-4000:] + r.stderr[-4000:])
        raise SystemExit(f"trainer failed with rc {r.returncode}")


class Scorers:
    """The promoter's jax-backed probes, built ONCE: ``decode_nll`` on a
    fixed slice of the corpus eval split (the accuracy gate and the fixed
    canary scorer — scored through the serving decode path, the exact
    kernels the fleet serves with), and a timed decode probe (the perf
    gate). Params load through the same ``load_params_or_state`` fallback
    the replicas use, cached by path."""

    def __init__(self, args):
        import jax
        import jax.numpy as jnp

        from csed_514_project_distributed_training_using_pytorch_tpu.data import (
            stream as stream_mod,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.models import (
            lm,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        self._checkpoint = checkpoint
        meta = stream_mod.load_meta(args.corpus)
        self.seq_len = int(meta["seq_len"])
        self.vocab = int(meta["vocab"])
        self.model = lm.TransformerLM(
            vocab_size=self.vocab + 1, seq_len=self.seq_len,
            embed_dim=args.embed_dim, num_layers=args.num_layers,
            num_heads=args.num_heads)
        self.template = self.model.init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, self.seq_len), jnp.int32))["params"]
        ev = stream_mod.eval_tokens(args.corpus)
        self.eval_tokens = np.asarray(ev[:args.gate_eval_rows], np.int32)
        self._score = jax.jit(
            lambda p, t: lm.decode_nll(self.model, p, t))
        # Compile outside every measured window (the perf probe especially).
        float(self._score(self.template, self.eval_tokens))
        self._params_cache: dict[str, object] = {}

    def params(self, path: str):
        got = self._params_cache.get(path)
        if got is None:
            got = self._checkpoint.load_params_or_state(path, self.template)
            self._params_cache = {path: got}     # one-slot: stores are small
        return got

    def nll(self, path: str) -> float:
        return float(self._score(self.params(path), self.eval_tokens))

    def perf(self, path: str) -> float:
        p = self.params(path)
        float(self._score(p, self.eval_tokens))     # absorb transfer cost
        t0 = time.perf_counter()
        float(self._score(p, self.eval_tokens))
        return time.perf_counter() - t0

    def sample_nll(self, samples: list[dict],
                   scorer_path: str) -> float | None:
        """Mean NLL of the sampled full sequences under the FIXED scorer at
        ``scorer_path`` (the incumbent) — the canary-vs-fleet comparison
        scores BOTH sides' tokens with the same params, so a regressed
        canary's generated tokens read as surprising while the fleet's read
        as expected."""
        rows = [s["tokens"] for s in samples
                if len(s["tokens"]) == self.seq_len]
        if not rows:
            return None
        return float(self._score(self.params(scorer_path),
                                 np.asarray(rows, np.int32)))


class Traffic(threading.Thread):
    """Closed-loop fleet load: ``concurrency`` in-flight requests cycling
    over eval-split prompts, every completion tallied by finish — the
    zero-lost-requests evidence across every rolling reload."""

    def __init__(self, router, prompts, *, concurrency, max_new, timeout_s):
        super().__init__(daemon=True, name="loop-traffic")
        self.router = router
        self.prompts = prompts
        self.concurrency = concurrency
        self.max_new = max_new
        self.timeout_s = timeout_s
        self.stop_ev = threading.Event()
        self.ok = 0
        self.finishes: dict[str, int] = {}
        self.errors = 0

    def run(self):
        i = 0
        while not self.stop_ev.is_set():
            futs = []
            for k in range(self.concurrency):
                prompt = self.prompts[(i + k) % len(self.prompts)]
                try:
                    futs.append(self.router.submit(
                        prompt, max_new_tokens=self.max_new,
                        timeout_s=self.timeout_s))
                except Exception:
                    self.errors += 1
            i += self.concurrency
            for f in futs:
                try:
                    comp = f.result(self.timeout_s + 60.0)
                except Exception:
                    self.errors += 1
                    continue
                self.ok += comp.ok
                self.finishes[comp.finish] = \
                    self.finishes.get(comp.finish, 0) + 1
            time.sleep(0.02)

    def halt(self):
        self.stop_ev.set()
        self.join(self.timeout_s + 120.0)

    @property
    def lost(self) -> int:
        return (self.errors
                + sum(n for f, n in self.finishes.items() if f != "ok"))


def publish_corrupted(store: str, src_path: str, *, step: int,
                      seed: int) -> str:
    """Fabricate the regression the promoter must catch: the incumbent's
    params plus heavy seeded noise, republished as a NEW versioned candidate
    with a CLEAN health stamp — the trainer-side immune system vouched for
    it, so only the promoter's own measurements stand between it and the
    fleet."""
    from flax import serialization

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    with open(src_path, "rb") as f:
        state = serialization.msgpack_restore(f.read())
    rng = np.random.default_rng(seed)

    def corrupt(node):
        for key, val in node.items():
            if isinstance(val, dict):
                corrupt(val)
            elif hasattr(val, "dtype") and np.issubdtype(np.dtype(val.dtype),
                                                         np.floating):
                node[key] = (np.asarray(val)
                             + rng.normal(0.0, 2.0, np.shape(val))
                             ).astype(val.dtype)

    corrupt(state["params"])
    blob = serialization.msgpack_serialize(state)
    name = f"ckpt_{step:08d}.msgpack"
    path = os.path.join(store, name)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    man = checkpoint.load_manifest(store)
    man["entries"].append({
        "file": name, "step": step,
        "sha256": hashlib.sha256(blob).hexdigest(), "bytes": len(blob),
        "unix_time": time.time(),
        "health": {"clean": True, "anomalies": 0, "skipped": 0, "step": step},
    })
    mtmp = os.path.join(store, "manifest.json.tmp")
    with open(mtmp, "w") as f:
        json.dump(man, f)
    os.replace(mtmp, os.path.join(store, "manifest.json"))
    return path


def _fleet_checkpoint(router) -> str:
    cmd = router._command
    for i, tok in enumerate(cmd):
        if tok == "--checkpoint" and i + 1 < len(cmd):
            return cmd[i + 1]
    return ""


def run_promote_and_rollback(args, out_dir: str,
                             scorers: Scorers) -> tuple[dict, dict]:
    """Legs 1+2 on ONE fleet session: concurrent train+serve with promotion,
    then the forced-rollback scenario against the promoted incumbent."""
    from csed_514_project_distributed_training_using_pytorch_tpu.deploy import (
        CanaryConfig,
        GateConfig,
        Promoter,
        read_ledger,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.obs.slo import (
        SLOSpec,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.router import (
        Router,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    wd = args.work_dir
    rd = os.path.join(wd, "train")
    store = os.path.join(rd, "checkpoints")
    tele_a = os.path.join(wd, "train_initial.jsonl")
    tele_b = os.path.join(wd, "train_continue.jsonl")

    print(f"== promote leg: initial {args.initial_epochs}-epoch train")
    run_train(train_argv(args, epochs=args.initial_epochs, results_dir=rd,
                         telemetry=tele_a), cwd=wd)
    ckpt0 = checkpoint.newest_valid_checkpoint(store)
    if not ckpt0:
        raise SystemExit("initial training produced no versioned checkpoint")
    print(f"   serving from {os.path.basename(ckpt0)}")

    replica_cmd = ["-m", f"{PKG}.serving.replica",
                   "--checkpoint", ckpt0,
                   "--seq-len", str(scorers.seq_len),
                   "--num-levels", str(scorers.vocab),
                   "--embed-dim", str(args.embed_dim),
                   "--num-layers", str(args.num_layers),
                   "--num-heads", str(args.num_heads),
                   "--num-slots", "4", "--max-pending", "32",
                   "--prefill-chunks", str(scorers.seq_len),
                   "--seed", "0"]
    # affinity=False: the closed loop cycles a small prompt set, and prefix
    # affinity would pin every prompt to its first-seen replica — the canary
    # would sit at zero requests forever. Least-loaded routing spreads the
    # loop so both sides of the canary comparison accumulate evidence.
    router = Router(
        replica_cmd, num_replicas=args.replicas, platform="cpu",
        affinity=False,
        heartbeat_dir=os.path.join(wd, "hb"), heartbeat_timeout_s=120.0,
        backoff_s=0.5, connect_timeout_s=600.0,
        drain_timeout_s=120.0, warm_prefixes=0,
        telemetry=os.path.join(out_dir, "router.jsonl"),
        snapshot_interval_s=2.0,
        slo=SLOSpec.parse(args.slo),
        sample_completions=16).start()
    prompt_len = scorers.seq_len - args.max_new_tokens
    prompts = [np.asarray(row[:prompt_len], np.int32)
               for row in scorers.eval_tokens[:args.traffic_prompts]]
    traffic = Traffic(router, prompts, concurrency=args.concurrency,
                      max_new=args.max_new_tokens,
                      timeout_s=args.request_timeout_s)
    promote_doc = rollback_doc = None
    try:
        if not router.wait_ready(900.0):
            raise SystemExit("fleet never became ready")
        traffic.start()

        print(f"   trainer continues to {args.total_epochs} epochs "
              f"(throttle {args.train_throttle_s}s/batch) while the fleet "
              f"serves")
        proc = subprocess.Popen(
            train_argv(args, epochs=args.total_epochs, results_dir=rd,
                       telemetry=tele_b, resume_from=ckpt0,
                       throttle=args.train_throttle_s),
            cwd=wd, env=_child_env(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

        prom = Promoter(
            store, router=router,
            nll_fn=scorers.nll, perf_fn=scorers.perf,
            gate=GateConfig(nll_budget=args.nll_budget,
                            perf_tolerance=args.perf_tolerance,
                            perf_probes=3),
            canary=CanaryConfig(window_s=args.canary_window_s,
                                min_requests=args.canary_min_requests,
                                attainment_margin=args.attainment_margin,
                                nll_margin=args.nll_margin),
            ledger_path=os.path.join(out_dir, "promotion_ledger.jsonl"),
            telemetry=os.path.join(out_dir, "promote_telemetry.jsonl"),
            incumbent=ckpt0)
        # The fixed canary scorer: the incumbent AT JUDGMENT TIME (promotion
        # moves it; both sides of one comparison always share one scorer).
        prom.sample_nll_fn = \
            lambda samples: scorers.sample_nll(samples, prom.incumbent)
        prom.run(stop_fn=lambda: proc.poll() is not None, poll_s=1.0)
        out = proc.communicate()[0]
        if proc.returncode != 0:
            sys.stderr.write(out[-4000:])
            raise SystemExit(f"continuing trainer failed rc {proc.returncode}")
        promoted_ckpt = prom.incumbent
        print(f"   promoter: {prom.counts} — incumbent now "
              f"{os.path.basename(promoted_ckpt)}")

        promote_doc = {
            "initial_checkpoint": os.path.basename(ckpt0),
            "final_incumbent": os.path.basename(promoted_ckpt),
            "promoter_counts": dict(prom.counts),
            "incumbent_advanced": promoted_ckpt != ckpt0,
        }

        # ---- forced rollback, same fleet ----
        newest_step = max(e.get("step", 0) for e in
                          checkpoint.load_manifest(store)["entries"])
        print("== rollback leg: corrupted candidate vs the gate")
        publish_corrupted(store, promoted_ckpt, step=newest_step + 1000,
                          seed=args.seed + 17)
        gate_acts = prom.run_once()
        print(f"   gate verdict: {gate_acts}")

        print("   corrupted candidate vs the canary (gate loosened)")
        publish_corrupted(store, promoted_ckpt, step=newest_step + 2000,
                          seed=args.seed + 29)
        prom.gate = GateConfig(nll_budget=1e9, perf_tolerance=1e9)
        canary_acts = prom.run_once()
        print(f"   canary verdict: {canary_acts}")
        fleet_ckpt = _fleet_checkpoint(router)

        # Post-rollback proof of life: the fleet serves the incumbent.
        settle = traffic.ok
        deadline = time.monotonic() + 120.0
        while traffic.ok < settle + args.concurrency \
                and time.monotonic() < deadline:
            time.sleep(0.25)
    finally:
        traffic.halt()
        summary = router.stop()
        try:
            prom.close()
        except Exception:
            pass
    ledger_actions = [r["action"] for r in
                      read_ledger(os.path.join(out_dir,
                                               "promotion_ledger.jsonl"))]
    promote_doc.update({
        "traffic": {"ok": traffic.ok, "lost": traffic.lost,
                    "finishes": traffic.finishes, "errors": traffic.errors},
        "router_summary": {k: summary.get(k) for k in
                           ("requests", "ok", "failed", "redispatches",
                            "restarts") if k in summary},
        "ledger_actions": ledger_actions,
    })
    rollback_doc = {
        "gate_actions": gate_acts,
        "canary_actions": canary_acts,
        "caught_at_gate": gate_acts == ["gate_fail"],
        "rolled_back_from_canary": canary_acts == ["rolled_back"],
        "fleet_checkpoint_after": os.path.basename(fleet_ckpt),
        "fleet_on_last_good": fleet_ckpt == promoted_ckpt,
        "incumbent_after": os.path.basename(prom.incumbent),
    }
    return promote_doc, rollback_doc


def run_resume_leg(args) -> dict:
    """Leg 3: uninterrupted vs split-and-resume training — final model
    bitwise identical, per-epoch stream digests identical."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    wd = args.work_dir
    full_rd = os.path.join(wd, "resume_full")
    s1_rd = os.path.join(wd, "resume_split1")
    s2_rd = os.path.join(wd, "resume_split2")
    full_tele = os.path.join(wd, "resume_full.jsonl")
    s2_tele = os.path.join(wd, "resume_split2.jsonl")
    total, split = args.resume_total_epochs, args.resume_split_epochs
    print(f"== resume leg: {total} epochs uninterrupted vs "
          f"{split}+resume")
    run_train(train_argv(args, epochs=total, results_dir=full_rd,
                         telemetry=full_tele, guard=False), cwd=wd)
    run_train(train_argv(args, epochs=split, results_dir=s1_rd, guard=False),
              cwd=wd)
    mid = checkpoint.newest_valid_checkpoint(
        os.path.join(s1_rd, "checkpoints"))
    cursor = checkpoint.cursor_for(mid)
    run_train(train_argv(args, epochs=total, results_dir=s2_rd,
                         telemetry=s2_tele, resume_from=mid, guard=False),
              cwd=wd)

    def digests(path):
        out = {}
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("event") == "data" and \
                        row.get("stream_digest") is not None:
                    out[row["epoch"]] = row["stream_digest"]
        return out

    with open(os.path.join(full_rd, "model_lm.ckpt"), "rb") as f:
        full_bytes = f.read()
    with open(os.path.join(s2_rd, "model_lm.ckpt"), "rb") as f:
        split_bytes = f.read()
    d_full, d_split = digests(full_tele), digests(s2_tele)
    tail = {e: d_full.get(e) == d_split.get(e)
            for e in d_split}                  # resumed epochs only
    bitwise = full_bytes == split_bytes
    print(f"   cursor {cursor}; bitwise={'OK' if bitwise else 'DIVERGED'}, "
          f"digests {tail}")
    return {
        "total_epochs": total, "split_at": split,
        "resume_cursor": cursor,
        "bitwise_identical": bitwise,
        "stream_digests_match": all(tail.values()) and bool(tail),
        "digests_full": d_full, "digests_resumed": d_split,
    }


def run_data_wait_leg(args, out_dir: str) -> dict:
    """Leg 4: a throttled streaming loader must surface in the goodput
    ledger's ``data_wait_s`` segment, with the exclusive decomposition still
    summing to wall ±1%."""
    from csed_514_project_distributed_training_using_pytorch_tpu.obs import (
        goodput,
    )

    wd = args.work_dir
    rd = os.path.join(wd, "throttled")
    tele = os.path.join(out_dir, "train_throttled.jsonl")
    if os.path.exists(tele):
        os.remove(tele)                # goodput reads ONE attempt here
    print(f"== data_wait leg: {args.throttle_epochs} epochs at "
          f"{args.throttle_s}s/batch")
    run_train(train_argv(args, epochs=args.throttle_epochs, results_dir=rd,
                         telemetry=tele, throttle=args.throttle_s,
                         guard=False, keep=2), cwd=wd)
    report = goodput.decompose([tele])
    seg = report["segments"]
    total = sum(seg.values())
    wall = report["wall_s"]
    gap = abs(total - wall) + report["unaccounted_s"]
    doc = {
        "throttle_s": args.throttle_s,
        "wall_s": wall,
        "segments": seg,
        "segments_total_s": total,
        "unaccounted_s": report["unaccounted_s"],
        "data_wait_s": seg["data_wait_s"],
        "data_wait_positive": seg["data_wait_s"] > 0.0,
        "sums_to_wall_1pct": gap <= 0.01 * wall,
    }
    with open(os.path.join(out_dir, "goodput.json"), "w") as f:
        json.dump(report, f, indent=1)
    print(f"   data_wait {seg['data_wait_s']:.3f}s of {wall:.3f}s wall "
          f"(gap {gap:.4f}s)")
    return doc


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--corpus", default=_CORPUS)
    p.add_argument("--work-dir", default="/tmp/train_serve_loop_work")
    p.add_argument("--out-dir", default="bench_results/promote_loop_cpu")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--embed-dim", type=int, default=32)
    p.add_argument("--num-layers", type=int, default=1)
    p.add_argument("--num-heads", type=int, default=2)
    p.add_argument("--initial-epochs", type=int, default=1)
    p.add_argument("--total-epochs", type=int, default=6)
    p.add_argument("--train-throttle-s", type=float, default=0.3,
                   help="continuing trainer's per-batch brake so checkpoints "
                        "land WHILE the fleet serves (0 = as fast as it can)")
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--traffic-prompts", type=int, default=16)
    p.add_argument("--request-timeout-s", type=float, default=300.0)
    p.add_argument("--slo", default="ttft=30,e2e=120,window=60")
    p.add_argument("--gate-eval-rows", type=int, default=16)
    p.add_argument("--nll-budget", type=float, default=0.25,
                   help="gate: candidate decode_nll may exceed incumbent by "
                        "at most this (nats/token)")
    p.add_argument("--perf-tolerance", type=float, default=5.0,
                   help="gate: relative perf-probe slack (CPU probe noise is "
                        "large; the gate still catches order-of-magnitude "
                        "regressions)")
    p.add_argument("--canary-window-s", type=float, default=8.0)
    p.add_argument("--canary-min-requests", type=int, default=3)
    p.add_argument("--attainment-margin", type=float, default=0.25)
    p.add_argument("--nll-margin", type=float, default=0.5,
                   help="canary: sampled-token NLL margin vs the fleet under "
                        "the shared last-good scorer. The fleet's greedy "
                        "tokens are scored by the params that CHOSE them "
                        "(low by construction), so a sane successor sits a "
                        "little above the fleet; corrupted params decode "
                        "near-uniform garbage (~ln(vocab) at generated "
                        "positions) and clear this by a wide gap")
    p.add_argument("--resume-total-epochs", type=int, default=4)
    p.add_argument("--resume-split-epochs", type=int, default=2)
    p.add_argument("--throttle-epochs", type=int, default=2)
    p.add_argument("--throttle-s", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true",
                   help="CI smoke sizing: 2 replicas, shorter runs")
    args = p.parse_args(argv)
    if args.quick:
        args.replicas = min(args.replicas, 2)
        args.total_epochs = min(args.total_epochs, 4)
        args.canary_window_s = min(args.canary_window_s, 5.0)
        args.resume_total_epochs = min(args.resume_total_epochs, 3)
        args.resume_split_epochs = min(args.resume_split_epochs, 1)
        args.train_throttle_s = min(args.train_throttle_s, 0.2)

    # Trainer subprocesses run with cwd=work_dir, so relative --out-dir
    # telemetry paths would resolve against the wrong root: absolutize both.
    args.out_dir = os.path.abspath(args.out_dir)
    args.work_dir = os.path.abspath(args.work_dir)
    os.makedirs(args.out_dir, exist_ok=True)
    os.makedirs(args.work_dir, exist_ok=True)
    t0 = time.monotonic()

    scorers = Scorers(args)
    promote_doc, rollback_doc = run_promote_and_rollback(
        args, args.out_dir, scorers)
    resume_doc = run_resume_leg(args)
    data_doc = run_data_wait_leg(args, args.out_dir)

    gates = {
        "candidate_promoted_fleet_wide":
            promote_doc["promoter_counts"]["promoted"] >= 1
            and promote_doc["incumbent_advanced"],
        "zero_lost_requests":
            promote_doc["traffic"]["lost"] == 0
            and promote_doc["traffic"]["ok"] > 0,
        "regressed_candidate_caught":
            rollback_doc["caught_at_gate"]
            and rollback_doc["rolled_back_from_canary"],
        "fleet_on_last_good_after_rollback":
            rollback_doc["fleet_on_last_good"],
        "resume_bitwise_identical":
            resume_doc["bitwise_identical"]
            and resume_doc["stream_digests_match"],
        "data_wait_measured":
            data_doc["data_wait_positive"],
        "goodput_sums_to_wall_1pct":
            data_doc["sums_to_wall_1pct"],
    }
    doc = {
        "metric": "train→serve promotion loop (DESIGN.md §26)",
        "corpus": args.corpus,
        "quick": args.quick,
        "wall_s": time.monotonic() - t0,
        "promote": promote_doc,
        "rollback": rollback_doc,
        "resume": resume_doc,
        "data_wait": data_doc,
        "gates": gates,
    }
    out = os.path.join(args.out_dir, "summary.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"gates: {gates}")
    print(f"wrote {out}")
    return 0 if all(gates.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
