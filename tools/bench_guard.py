"""Continuous perf-regression gate: median-of-N microbenches vs a committed baseline.

Tier-1 keeps the repo CORRECT; nothing so far kept it FAST — a PR could halve
decode tokens/s and land green. This tool is the guard: a small committed
microbench suite covering the repo's hot paths, run median-of-N (the noise
defense: the median of 5 short runs is far more stable than any single run on
a shared machine), compared metric-by-metric against
``bench_results/guard_baseline.json`` with a per-metric tolerance. Exit 0 =
within tolerance, exit 3 = regression, with the full measurement written as a
JSON artifact either way — the repo's bench trajectory, one document per run.

The suite (tiny CPU-fixture models — the gate must run in CI seconds, and a
regression that shows on the fixture shows on the real model):

=================  ==================================================================
``decode_tick_s``  one slot-engine decode step, 4 busy slots, empty prompts
                   (pure decode: the serving hot loop, ``engine.step``)
``paged_decode_tick_s``  the same decode step on the paged-KV engine
                   (``kv_layout="paged"`` — the gather-adapter overhead gate)
``prefill_chunk_s``  one chunked-prefill program invocation (host wall per chunk,
                   from the engine's own ``prefill_wall_s`` ledger)
``spec_verify_s``  one speculative verify tick (ngram drafting + the batched
                   K-token verify program) on a repetitive prompt mixture
``lm_train_step_s``  one jitted LM train step (next-token loss + SGD) on a
                   batch-8 fixture — the training hot loop
=================  ==================================================================

Compile time is excluded everywhere (a warmup invocation precedes every
timed region): the gate watches steady-state throughput, and compile
regressions are visible in telemetry's ``compile`` events instead.

Noise policy: each metric's tolerance is a fractional regression allowance
(default 0.6: fail only on a >1.6x slowdown — shared CI runners jitter tens
of percent, and the gate's job is catching the 2x-10x accidents, not 5%
drift). ``--update-baseline`` re-measures and rewrites the baseline; the
baseline records its host fingerprint and the gate WARNS (never fails) on a
fingerprint mismatch — absolute seconds only transfer between like machines,
which is also why the CI job stays non-blocking (advisory trend + artifact).

Telemetry: one ``{"event": "bench_guard", ...}`` line per metric via
``--telemetry`` (the registered kind — renders in tools/telemetry_report.py),
so gate runs join the same A-vs-B machinery as every other measurement.

Usage::

    python tools/bench_guard.py                      # gate vs committed baseline
    python tools/bench_guard.py --update-baseline    # re-seed the baseline
    python tools/bench_guard.py --runs 7 --out bench_results/guard_run.json
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import statistics
import sys
import time

# Script-mode import path: ``python tools/bench_guard.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join("bench_results", "guard_baseline.json")
DEFAULT_TOLERANCE = 0.6
EXIT_REGRESSION = 3
EXIT_NO_BASELINE = 2

SMALL = dict(vocab_size=17, seq_len=64, embed_dim=32, num_layers=2,
             num_heads=4)


def _host_fingerprint() -> dict:
    import jax
    dev = jax.devices()[0]
    return {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "device_count": len(jax.devices()),
        "python": platform.python_version(),
        "jax": jax.__version__,
        "machine": platform.machine(),
    }


def _build_engine(**overrides):
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        ContinuousBatchingEngine,
    )

    model = lm.TransformerLM(**SMALL)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    kw = dict(num_slots=4, seed=0, prefill_chunk_sizes=(16,))
    kw.update(overrides)
    return model, ContinuousBatchingEngine(model, params, **kw)


def _drain(engine) -> int:
    """Run the engine until every slot resolves; returns the step count."""
    steps = 0
    while engine.num_active:
        engine.step()
        steps += 1
    return steps


def bench_decode_tick() -> float:
    """Seconds per decode step with 4 busy slots (empty prompts: no prefill
    in the timed region — this is the pure decode hot loop)."""
    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        Request,
    )

    model, engine = _build_engine()

    def admit(max_new):
        reqs = [Request(prompt=np.zeros(0, np.int32), max_new_tokens=max_new,
                        request_id=i) for i in range(4)]
        engine.admit_many(list(zip(engine.free_slots(), reqs)))

    admit(4)
    _drain(engine)                      # compile, off the clock
    admit(32)
    t0 = time.perf_counter()
    steps = _drain(engine)
    return (time.perf_counter() - t0) / steps


def bench_paged_decode_tick() -> float:
    """Seconds per decode step on the PAGED engine, same workload as
    ``decode_tick_s`` — the gather-adapter overhead over the contiguous hot
    loop is exactly the ratio of these two metrics."""
    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        Request,
    )

    model, engine = _build_engine(kv_layout="paged", page_size=16)

    def admit(max_new):
        reqs = [Request(prompt=np.zeros(0, np.int32), max_new_tokens=max_new,
                        request_id=i) for i in range(4)]
        engine.admit_many(list(zip(engine.free_slots(), reqs)))

    admit(4)
    _drain(engine)                      # compile, off the clock
    admit(32)
    t0 = time.perf_counter()
    steps = _drain(engine)
    return (time.perf_counter() - t0) / steps


def bench_prefill_chunk() -> float:
    """Host wall per chunked-prefill program invocation (the engine's own
    ``prefill_wall_s / prefill_invocations`` ledger — queueing excluded)."""
    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        Request,
    )

    model, engine = _build_engine()
    rng = np.random.default_rng(7)

    def run_one(rid):
        prompt = rng.integers(0, model.vocab_size - 1,
                              size=48).astype(np.int32)
        engine.admit_many([(engine.free_slots()[0],
                            Request(prompt=prompt, max_new_tokens=1,
                                    request_id=rid))])
        _drain(engine)

    run_one(0)                          # compile, off the clock
    engine.reset_stats()
    for rid in range(1, 5):
        run_one(rid)
    return engine.prefill_wall_s / max(engine.prefill_invocations, 1)


def bench_spec_verify() -> float:
    """Seconds per speculative verify tick (ngram draft + batched K-token
    verify) on a repetitive prompt the drafter can actually hit."""
    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        Request,
    )

    model, engine = _build_engine(spec="ngram", spec_k=4)

    def admit(max_new):
        reqs = []
        for i in range(4):
            prompt = np.tile(np.arange(1, 5, dtype=np.int32), 4)
            reqs.append(Request(prompt=prompt, max_new_tokens=max_new,
                                request_id=i))
        engine.admit_many(list(zip(engine.free_slots(), reqs)))

    admit(4)
    _drain(engine)                      # compile draft+verify, off the clock
    engine.take_spec_records()
    admit(32)
    _drain(engine)
    recs = engine.take_spec_records()
    walls = [r["verify_wall_s"] + (r.get("draft_wall_s") or 0.0)
             for r in recs if r.get("verify_wall_s") is not None]
    if not walls:
        raise RuntimeError("spec_verify produced no timed verify records")
    return sum(walls) / len(walls)


def bench_lm_train_step() -> float:
    """Seconds per jitted LM train step (next-token loss, SGD) on the CPU
    fixture: batch 8, the SMALL transformer."""
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm,
    )

    model = lm.TransformerLM(**SMALL)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, model.seq_len),
                                0, model.vocab_size - 1, jnp.int32)

    def loss_fn(p, xs):
        return lm.next_token_loss(model, p, xs, None, deterministic=True)

    @jax.jit
    def step(p, xs):
        loss, grads = jax.value_and_grad(loss_fn)(p, xs)
        return jax.tree_util.tree_map(lambda a, g: a - 0.01 * g, p, grads), loss

    params, loss = step(params, tokens)     # compile, off the clock
    loss.block_until_ready()
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        params, loss = step(params, tokens)
    loss.block_until_ready()
    return (time.perf_counter() - t0) / iters


SUITE = {
    "decode_tick_s": bench_decode_tick,
    "paged_decode_tick_s": bench_paged_decode_tick,
    "prefill_chunk_s": bench_prefill_chunk,
    "spec_verify_s": bench_spec_verify,
    "lm_train_step_s": bench_lm_train_step,
}


def measure(names, runs: int) -> dict:
    """``runs`` interleaved passes over the suite; per metric the MEDIAN of
    its samples (interleaving decorrelates a transient machine hiccup from
    any single metric)."""
    samples: dict[str, list] = {name: [] for name in names}
    for _ in range(runs):
        for name in names:
            samples[name].append(SUITE[name]())
    return {name: {"median_s": statistics.median(vals), "samples": vals}
            for name, vals in samples.items()}


def gate(measured: dict, baseline: dict, default_tolerance: float) -> dict:
    """Compare measured medians against the baseline document. Returns the
    verdict dict (per-metric ratio/tolerance/pass + overall)."""
    out: dict = {"metrics": {}, "pass": True, "failures": []}
    base_metrics = baseline.get("metrics", {})
    for name, m in measured.items():
        base = base_metrics.get(name)
        row = dict(m)
        if base is None:
            row.update(baseline_s=None, ratio=None, tolerance=None,
                       **{"pass": False})
            out["pass"] = False
            out["failures"].append(f"{name}: not in baseline "
                                   f"(--update-baseline to add it)")
        else:
            tol = float(base.get("tolerance", default_tolerance))
            ratio = m["median_s"] / base["median_s"]
            ok = ratio <= 1.0 + tol
            row.update(baseline_s=base["median_s"], ratio=ratio,
                       tolerance=tol, **{"pass": ok})
            if not ok:
                out["pass"] = False
                out["failures"].append(
                    f"{name}: {m['median_s']:.6f}s vs baseline "
                    f"{base['median_s']:.6f}s = {ratio:.2f}x "
                    f"(allowed {1.0 + tol:.2f}x)")
        out["metrics"][name] = row
    # A metric the baseline pins but this run skipped is a hole in the gate.
    for name in base_metrics:
        if name not in measured:
            out["pass"] = False
            out["failures"].append(f"{name}: in baseline but not measured "
                                   f"(suite filter too narrow?)")
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--runs", type=int, default=5,
                   help="suite passes; each metric gates on its MEDIAN")
    p.add_argument("--suite", default=",".join(SUITE),
                   help="comma-separated metric subset")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="default fractional regression allowance for metrics "
                        "whose baseline entry pins none")
    p.add_argument("--out", default="",
                   help="write the run's JSON artifact here (the bench "
                        "trajectory document)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite --baseline from this run instead of gating")
    p.add_argument("--telemetry", default="",
                   help="append one bench_guard event per metric (JSONL)")
    p.add_argument("--inject-regression", default="",
                   help="TESTING ONLY: 'metric=factor' multiplies that "
                        "metric's measurement — proves the gate trips")
    args = p.parse_args(argv)

    names = [n.strip() for n in args.suite.split(",") if n.strip()]
    unknown = [n for n in names if n not in SUITE]
    if unknown:
        p.error(f"unknown suite metric(s) {unknown}; have {list(SUITE)}")

    # Fail the unseeded case BEFORE paying for the measurement: the suite is
    # minutes of model builds/compiles, and without a baseline there is
    # nothing to gate against anyway.
    if not args.update_baseline and not os.path.exists(args.baseline):
        print(f"[bench_guard] no baseline at {args.baseline} — run with "
              f"--update-baseline to seed it", file=sys.stderr)
        return EXIT_NO_BASELINE

    measured = measure(names, max(1, args.runs))
    if args.inject_regression:
        name, _, factor = args.inject_regression.partition("=")
        if name not in measured:
            p.error(f"--inject-regression names unknown metric {name!r}")
        measured[name]["median_s"] *= float(factor)

    host = _host_fingerprint()
    now = time.time()

    if args.update_baseline:
        doc = {
            "schema": 1,
            "created_unix": now,
            "runs": args.runs,
            "host": host,
            "tolerance_default": args.tolerance,
            "metrics": {name: {"median_s": m["median_s"],
                               "tolerance": args.tolerance}
                        for name, m in measured.items()},
        }
        os.makedirs(os.path.dirname(args.baseline) or ".", exist_ok=True)
        with open(args.baseline, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        for name, m in measured.items():
            print(f"[bench_guard] baseline {name} = {m['median_s']:.6f}s")
        print(f"[bench_guard] baseline written: {args.baseline}")
        return 0

    with open(args.baseline) as f:
        baseline = json.load(f)

    verdict = gate(measured, baseline,
                   baseline.get("tolerance_default", args.tolerance))
    base_host = baseline.get("host") or {}
    host_match = all(base_host.get(k) == host.get(k)
                     for k in ("platform", "device_kind", "machine"))
    if not host_match:
        print(f"[bench_guard] WARNING: host fingerprint differs from the "
              f"baseline's ({base_host.get('device_kind')} vs "
              f"{host.get('device_kind')}) — absolute seconds may not "
              f"transfer; treat this gate as advisory", file=sys.stderr)

    artifact = {
        "schema": 1,
        "unix_time": now,
        "runs": args.runs,
        "host": host,
        "host_matches_baseline": host_match,
        "baseline": args.baseline,
        **verdict,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.telemetry:
        # The jax-free appender: bench_guard events join the shared reader /
        # report-CLI machinery like every other telemetry stream.
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
            JsonlWriter,
        )

        w = JsonlWriter(args.telemetry)
        for name, row in verdict["metrics"].items():
            w.emit({"event": "bench_guard", "metric": name,
                    "median_s": row["median_s"],
                    "baseline_s": row.get("baseline_s"),
                    "ratio": row.get("ratio"),
                    "tolerance": row.get("tolerance"),
                    "pass": row["pass"], "runs": args.runs,
                    "unix_time": now})
        w.close()

    for name, row in sorted(verdict["metrics"].items()):
        ratio = row.get("ratio")
        print(f"[bench_guard] {name}: median {row['median_s']:.6f}s"
              + (f"  baseline {row['baseline_s']:.6f}s  ratio {ratio:.2f}x"
                 if ratio is not None else "  (no baseline entry)")
              + ("  ok" if row["pass"] else "  REGRESSION"))
    if not verdict["pass"]:
        for failure in verdict["failures"]:
            print(f"[bench_guard] FAIL {failure}", file=sys.stderr)
        return EXIT_REGRESSION
    print(f"[bench_guard] pass: {len(verdict['metrics'])} metric(s) within "
          f"tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
