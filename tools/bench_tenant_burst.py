"""Two-tenant burst bench: the committed multi-tenant SLO-tier artifact.

The contended-serving scenario DESIGN.md §22 is judged by: a paid high-SLO
tenant offers a steady Poisson stream while a best-effort tenant slams the
same engine with a ~3x burst load. Three legs, one JSON document:

- **baseline** — the paid schedule alone (unloaded): its TTFT p95 is the
  reference the loaded run is held to;
- **burst** — the SAME paid schedule (same seed, same prompts, same arrival
  offsets) plus the best-effort bursts. The gates:

  1. paid TTFT p95 within ``--ttft-slack`` (default 15%) of the unloaded
     baseline, past ONE measured scheduling quantum — the pass (decode
     program + chunk budget) in flight when a request arrives, which is
     host program granularity, not policy (sub-ms on accelerators; multi-ms
     on this CPU where one decode step costs ~3-4ms against an ~8ms
     baseline TTFT). Median over ``--repeats`` pairs (one-sided noise, the
     ``bench_guard`` rationale). The squeeze lands on best-effort, not on
     the promise; the raw unadjusted ratio is committed alongside;
  2. the squeeze is REAL: sheds + preemptions > 0 (best-effort work was
     displaced/refused and/or parked mid-decode);
  3. zero lost requests: every accepted submit resolves (ok, timeout, or
     shed — never a hung future), and every refusal is a typed
     QueueFull/QuotaExceeded/Shed;
  4. zero orphan traces (the burst leg runs fully traced; every trace ends
     in a terminal resolve span — parked/resumed requests included);

- **oracle** — every request that finished ``ok`` in the burst leg (the
  preempted-then-resumed best-effort ones especially) is re-decoded alone on
  a fresh engine and must match token-for-token: park/resume is a schedule
  change, never a math change.

Exit codes: 0 = all gates pass, 3 = a gate failed (the non-blocking CI
``tenant-smoke`` job runs ``--quick`` and uploads the summary either way).

Usage::

    python tools/bench_tenant_burst.py --out-dir bench_results/tenant_burst_cpu
    python tools/bench_tenant_burst.py --quick --out-dir /tmp/tb
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PAID_SLO = "ttft=0.5,e2e=30"


def build_model(args):
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm,
    )

    model = lm.TransformerLM(
        vocab_size=args.num_levels + 1, seq_len=args.seq_len,
        embed_dim=args.embed_dim, num_layers=args.num_layers,
        num_heads=args.num_heads)
    params = model.init({"params": jax.random.PRNGKey(args.seed)},
                        jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    if args.checkpoint:
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint,
        )

        params = checkpoint.load_params_or_state(args.checkpoint, params)
    return model, params


def make_engine(model, params, args):
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        ContinuousBatchingEngine,
        Request,
    )

    eng = ContinuousBatchingEngine(
        model, params, num_slots=args.num_slots, seed=args.seed,
        prefill_chunk_sizes=(args.chunk,),
        # Budget sized so one paid prompt's whole chunk plan fits a single
        # engine pass: a decode step interleaved mid-prefill is pure TTFT
        # tax on the high tier (the budget still bounds a pathological
        # prompt at 16 chunks/step — decode never starves for long).
        prefill_chunk_budget=16,
        prefix_cache_entries=args.prefix_cache)
    # Warm every program (decode, chunk prefill, install, snapshot) before
    # anything is measured: TTFT percentiles must measure the schedule, not
    # XLA compiles.
    rng = np.random.default_rng(args.seed + 17)
    wp = rng.integers(0, args.num_levels,
                      size=min(args.chunk, args.seq_len - 4)).astype(np.int32)
    eng.run([Request(prompt=wp, max_new_tokens=2)])
    eng.run([Request(prompt=wp, max_new_tokens=2)])      # cache-hit install
    eng.reset_stats()
    return eng


def make_schedules(args):
    """Seeded arrival schedules: ``(offset_s, prompt, max_new)`` triples.
    Paid is Poisson at ``--paid-rate``; best-effort arrives in back-to-back
    bursts whose aggregate offered rate is ~``--burst-factor`` times paid's."""
    rng = np.random.default_rng(args.seed + 1)
    paid = []
    t = 0.0
    for _ in range(args.paid_requests):
        t += float(rng.exponential(1.0 / args.paid_rate))
        plen = int(rng.integers(args.paid_prompt_min, args.paid_prompt_max))
        prompt = rng.integers(0, args.num_levels, size=plen).astype(np.int32)
        paid.append((t, prompt, int(rng.integers(8, args.paid_max_new + 1))))
    horizon = t
    free = []
    n_free = int(args.paid_requests * args.burst_factor)
    burst_gap = horizon / max(1, (n_free // args.burst_size))
    t = 0.05
    for i in range(n_free):
        if i and i % args.burst_size == 0:
            t += burst_gap                       # next spike
        plen = int(rng.integers(4, args.free_prompt_max))
        prompt = rng.integers(0, args.num_levels, size=plen).astype(np.int32)
        free.append((t, prompt,
                     int(rng.integers(args.free_max_new // 2,
                                      args.free_max_new + 1))))
    return paid, free


def run_leg(model, params, args, paid_sched, free_sched, *,
            tele_path: str = "", trace_dir: str = ""):
    import gc

    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        Server,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.scheduler import (
        QueueFull,
        QuotaExceeded,
        Shed,
        parse_tenants,
    )

    # The service classes: paid = top tier with the TTFT promise; free =
    # weight-1 preemptible best-effort. No slot cap: eviction IS the
    # protection under test — a capped variant idles the reserved slot
    # between paid arrivals and serializes overlapping paid requests.
    tenants = parse_tenants(
        f"paid:w=4,prio=2,slo={PAID_SLO.replace('=', ':').replace(',', '+')};"
        f"free:w=1,preempt=1")
    eng = make_engine(model, params, args)
    srv = Server(eng, tenants=tenants, max_pending=args.max_pending,
                 telemetry=tele_path or None,
                 trace=(os.path.join(trace_dir, "server.jsonl")
                        if trace_dir else None)).start()
    lock = threading.Lock()
    futures: dict[str, list] = {"paid": [], "free": []}
    refused = {"paid": 0, "free": 0}
    t0 = time.monotonic()

    def offer(tenant, sched):
        for off, prompt, max_new in sched:
            delay = t0 + off - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                fut = srv.submit(prompt, max_new_tokens=max_new,
                                 tenant=tenant)
            except (QueueFull, QuotaExceeded, Shed):
                with lock:
                    refused[tenant] += 1
                continue
            with lock:
                futures[tenant].append(fut)

    threads = [threading.Thread(target=offer, args=("paid", paid_sched))]
    if free_sched:
        threads.append(threading.Thread(target=offer, args=("free",
                                                            free_sched)))
    # GC pinned for the measured window: a gen-2 collection pause (~20-30ms
    # on this class of box) landing inside one chunk program poisons that
    # request's TTFT — and the burst leg allocates ~5x the objects of the
    # baseline, so the pauses land one-sidedly on the loaded leg. Real
    # serving processes pin/tune the collector for the same reason; the
    # bench measures the scheduler, not CPython's collector.
    gc.collect()
    gc.disable()
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        comps = {t: [f.result(timeout=300) for f in futures[t]]
                 for t in futures}
        srv.stop()
    finally:
        gc.enable()
        gc.collect()

    def pcts(vals):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
            percentiles,
        )

        return percentiles([v for v in vals if v is not None])

    # The engine's scheduling QUANTUM on this host: an arrival mid-pass
    # waits for the pass in flight — up to one decode program plus the
    # chunk budget's worth of prefill invocations — before the scheduler
    # can even see it. Both terms are measured from THIS leg (mean chunk
    # wall from the engine's ledger; a decode pass from the paid stream's
    # median inter-token time), so the latency gate can separate "the
    # scheduler failed to protect the tier" from "one program's granularity
    # on this host" — on accelerator-class program times (~100us) the
    # quantum is sub-ms and the gate degenerates to the pure ratio.
    chunk_wall = (eng.prefill_wall_s / eng.prefill_invocations
                  if eng.prefill_invocations else 0.0)
    tpots = sorted(c.tpot_s for c in comps["paid"] if c.tpot_s is not None)
    decode_pass = tpots[len(tpots) // 2] if tpots else 0.0
    out = {"refused": refused,
           "preemptions": eng.preemptions, "resumes": eng.resumes,
           "quantum_s": eng.prefill_chunk_budget * chunk_wall + decode_pass,
           "queue": srv.queue.snapshot(), "tenants": {}}
    for tenant, cs in comps.items():
        out["tenants"][tenant] = {
            "submitted": len(cs) + refused[tenant],
            "resolved": len(cs),
            "ok": sum(c.ok for c in cs),
            "timeout": sum(c.finish == "timeout" for c in cs),
            "shed": sum(c.finish == "shed" for c in cs),
            "preemptions": sum(c.preemptions for c in cs),
            "ttft_s": pcts([c.ttft_s for c in cs]),
            "e2e_s": pcts([c.e2e_s for c in cs]),
        }
    return out, comps, eng


def oracle_check(model, params, args, comps) -> dict:
    """Re-decode every ok completion alone on a fresh engine: the burst leg's
    emitted stream (preempted/resumed requests included) must be
    token-identical — park/resume and tenant scheduling are schedule changes,
    never math changes."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.engine import (
        ContinuousBatchingEngine,
        Request,
    )

    eng = ContinuousBatchingEngine(model, params, num_slots=4,
                                   seed=args.seed,
                                   prefill_chunk_sizes=(args.chunk,))
    checked = mismatched = preempted_checked = 0
    for cs in comps.values():
        for c in cs:
            if not c.ok:
                continue
            want = eng.run([Request(prompt=c.request.prompt,
                                    max_new_tokens=c.request.max_new_tokens)]
                           )[0].tokens
            checked += 1
            preempted_checked += c.preemptions > 0
            if not np.array_equal(want, c.tokens):
                mismatched += 1
    return {"checked": checked, "preempted_checked": preempted_checked,
            "mismatched": mismatched}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--out-dir", default="bench_results/tenant_burst_cpu")
    p.add_argument("--checkpoint", default="",
                   help="trained params (default: seeded init — identity "
                        "and latency gates hold either way)")
    p.add_argument("--quick", action="store_true",
                   help="CI sizing: fewer requests, same gates")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--seq-len", type=int, default=384)
    p.add_argument("--num-levels", type=int, default=16)
    p.add_argument("--embed-dim", type=int, default=96)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-slots", type=int, default=4)
    p.add_argument("--chunk", type=int, default=32)
    p.add_argument("--prefix-cache", type=int, default=16)
    p.add_argument("--max-pending", type=int, default=8)
    p.add_argument("--paid-requests", type=int, default=64)
    p.add_argument("--paid-rate", type=float, default=8.0)
    p.add_argument("--paid-prompt-min", type=int, default=128)
    p.add_argument("--paid-prompt-max", type=int, default=224)
    p.add_argument("--paid-max-new", type=int, default=24)
    p.add_argument("--burst-factor", type=float, default=3.0)
    p.add_argument("--burst-size", type=int, default=12)
    p.add_argument("--free-prompt-max", type=int, default=32)
    p.add_argument("--free-max-new", type=int, default=160)
    p.add_argument("--ttft-slack", type=float, default=0.15,
                   help="paid TTFT p95 may grow by at most this fraction "
                        "under the burst (median ratio over --repeats)")
    p.add_argument("--repeats", type=int, default=3,
                   help="baseline/burst pairs to run; the latency gate takes "
                        "the MEDIAN ratio (bench_guard's rationale, §21: "
                        "shared-machine noise is one-sided — an OS hiccup "
                        "inflates one pair's p95, nothing ever deflates it)")
    args = p.parse_args(argv)
    if args.quick:
        # CI sizing: fewer requests and one pair mean p95 is the statistics
        # of a handful of samples on a shared noisy runner — the smoke gate
        # is a gross-regression trip wire (the FIFO-prefill bug was a 9.8x
        # inflation), not the committed 15% claim, which the full
        # median-of-repeats artifact run holds.
        args.paid_requests = 24
        args.repeats = 1
        if args.ttft_slack == 0.15:
            args.ttft_slack = 0.5
    os.makedirs(args.out_dir, exist_ok=True)

    model, params = build_model(args)
    paid_sched, free_sched = make_schedules(args)
    print(f"paid: {len(paid_sched)} requests over "
          f"{paid_sched[-1][0]:.1f}s; free: {len(free_sched)} requests "
          f"in bursts of {args.burst_size}")

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        trace as trace_mod,
    )

    trace_dir = os.path.join(args.out_dir, "trace")
    os.makedirs(trace_dir, exist_ok=True)
    tele = os.path.join(args.out_dir, "serve_burst.jsonl")
    repeats = []
    base = burst = comps = oracle = tsum = None
    for rep in range(args.repeats):
        print(f"== pair {rep + 1}/{args.repeats} — "
              f"leg A: paid alone (unloaded baseline)")
        base, _, _ = run_leg(model, params, args, paid_sched, [])
        base_p95 = base["tenants"]["paid"]["ttft_s"]["p95"]
        print(f"   paid ttft p95 {base_p95 * 1e3:.1f}ms "
              f"(p50 {base['tenants']['paid']['ttft_s']['p50'] * 1e3:.1f}ms)")
        print(f"== pair {rep + 1}/{args.repeats} — "
              f"leg B: paid + {args.burst_factor:g}x best-effort burst "
              f"(traced)")
        for stale in os.listdir(trace_dir):  # span files APPEND across runs
            os.unlink(os.path.join(trace_dir, stale))
        burst, comps, _ = run_leg(model, params, args, paid_sched,
                                  free_sched, tele_path=tele,
                                  trace_dir=trace_dir)
        burst_p95 = burst["tenants"]["paid"]["ttft_s"]["p95"]
        # The queue's lane tally covers BOTH shed flavors (refused arrivals
        # AND displaced victims); the completion-side count would double-
        # charge the displaced ones.
        sheds = burst["queue"]["shed"]
        quantum = burst["quantum_s"]
        adj_ratio = max(burst_p95 - quantum, 0.0) / base_p95
        print(f"   paid ttft p95 {burst_p95 * 1e3:.1f}ms  "
              f"(raw ratio {burst_p95 / base_p95:.3f}x; "
              f"{adj_ratio:.3f}x past the {quantum * 1e3:.1f}ms "
              f"scheduling quantum)")
        print(f"   squeeze: {burst['preemptions']} preemption(s), "
              f"{burst['resumes']} resume(s), {sheds} shed(s), "
              f"{burst['queue']['rejected']} queue-full, "
              f"free refused {burst['refused']['free']}")

        print("   oracle: re-decode every ok completion on a fresh engine")
        oracle = oracle_check(model, params, args, comps)
        print(f"   {oracle['checked']} checked "
              f"({oracle['preempted_checked']} preempted-then-resumed), "
              f"{oracle['mismatched']} mismatched")
        spans, _ = trace_mod.read_spans([trace_dir])
        tsum = trace_mod.summarize_traces(spans)
        print(f"   trace: {tsum['traces']} traces, {tsum['spans']} spans, "
              f"{tsum['orphans']} orphan(s)")
        offered = {"paid": len(paid_sched), "free": len(free_sched)}
        # Lost = offered (the schedule, an INDEPENDENT count) minus settled
        # futures minus typed refusals — row["submitted"] is derived from
        # the same future list as "resolved", which would make this gate a
        # tautology.
        lost = sum(
            offered[t] - row["resolved"] - burst["refused"][t]
            for t, row in burst["tenants"].items())
        repeats.append({
            "baseline_ttft_p95_s": base_p95,
            "burst_ttft_p95_s": burst_p95,
            "ratio": burst_p95 / base_p95,
            "quantum_s": quantum,
            "quantum_adjusted_ratio": adj_ratio,
            "sheds": sheds,
            "preemptions": burst["preemptions"],
            "oracle": oracle,
            "orphans": tsum["orphans"],
            "lost": lost,
        })

    ratios = sorted(r["ratio"] for r in repeats)
    adj_ratios = sorted(r["quantum_adjusted_ratio"] for r in repeats)
    median_ratio = ratios[len(ratios) // 2]
    median_adj = adj_ratios[len(adj_ratios) // 2]
    sheds = sum(r["sheds"] for r in repeats)
    preemptions = sum(r["preemptions"] for r in repeats)
    gates = {
        # Median over the pairs: one-sided scheduling noise (a 20ms OS
        # hiccup inside one prefill) inflates a single pair's p95 but can
        # never deflate one — the median is the honest location estimate on
        # a shared box (same rationale as tools/bench_guard.py). The gate
        # allows ONE measured scheduling quantum (the pass in flight when a
        # paid request arrives — see run_leg) on top of the 15%: that term
        # is this host's program granularity, not a scheduling failure, and
        # vanishes on accelerator-class program times; the raw ratio rides
        # along in the artifact for exactly that comparison.
        "paid_ttft_p95_ratio": {
            "value": median_adj,
            "median_raw_ratio": median_ratio,
            "per_repeat_raw": ratios,
            "per_repeat_quantum_adjusted": adj_ratios,
            "quantum_s": [r["quantum_s"] for r in repeats],
            "limit": 1.0 + args.ttft_slack,
            "pass": median_adj <= 1.0 + args.ttft_slack},
        # The ISSUE's acceptance bar: the squeeze landed on best-effort —
        # via eviction (preemptions), displacement/refusal (sheds), or both.
        "squeeze_absorbed": {
            "sheds": sheds, "preemptions": preemptions,
            "pass": sheds + preemptions > 0},
        "token_identity": {
            "checked": sum(r["oracle"]["checked"] for r in repeats),
            "preempted_checked": sum(r["oracle"]["preempted_checked"]
                                     for r in repeats),
            "mismatched": sum(r["oracle"]["mismatched"] for r in repeats),
            "pass": all(r["oracle"]["mismatched"] == 0 for r in repeats)
            and any(r["oracle"]["preempted_checked"] > 0 for r in repeats)},
        "zero_lost": {"lost": sum(r["lost"] for r in repeats),
                      "pass": all(r["lost"] == 0 for r in repeats)},
        "zero_orphans": {"orphans": sum(r["orphans"] for r in repeats),
                         "pass": all(r["orphans"] == 0 for r in repeats)},
    }
    doc = {
        "bench": "tenant_burst",
        "config": {k: getattr(args, k.replace("-", "_"))
                   for k in ("seq_len", "embed_dim", "num_layers",
                             "num_slots", "chunk", "max_pending",
                             "paid_requests", "paid_rate", "burst_factor",
                             "burst_size", "seed", "quick", "repeats")},
        "paid_slo": PAID_SLO,
        "repeats": repeats,
        "baseline": base,                     # the LAST pair's full legs
        "burst": burst,
        "oracle": oracle,
        "trace": {"traces": tsum["traces"], "spans": tsum["spans"],
                  "orphans": tsum["orphans"],
                  "segments": tsum["segments"]},
        "gates": gates,
        "pass": all(g["pass"] for g in gates.values()),
    }
    out = os.path.join(args.out_dir, "summary.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, default=float)
    print(f"summary -> {out}  ({'PASS' if doc['pass'] else 'FAIL'})")
    for name, g in gates.items():
        print(f"   gate {name}: {'ok' if g['pass'] else 'FAIL'} "
              f"{ {k: v for k, v in g.items() if k != 'pass'} }")
    return 0 if doc["pass"] else 3


if __name__ == "__main__":
    sys.exit(main())
