"""Speculative-decoding A/B on the chat scenario — the committed-artifact bench.

Runs the SAME seeded multi-turn chat workload (``tools/serve_loadgen.py
--scenario chat`` semantics: each turn resubmits prior context + the model's
reply + fresh user tokens — the traffic n-gram self-speculation exists for)
through a spec-off and a spec-on serving stack built from a REAL checkpoint,
and writes one JSON document with the three numbers the subsystem is judged
by:

- **token_match_rate** — greedy speculative decode must be token-identical to
  plain decode (1.0, compared request-by-request across the two runs);
- **accepted_tokens_per_step** — emitted tokens per slot per verify-program
  invocation (plain decode is exactly 1.0; every 0.1 above it is cache-read
  amortization);
- **invocation_ratio** — decode program invocations per generated token,
  A over B (>= 1.5x fewer invocations is the acceptance bar: the per-request
  HBM lever, since each invocation streams the full KV working set).

The engine-level pair runs in-process (deterministic, counters readable);
``--fleet`` additionally drives a 2-replica router fleet through
``serve_loadgen`` for both sides and embeds the fleet summaries (fleet-wide
tokens/s + the router's aggregated spec ledger). Without ``--checkpoint`` the
tool first trains the pixel LM on the committed MNIST IDX fixture
(``train.lm``, the quant A/B's recipe) so the artifact always reflects a
trained model, not a random init.

Usage::

    python tools/bench_spec_ab.py --out bench_results/spec_ab_cpu.json
    python tools/bench_spec_ab.py --checkpoint results/model_lm.ckpt --fleet
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "fixtures", "mnist_idx")


def ensure_checkpoint(args) -> str:
    """``--checkpoint`` verbatim, else train the default pixel LM on the
    committed MNIST fixture (real gradients, real perplexity — the artifact's
    'real checkpoint' requirement) and return the saved TrainState path."""
    if args.checkpoint:
        return args.checkpoint
    cached = os.path.join(args.workdir, "model_lm.ckpt")
    if os.path.exists(cached):
        print(f"reusing trained checkpoint {cached}")
        return cached
    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        lm as lm_train,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
        LMConfig,
    )

    os.makedirs(args.workdir, exist_ok=True)
    # The committed fixture is 128 train / 100 test images; both batch knobs
    # must divide their splits.
    cfg = LMConfig(epochs=args.train_epochs, batch_size=32, eval_batch=50,
                   data_dir=args.data_dir, generate=0,
                   results_dir=args.workdir,
                   images_dir=os.path.join(args.workdir, "images"))
    print(f"training checkpoint: {args.train_epochs} epochs on {args.data_dir}")
    lm_train.main(cfg)
    return os.path.join(args.workdir, "model_lm.ckpt")


def chat_args(args):
    """The ``run_chat`` knob namespace (mirrors serve_loadgen's chat flags)."""
    return argparse.Namespace(
        seed=args.seed, sessions=args.sessions, turns=args.turns,
        turn_user_tokens=4, max_new_tokens=args.max_new_tokens,
        seq_len=784, temperature=0.0, top_k=0, top_p=1.0,
        prompt_dist="custom", prompt_lens=args.prompt_lens)


def run_side(model, params, args, loadgen, *, spec: str) -> tuple[dict, dict]:
    """One in-process chat run; returns (metrics, completions-by-prompt)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
        Request,
        Server,
    )

    kw = {}
    if spec != "off":
        kw = dict(spec=spec, spec_k=args.spec_k)
        if spec == "draft-lm":
            # The replica's draft-LM recipe: 1 layer, half the embed width,
            # seeded init (acceptance is the draft model's quality — train
            # one and point the fleet legs' --draft-checkpoint at it for a
            # serious draft-LM artifact; ngram is the committed default).
            import jax
            import jax.numpy as jnp

            from csed_514_project_distributed_training_using_pytorch_tpu.models import (
                lm,
            )
            from csed_514_project_distributed_training_using_pytorch_tpu.serving.spec.draft_lm import (
                DraftLMDrafter,
            )

            dm = lm.TransformerLM(vocab_size=model.vocab_size,
                                  seq_len=model.seq_len,
                                  embed_dim=model.embed_dim // 2,
                                  num_layers=1, num_heads=model.num_heads)
            dp = dm.init({"params": jax.random.PRNGKey(args.seed + 1)},
                         jnp.zeros((1, dm.seq_len), jnp.int32))["params"]
            kw["drafter"] = DraftLMDrafter(dm, dp)
    engine = ContinuousBatchingEngine(model, params,
                                      num_slots=args.num_slots, **kw)
    # Warmup: compile decode/verify + every chunk size, then measure from a
    # clean ledger (the loadgen --warmup recipe).
    rng = np.random.default_rng(args.seed + 17)
    warm = rng.integers(0, model.vocab_size - 1, size=48).astype(np.int32)
    engine.run([Request(prompt=warm, max_new_tokens=4)])
    engine.run([Request(prompt=np.zeros(0, np.int32), max_new_tokens=2)])
    engine.reset_stats()
    server = Server(engine).start()
    t0 = time.monotonic()
    comps, rejected, _ = loadgen.run_chat(server, chat_args(args),
                                          model.vocab_size)
    wall = time.monotonic() - t0
    server.stop()
    assert rejected == 0 and all(c.ok for c in comps)
    new_tokens = sum(c.new_tokens for c in comps)
    metrics = {
        "spec": spec,
        "spec_k": args.spec_k if spec != "off" else None,
        "requests": len(comps),
        "new_tokens": new_tokens,
        "wall_s": wall,
        "tokens_per_s": new_tokens / wall,
        "decode_invocations": engine.steps,
        "generated_tokens": engine.generated_tokens,
        "invocations_per_token": engine.steps / engine.generated_tokens,
        "spec_stats": engine.spec_stats(),
        "decode_compilations": engine.trace_count,
        "verify_compilations": dict(engine.verify_trace_counts),
        "prefill_compilations": dict(engine.prefill_trace_counts),
    }
    by_prompt = {}
    for c in comps:
        by_prompt[tuple(int(x) for x in c.request.prompt)] = \
            np.asarray(c.tokens, np.int32)
    return metrics, by_prompt


def run_fleet_side(args, loadgen, ckpt: str, *, spec: str) -> dict:
    """One 2-replica router-fleet chat run via serve_loadgen; returns its
    --summary-json document (fleet tokens/s + the router's spec ledger)."""
    out = os.path.join(args.workdir, f"fleet_{spec}.json")
    argv = ["--replicas", "2", "--scenario", "chat",
            "--sessions", str(args.sessions), "--turns", str(args.turns),
            "--max-new-tokens", str(args.max_new_tokens),
            "--prompt-lens", args.prompt_lens,
            "--num-slots", str(args.num_slots),
            "--checkpoint", ckpt, "--seed", str(args.seed),
            "--spec", spec, "--spec-k", str(args.spec_k),
            "--summary-json", out]
    rc = loadgen.main(argv)
    if rc != 0:
        raise SystemExit(f"fleet leg ({spec}) failed with rc {rc}")
    with open(out) as f:
        return json.load(f)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--checkpoint", default="",
                   help="trained train.lm TrainState/params (default: train "
                        "one on the committed MNIST fixture first)")
    p.add_argument("--train-epochs", type=int, default=12)
    p.add_argument("--data-dir", default=_FIXTURE)
    p.add_argument("--workdir", default="/tmp/spec_ab_work",
                   help="scratch dir for the trained checkpoint + fleet "
                        "summaries")
    p.add_argument("--spec", default="ngram", choices=("ngram", "draft-lm"))
    p.add_argument("--spec-k", type=int, default=4)
    p.add_argument("--num-slots", type=int, default=4)
    p.add_argument("--sessions", type=int, default=6)
    p.add_argument("--turns", type=int, default=3)
    p.add_argument("--max-new-tokens", type=int, default=48)
    p.add_argument("--prompt-lens", default="32,64,96")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--fleet", action="store_true",
                   help="also run the 2-replica router-fleet A/B and embed "
                        "both fleet summaries")
    p.add_argument("--gate-tokens-per-step", type=float, default=1.5,
                   help="minimum accepted-tokens/step (the acceptance bar)")
    p.add_argument("--gate-invocation-ratio", type=float, default=1.5,
                   help="minimum A/B decode-invocations-per-token ratio")
    p.add_argument("--out", default="bench_results/spec_ab_cpu.json")
    args = p.parse_args(argv)

    import importlib.util

    spec_mod = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(_REPO, "tools", "serve_loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(loadgen)

    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    ckpt = ensure_checkpoint(args)
    model = lm.TransformerLM()          # the train.lm default pixel LM
    import jax.numpy as jnp

    init = model.init({"params": jax.random.PRNGKey(0)},
                      jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    params = checkpoint.load_params_or_state(ckpt, init)

    print("== A: spec off")
    a, toks_a = run_side(model, params, args, loadgen, spec="off")
    print(f"   {a['new_tokens']} tokens in {a['decode_invocations']} "
          f"invocations, {a['tokens_per_s']:.1f} tokens/s")
    print(f"== B: spec {args.spec} k={args.spec_k}")
    b, toks_b = run_side(model, params, args, loadgen, spec=args.spec)
    sp = b["spec_stats"]
    print(f"   {b['new_tokens']} tokens in {b['decode_invocations']} "
          f"invocations, {b['tokens_per_s']:.1f} tokens/s, "
          f"accepted tok/step {sp['accepted_tokens_per_step']:.2f}, "
          f"acceptance rate {sp['acceptance_rate']:.2f}")

    # Greedy chat is deterministic per prompt, so the two runs' completions
    # join on the exact prompt tokens.
    assert toks_a.keys() == toks_b.keys(), "workloads diverged"
    matched = total = 0
    for key in toks_a:
        ta, tb = toks_a[key], toks_b[key]
        total += 1
        matched += int(len(ta) == len(tb) and bool(np.array_equal(ta, tb)))
    token_match_rate = matched / total
    invocation_ratio = (a["invocations_per_token"]
                        / b["invocations_per_token"])
    doc = {
        "metric": f"speculative-decoding A/B ({args.spec} k={args.spec_k}, "
                  f"chat scenario)",
        "checkpoint": ckpt,
        "trained_epochs": None if args.checkpoint else args.train_epochs,
        "scenario": {"sessions": args.sessions, "turns": args.turns,
                     "max_new_tokens": args.max_new_tokens,
                     "prompt_lens": args.prompt_lens,
                     "num_slots": args.num_slots, "seed": args.seed},
        "a": a,
        "b": b,
        "token_match_rate": token_match_rate,
        "accepted_tokens_per_step": sp["accepted_tokens_per_step"],
        "acceptance_rate": sp["acceptance_rate"],
        "invocation_ratio": invocation_ratio,
        "tokens_per_s_ratio": b["tokens_per_s"] / a["tokens_per_s"],
    }
    print(f"== token match {token_match_rate:.3f}, "
          f"{invocation_ratio:.2f}x fewer invocations/token, "
          f"tokens/s ratio {doc['tokens_per_s_ratio']:.2f}x")

    if args.fleet:
        print("== fleet legs (2 replicas each)")
        doc["fleet"] = {"a": run_fleet_side(args, loadgen, ckpt, spec="off"),
                        "b": run_fleet_side(args, loadgen, ckpt,
                                            spec=args.spec)}

    problems = []
    if token_match_rate < 1.0:
        problems.append(f"token match {token_match_rate:.3f} < 1.0")
    if sp["accepted_tokens_per_step"] < args.gate_tokens_per_step:
        problems.append(f"accepted tok/step {sp['accepted_tokens_per_step']:.2f} "
                        f"< {args.gate_tokens_per_step}")
    if invocation_ratio < args.gate_invocation_ratio:
        problems.append(f"invocation ratio {invocation_ratio:.2f} "
                        f"< {args.gate_invocation_ratio}")
    doc["gates_passed"] = not problems
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"artifact -> {args.out}")
    if problems:
        print("GATES FAILED: " + "; ".join(problems))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
