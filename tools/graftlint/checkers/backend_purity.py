"""backend-purity: declared jax-free modules must not reach jax, even transitively.

The rule (rules.BACKEND_FREE): the fleet-side modules — router, autoscaler,
scheduler, supervisor, the jsonl/trace writers, the loadgen — must be importable
without paying for (let alone initializing) a jax backend. The failure mode is
never a literal ``import jax`` in the file; it is three hops away: module A
imports B for a dataclass, B imports C for a helper, C imports jax at top
level. Or subtler — the PARENT PACKAGE: an eager ``from .step import ...`` in
``train/__init__.py`` made every ``from train.launch import Fleet`` (the
router's and supervisor's fleet handle) execute jax's import, which is exactly
what this checker caught on the tree it first ran against.

Lazy (function-body) imports are the sanctioned escape: they defer the cost to
the call that needs it, and the graph records but does not traverse them. A
deliberately jax-reaching top-level import (the root package's env-gated
platform-pin shim) carries a line pragma with its justification.

The finding points at the first import line in the DECLARED module whose edge
begins the offending chain, and the message spells out the full chain — the
fix is usually to make one hop lazy, and the chain says which.
"""

from __future__ import annotations

from tools.graftlint import rules
from tools.graftlint.core import Checker, Finding, Module


class BackendPurity(Checker):
    name = "backend-purity"
    description = ("declared backend-free modules must not reach "
                   f"{'/'.join(rules.BACKEND_MODULES)} through any top-level "
                   "import, transitively (incl. parent-package __init__s)")

    def visit(self, module: Module, graph) -> list[Finding]:
        if not rules.matches(graph, module, rules.BACKEND_FREE):
            return []
        closure = graph.closure(module.name, skip_check=self.name)
        findings: list[Finding] = []
        reported: set[str] = set()
        for reached in sorted(closure):
            top = reached.split(".")[0]
            if top not in rules.BACKEND_MODULES or top in reported:
                continue
            reported.add(top)
            chain = graph.chain(closure, reached)
            # Attribute the finding to the first hop out of the declared
            # module (the import statement the fix will touch or make lazy).
            line = _first_hop_line(closure, chain, module.name)
            findings.append(Finding(
                path=module.path, line=line, col=1, check=self.name,
                message=(f"declared backend-free but reaches '{reached}' "
                         f"via top-level imports: {' -> '.join(chain)}")))
        return findings


def _first_hop_line(closure, chain: list[str], start: str) -> int:
    """Line (in the declared module) of the edge that leaves it first.

    Parent-package hops carry line 0 (they are implied, not written); fall back
    to 1 so the finding still lands at the top of the file.
    """
    for hop in chain[1:]:
        via, line = closure[hop]
        if via == start and line:
            return line
    return 1
