"""The checker registry: one module per house rule, assembled here.

Adding a checker (DESIGN.md §19): write a ``Checker`` subclass in a new module
under ``checkers/``, give it a unique kebab-case ``name`` (that name is the
pragma/baseline/CLI handle), import it below, append an instance to
``ALL_CHECKERS``, and add a true-positive + false-positive fixture pair to
``tests/test_graftlint.py``. The meta-test then holds the whole repo to it.
"""

from __future__ import annotations

from tools.graftlint.checkers.backend_purity import BackendPurity
from tools.graftlint.checkers.host_sync import HostSyncHazard
from tools.graftlint.checkers.process0_gate import Process0Gate
from tools.graftlint.checkers.resolve_guard import ResolveGuard
from tools.graftlint.checkers.retrace import RetraceHazard
from tools.graftlint.checkers.telemetry_schema import TelemetrySchema

ALL_CHECKERS = (
    BackendPurity(),
    ResolveGuard(),
    TelemetrySchema(),
    Process0Gate(),
    HostSyncHazard(),
    RetraceHazard(),
)

CHECKS_BY_NAME = {c.name: c for c in ALL_CHECKERS}

__all__ = ["ALL_CHECKERS", "CHECKS_BY_NAME"]
