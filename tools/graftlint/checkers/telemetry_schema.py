"""telemetry-schema: every emitted event kind must be in the central registry.

PR 8 added the report-side drift footer ("N unrecognized events"); this checker
kills the drift AT THE SOURCE. The registry — ``utils/telemetry_events.py``'s
``EVENT_KINDS`` dict literal — is the one sanctioned vocabulary;
``tools/telemetry_report.py::KNOWN_EVENTS`` is derived from it, and this
checker closes the loop: any ``{"event": "<literal>"}`` dict display (or
``.setdefault("event", "<literal>")``) in the package, tools, or bench scripts
whose kind is not registered is a lint error. Adding an event kind therefore
HAS to touch the registry, which is what keeps emitters and report tools
agreeing forever.

The registry is read by AST, never imported: graftlint must run on a bare
Python with no repo deps installed. That is also why EVENT_KINDS must stay a
pure dict literal (its module docstring says so) — a computed key would be
invisible here, and this checker flags the registry itself if it stops being
parseable.
"""

from __future__ import annotations

import ast

from tools.graftlint import rules
from tools.graftlint.core import Checker, Finding, Module


def load_registry(graph) -> tuple[set[str] | None, str]:
    """Extract the registered kinds from the registry module's AST.

    Returns ``(kinds, registry_path)``; ``kinds`` is None when the registry is
    missing or not a pure dict literal (the checker then reports on the
    registry instead of silently passing everything).
    """
    path = rules.package_relpath(graph, rules.EVENT_REGISTRY)
    mod = graph.module_for_relpath(path)
    if mod is None:
        return None, path
    for node in ast.walk(mod.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == rules.EVENT_REGISTRY_NAME
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, ast.Dict):
            return None, path
        kinds: set[str] = set()
        for key in value.keys:
            if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                return None, path       # computed key: registry not static
            kinds.add(key.value)
        return kinds, path
    return None, path


class TelemetrySchema(Checker):
    name = "telemetry-schema"
    description = ("every {\"event\": \"...\"} literal must use a kind "
                   "registered in utils/telemetry_events.py::EVENT_KINDS")

    def visit(self, module: Module, graph) -> list[Finding]:
        kinds, registry_path = load_registry(graph)
        if kinds is None:
            if module.path != registry_path and graph.module_for_relpath(
                    registry_path) is not None:
                return []               # report once, on the registry module
            return [Finding(
                path=module.path if module.path == registry_path else registry_path,
                line=1, col=1, check=self.name,
                message=(f"event registry {registry_path} missing or "
                         f"{rules.EVENT_REGISTRY_NAME} is not a pure dict "
                         f"literal — the schema gate cannot read it"))]
        if module.path == registry_path:
            return []                   # the registry defines, never emits
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            kind_node = _emitted_kind(node)
            if kind_node is None:
                continue
            kind = kind_node.value
            if kind not in kinds:
                findings.append(module.finding(
                    self.name, kind_node,
                    f"event kind '{kind}' is not in "
                    f"{registry_path}::{rules.EVENT_REGISTRY_NAME} — register "
                    f"it (with its producer) or the report tools will count "
                    f"it as schema drift"))
        return findings


def _emitted_kind(node: ast.AST) -> ast.Constant | None:
    """The string-literal kind of an emitted event, if ``node`` is one.

    Two shapes: a dict display with an ``"event"`` key whose value is a string
    literal, and ``payload.setdefault("event", "<kind>")``. Non-literal kinds
    (variables) pass — the registry gate is for the static vocabulary; dynamic
    kinds are the readers' passthrough case.
    """
    if isinstance(node, ast.Dict):
        for key, value in zip(node.keys, node.values):
            if (isinstance(key, ast.Constant) and key.value == "event"
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, str)):
                return value
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "setdefault" and len(node.args) == 2):
        key, value = node.args
        if (isinstance(key, ast.Constant) and key.value == "event"
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)):
            return value
    return None
