"""resolve-guard: every Future resolve must survive losing the resolve race.

The PR 6/8 bug class, twice shipped and twice review-hardened: a
``concurrent.futures.Future`` in the serving stack can be resolved from
multiple threads — the decode loop, the router's drain sweep, the monitor's
abort path, a caller's ``cancel()`` — and whoever loses the race gets
``InvalidStateError``. An unguarded ``set_result``/``set_exception`` then
kills its thread: PR 6's review found exactly that taking down the router's
monitor thread (CHANGES.md), and PR 8 re-found it on the stop()-sweep path.

The rule: a ``.set_result(...)`` / ``.set_exception(...)`` call must sit in
the BODY of a ``try`` whose handlers catch ``InvalidStateError`` (bare
``except``/``except Exception`` also qualifies — strictly wider), or inside a
helper function registered in ``rules.RESOLVE_HELPERS``. Calls in an
``else``/``finally`` block of such a try are NOT covered — those run outside
the guarded region.
"""

from __future__ import annotations

import ast

from tools.graftlint import rules
from tools.graftlint.core import Checker, Finding, Module, dotted_name, iter_with_ancestors

RESOLVE_ATTRS = ("set_result", "set_exception")
GUARD_EXC = "InvalidStateError"
WIDE_EXC = ("Exception", "BaseException")


def _handler_catches(handler: ast.ExceptHandler) -> bool:
    """Does this except clause catch InvalidStateError (or wider)?"""
    if handler.type is None:                       # bare except
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    for t in types:
        name = dotted_name(t) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf == GUARD_EXC or leaf in WIDE_EXC:
            return True
    return False


def _in_guarded_try(node: ast.AST, ancestors) -> bool:
    """Is ``node`` inside the BODY of a try whose handlers cover the guard?"""
    chain = list(ancestors) + [node]
    for i, anc in enumerate(chain[:-1]):
        if not isinstance(anc, ast.Try):
            continue
        # A function defined inside the try runs LATER, outside the guard.
        if any(isinstance(mid, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) for mid in chain[i + 1:-1]):
            continue
        child = chain[i + 1]
        # The guarded region is try's body only — else/finally/handlers run
        # outside it.
        in_body = any(child is stmt or _contains(stmt, child)
                      for stmt in anc.body)
        if in_body and any(_handler_catches(h) for h in anc.handlers):
            return True
    return False


def _contains(tree: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(tree))


class ResolveGuard(Checker):
    name = "resolve-guard"
    description = ("Future.set_result/set_exception must be guarded by "
                   "try/except InvalidStateError (or live in a registered "
                   "resolve helper)")

    def visit(self, module: Module, graph) -> list[Finding]:
        findings: list[Finding] = []
        for node, ancestors in iter_with_ancestors(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in RESOLVE_ATTRS):
                continue
            if _in_guarded_try(node, ancestors):
                continue
            func_names = {a.name for a in ancestors
                          if isinstance(a, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            if func_names & set(rules.RESOLVE_HELPERS):
                continue
            findings.append(module.finding(
                self.name, node,
                f"unguarded .{node.func.attr}() — losing the resolve race "
                f"raises InvalidStateError and kills this thread; wrap in "
                f"try/except concurrent.futures.InvalidStateError"))
        return findings
