"""process0-gate: SPMD trainer paths write files only through process-0 gates.

Every process in a fleet runs the trainer module (SPMD: the program is the
same everywhere; only the data differs). A raw file write there executes N
times against one path — torn JSONL, clobbered checkpoints, duplicated plots.
The repo's writers are therefore all internally gated (``TelemetryWriter``
checks ``metrics.is_logging_process()`` in ``enabled``; ``save_metrics_jsonl``,
``utils.plotting``, the checkpoint savers likewise), and trainer code calls
them unconditionally. This checker enforces the complement: inside the trainer
modules (rules.GATED_WRITE_MODULES), a RAW write primitive — ``open`` with a
writing mode, ``json.dump``, ``pickle.dump``, ``np.save*``, ``savefig``,
``Path.write_text/bytes``, ``shutil.copy*``, ``_atomic_write`` — must sit
under an explicit ``if is_logging_process():`` / ``if jax.process_index() ==
0:`` gate. Calls to the gated helper APIs are not writes at this layer and
pass untouched.

Multi-host-safety nuance this rule deliberately preserves: SPMD *computation*
(e.g. the health param-norm program) must run on EVERY process — only the
WRITE is gated. The checker therefore looks at write primitives, not at
everything under an ungated branch.
"""

from __future__ import annotations

import ast

from tools.graftlint import rules
from tools.graftlint.core import Checker, Finding, Module, dotted_name, iter_with_ancestors

WRITE_MODES = set("wax+")
# (module-ish base names, attr) pairs that ARE raw writes when called.
WRITE_ATTRS = {
    ("json", "dump"), ("pickle", "dump"), ("shutil", "copy"),
    ("shutil", "copy2"), ("shutil", "copyfile"), ("shutil", "move"),
    ("np", "save"), ("np", "savez"), ("np", "savez_compressed"),
    ("numpy", "save"), ("numpy", "savez"), ("numpy", "savez_compressed"),
}
# Attribute calls that write regardless of base (pathlib / matplotlib handles).
WRITE_ANY_BASE_ATTRS = {"write_text", "write_bytes", "savefig"}
WRITE_NAMES = {"_atomic_write"}
GATE_MARKERS = {"is_logging_process", "process_index"}


def _is_write_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        if func.id in WRITE_NAMES:
            return True
        if func.id == "open":
            return _open_mode_writes(node)
        return False
    if isinstance(func, ast.Attribute):
        if func.attr in WRITE_ANY_BASE_ATTRS:
            return True
        base = dotted_name(func.value)
        if not base:
            return False
        return (base.split(".")[-1], func.attr) in WRITE_ATTRS
    return False


def _open_mode_writes(node: ast.Call) -> bool:
    """``open(path, mode)`` with a literal writing mode. Default mode reads."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(set(mode.value) & WRITE_MODES)
    return True                      # dynamic mode: can't prove it reads — flag


def _under_gate(ancestors) -> bool:
    """Any enclosing ``if`` whose test mentions a process-0 gate marker."""
    for anc in ancestors:
        if isinstance(anc, ast.If):
            for n in ast.walk(anc.test):
                name = None
                if isinstance(n, ast.Attribute):
                    name = n.attr
                elif isinstance(n, ast.Name):
                    name = n.id
                if name in GATE_MARKERS:
                    return True
    return False


class Process0Gate(Checker):
    name = "process0-gate"
    description = ("raw file writes in SPMD trainer modules must sit under an "
                   "is_logging_process()/process_index()==0 gate (or go "
                   "through the internally-gated writer helpers)")

    def visit(self, module: Module, graph) -> list[Finding]:
        if not rules.matches(graph, module, rules.GATED_WRITE_MODULES):
            return []
        findings: list[Finding] = []
        for node, ancestors in iter_with_ancestors(module.tree):
            if not (isinstance(node, ast.Call) and _is_write_call(node)):
                continue
            if _under_gate(ancestors):
                continue
            what = (dotted_name(node.func) or
                    getattr(node.func, "attr", "") or "write")
            findings.append(module.finding(
                self.name, node,
                f"raw write '{what}(...)' in an SPMD trainer path without a "
                f"process-0 gate — every fleet process executes this line; "
                f"gate it with is_logging_process() or use a gated writer"))
        return findings
