"""host-sync-hazard: no device→host syncs on traced values in the hot loops.

The whole performance argument of this repo is "the step is a program": the
epoch is one compiled scan, decode is one fixed-shape program per tick, and
the host only ever forces a device value when the design says so (the engine's
single per-step token fetch). The reference's per-step ``loss.item()``
(src/train_dist.py:85) is the anti-pattern — one blocking round-trip per
step, serializing device against host.

This checker runs a small, function-local DEVICE-TAINT analysis over the
configured hot regions (rules.HOT_REGIONS):

- **sources** — calls through a ``*_jit``-suffixed binding (``self._step_jit``,
  ``prefill_jits[size]``), an immediately-invoked ``jax.jit(...)``, and — in
  ``"scan-bodies"`` mode — every parameter of a function passed to
  ``lax.scan`` (inside the traced body, everything is a tracer).
- **propagation** — assignment from a tainted name/subscript taints the
  target; tuple unpacking taints every element; reassignment from an untainted
  expression clears.
- **sinks** — ``float()``/``int()``/``bool()`` on a tainted value, ``.item()``
  / ``.tolist()``, ``np.asarray``/``np.array``, ``jax.device_get``. Each sink
  on tainted data is one host sync per loop iteration: a finding.

A sanctioned sync (the engine's one token fetch per decode step) carries a
line pragma with its justification; everything else is a regression of the
one-program design. The analysis is deliberately local and conservative-
in-both-directions: attributes are not tracked (storing to ``self._cache``
escapes), so a checker miss is possible — but a flagged line is a real sync.
"""

from __future__ import annotations

import ast

from tools.graftlint import rules
from tools.graftlint.core import Checker, Finding, Module, dotted_name

SINK_BUILTINS = {"float", "int", "bool"}
SINK_METHODS = {"item", "tolist"}
SINK_NP_ATTRS = {"asarray", "array"}


def _is_device_call(node: ast.Call) -> bool:
    """Call whose result lives on device: ``*_jit(...)`` / ``*_jits[...](...)``
    bindings and immediately-invoked ``jax.jit(...)``."""
    func = node.func
    if isinstance(func, ast.Name) and func.id.endswith("_jit"):
        return True
    if isinstance(func, ast.Attribute) and func.attr.endswith("_jit"):
        return True
    if isinstance(func, ast.Subscript):
        base = func.value
        leaf = (base.attr if isinstance(base, ast.Attribute)
                else base.id if isinstance(base, ast.Name) else "")
        if leaf.endswith("_jits"):
            return True
    if isinstance(func, ast.Call):
        inner = dotted_name(func.func) or ""
        if inner.split(".")[-1] in ("jit", "pjit"):
            return True
    return False


def _tainted_expr(node: ast.AST, taint: set[str]) -> bool:
    """Does this expression carry a device value from a tainted local?"""
    if isinstance(node, ast.Name):
        return node.id in taint
    if isinstance(node, (ast.Subscript, ast.Starred)):
        return _tainted_expr(node.value, taint)
    if isinstance(node, ast.Call):
        return _is_device_call(node)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_tainted_expr(e, taint) for e in node.elts)
    if isinstance(node, ast.BinOp):
        return (_tainted_expr(node.left, taint)
                or _tainted_expr(node.right, taint))
    return False


def _sink(node: ast.Call, taint: set[str]) -> str | None:
    """If ``node`` is a host-sync sink applied to tainted data, name the sink."""
    func = node.func
    args_tainted = any(_tainted_expr(a, taint) for a in node.args)
    if isinstance(func, ast.Name) and func.id in SINK_BUILTINS:
        return func.id if args_tainted else None
    if isinstance(func, ast.Attribute):
        if func.attr in SINK_METHODS and _tainted_expr(func.value, taint):
            return f".{func.attr}()"
        base = dotted_name(func.value) or ""
        leaf = base.split(".")[-1]
        if leaf in ("np", "numpy") and func.attr in SINK_NP_ATTRS:
            return f"{leaf}.{func.attr}" if args_tainted else None
        if base in ("jax",) and func.attr == "device_get":
            return "jax.device_get" if args_tainted else None
    return None


class _RegionAnalysis:
    """One hot function's statement-ordered taint pass."""

    def __init__(self, checker: "HostSyncHazard", module: Module,
                 pre_tainted: set[str]):
        self.checker = checker
        self.module = module
        self.taint = set(pre_tainted)
        self.findings: list[Finding] = []

    def run(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[Finding]:
        for stmt in fn.body:
            self._stmt(stmt)
        return self.findings

    # -- statements ---------------------------------------------------------------

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            self._scan_sinks(stmt.value)
            tainted = _tainted_expr(stmt.value, self.taint) and not \
                self._value_is_synced(stmt.value)
            for target in stmt.targets:
                self._bind(target, tainted)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._scan_sinks(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self._scan_sinks(stmt.value)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_sinks(stmt.value)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._scan_sinks(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, ast.For):
            self._scan_sinks(stmt.iter)
            if _tainted_expr(stmt.iter, self.taint):
                self._bind(stmt.target, True)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, (ast.With, ast.Try)):
            body = list(stmt.body)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    body += h.body
                body += stmt.orelse + stmt.finalbody
            for s in body:
                self._stmt(s)
        # Nested defs/classes: not entered — their bodies run elsewhere.

    def _value_is_synced(self, value: ast.AST) -> bool:
        """``x = np.asarray(dev)`` — the CALL is the (flagged) sync; the result
        is host data, so the target must not stay tainted."""
        return isinstance(value, ast.Call) and _sink(value, self.taint) is not None

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.taint.add if tainted else self.taint.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)
        # Attribute/subscript stores: escape, untracked.

    def _scan_sinks(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            sink = _sink(node, self.taint)
            if sink is not None:
                self.findings.append(self.module.finding(
                    self.checker.name, node,
                    f"host sync '{sink}' on a device value inside a hot "
                    f"loop — this blocks on the accelerator every iteration; "
                    f"batch the fetch or move it out of the loop"))


class HostSyncHazard(Checker):
    name = "host-sync-hazard"
    description = ("no .item()/float()/int()/np.asarray/device_get on device "
                   "values inside the configured decode/step hot loops")

    def visit(self, module: Module, graph) -> list[Finding]:
        region = None
        for rule_path, spec in rules.HOT_REGIONS.items():
            if module.path == rules.package_relpath(graph, rule_path):
                region = spec
        if region is None:
            return []
        findings: list[Finding] = []
        if region == "scan-bodies":
            for fn in _scan_bodies(module.tree):
                pre = {a.arg for a in fn.args.args + fn.args.posonlyargs
                       + fn.args.kwonlyargs}
                findings += _RegionAnalysis(self, module, pre).run(fn)
        else:
            for node in ast.walk(module.tree):
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node.name in region):
                    findings += _RegionAnalysis(self, module, set()).run(node)
        return findings


def _scan_bodies(tree: ast.Module):
    """Local functions passed as the first argument to ``lax.scan`` /
    ``jax.lax.scan`` — inside them, every parameter is a tracer.

    Scoped name resolution: several builders in one module each define their
    own inner ``body``; a scan call binds to the def sharing its innermost
    enclosing function, not to the first ``body`` in the file.
    """
    from tools.graftlint.core import iter_with_ancestors

    def scope_of(ancestors) -> tuple:
        return tuple(a for a in ancestors
                     if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)))

    defs: list[tuple[tuple, ast.FunctionDef]] = []
    calls: list[tuple[tuple, str]] = []
    for node, ancestors in iter_with_ancestors(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.append((scope_of(ancestors), node))
        elif isinstance(node, ast.Call) and node.args:
            callee = dotted_name(node.func) or ""
            if callee.split(".")[-1] == "scan" and "lax" in callee \
                    and isinstance(node.args[0], ast.Name):
                calls.append((scope_of(ancestors), node.args[0].id))

    yielded: set[int] = set()
    for call_scope, name in calls:
        # Deepest def visible from the call site (def's scope is a prefix of
        # the call's scope chain).
        best = None
        for def_scope, fn in defs:
            if fn.name != name:
                continue
            if call_scope[:len(def_scope)] == def_scope:
                if best is None or len(def_scope) > len(best[0]):
                    best = (def_scope, fn)
        if best is not None and id(best[1]) not in yielded:
            yielded.add(id(best[1]))
            yield best[1]
