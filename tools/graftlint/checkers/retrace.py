"""retrace-hazard: jit call sites must compile once, not once per call.

The training side pins this dynamically (``trace_count`` assertions in the
serving/prefill tests); this checker is the static complement, catching the
three shapes that defeat jit's cache before a test ever runs:

1. **immediately-invoked jit** — ``jax.jit(f)(x)`` inside a function body
   builds a FRESH jit wrapper (and usually a fresh lambda) on every call, so
   nothing is ever cached: one XLA compile per invocation. At module scope it
   runs once and is fine; inside ``def`` it is the compile-per-call bug.
   Sanctioned cold paths (a once-per-run sampling helper) carry a line pragma
   with the justification.
2. **jit built in a loop** — ``for ...: f = jax.jit(...)`` re-wraps per
   iteration; hoist it or memoize (the ``cached_sharded_compile`` idiom —
   jit under an ``if key not in cache`` is the sanctioned memoized form and
   is not flagged, because it is not lexically inside a loop).
3. **unhashable static args** — a call site passing a list/dict/set literal
   in a position the local ``jax.jit(..., static_argnums=/static_argnames=)``
   wrapper declared static: jax raises ``Unhashable static arguments`` at
   runtime — or worse, a caller "fixes" it by passing a tuple derived from
   per-request values, compiling one program per request. Resolved locally:
   the wrapper assignment and the call site must be in the same module.
"""

from __future__ import annotations

import ast

from tools.graftlint import rules
from tools.graftlint.core import Checker, Finding, Module, dotted_name, iter_with_ancestors

JIT_NAMES = {"jit", "pjit"}


def _is_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func) or ""
    return name.split(".")[-1] in JIT_NAMES


class RetraceHazard(Checker):
    name = "retrace-hazard"
    description = ("no per-call jax.jit wrappers (immediately-invoked or "
                   "loop-built) and no unhashable literals in declared-static "
                   "arg positions")

    def visit(self, module: Module, graph) -> list[Finding]:
        findings: list[Finding] = []
        static_decls = _local_static_decls(module.tree)
        # One-shot scripts (bench sweeps, the dryrun entry) invoke each jit
        # exactly once by construction — the per-call rules are library rules.
        library = (not rules.RETRACE_LIBRARY_ONLY
                   or module.path.startswith(f"{graph.package}/"))
        for node, ancestors in iter_with_ancestors(module.tree):
            if not isinstance(node, ast.Call):
                continue
            in_function = any(isinstance(a, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))
                              for a in ancestors)
            # 1. jax.jit(f)(args...) inside a function body.
            if library and _is_jit_call(node.func) and in_function:
                findings.append(module.finding(
                    self.name, node,
                    "immediately-invoked jax.jit builds a fresh wrapper per "
                    "call — nothing caches, one XLA compile per invocation; "
                    "hoist the jit (or memoize it) so the program compiles "
                    "once"))
            # 2. jax.jit(...) lexically inside a For/While loop.
            if library and _is_jit_call(node) and any(
                    isinstance(a, (ast.For, ast.While)) for a in ancestors):
                findings.append(module.finding(
                    self.name, node,
                    "jax.jit built inside a loop re-wraps (and recompiles) "
                    "per iteration; hoist it out of the loop or memoize by "
                    "key"))
            # 3. unhashable literal in a declared-static position.
            findings += _static_arg_violations(self, module, node, static_decls)
        return findings


def _local_static_decls(tree: ast.Module) -> dict[str, tuple[set[int], set[str]]]:
    """``name -> (static positions, static kwarg names)`` for every local
    ``name = jax.jit(f, static_argnums=..., static_argnames=...)`` binding
    (plain or ``self.name = ...``)."""
    decls: dict[str, tuple[set[int], set[str]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not _is_jit_call(node.value):
            continue
        nums: set[int] = set()
        names: set[str] = set()
        for kw in node.value.keywords:
            if kw.arg == "static_argnums":
                nums |= _int_literals(kw.value)
            elif kw.arg == "static_argnames":
                names |= _str_literals(kw.value)
        if not nums and not names:
            continue
        for target in node.targets:
            key = _binding_key(target)
            if key:
                decls[key] = (nums, names)
    return decls


def _binding_key(target: ast.AST) -> str | None:
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):   # self._foo_jit and friends
        return target.attr
    return None


def _int_literals(node: ast.AST) -> set[int]:
    out: set[int] = set()
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.add(e.value)
    return out


def _str_literals(node: ast.AST) -> set[str]:
    out: set[str] = set()
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def _static_arg_violations(checker, module: Module, call: ast.Call,
                           decls) -> list[Finding]:
    key = _binding_key(call.func) if isinstance(
        call.func, (ast.Name, ast.Attribute)) else None
    if key is None or key not in decls:
        return []
    nums, names = decls[key]
    findings: list[Finding] = []
    for i, arg in enumerate(call.args):
        if i in nums and _unhashable_literal(arg):
            findings.append(module.finding(
                checker.name, arg,
                f"unhashable {_literal_kind(arg)} literal passed in static "
                f"position {i} of '{key}' — jax raises on unhashable static "
                f"args; pass a tuple (and make sure it is not derived from "
                f"per-request values)"))
    for kw in call.keywords:
        if kw.arg in names and _unhashable_literal(kw.value):
            findings.append(module.finding(
                checker.name, kw.value,
                f"unhashable {_literal_kind(kw.value)} literal passed for "
                f"static argname '{kw.arg}' of '{key}' — jax raises on "
                f"unhashable static args; pass a tuple (and make sure it is "
                f"not derived from per-request values)"))
    return findings


def _unhashable_literal(node: ast.AST) -> bool:
    return isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp))


def _literal_kind(node: ast.AST) -> str:
    return {ast.List: "list", ast.Dict: "dict", ast.Set: "set",
            ast.ListComp: "list", ast.DictComp: "dict",
            ast.SetComp: "set"}.get(type(node), "container")
