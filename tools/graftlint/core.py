"""graftlint core types: Finding, Module (parsed file + pragmas), Checker API.

The contract every checker implements::

    class MyChecker(Checker):
        name = "my-check"
        description = "one line for --list-checks"
        def visit(self, module, graph) -> list[Finding]: ...

``visit`` is called once per discovered module with the shared
:class:`~tools.graftlint.graph.ImportGraph`; a checker that only cares about
some modules returns ``[]`` for the rest. Findings are plain data — the runner
owns pragma suppression, baseline subtraction, ordering, and exit codes, so a
checker never needs to reason about any of that.

Pragmas (suppression is per-check and deliberately loud in the source)::

    x = f()   # graftlint: disable=host-sync-hazard  (reason next to it)
    # graftlint: disable-file=telemetry-schema

A line pragma suppresses findings REPORTED ON that physical line (checkers
report the precise offending line, so the pragma sits next to the sanctioned
call, not somewhere above it); a file pragma suppresses the check everywhere in
the file. ``disable=all`` exists for generated files and is not used in-tree.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(?P<scope>disable(?:-file)?)\s*=\s*(?P<checks>[A-Za-z0-9_,-]+)")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location. ``path`` is repo-relative
    POSIX; ``message`` is self-contained (the baseline matches on it, so it
    must not embed line numbers — those drift with unrelated edits)."""

    path: str
    line: int
    col: int
    check: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "check": self.check, "message": self.message}

    @property
    def baseline_key(self) -> tuple[str, str, str]:
        """Identity for baseline matching: line numbers excluded on purpose —
        a grandfathered finding must not resurface because code above it moved."""
        return (self.check, self.path, self.message)


def parse_pragmas(source: str) -> tuple[set[str], dict[int, set[str]]]:
    """Extract ``(file_level, by_line)`` pragma sets from ``source``.

    Tokenized, not regex-over-raw-lines: only COMMENT tokens count, so pragma
    syntax QUOTED in a docstring or string literal (someone documenting the
    mechanism — this module's own docstring does) can never silently disable a
    check. A trailing comment on line N suppresses findings reported at line N
    even when the enclosing statement starts earlier.
    """
    file_level: set[str] = set()
    by_line: dict[int, set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        # Unparseable source never gets this far (Module.parse ast-parses),
        # but fail open rather than crash the whole run.
        return file_level, by_line
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = PRAGMA_RE.search(tok.string)
        if not m:
            continue
        checks = {c.strip() for c in m.group("checks").split(",") if c.strip()}
        if m.group("scope") == "disable-file":
            file_level |= checks
        else:
            by_line.setdefault(tok.start[0], set()).update(checks)
    return file_level, by_line


@dataclasses.dataclass
class Module:
    """One parsed source file: dotted name, repo-relative path, AST, pragmas.

    ``name`` is the real dotted import name for package modules
    (``<pkg>.serving.router``); scripts outside a package get a pseudo-name
    from their path (``tools.serve_loadgen``, ``bench_lm``) which is never used
    for import resolution — only package names are resolvable targets.
    """

    name: str
    path: str                      # repo-relative, posix separators
    tree: ast.Module
    source: str
    is_package_init: bool = False
    file_pragmas: set[str] = dataclasses.field(default_factory=set)
    line_pragmas: dict[int, set[str]] = dataclasses.field(default_factory=dict)

    @classmethod
    def parse(cls, name: str, path: str, source: str,
              *, is_package_init: bool = False) -> "Module":
        file_level, by_line = parse_pragmas(source)
        return cls(name=name, path=path, tree=ast.parse(source),
                   source=source, is_package_init=is_package_init,
                   file_pragmas=file_level, line_pragmas=by_line)

    def suppressed(self, check: str, line: int) -> bool:
        for got in (self.file_pragmas, self.line_pragmas.get(line, ())):
            if check in got or "all" in got:
                return True
        return False

    def finding(self, check: str, node: ast.AST, message: str) -> Finding:
        return Finding(path=self.path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       check=check, message=message)


class Checker:
    """Base class; subclasses set ``name``/``description`` and implement
    ``visit``. Stateless across modules by convention — the runner may call
    ``visit`` in any module order."""

    name: str = ""
    description: str = ""

    def visit(self, module: Module, graph) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


def iter_with_ancestors(tree: ast.AST):
    """Yield ``(node, ancestors)`` for every node, ancestors outermost-first.
    The shared scaffolding for context-sensitive rules (is this call inside a
    try/except? inside which function? under which ``if`` gate?)."""
    stack: list[ast.AST] = []

    def walk(node: ast.AST):
        yield node, tuple(stack)
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from walk(child)
        stack.pop()

    yield from walk(tree)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
