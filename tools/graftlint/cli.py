"""``python -m tools.graftlint`` — the CLI and CI gate.

Exit codes: 0 clean (no non-baselined findings), 1 findings, 2 usage/internal
error. ``--json`` emits one machine-readable document (the CI failure
artifact); text mode prints ``path:line:col: [check] message`` lines, sorted,
plus a one-line summary. Stale baseline entries are always surfaced — a
baseline must shrink, not rot.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.graftlint.baseline import default_baseline_path, load_baseline
from tools.graftlint.checkers import ALL_CHECKERS
from tools.graftlint.runner import run_lint


def default_root() -> str:
    """The repo root: two levels above this package (tools/graftlint/..)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST/import-graph lint: this repo's invariants as code")
    parser.add_argument("--root", default=None,
                        help="repo root (default: derived from this file)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output (CI artifact)")
    parser.add_argument("--checks", default="",
                        help="comma-separated checker names (default: all)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: tools/graftlint/"
                             "baseline.json under --root)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(explicit, diff-reviewed) and exit 0")
    parser.add_argument("--list-checks", action="store_true",
                        help="list checkers and exit")
    args = parser.parse_args(argv)

    if args.list_checks:
        for c in ALL_CHECKERS:
            print(f"{c.name:20s} {c.description}")
        return 0

    root = os.path.abspath(args.root or default_root())
    checks = [c.strip() for c in args.checks.split(",") if c.strip()]
    if args.update_baseline and checks:
        # A filtered run sees only its own checkers' findings; saving it would
        # silently delete every OTHER checker's grandfathered entries.
        print("graftlint: error: --update-baseline requires a full run "
              "(drop --checks)", file=sys.stderr)
        return 2
    try:
        findings, graph = run_lint(root, checks=checks or None)
        baseline = load_baseline(args.baseline
                                 or default_baseline_path(root))
    except (ValueError, RuntimeError, OSError, SyntaxError) as err:
        print(f"graftlint: error: {err}", file=sys.stderr)
        return 2

    if args.update_baseline:
        baseline.save(findings)
        print(f"graftlint: wrote {len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'} to {baseline.path}")
        return 0

    new, baselined, stale = baseline.split(findings)

    if args.as_json:
        print(json.dumps({
            "root": root,
            "modules": len(graph.modules),
            "checks": [c.name for c in ALL_CHECKERS] if not checks else checks,
            "findings": [f.to_json() for f in new],
            "baselined": [f.to_json() for f in baselined],
            "stale_baseline_entries": stale,
            "ok": not new,
        }, indent=2))
    else:
        for f in new:
            print(f.format())
        if stale:
            print(f"graftlint: note: {len(stale)} stale baseline "
                  f"entr{'y' if len(stale) == 1 else 'ies'} in "
                  f"{baseline.path} no longer match anything — remove them")
        suffix = f" ({len(baselined)} baselined)" if baselined else ""
        status = "FAILED" if new else "ok"
        print(f"graftlint: {status}: {len(new)} finding"
              f"{'' if len(new) == 1 else 's'} across {len(graph.modules)} "
              f"modules{suffix}")
    return 1 if new else 0


if __name__ == "__main__":      # pragma: no cover - exercised via __main__
    sys.exit(main())
