"""Entry point: ``python -m tools.graftlint``."""

import sys

from tools.graftlint.cli import main

sys.exit(main())
