"""The lint runner: graph once, every checker over every module, pragmas applied.

Kept separate from the CLI so tests (and future tooling) drive a single
function: ``run_lint(root)`` returns plain findings; exit codes, baselines,
and rendering are the CLI's business.
"""

from __future__ import annotations

from tools.graftlint.checkers import ALL_CHECKERS, CHECKS_BY_NAME
from tools.graftlint.core import Checker, Finding
from tools.graftlint.graph import ImportGraph, build_graph


def run_lint(root: str, *, checks: list[str] | None = None,
             graph: ImportGraph | None = None,
             checkers: tuple[Checker, ...] | None = None,
             ) -> tuple[list[Finding], ImportGraph]:
    """Run the selected checkers over every discovered module.

    ``checks`` filters by checker name (unknown names raise — a typo'd
    ``--checks`` must not silently lint nothing). Pragma suppression happens
    here, centrally: checkers report every violation they see and never read
    pragmas themselves.
    """
    if graph is None:
        graph = build_graph(root)
    if checkers is None:
        if checks:
            unknown = sorted(set(checks) - set(CHECKS_BY_NAME))
            if unknown:
                raise ValueError(
                    f"unknown check(s) {unknown}; known: "
                    f"{sorted(CHECKS_BY_NAME)}")
            checkers = tuple(CHECKS_BY_NAME[c] for c in checks)
        else:
            checkers = ALL_CHECKERS
    findings: list[Finding] = []
    seen: set[Finding] = set()
    for name in sorted(graph.modules):
        module = graph.modules[name]
        for checker in checkers:
            for finding in checker.visit(module, graph):
                # Dedup: a repo-level problem (e.g. a missing event registry)
                # is reported identically from several modules' visits.
                if finding in seen:
                    continue
                seen.add(finding)
                # Pragmas live in the file the finding points AT (a checker
                # may attribute a repo-level problem to another module).
                owner = (module if finding.path == module.path
                         else graph.module_for_relpath(finding.path)) or module
                if not owner.suppressed(finding.check, finding.line):
                    findings.append(finding)
    return sorted(findings), graph
