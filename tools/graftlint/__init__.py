"""graftlint: the repo's invariants as code — an AST/import-graph lint pass.

Every review-hardening list from PR 6 through PR 9 re-broke the same few
invariant classes: a jax import leaking into a backend-free module, an
unguarded ``Future.set_result`` resolve race, a telemetry event kind the report
tools don't know, a writer missing its process-0 gate, a host sync slipping
into a decode hot loop, a jit call site that retraces per request. Each of
these is a *convention* the code depends on but nothing enforced — the class
of failure arxiv 2204.06514 (PAPERS.md) says must be mechanically checked,
not remembered. This package checks them at commit time:

- ``core``      ``Finding``/``Module`` types, ``# graftlint: disable=`` pragma
                parsing, the ``Checker`` base API
- ``graph``     module discovery + the transitive import graph (top-level vs
                lazy edges, parent-package ``__init__`` edges)
- ``rules``     the house-rule configuration: which modules are declared
                backend-free, which functions are hot loops, which trainer
                modules must gate writes
- ``checkers``  the six repo-specific checkers (see ``checkers/__init__.py``)
- ``baseline``  the committed grandfathered-findings file (ships empty: every
                true finding on the current tree was fixed in the PR that
                introduced this tool)
- ``cli``       ``python -m tools.graftlint [--json]`` — exit 0 clean, 1 on
                any non-baselined finding, 2 on usage/internal error

Deliberately stdlib-only and import-free with respect to the repo: graftlint
*parses* the tree (including ``utils/telemetry_events.py``, the event-kind
registry) and never imports it, so the CI gate runs in seconds on a bare
Python with no jax/flax/numpy installed and can never initialize a backend.

Run it::

    python -m tools.graftlint            # human findings, file:line:col
    python -m tools.graftlint --json     # machine-readable (CI artifact)

Suppress a single sanctioned line with a trailing
``# graftlint: disable=<check>`` (a reason comment next to it is house style);
suppress a whole file with ``# graftlint: disable-file=<check>`` on its own
line. DESIGN.md §19 documents each checker and how to add one.
"""

from tools.graftlint.baseline import Baseline, load_baseline
from tools.graftlint.checkers import ALL_CHECKERS
from tools.graftlint.core import Checker, Finding, Module
from tools.graftlint.graph import ImportGraph, build_graph
from tools.graftlint.runner import run_lint

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "Checker",
    "Finding",
    "ImportGraph",
    "Module",
    "build_graph",
    "load_baseline",
    "run_lint",
]
