"""The committed grandfathered-findings file (``tools/graftlint/baseline.json``).

A lint gate that lands red is a gate people turn off — so a new checker with
pre-existing findings lands GREEN by baselining them: the tool subtracts
baselined findings from its output, and the gate only fails on NEW ones. The
file is committed, reviewed, and expected to shrink; this repo's ships EMPTY
(every true finding on the tree the tool first ran against was fixed in the
same PR), which is the healthy steady state.

Matching is by ``(check, path, message)`` — line numbers are deliberately
excluded so a grandfathered finding does not resurface because unrelated code
above it moved. A baseline entry that no longer matches anything is reported
as stale (``--json`` carries it; text mode prints a note): baselines must not
silently rot into dead weight.

``--update-baseline`` rewrites the file from the current findings — an
explicit, diff-reviewed act, never something the gate does on its own.
"""

from __future__ import annotations

import dataclasses
import json
import os

from tools.graftlint.core import Finding


@dataclasses.dataclass
class Baseline:
    path: str
    entries: list[dict]

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """``(new, baselined, stale_entries)``."""
        keys = {(e.get("check", ""), e.get("path", ""), e.get("message", ""))
                for e in self.entries}
        new = [f for f in findings if f.baseline_key not in keys]
        old = [f for f in findings if f.baseline_key in keys]
        live = {f.baseline_key for f in old}
        stale = [e for e in self.entries
                 if (e.get("check", ""), e.get("path", ""),
                     e.get("message", "")) not in live]
        return new, old, stale

    def save(self, findings: list[Finding]) -> None:
        payload = [{"check": f.check, "path": f.path, "message": f.message}
                   for f in sorted(findings)]
        with open(self.path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")


def default_baseline_path(root: str) -> str:
    return os.path.join(root, "tools", "graftlint", "baseline.json")


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline(path=path, entries=[])
    with open(path, encoding="utf-8") as fh:
        entries = json.load(fh)
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path} must be a JSON list")
    return Baseline(path=path, entries=entries)
