"""House-rule configuration: WHICH modules each checker binds to.

Checker *logic* lives in ``tools/graftlint/checkers/``; this module is the one
place the repo-specific scope decisions live, so adding a module to a rule is a
one-line diff reviewed next to the other scope choices. All paths are
package-relative (``serving/router.py``) or repo-relative for scripts
(``tools/serve_loadgen.py``); ``resolve()`` maps them onto graph modules.
"""

from __future__ import annotations

# -- backend-purity -----------------------------------------------------------------
# Modules DECLARED jax-free: importing one must not reach jax/jaxlib through any
# top-level import, transitively (lazy function-body imports are the sanctioned
# on-demand escape). The fleet-side doctrine (utils/jsonl.py docstring): a
# process that supervises accelerator-owning children must never claim a device
# itself — and the cheapest way to guarantee "never initializes a backend" is
# "never even imports it".
BACKEND_FREE = (
    "serving/router.py",
    "serving/autoscaler.py",
    "serving/scheduler.py",
    "serving/prefix_cache.py",
    "serving/tiers.py",
    "serving/wire.py",
    "resilience/supervisor.py",
    "resilience/heartbeat.py",
    "resilience/preemption.py",
    "resilience/faults.py",
    "resilience/netfaults.py",
    "resilience/poison.py",
    "utils/jsonl.py",
    "utils/trace.py",
    "utils/telemetry_events.py",
    "obs/hist.py",
    "obs/slo.py",
    "obs/goodput.py",
    "tools/serve_loadgen.py",
    "tools/trace_report.py",
    "tools/fleet_top.py",
)

# Import targets that count as "the backend" for backend-purity.
BACKEND_MODULES = ("jax", "jaxlib", "flax")

# -- telemetry-schema ---------------------------------------------------------------
# The one registry every emitted {"event": "..."} literal must appear in.
# graftlint reads it by AST (EVENT_KINDS dict literal), never by import.
EVENT_REGISTRY = "utils/telemetry_events.py"
EVENT_REGISTRY_NAME = "EVENT_KINDS"

# -- process0-gate ------------------------------------------------------------------
# SPMD trainer paths: every process runs this code, so any file write must go
# through an internally process-0-gated helper (TelemetryWriter,
# metrics.save_metrics_jsonl, utils.plotting, the checkpoint savers) or sit
# under an explicit `if is_logging_process():` / `if jax.process_index() == 0:`
# gate — otherwise N processes race on one path.
GATED_WRITE_MODULES = (
    "train/single.py",
    "train/distributed.py",
    "train/composed.py",
    "train/lm.py",
    "train/smoke.py",
)

# -- host-sync-hazard ---------------------------------------------------------------
# Hot regions: per module, either a tuple of function/method names whose bodies
# form the per-token / per-step host loop, or "scan-bodies" meaning every local
# function passed to lax.scan (the compiled epoch's step body). Inside a hot
# region, forcing a device value to host (.item(), float()/int(), np.asarray,
# jax.device_get) is a per-iteration sync — the exact tax the one-program
# design exists to delete (reference src/train_dist.py:85).
HOT_REGIONS: dict[str, tuple[str, ...] | str] = {
    "serving/engine.py": ("step", "_spec_tick", "_run_prefill",
                          "_finish_prefill"),
    "train/step.py": "scan-bodies",
}

# Callee names whose RESULT is a device value (taint sources) are structural:
# any call through a `*_jit`-suffixed binding or subscript of a `*_jits`
# mapping, plus immediately-invoked jax.jit — see checkers/host_sync.py.

# -- retrace-hazard -----------------------------------------------------------------
# The per-call-jit rules (immediately-invoked / loop-built wrappers) bind to
# LIBRARY code only — the package, where a wrapper built per call really does
# mean one XLA compile per request/epoch. One-shot harnesses (__graft_entry__
# dryrun legs, bench sweeps that deliberately compile one program per swept
# config) invoke each jit exactly once by construction, so the rule would only
# generate pragma noise there. The unhashable-static-literal rule stays global:
# that one is a runtime error wherever it appears.
RETRACE_LIBRARY_ONLY = True

# -- resolve-guard ------------------------------------------------------------------
# Helper functions allowed to call set_result/set_exception without an inline
# try/except InvalidStateError (none today: the repo idiom is the inline guard;
# a future `resolve_future()` helper registers itself here).
RESOLVE_HELPERS: tuple[str, ...] = ()

# -- scope helpers ------------------------------------------------------------------


def package_relpath(graph, rule_path: str) -> str:
    """Rule path -> repo-relative path (`tools/...` passes through unchanged)."""
    if rule_path.startswith("tools/"):
        return rule_path
    return f"{graph.package}/{rule_path}"


def matches(graph, module, rule_paths) -> bool:
    return any(module.path == package_relpath(graph, p) for p in rule_paths)
