"""Module discovery + the transitive import graph graftlint checks against.

What counts as an edge (this is the load-bearing design decision, so it is
written down once, here):

- **top-level edges** — ``import``/``from ... import`` statements that execute
  at module import time: module body, and bodies of module-level ``if``/
  ``try``/``with``/class blocks (a conditional import still statically reaches
  its target — whether it fires is an env question the lint cannot answer, so
  it counts; a sanctioned one carries a line pragma).
- **lazy edges** — imports inside function/method bodies. These defer the cost
  to call time and are this repo's one sanctioned mechanism for a backend-free
  module to reach heavyweight deps on demand (e.g. the supervisor importing
  ``utils.checkpoint`` inside its resume path). Recorded, but not traversed by
  the backend-purity closure.
- **parent-package edges** — importing ``a.b.c`` executes ``a/__init__`` and
  ``a/b/__init__`` first. These are real runtime imports and ARE traversed:
  an eager ``from .step import ...`` in ``train/__init__.py`` makes EVERY
  ``train.*`` import reach jax, which is exactly the leak class this graph
  exists to catch (found and fixed when this tool landed).
- ``from pkg.mod import name`` edges to ``pkg.mod`` and — when ``pkg.mod.name``
  is itself a repo module — to the submodule too.

External modules (not found in the repo) are terminal nodes identified by
their top-level name (``jax``, ``numpy``, ...).
"""

from __future__ import annotations

import ast
import dataclasses
import os

from tools.graftlint.core import Module

# Directories never scanned (data/artifacts/caches, never source).
SKIP_DIRS = {"__pycache__", ".git", ".github", "bench_results", "images",
             "tests", "related"}


@dataclasses.dataclass(frozen=True)
class ImportEdge:
    """One import statement's contribution: ``target`` is a dotted module name
    (repo or external), ``line`` its statement line in the source module,
    ``lazy`` True for function-body imports."""

    target: str
    line: int
    lazy: bool


class ImportGraph:
    """The parsed repo: ``modules`` by dotted name, plus per-module edges."""

    def __init__(self, root: str):
        self.root = root
        self.modules: dict[str, Module] = {}
        self.package: str = ""              # the single top-level package name
        self._edges: dict[str, list[ImportEdge]] = {}

    # -- discovery ----------------------------------------------------------------

    def add_module(self, module: Module) -> None:
        self.modules[module.name] = module
        self._edges[module.name] = _collect_edges(module)

    def module_for_relpath(self, relpath: str) -> Module | None:
        """Module by repo-relative POSIX path (how rules.py names things)."""
        for mod in self.modules.values():
            if mod.path == relpath:
                return mod
        return None

    # -- edges --------------------------------------------------------------------

    def edges(self, name: str, *, include_lazy: bool = False) -> list[ImportEdge]:
        out = self._edges.get(name, [])
        return out if include_lazy else [e for e in out if not e.lazy]

    @staticmethod
    def parents(name: str) -> list[str]:
        """``a.b.c`` -> ``["a", "a.b"]`` — the package inits importing it runs."""
        parts = name.split(".")
        return [".".join(parts[:i]) for i in range(1, len(parts))]

    def closure(self, start: str, *, skip_check: str = "") -> dict[str, tuple[str, int]]:
        """Transitive top-level import closure from repo module ``start``.

        Returns ``{reached_name: (via_module, via_line)}`` for every module —
        repo or external — reachable through top-level edges, including
        parent-package edges. ``skip_check``: edges whose source line carries a
        ``# graftlint: disable=<skip_check>`` pragma are not traversed (the
        sanctioned-import escape hatch).
        """
        seen: dict[str, tuple[str, int]] = {start: ("", 0)}
        frontier = [start]
        while frontier:
            name = frontier.pop()
            mod = self.modules.get(name)
            targets: list[tuple[str, str, int]] = []
            if mod is not None:
                for edge in self.edges(name):
                    if skip_check and mod.suppressed(skip_check, edge.line):
                        continue
                    targets.append((edge.target, name, edge.line))
            # Importing any module first executes its parent packages' inits.
            for parent in self.parents(name):
                targets.append((parent, name, 0))
            for target, via, line in targets:
                if target in seen:
                    continue
                seen[target] = (via, line)
                # External names are terminal; repo modules recurse.
                frontier.append(target)
        return seen

    def chain(self, closure: dict[str, tuple[str, int]], target: str) -> list[str]:
        """Human-readable import chain from the closure start to ``target``."""
        hops = [target]
        while True:
            via, _line = closure[hops[-1]]
            if not via:
                break
            hops.append(via)
        return list(reversed(hops))


def _collect_edges(module: Module) -> list[ImportEdge]:
    """All import statements in ``module``, classified top-level vs lazy."""
    edges: list[ImportEdge] = []

    def visit(node: ast.AST, lazy: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_lazy = lazy or isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            if isinstance(child, ast.Import):
                for alias in child.names:
                    edges.append(ImportEdge(alias.name, child.lineno, lazy))
            elif isinstance(child, ast.ImportFrom):
                base = _resolve_from(module, child)
                if base:
                    edges.append(ImportEdge(base, child.lineno, lazy))
                    for alias in child.names:
                        if alias.name != "*":
                            # Submodule edge; pruned to real modules at
                            # traversal time (unknown names are terminal and
                            # harmless — they resolve to nothing).
                            edges.append(ImportEdge(f"{base}.{alias.name}",
                                                    child.lineno, lazy))
            visit(child, child_lazy)

    visit(module.tree, lazy=False)
    return edges


def _resolve_from(module: Module, node: ast.ImportFrom) -> str:
    """Absolute dotted base of a ``from ... import`` (handles relative levels)."""
    if node.level == 0:
        return node.module or ""
    # Relative: strip `level` trailing components from the module's package.
    parts = module.name.split(".")
    if not module.is_package_init:
        parts = parts[:-1]
    anchor = parts[: len(parts) - (node.level - 1)] if node.level > 1 else parts
    base = ".".join(anchor)
    return f"{base}.{node.module}" if node.module else base


def discover_package(root: str) -> str:
    """The repo's one top-level package (a root dir with ``__init__.py``)."""
    candidates = []
    for entry in sorted(os.listdir(root)):
        if entry in SKIP_DIRS or entry.startswith("."):
            continue
        if os.path.isfile(os.path.join(root, entry, "__init__.py")):
            candidates.append(entry)
    # tools/ is a namespace dir (no __init__.py) so it never competes.
    if len(candidates) != 1:
        raise RuntimeError(
            f"expected exactly one top-level package under {root}, "
            f"found {candidates}")
    return candidates[0]


def build_graph(root: str) -> ImportGraph:
    """Parse the repo into an :class:`ImportGraph`.

    Scanned: the package tree, ``tools/**/*.py`` (including graftlint itself —
    the linter holds itself to the house rules), and top-level scripts
    (``bench*.py``, ``__graft_entry__.py``). ``tests/`` is excluded: tests
    deliberately construct counterexamples (unknown event kinds, synthetic
    violations) that are correct AS tests.
    """
    graph = ImportGraph(root)
    graph.package = discover_package(root)

    def add(relpath: str, name: str, *, is_package_init: bool = False) -> None:
        full = os.path.join(root, relpath)
        with open(full, encoding="utf-8") as f:
            source = f.read()
        graph.add_module(Module.parse(name, relpath.replace(os.sep, "/"),
                                      source, is_package_init=is_package_init))

    def walk_tree(base: str) -> None:
        """Discover every .py under ``base`` (one rule for package AND tools)."""
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, base)):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS and not d.startswith("."))
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fname), root)
                dotted = rel[:-3].replace(os.sep, ".")
                is_init = fname == "__init__.py"
                if is_init:
                    dotted = dotted.rsplit(".", 1)[0]
                add(rel, dotted, is_package_init=is_init)

    walk_tree(graph.package)             # the package tree
    if os.path.isdir(os.path.join(root, "tools")):
        walk_tree("tools")               # tools/ scripts + graftlint itself

    # Top-level scripts.
    for entry in sorted(os.listdir(root)):
        if entry.endswith(".py") and os.path.isfile(os.path.join(root, entry)):
            add(entry, entry[:-3])

    return graph
