#!/bin/bash
# Serialized hardware follow-ups to run whenever a real TPU chip is reachable.
# The TPU claim is exclusive (a second jax process BLOCKS in backend init until the
# holder exits), so each step must fully finish before the next starts. If a step is
# killed, prefer SIGTERM and expect the lease to take a long time to free afterwards.
#
# Outputs land under ${HW_OUT:-/tmp/hw}. Run from anywhere:  bash tools/hw_followups.sh
set -u
cd "$(dirname "$0")/.."
OUT=${HW_OUT:-/tmp/hw}
mkdir -p "$OUT"

echo "=== 0. chip reachable? (two tries — tunnelled backend init can be merely slow) ==="
rc=1
for attempt in 1 2; do
  timeout 240 python -c "import jax; print(jax.devices())" > "$OUT/probe.out" 2>&1
  rc=$?
  [ $rc -eq 0 ] && break
  echo "probe attempt $attempt rc=$rc — waiting 60s before retry"
  sleep 60
done
cat "$OUT/probe.out" | tail -1
if [ $rc -ne 0 ]; then echo "chip unreachable (rc=$rc) — aborting"; exit 1; fi

echo "=== 1. headline bench at shipped defaults — FIRST: the verdict's number of record"
echo "    (a window can close any time; this also primes bench_results/.jax_cache) ==="
BENCH_TPU_RETRY_SECONDS=300 BENCH_ATTEMPT_TIMEOUT_SECONDS=240 \
  timeout --kill-after=60 --signal=TERM 2700 python bench.py \
  > "$OUT/bench_defaults.json" 2> "$OUT/bench_defaults.err"
echo "bench rc=$? ($OUT/bench_defaults.json)"

echo "=== 1b. flash-attention hardware tests (Mosaic compile + parity, fwd/bwd) ==="
FRAMEWORK_TEST_PLATFORM=tpu timeout --kill-after=60 --signal=TERM 1200 python -m pytest \
  tests/test_pallas_attention.py -q > "$OUT/flash_tpu_test.out" 2>&1
echo "flash tests rc=$? (out: $OUT/flash_tpu_test.out)"

echo "=== 2. long-context attention microbench (flash vs dense; r3: through 64k tokens," \
     "where dense hits the O(S^2) wall — that wall is the result) ==="
timeout --kill-after=60 --signal=TERM 2700 python bench_attention.py \
  --seq-lens 1024 2048 4096 8192 16384 32768 65536 \
  --plot "$OUT/attention_flash_vs_dense_tpu.png" \
  --out "$OUT/bench_attention_tpu.jsonl" > /dev/null 2> "$OUT/bench_attention.err"
echo "bench_attention rc=$? (rows: $OUT/bench_attention_tpu.jsonl)"

echo "=== 2a. flash block-size tune for the S<=8k regime (r3: flash trailed dense by" \
     "up to 4% at the default 128 block in the r2 capture) ==="
timeout --kill-after=60 --signal=TERM 2700 python bench_attention.py \
  --seq-lens 2048 4096 8192 --block-sweep 128 256 512 \
  --out "$OUT/bench_attention_blocktune.jsonl" > /dev/null 2> "$OUT/blocktune.err"
echo "block tune rc=$? (rows: $OUT/bench_attention_blocktune.jsonl)"

echo "=== 2b. transformer MFU bench (MXU-shaped: d_model 256, seq 256, batch 64; r3) ==="
timeout --kill-after=60 --signal=TERM 1800 python bench_transformer.py \
  > "$OUT/bench_transformer_tpu.json" 2> "$OUT/bench_transformer.err"
echo "bench_transformer rc=$? ($OUT/bench_transformer_tpu.json)"
timeout --kill-after=60 --signal=TERM 1800 python bench_transformer.py --flash \
  > "$OUT/bench_transformer_flash_tpu.json" 2> "$OUT/bench_transformer_flash.err"
echo "bench_transformer --flash rc=$? ($OUT/bench_transformer_flash_tpu.json)"

echo "=== 2b2. pixel-LM throughput: train steps/s + KV-cache decode tokens/s (r3) ==="
timeout --kill-after=60 --signal=TERM 1800 python bench_lm.py \
  > "$OUT/bench_lm_tpu.json" 2> "$OUT/bench_lm.err"
echo "bench_lm rc=$? ($OUT/bench_lm_tpu.json)"
timeout --kill-after=60 --signal=TERM 1800 python bench_lm.py --kv-heads 2 --rope \
  > "$OUT/bench_lm_gqa_rope_tpu.json" 2> "$OUT/bench_lm_gqa.err"
echo "bench_lm --kv-heads 2 --rope rc=$? ($OUT/bench_lm_gqa_rope_tpu.json)"

echo "=== 2c. banded (sliding-window) flash at long S (r3: O(S*W) compute — the" \
     "local-attention regime where full attention is off the chart) ==="
timeout --kill-after=60 --signal=TERM 1800 python bench_attention.py \
  --seq-lens 16384 32768 65536 131072 --window 4096 \
  --out "$OUT/bench_attention_window_tpu.jsonl" > /dev/null 2> "$OUT/window.err"
echo "windowed bench rc=$? (rows: $OUT/bench_attention_window_tpu.jsonl)"

echo "=== done ==="
