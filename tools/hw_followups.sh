#!/bin/bash
# Serialized hardware follow-ups to run whenever a real TPU chip is reachable.
# The TPU claim is exclusive (a second jax process BLOCKS in backend init until the
# holder exits), so each step must fully finish before the next starts. If a step is
# killed, prefer SIGTERM and expect the lease to take a long time to free afterwards.
#
# r5 ordering: the steps are sorted by verdict priority so a short window still
# captures the items of record in order — (1) headline bench + cache priming,
# (2) the 27/27 TPU-gated pallas log at HEAD (r4 verdict ask #1, BOTH layouts),
# (3) attention roofline rows with the r5 elision/mask-split kernels and the
# native-vs-packed layout comparison (ask #2/#3), (4) large-transformer MFU with
# and without FLASH_NATIVE_LAYOUT (ask #3), (5) decode sweep + the per-op
# decomposition artifact (ask #6), then the longer sweeps.
#
# Outputs land under ${HW_OUT:-/tmp/hw}. Run from anywhere:  bash tools/hw_followups.sh
set -u
cd "$(dirname "$0")/.."
OUT=${HW_OUT:-/tmp/hw}
mkdir -p "$OUT"

echo "=== 0. chip reachable? (two tries — tunnelled backend init can be merely slow) ==="
rc=1
for attempt in 1 2; do
  timeout 240 python -c "import jax; print(jax.devices())" > "$OUT/probe.out" 2>&1
  rc=$?
  [ $rc -eq 0 ] && break
  echo "probe attempt $attempt rc=$rc — waiting 60s before retry"
  sleep 60
done
tail -1 "$OUT/probe.out"
if [ $rc -ne 0 ]; then echo "chip unreachable (rc=$rc) — aborting"; exit 1; fi

echo "=== 1. headline bench at shipped defaults — FIRST: the verdict's number of record"
echo "    (a window can close any time; this also primes bench_results/.jax_cache) ==="
BENCH_TPU_RETRY_SECONDS=300 BENCH_ATTEMPT_TIMEOUT_SECONDS=240 \
  timeout --kill-after=60 --signal=TERM 2700 python bench.py \
  > "$OUT/bench_defaults.json" 2> "$OUT/bench_defaults.err"
echo "bench rc=$? ($OUT/bench_defaults.json)"

echo "=== 2. TPU-gated pallas suite at HEAD — the r4 verdict's 27/27 ask, now incl."
echo "    both flash layouts (native [B,S,H,D] Mosaic compile is chip-only) ==="
FRAMEWORK_TEST_PLATFORM=tpu timeout --kill-after=60 --signal=TERM 1800 python -m pytest \
  tests/test_pallas_attention.py tests/test_pallas.py -q > "$OUT/flash_tpu_test.out" 2>&1
echo "pallas tests rc=$? (out: $OUT/flash_tpu_test.out — commit this log)"

echo "=== 3. long-context attention roofline rows (r5 elision + mask-split kernels;"
echo "    rows now carry achieved FLOP/s + %-of-bf16-peak; target >=40% at S>=8k) ==="
timeout --kill-after=60 --signal=TERM 2700 python bench_attention.py \
  --dtype bfloat16 --seq-lens 2048 4096 8192 16384 32768 65536 \
  --plot "$OUT/attention_flash_vs_dense_tpu.png" \
  --out "$OUT/bench_attention_tpu.jsonl" > /dev/null 2> "$OUT/bench_attention.err"
echo "bench_attention rc=$? (rows: $OUT/bench_attention_tpu.jsonl)"

echo "=== 3b. native-layout comparison at the same sizes (prices the H-strided DMA"
echo "    against the repack copies it deletes — flips the default if it wins) ==="
timeout --kill-after=60 --signal=TERM 2700 python bench_attention.py \
  --dtype bfloat16 --seq-lens 2048 8192 32768 --native-layout \
  --out "$OUT/bench_attention_native_tpu.jsonl" > /dev/null 2> "$OUT/native.err"
echo "native-layout rows rc=$? ($OUT/bench_attention_native_tpu.jsonl)"

echo "=== 4. large-transformer MFU: packed vs native layout (r4: 59.7%; the trace"
echo "    attributes 11% of the step to the repacks — target >=65% native) ==="
timeout --kill-after=60 --signal=TERM 2700 python bench_transformer.py --large --flash \
  > "$OUT/bench_transformer_large_tpu.json" 2> "$OUT/transformer_large.err"
echo "large packed rc=$? ($OUT/bench_transformer_large_tpu.json)"
FLASH_NATIVE_LAYOUT=1 timeout --kill-after=60 --signal=TERM 2700 python bench_transformer.py --large --flash \
  > "$OUT/bench_transformer_large_native_tpu.json" 2> "$OUT/transformer_large_native.err"
echo "large native rc=$? ($OUT/bench_transformer_large_native_tpu.json)"

echo "=== 5. decode: sweep + the per-op decomposition artifact (r4 ask #6) ==="
timeout --kill-after=60 --signal=TERM 1800 python bench_lm.py --kv-heads 2 --rope \
  > "$OUT/bench_lm_gqa_rope_tpu.json" 2> "$OUT/bench_lm_gqa.err"
echo "bench_lm rc=$? ($OUT/bench_lm_gqa_rope_tpu.json)"
timeout --kill-after=60 --signal=TERM 1800 python tools/bench_decode_analysis.py \
  --out "$OUT/decode_analysis_tpu.json" > /dev/null 2> "$OUT/decode_analysis.err"
echo "decode analysis rc=$? ($OUT/decode_analysis_tpu.json)"

echo "=== 6. flash block retune under the r5 kernels (larger blocks may shift with"
echo "    elision; MAX_AUTO_BLOCK updates if so) ==="
timeout --kill-after=60 --signal=TERM 2700 python bench_attention.py \
  --dtype bfloat16 --seq-lens 8192 65536 --block-sweep 128 256 512 1024 \
  --out "$OUT/bench_attention_blocktune.jsonl" > /dev/null 2> "$OUT/blocktune.err"
echo "block tune rc=$? (rows: $OUT/bench_attention_blocktune.jsonl)"

echo "=== 7. banded (sliding-window) flash at long S ==="
timeout --kill-after=60 --signal=TERM 1800 python bench_attention.py \
  --dtype bfloat16 --seq-lens 16384 65536 131072 --window 4096 \
  --out "$OUT/bench_attention_window_tpu.jsonl" > /dev/null 2> "$OUT/window.err"
echo "windowed bench rc=$? (rows: $OUT/bench_attention_window_tpu.jsonl)"

echo "=== done — copy $OUT into bench_results/hw_r5/ and commit ==="
# (The pipeline-bubble artifact stays CPU-virtual: its stage mesh needs >=4
# devices and this environment has one chip.)
