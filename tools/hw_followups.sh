#!/bin/bash
# Serialized hardware follow-ups to run whenever a real TPU chip is reachable.
# The TPU claim is exclusive (a second jax process BLOCKS in backend init until the
# holder exits), so each step must fully finish before the next starts.
#
# Outputs land under ${HW_OUT:-/tmp/hw}. Run from anywhere:  bash tools/hw_followups.sh
set -u
cd "$(dirname "$0")/.."
OUT=${HW_OUT:-/tmp/hw}
mkdir -p "$OUT"

echo "=== 1. fused-kernel Mosaic hardware parity test ==="
# Settles whether the full whole-model Pallas kernel compiles through Mosaic on this
# chip (every individual construct is probe-verified; the full-kernel compile was
# still unresolved when the round-2 tunnel died — see ops/pallas_fused.py notes).
FRAMEWORK_TEST_PLATFORM=tpu timeout --kill-after=60 --signal=TERM 1800 python -m pytest \
  tests/test_pallas_fused.py::test_fused_step_on_tpu_matches_unfused -q \
  > "$OUT/fused_tpu_test.out" 2>&1
echo "fused test rc=$? (out: $OUT/fused_tpu_test.out)"

echo "=== 2. bench scan-unroll sweep ==="
for U in 1 4 8; do
  BENCH_UNROLL=$U BENCH_TPU_RETRY_SECONDS=300 BENCH_ATTEMPT_TIMEOUT_SECONDS=240 \
    timeout --kill-after=60 --signal=TERM 2700 python bench.py \
    > "$OUT/bench_unroll_$U.json" 2> "$OUT/bench_unroll_$U.err"
  echo "unroll=$U rc=$?"
done

echo "=== 3. bench pregather ==="
BENCH_PREGATHER=1 BENCH_TPU_RETRY_SECONDS=300 BENCH_ATTEMPT_TIMEOUT_SECONDS=240 \
  timeout --kill-after=60 --signal=TERM 2700 python bench.py \
  > "$OUT/bench_pregather.json" 2> "$OUT/bench_pregather.err"
echo "pregather rc=$?"

echo "=== done — compare values against bench_results/bench_r2_tpu.json (0.1944 s) ==="
