"""Load generator for the in-process serving engine: open/closed loop, Poisson arrivals.

Drives ``serving.Server`` (slot-based continuous batching over the KV-cache decoder)
with a reproducible synthetic workload and leaves a serve-telemetry JSONL behind for
``tools/telemetry_report.py``:

- **open loop** (``--mode open``): requests arrive on a Poisson process at
  ``--rate`` req/s regardless of completions — the latency-under-load probe (an
  overloaded server shows up as queue-wait/TTFT growth, and past ``--max-pending``
  as rejected requests, i.e. backpressure);
- **closed loop** (``--mode closed``): ``--concurrency`` clients each keep exactly
  one request in flight — the throughput probe (tokens/s at a fixed offered
  parallelism).

The prompt/length mix is sampled per request from ``--prompt-lens`` and
``[1, --max-new-tokens]`` under a seeded RNG, so an A-vs-B pair of runs offers
byte-identical workloads. Params come from a training checkpoint
(``--checkpoint results/model_lm.ckpt`` — either a full TrainState or a
params-only export) or a seeded random init when omitted (pure perf mode).

Usage::

    python tools/serve_loadgen.py --requests 32 --mode open --rate 16 \\
        --num-slots 8 --telemetry results/serve.jsonl
    python tools/serve_loadgen.py --requests 32 --mode closed --concurrency 8 \\
        --checkpoint results/model_lm.ckpt --telemetry results/serve.jsonl
    python tools/telemetry_report.py results/serve.jsonl
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

# Script-mode import path: ``python tools/serve_loadgen.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_model_and_params(args):
    """The decode model under test + its params (checkpoint or seeded init)."""
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm

    model = lm.TransformerLM(
        vocab_size=args.num_levels + 1, seq_len=args.seq_len,
        embed_dim=args.embed_dim, num_layers=args.num_layers,
        num_heads=args.num_heads,
        num_kv_heads=args.kv_heads or None,
        attention_window=args.attention_window, rope=args.rope)
    ref = model.init({"params": jax.random.PRNGKey(args.seed)},
                     jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    if not args.checkpoint:
        return model, ref
    from flax import serialization

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    with open(args.checkpoint, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    if isinstance(raw, dict) and "params" in raw:     # full TrainState checkpoint
        return model, serialization.from_state_dict(jax.device_get(ref),
                                                    raw["params"])
    # params-only export: the one checkpoint reader the repo already has
    return model, checkpoint.load_params(args.checkpoint, jax.device_get(ref))


def make_workload(args, vocab_size):
    """The seeded request mix: ``[(prompt, max_new, sampling), ...]``."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        SamplingParams,
    )

    rng = np.random.default_rng(args.seed)
    lens = [int(x) for x in args.prompt_lens.split(",") if x != ""]
    bad = [l for l in lens if not 0 <= l < args.seq_len]
    if bad:
        raise SystemExit(f"--prompt-lens entries outside [0, seq_len): {bad}")
    sampling = SamplingParams(temperature=args.temperature, top_k=args.top_k,
                              top_p=args.top_p)
    specs = []
    for _ in range(args.requests):
        p = int(rng.choice(lens))
        prompt = rng.integers(0, vocab_size - 1, size=p).astype(np.int32)
        new = int(rng.integers(1, args.max_new_tokens + 1))
        specs.append((prompt, new, sampling))
    return specs


def run_open_loop(server, specs, rate, rng):
    """Poisson arrivals at ``rate`` req/s; returns (futures, rejected_count)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        QueueFull,
    )

    futures, rejected = [], 0
    for prompt, new, sampling in specs:
        time.sleep(float(rng.exponential(1.0 / rate)))
        try:
            futures.append(server.submit(prompt, max_new_tokens=new,
                                         sampling=sampling))
        except QueueFull:
            rejected += 1                       # backpressure: load is shed, not queued
    return futures, rejected


def run_closed_loop(server, specs, concurrency):
    """``concurrency`` clients, each one request in flight; returns
    ``(futures, rejected_count)`` — backpressure sheds the request, the client
    moves on (mirrors the open loop's accounting)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        QueueFull,
    )

    it = iter(specs)
    lock = threading.Lock()
    futures: list = []
    rejected = [0]

    def client():
        while True:
            with lock:
                spec = next(it, None)
            if spec is None:
                return
            prompt, new, sampling = spec
            try:
                fut = server.submit(prompt, max_new_tokens=new, sampling=sampling)
            except QueueFull:
                with lock:
                    rejected[0] += 1
                continue
            with lock:
                futures.append(fut)
            fut.result()                        # keep exactly one in flight

    threads = [threading.Thread(target=client, name=f"loadgen-{i}")
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return futures, rejected[0]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    m = p.add_argument_group("model")
    m.add_argument("--checkpoint", default="",
                   help="TrainState or params msgpack from train.lm (default: "
                        "seeded random init — pure perf mode)")
    m.add_argument("--seq-len", type=int, default=784)
    m.add_argument("--num-levels", type=int, default=16)
    m.add_argument("--embed-dim", type=int, default=64)
    m.add_argument("--num-layers", type=int, default=2)
    m.add_argument("--num-heads", type=int, default=4)
    m.add_argument("--kv-heads", type=int, default=0)
    m.add_argument("--attention-window", type=int, default=0)
    m.add_argument("--rope", action="store_true")
    e = p.add_argument_group("engine/server")
    e.add_argument("--num-slots", type=int, default=8)
    e.add_argument("--max-pending", type=int, default=128)
    e.add_argument("--timeout-s", type=float, default=0.0,
                   help="per-request deadline, 0 = none")
    g = p.add_argument_group("load")
    g.add_argument("--mode", choices=("open", "closed"), default="open")
    g.add_argument("--rate", type=float, default=8.0,
                   help="open loop: Poisson arrival rate, req/s")
    g.add_argument("--concurrency", type=int, default=4,
                   help="closed loop: clients with one request in flight each")
    g.add_argument("--requests", type=int, default=32)
    g.add_argument("--prompt-lens", default="0,16,64",
                   help="comma list; each request draws uniformly from it")
    g.add_argument("--max-new-tokens", type=int, default=32,
                   help="each request draws its length from [1, this]")
    g.add_argument("--temperature", type=float, default=0.0)
    g.add_argument("--top-k", type=int, default=0)
    g.add_argument("--top-p", type=float, default=1.0)
    g.add_argument("--seed", type=int, default=0)
    p.add_argument("--telemetry", default="",
                   help="serve JSONL path (render with tools/telemetry_report.py)")
    args = p.parse_args(argv)
    if args.mode == "open" and args.rate <= 0:
        raise SystemExit("--rate must be > 0 in open-loop mode")
    if args.mode == "closed" and args.concurrency < 1:
        raise SystemExit("--concurrency must be >= 1 in closed-loop mode")
    if args.max_new_tokens < 1:
        raise SystemExit("--max-new-tokens must be >= 1")

    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
        Server,
    )

    model, params = build_model_and_params(args)
    specs = make_workload(args, model.vocab_size)
    engine = ContinuousBatchingEngine(model, params, num_slots=args.num_slots,
                                      seed=args.seed)
    server = Server(engine, max_pending=args.max_pending,
                    default_timeout_s=args.timeout_s or None,
                    telemetry=args.telemetry)
    server.start()
    t0 = time.monotonic()
    if args.mode == "open":
        futures, rejected = run_open_loop(server, specs, args.rate,
                                          np.random.default_rng(args.seed + 1))
    else:
        futures, rejected = run_closed_loop(server, specs, args.concurrency)
    comps = [f.result() for f in futures]
    server.stop()                               # graceful drain (a no-op by now)
    wall = time.monotonic() - t0

    ok = sum(c.ok for c in comps)
    timeouts = sum(c.finish == "timeout" for c in comps)
    new_tokens = sum(c.new_tokens for c in comps)
    print(f"{args.mode}-loop: {len(comps)} completed ({ok} ok, {timeouts} timeout, "
          f"{rejected} rejected) in {wall:.2f}s")
    occ = engine.slot_occupancy                 # None when no step ever ran
    print(f"generated {new_tokens} tokens, {new_tokens / wall:.1f} tokens/s, "
          f"slot occupancy {'-' if occ is None else f'{occ:.2f}'}, "
          f"decode compilations {engine.trace_count}")
    if args.telemetry:
        print(f"serve telemetry -> {args.telemetry} "
              f"(render: python tools/telemetry_report.py {args.telemetry})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
