"""Load generator for the in-process serving engine: open/closed loop, Poisson arrivals.

Drives ``serving.Server`` (slot-based continuous batching over the KV-cache decoder)
with a reproducible synthetic workload and leaves a serve-telemetry JSONL behind for
``tools/telemetry_report.py``:

- **open loop** (``--mode open``): requests arrive on a Poisson process at
  ``--rate`` req/s regardless of completions — the latency-under-load probe (an
  overloaded server shows up as queue-wait/TTFT growth, and past ``--max-pending``
  as rejected requests, i.e. backpressure);
- **closed loop** (``--mode closed``): ``--concurrency`` clients each keep exactly
  one request in flight — the throughput probe (tokens/s at a fixed offered
  parallelism).

The prompt/length mix is sampled per request from ``--prompt-lens`` and
``[1, --max-new-tokens]`` under a seeded RNG, so an A-vs-B pair of runs offers
byte-identical workloads. ``--prompt-dist long`` swaps in a long-prompt mixture
(half to three-quarters of ``seq_len``) that actually exercises the chunked
prefill path, and ``--shared-prefix-len N`` gives every prompt the same first
``N`` tokens (the system-prompt pattern the prefix KV cache exists for). Params
come from a training checkpoint (``--checkpoint results/model_lm.ckpt`` — either
a full TrainState or a params-only export) or a seeded random init when omitted
(pure perf mode).

Prefill knobs mirror the engine's: ``--prefill-chunks 32,128,512`` (empty string
= legacy prefill-as-decode — the A/B switch), ``--prefill-budget`` chunks per
engine step, ``--prefix-cache N`` LRU entries. The run summary reports prefill
token throughput and prefix-cache hits alongside decode tokens/s, and
``--summary-json PATH`` writes the whole summary (TTFT/e2e percentiles included)
as one JSON document for committed A-vs-B artifacts.

Usage::

    python tools/serve_loadgen.py --requests 32 --mode open --rate 16 \\
        --num-slots 8 --telemetry results/serve.jsonl
    python tools/serve_loadgen.py --requests 32 --mode closed --concurrency 8 \\
        --checkpoint results/model_lm.ckpt --telemetry results/serve.jsonl
    python tools/serve_loadgen.py --prompt-dist long --prefix-cache 8 \\
        --shared-prefix-len 256 --summary-json results/prefill_on.json
    python tools/telemetry_report.py results/serve.jsonl
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

# Script-mode import path: ``python tools/serve_loadgen.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_model_and_params(args):
    """The decode model under test + its params (checkpoint or seeded init)."""
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm

    model = lm.TransformerLM(
        vocab_size=args.num_levels + 1, seq_len=args.seq_len,
        embed_dim=args.embed_dim, num_layers=args.num_layers,
        num_heads=args.num_heads,
        num_kv_heads=args.kv_heads or None,
        attention_window=args.attention_window, rope=args.rope)
    ref = model.init({"params": jax.random.PRNGKey(args.seed)},
                     jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    if not args.checkpoint:
        return model, ref
    from flax import serialization

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    with open(args.checkpoint, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    if isinstance(raw, dict) and "params" in raw:     # full TrainState checkpoint
        return model, serialization.from_state_dict(jax.device_get(ref),
                                                    raw["params"])
    # params-only export: the one checkpoint reader the repo already has
    return model, checkpoint.load_params(args.checkpoint, jax.device_get(ref))


def prompt_len_mix(args) -> list[int]:
    """The prompt-length mixture: ``--prompt-lens`` verbatim, or the ``long``
    preset — seq_len/2 .. 3·seq_len/4, the prompt-heavy regime where TTFT is
    dominated by prefill (the benchmark the chunked-prefill path exists for)."""
    if args.prompt_dist == "long":
        s = args.seq_len
        lens = sorted({max(1, s // 2), max(1, (5 * s) // 8),
                       max(1, min(s - 2, (3 * s) // 4))})
    else:
        lens = [int(x) for x in args.prompt_lens.split(",") if x != ""]
    bad = [l for l in lens if not 0 <= l < args.seq_len]
    if bad:
        raise SystemExit(f"prompt lengths outside [0, seq_len): {bad}")
    return lens


def make_workload(args, vocab_size):
    """The seeded request mix: ``[(prompt, max_new, sampling), ...]``.
    ``--shared-prefix-len N`` forces one common first-N-token prefix across all
    prompts (truncated for shorter ones) so repeated-prefix reuse is testable."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        SamplingParams,
    )

    rng = np.random.default_rng(args.seed)
    lens = prompt_len_mix(args)
    shared = rng.integers(0, vocab_size - 1,
                          size=max(args.shared_prefix_len, 0)).astype(np.int32)
    sampling = SamplingParams(temperature=args.temperature, top_k=args.top_k,
                              top_p=args.top_p)
    specs = []
    for _ in range(args.requests):
        p = int(rng.choice(lens))
        prompt = rng.integers(0, vocab_size - 1, size=p).astype(np.int32)
        k = min(len(shared), p)
        if k:
            prompt[:k] = shared[:k]
        new = int(rng.integers(1, args.max_new_tokens + 1))
        specs.append((prompt, new, sampling))
    return specs


def run_open_loop(server, specs, rate, rng):
    """Poisson arrivals at ``rate`` req/s; returns (futures, rejected_count)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        QueueFull,
    )

    futures, rejected = [], 0
    for prompt, new, sampling in specs:
        time.sleep(float(rng.exponential(1.0 / rate)))
        try:
            futures.append(server.submit(prompt, max_new_tokens=new,
                                         sampling=sampling))
        except QueueFull:
            rejected += 1                       # backpressure: load is shed, not queued
    return futures, rejected


def run_closed_loop(server, specs, concurrency):
    """``concurrency`` clients, each one request in flight; returns
    ``(futures, rejected_count)`` — backpressure sheds the request, the client
    moves on (mirrors the open loop's accounting)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        QueueFull,
    )

    it = iter(specs)
    lock = threading.Lock()
    futures: list = []
    rejected = [0]

    def client():
        while True:
            with lock:
                spec = next(it, None)
            if spec is None:
                return
            prompt, new, sampling = spec
            try:
                fut = server.submit(prompt, max_new_tokens=new, sampling=sampling)
            except QueueFull:
                with lock:
                    rejected[0] += 1
                continue
            with lock:
                futures.append(fut)
            fut.result()                        # keep exactly one in flight

    threads = [threading.Thread(target=client, name=f"loadgen-{i}")
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return futures, rejected[0]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    m = p.add_argument_group("model")
    m.add_argument("--checkpoint", default="",
                   help="TrainState or params msgpack from train.lm (default: "
                        "seeded random init — pure perf mode)")
    m.add_argument("--seq-len", type=int, default=784)
    m.add_argument("--num-levels", type=int, default=16)
    m.add_argument("--embed-dim", type=int, default=64)
    m.add_argument("--num-layers", type=int, default=2)
    m.add_argument("--num-heads", type=int, default=4)
    m.add_argument("--kv-heads", type=int, default=0)
    m.add_argument("--attention-window", type=int, default=0)
    m.add_argument("--rope", action="store_true")
    e = p.add_argument_group("engine/server")
    e.add_argument("--num-slots", type=int, default=8)
    e.add_argument("--max-pending", type=int, default=128)
    e.add_argument("--timeout-s", type=float, default=0.0,
                   help="per-request deadline, 0 = none")
    e.add_argument("--prefill-chunks", default="32,128,512",
                   help="static chunk-size set for batched prefill; empty = "
                        "legacy prefill-as-decode (the A/B switch)")
    e.add_argument("--prefill-budget", type=int, default=1,
                   help="prefill chunk invocations per engine step (decode "
                        "interleaving)")
    e.add_argument("--prefix-cache", type=int, default=0,
                   help="prefix KV cache LRU entries, 0 = off")
    e.add_argument("--warmup", type=int, default=1,
                   help="pre-measurement warmup rounds: compile the decode, "
                        "every prefill chunk size, and the prefix-cache install "
                        "path, then reset the engine's counters — so latency "
                        "percentiles measure the schedule, not XLA (0 = off)")
    g = p.add_argument_group("load")
    g.add_argument("--mode", choices=("open", "closed"), default="open")
    g.add_argument("--rate", type=float, default=8.0,
                   help="open loop: Poisson arrival rate, req/s")
    g.add_argument("--concurrency", type=int, default=4,
                   help="closed loop: clients with one request in flight each")
    g.add_argument("--requests", type=int, default=32)
    g.add_argument("--prompt-dist", choices=("custom", "long"), default="custom",
                   help="'long' = prompt-heavy mixture (seq_len/2..3/4) that "
                        "exercises prefill; 'custom' uses --prompt-lens")
    g.add_argument("--prompt-lens", default="0,16,64",
                   help="comma list; each request draws uniformly from it")
    g.add_argument("--shared-prefix-len", type=int, default=0,
                   help="force a common first-N-token prefix across prompts "
                        "(exercises the prefix KV cache)")
    g.add_argument("--max-new-tokens", type=int, default=32,
                   help="each request draws its length from [1, this]")
    g.add_argument("--temperature", type=float, default=0.0)
    g.add_argument("--top-k", type=int, default=0)
    g.add_argument("--top-p", type=float, default=1.0)
    g.add_argument("--seed", type=int, default=0)
    p.add_argument("--telemetry", default="",
                   help="serve JSONL path (render with tools/telemetry_report.py)")
    p.add_argument("--summary-json", default="",
                   help="write the run summary (percentiles + prefill stats) "
                        "as one JSON document — the committed-artifact format")
    args = p.parse_args(argv)
    if args.mode == "open" and args.rate <= 0:
        raise SystemExit("--rate must be > 0 in open-loop mode")
    if args.mode == "closed" and args.concurrency < 1:
        raise SystemExit("--concurrency must be >= 1 in closed-loop mode")
    if args.max_new_tokens < 1:
        raise SystemExit("--max-new-tokens must be >= 1")

    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
        Request,
        Server,
    )

    model, params = build_model_and_params(args)
    specs = make_workload(args, model.vocab_size)
    chunk_sizes = tuple(int(x) for x in args.prefill_chunks.split(",") if x)
    engine = ContinuousBatchingEngine(model, params, num_slots=args.num_slots,
                                      seed=args.seed,
                                      prefill_chunk_sizes=chunk_sizes,
                                      prefill_chunk_budget=args.prefill_budget,
                                      prefix_cache_entries=args.prefix_cache)
    if args.warmup:
        warm_rng = np.random.default_rng(args.seed + 17)
        for _ in range(args.warmup):
            # One request per chunk size (each plan = exactly that size), one
            # prompt-less decode, and a repeated prompt when the prefix cache is
            # on (compiles the hit-install path). reset_stats() wipes the
            # ledger — including warmup prefix entries — before measurement.
            for size in engine.prefill_chunk_sizes:
                wp = warm_rng.integers(
                    0, model.vocab_size - 1,
                    size=min(size, args.seq_len - 1)).astype(np.int32)
                engine.run([Request(prompt=wp, max_new_tokens=1)])
                if engine.prefix_cache is not None:
                    engine.run([Request(prompt=wp, max_new_tokens=1)])
            engine.run([Request(prompt=np.zeros(0, np.int32),
                                max_new_tokens=2)])
        engine.reset_stats()
    server = Server(engine, max_pending=args.max_pending,
                    default_timeout_s=args.timeout_s or None,
                    telemetry=args.telemetry)
    server.start()
    t0 = time.monotonic()
    if args.mode == "open":
        futures, rejected = run_open_loop(server, specs, args.rate,
                                          np.random.default_rng(args.seed + 1))
    else:
        futures, rejected = run_closed_loop(server, specs, args.concurrency)
    comps = [f.result() for f in futures]
    server.stop()                               # graceful drain (a no-op by now)
    wall = time.monotonic() - t0

    ok = sum(c.ok for c in comps)
    timeouts = sum(c.finish == "timeout" for c in comps)
    new_tokens = sum(c.new_tokens for c in comps)
    print(f"{args.mode}-loop: {len(comps)} completed ({ok} ok, {timeouts} timeout, "
          f"{rejected} rejected) in {wall:.2f}s")
    occ = engine.slot_occupancy                 # None when no step ever ran
    print(f"generated {new_tokens} tokens, {new_tokens / wall:.1f} tokens/s, "
          f"slot occupancy {'-' if occ is None else f'{occ:.2f}'}, "
          f"decode compilations {engine.trace_count}")
    prefill_rate = (engine.prefill_tokens / engine.prefill_wall_s
                    if engine.prefill_wall_s else None)
    hits = engine.prefix_cache.stats() if engine.prefix_cache else None
    print(f"prefilled {engine.prefill_tokens} prompt tokens in "
          f"{engine.prefill_invocations} chunks "
          f"({'-' if prefill_rate is None else f'{prefill_rate:.1f}'} tokens/s, "
          f"sizes {list(engine.prefill_chunk_sizes) or 'off'})"
          + (f", prefix hits {hits['hits']}/{hits['queries']} "
             f"({hits['hit_tokens']} tokens reused)" if hits else ""))
    if args.telemetry:
        print(f"serve telemetry -> {args.telemetry} "
              f"(render: python tools/telemetry_report.py {args.telemetry})")
    if args.summary_json:
        import json

        from csed_514_project_distributed_training_using_pytorch_tpu.utils.telemetry import (
            percentiles,
        )

        doc = {
            "mode": args.mode,
            "requests": len(comps), "ok": ok, "timeout": timeouts,
            "rejected": rejected, "wall_s": wall,
            "prompt_dist": args.prompt_dist,
            "prompt_lens": prompt_len_mix(args),
            "shared_prefix_len": args.shared_prefix_len,
            "num_slots": args.num_slots,
            "prefill_chunk_sizes": list(engine.prefill_chunk_sizes),
            "prefill_chunk_budget": args.prefill_budget,
            "prefix_cache_entries": args.prefix_cache,
            "new_tokens": new_tokens,
            "tokens_per_s": new_tokens / wall if wall else None,
            "prefill_tokens": engine.prefill_tokens,
            "prefill_chunks": engine.prefill_invocations,
            "prefill_wall_s": engine.prefill_wall_s,
            "prefill_tokens_per_s": prefill_rate,
            "prefix_cache": hits,
            "decode_compilations": engine.trace_count,
            "prefill_compilations": dict(engine.prefill_trace_counts),
            "ttft_s": percentiles([c.ttft_s for c in comps]),
            "e2e_s": percentiles([c.e2e_s for c in comps]),
            "queue_wait_s": percentiles([c.queue_wait_s for c in comps]),
        }
        with open(args.summary_json, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"summary json -> {args.summary_json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
