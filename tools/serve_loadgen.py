"""Load generator for the serving stack: open/closed loop, chat sessions, fleets.

Drives ``serving.Server`` (one in-process engine) or — with ``--replicas N`` —
``serving.Router`` (a process-per-replica fleet over ``serving/replica.py``)
with a reproducible synthetic workload and leaves a telemetry JSONL behind for
``tools/telemetry_report.py``:

- **open loop** (``--mode open``): requests arrive on a Poisson process at
  ``--rate`` req/s regardless of completions — the latency-under-load probe (an
  overloaded server shows up as queue-wait/TTFT growth, and past ``--max-pending``
  as rejected requests, i.e. backpressure);
- **closed loop** (``--mode closed``): ``--concurrency`` clients each keep exactly
  one request in flight — the throughput probe (tokens/s at a fixed offered
  parallelism);
- **chat** (``--scenario chat``): ``--sessions`` concurrent multi-turn sessions,
  each turn resubmitting the prior context plus the model's reply plus a few
  fresh "user" tokens — the workload where prefix reuse actually pays, because
  every turn's prompt extends the previous one. With ``--replicas N`` this is
  the prefix-affinity A/B: ``--affinity on`` routes a session's turns to the
  replica whose ``prefix_cache`` holds its history, ``--affinity off`` is the
  least-loaded baseline (compare the summaries' prefix-cache hit rates).

The prompt/length mix is sampled per request from ``--prompt-lens`` and
``[1, --max-new-tokens]`` under a seeded RNG, so an A-vs-B pair of runs offers
byte-identical workloads. ``--prompt-dist long`` swaps in a long-prompt mixture
(half to three-quarters of ``seq_len``) that actually exercises the chunked
prefill path, and ``--shared-prefix-len N`` gives every prompt the same first
``N`` tokens (the system-prompt pattern the prefix KV cache exists for). Params
come from a training checkpoint (``--checkpoint results/model_lm.ckpt`` — either
a full TrainState or a params-only export) or a seeded random init when omitted
(pure perf mode).

Prefill knobs mirror the engine's: ``--prefill-chunks 32,128,512`` (empty string
= legacy prefill-as-decode — the A/B switch), ``--prefill-budget`` chunks per
engine step, ``--prefix-cache N`` LRU entries. The run summary reports prefill
token throughput and prefix-cache hits alongside decode tokens/s, and
``--summary-json PATH`` writes the whole summary (TTFT/e2e percentiles included)
as one JSON document for committed A-vs-B artifacts.

Usage::

    python tools/serve_loadgen.py --requests 32 --mode open --rate 16 \\
        --num-slots 8 --telemetry results/serve.jsonl
    python tools/serve_loadgen.py --requests 32 --mode closed --concurrency 8 \\
        --checkpoint results/model_lm.ckpt --telemetry results/serve.jsonl
    python tools/serve_loadgen.py --prompt-dist long --prefix-cache 8 \\
        --shared-prefix-len 256 --summary-json results/prefill_on.json
    python tools/serve_loadgen.py --replicas 2 --scenario chat --sessions 8 \\
        --turns 4 --prefix-cache 8 --affinity on --telemetry results/router.jsonl \\
        --summary-json results/chat_affinity_on.json
    python tools/telemetry_report.py results/serve.jsonl
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

# Script-mode import path: ``python tools/serve_loadgen.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def prompt_len_mix(args) -> list[int]:
    """The prompt-length mixture: ``--prompt-lens`` verbatim, or the ``long``
    preset — seq_len/2 .. 3·seq_len/4, the prompt-heavy regime where TTFT is
    dominated by prefill (the benchmark the chunked-prefill path exists for)."""
    if args.prompt_dist == "long":
        s = args.seq_len
        lens = sorted({max(1, s // 2), max(1, (5 * s) // 8),
                       max(1, min(s - 2, (3 * s) // 4))})
    else:
        lens = [int(x) for x in args.prompt_lens.split(",") if x != ""]
    bad = [l for l in lens if not 0 <= l < args.seq_len]
    if bad:
        raise SystemExit(f"prompt lengths outside [0, seq_len): {bad}")
    return lens


def tenant_shares(text: str) -> dict[str, float]:
    """The loadgen-side reading of the ``--tenants`` grammar: tenant names
    plus their ``share=`` traffic fractions (the scheduler ignores ``share`` —
    it is offered-load mix, not service class), normalized to sum to 1.
    Tenants without a share split the remainder equally."""
    shares: dict[str, float] = {}
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, _, body = chunk.partition(":")
        share = None
        for part in body.split(","):
            key, _, value = part.strip().partition("=")
            if key.strip() == "share":
                share = float(value)
        shares[name.strip()] = share
    named = sum(v for v in shares.values() if v is not None)
    rest = [k for k, v in shares.items() if v is None]
    for k in rest:
        shares[k] = max(0.0, 1.0 - named) / len(rest)
    total = sum(shares.values()) or 1.0
    return {k: v / total for k, v in shares.items()}


def make_workload(args, vocab_size):
    """The seeded request mix: ``[(prompt, max_new, sampling, tenant), ...]``.
    ``--shared-prefix-len N`` forces one common first-N-token prefix across all
    prompts (truncated for shorter ones) so repeated-prefix reuse is testable.
    With ``--tenants``, each request draws its tenant from the ``share=``
    traffic mix under the same seed — an A-vs-B pair of runs offers
    byte-identical per-tenant workloads."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        SamplingParams,
    )

    rng = np.random.default_rng(args.seed)
    lens = prompt_len_mix(args)
    shared = rng.integers(0, vocab_size - 1,
                          size=max(args.shared_prefix_len, 0)).astype(np.int32)
    sampling = SamplingParams(temperature=args.temperature, top_k=args.top_k,
                              top_p=args.top_p)
    shares = tenant_shares(args.tenants) if getattr(args, "tenants", "") \
        else {"default": 1.0}
    names = sorted(shares)
    probs = np.asarray([shares[n] for n in names])
    specs = []
    for _ in range(args.requests):
        p = int(rng.choice(lens))
        prompt = rng.integers(0, vocab_size - 1, size=p).astype(np.int32)
        k = min(len(shared), p)
        if k:
            prompt[:k] = shared[:k]
        new = int(rng.integers(1, args.max_new_tokens + 1))
        tenant = str(rng.choice(names, p=probs))
        specs.append((prompt, new, sampling, tenant))
    return specs


def _tally_refusal(rejections: dict, tenant: str, exc, lock) -> None:
    """The three-way refusal ledger (one owner — open/closed/chat loops all
    report through it): ``QueueFull`` (capacity backpressure),
    ``QuotaExceeded`` (over the tenant's contract), ``Shed`` (priority-
    ordered overload shedding), totals and per tenant."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        QueueFull,
        QuotaExceeded,
    )

    key = ("rejected" if isinstance(exc, QueueFull)
           else "quota_rejected" if isinstance(exc, QuotaExceeded)
           else "shed_submits")
    with lock:
        rejections[key] += 1
        rejections["by_tenant"].setdefault(
            tenant, {"rejected": 0, "quota_rejected": 0,
                     "shed_submits": 0})[key] += 1


def _submit_counted(server, spec, futures, rejections, lock):
    """One submit through the refusal ledger; returns the future or None."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        QueueFull,
        QuotaExceeded,
        Shed,
    )

    prompt, new, sampling, tenant = spec
    try:
        fut = server.submit(prompt, max_new_tokens=new, sampling=sampling,
                            **({"tenant": tenant}
                               if tenant != "default" else {}))
    except (QueueFull, QuotaExceeded, Shed) as e:
        _tally_refusal(rejections, tenant, e, lock)
        return None
    with lock:
        futures.append(fut)
    return fut


def new_rejections() -> dict:
    return {"rejected": 0, "quota_rejected": 0, "shed_submits": 0,
            "by_tenant": {}}


def run_open_loop(server, specs, rate, rng, *, pattern="poisson",
                  burst_size=8, burst_idle_s=1.0, burst_tenant=""):
    """Open-loop arrivals; returns (futures, rejections dict).

    ``pattern="poisson"`` is the classic memoryless stream at ``rate`` req/s.
    ``pattern="burst"`` is the elasticity workload: ``burst_size`` requests
    arrive back-to-back (an arrival spike that piles the router queue up and
    ages its head — the autoscaler's scale-up signal), then ``burst_idle_s``
    of silence (the valley where utilization falls and a sustained-idle fleet
    earns a scale-down).

    ``burst_tenant`` (with a multi-tenant workload) is the contended-serving
    scenario: THAT tenant's stream arrives in bursts while every other tenant
    stays Poisson at its share of ``rate`` — the committed tenant-burst
    artifact drives exactly this shape (paid steady, best-effort spiking 3x)."""
    futures: list = []
    rejections = new_rejections()
    tenants = sorted({s[3] for s in specs})
    if len(tenants) <= 1 and not burst_tenant:
        lone = threading.Lock()
        for i, spec in enumerate(specs):
            if pattern == "burst":
                if i and i % burst_size == 0:
                    time.sleep(burst_idle_s)
            else:
                time.sleep(float(rng.exponential(1.0 / rate)))
            _submit_counted(server, spec, futures, rejections, lone)
        return futures, rejections
    # Multi-tenant: one arrival stream per tenant (each at its request-count
    # share of the aggregate rate), so tenant mixes are independent processes
    # — a burst on one never thins another's offered load.
    lock = threading.Lock()
    by_tenant = {t: [s for s in specs if s[3] == t] for t in tenants}

    def stream(tenant: str, tspecs, seed: int):
        trng = np.random.default_rng(seed)
        trate = max(rate * len(tspecs) / max(len(specs), 1), 1e-6)
        bursty = (tenant == burst_tenant
                  or (pattern == "burst" and not burst_tenant))
        for i, spec in enumerate(tspecs):
            if bursty:
                if i and i % burst_size == 0:
                    time.sleep(burst_idle_s)
            else:
                time.sleep(float(trng.exponential(1.0 / trate)))
            _submit_counted(server, spec, futures, rejections, lock)

    threads = [threading.Thread(target=stream, args=(t, by_tenant[t], i + 11),
                                name=f"loadgen-{t}")
               for i, t in enumerate(tenants)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return futures, rejections


def run_closed_loop(server, specs, concurrency):
    """``concurrency`` clients, each one request in flight; returns
    ``(futures, rejections dict)`` — a refused submit sheds the request, the
    client moves on (mirrors the open loop's accounting)."""
    it = iter(specs)
    lock = threading.Lock()
    futures: list = []
    rejections = new_rejections()

    def client():
        while True:
            with lock:
                spec = next(it, None)
            if spec is None:
                return
            fut = _submit_counted(server, spec, futures, rejections, lock)
            if fut is not None:
                fut.result()                    # keep exactly one in flight

    threads = [threading.Thread(target=client, name=f"loadgen-{i}")
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return futures, rejections


def run_chat(front, args, vocab_size):
    """``--sessions`` concurrent multi-turn sessions against ``front`` (Server
    or Router — same ``submit`` surface). Each session thread keeps one request
    in flight: turn t's prompt is the full emitted stream of turn t-1 (context +
    reply) plus ``--turn-user-tokens`` fresh tokens. Greedy decode makes the
    whole workload deterministic given the params, so an A-vs-B pair of runs
    (e.g. affinity on/off) offers byte-identical traffic.

    Returns ``(completions, rejections, sessions_done)`` — a session counts
    done when it ran all its turns (or cleanly hit the seq_len ceiling). With
    ``--tenants``, each SESSION draws its tenant from the ``share=`` mix (a
    session is one user; its turns share a class)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.scheduler import (
        QueueFull,
        QuotaExceeded,
        SamplingParams,
        Shed,
    )

    sampling = SamplingParams(temperature=args.temperature, top_k=args.top_k,
                              top_p=args.top_p)
    lens = [l for l in prompt_len_mix(args) if l > 0] or [1]
    lock = threading.Lock()
    comps: list = []
    rejections = new_rejections()
    done_sessions = [0]
    errors: list = []
    shares = (tenant_shares(args.tenants)
              if getattr(args, "tenants", "") else {"default": 1.0})
    names = sorted(shares)
    probs = np.asarray([shares[n] for n in names])

    def session(sid: int):
        rng = np.random.default_rng(args.seed + 1000 * (sid + 1))
        tenant = str(rng.choice(names, p=probs))
        prompt = rng.integers(0, vocab_size - 1,
                              size=int(rng.choice(lens))).astype(np.int32)
        for _ in range(args.turns):
            new = int(rng.integers(1, args.max_new_tokens + 1))
            if len(prompt) + new >= args.seq_len:
                break                      # context window full: session over
            try:
                fut = front.submit(prompt, max_new_tokens=new,
                                   sampling=sampling,
                                   **({"tenant": tenant}
                                      if tenant != "default" else {}))
            except (QueueFull, QuotaExceeded, Shed) as e:
                _tally_refusal(rejections, tenant, e, lock)
                return                     # overloaded: the session gives up
            comp = fut.result()
            with lock:
                comps.append(comp)
            if not comp.ok:
                return
            user = rng.integers(0, vocab_size - 1,
                                size=args.turn_user_tokens).astype(np.int32)
            prompt = np.concatenate([np.asarray(comp.tokens, np.int32), user])
        with lock:
            done_sessions[0] += 1

    def guarded(sid: int):
        # A failed front end (e.g. ServerStopped after every replica died)
        # must surface as a loadgen failure, not as a silently shorter run.
        try:
            session(sid)
        except BaseException as e:         # noqa: BLE001 — recorded, re-raised
            with lock:
                errors.append((sid, e))

    threads = [threading.Thread(target=guarded, args=(i,), name=f"chat-{i}")
               for i in range(args.sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        sid, first = errors[0]
        raise RuntimeError(
            f"{len(errors)}/{args.sessions} chat sessions died "
            f"(first: session {sid}: {type(first).__name__}: {first})") from first
    return comps, rejections, done_sessions[0]


class _TracedFront:
    """Wrap a ``Server``/``Router`` front end so every loadgen request is a
    trace ORIGIN: a fresh ``trace_id`` per submit (propagated through the
    whole serve path) and a ``client`` span — submit call to future
    resolution, the outermost span of the tree and the latency the user
    actually felt. Everything else (``stop`` etc.) passes through."""

    def __init__(self, inner, tracer):
        self._inner = inner
        self._tracer = tracer

    def submit(self, prompt, **kw):
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.trace import (
            new_trace_id,
        )

        tid = new_trace_id()
        t0 = time.monotonic()
        fut = self._inner.submit(prompt, trace_id=tid, **kw)

        def _done(f, tid=tid, t0=t0):
            try:
                finish = f.result().finish
            except BaseException as e:       # noqa: BLE001 — span records it
                finish = f"error:{type(e).__name__}"
            self._tracer.span("client", tid, t0, time.monotonic(),
                              finish=finish)

        fut.add_done_callback(_done)
        return fut

    def __getattr__(self, name):
        return getattr(self._inner, name)


def build_replica_command(args) -> list[str]:
    """The ``serving/replica.py`` argv mirroring this run's model/engine flags
    (the router appends --port/--replica-id/--heartbeat-dir per replica)."""
    pkg = "csed_514_project_distributed_training_using_pytorch_tpu"
    if getattr(args, "echo", False):
        # Jax-free replicas: the elasticity/router-mechanics smoke — the
        # protocol, lifecycle, and scale paths are the same code, only the
        # engine is a deterministic pure function.
        cmd = ["-m", f"{pkg}.serving.replica", "--echo",
               "--seq-len", str(args.seq_len),
               "--num-levels", str(args.num_levels),
               "--num-slots", str(args.num_slots),
               "--max-pending", str(args.max_pending)]
        if args.echo_delay_s:
            cmd += ["--echo-delay-s", str(args.echo_delay_s)]
        return cmd
    cmd = ["-m", f"{pkg}.serving.replica",
           "--seq-len", str(args.seq_len), "--num-levels", str(args.num_levels),
           "--embed-dim", str(args.embed_dim),
           "--num-layers", str(args.num_layers),
           "--num-heads", str(args.num_heads), "--kv-heads", str(args.kv_heads),
           "--attention-window", str(args.attention_window),
           "--seed", str(args.seed),
           "--num-slots", str(args.num_slots),
           "--max-pending", str(args.max_pending),
           "--timeout-s", str(args.timeout_s),
           "--prefill-chunks", args.prefill_chunks,
           "--prefill-budget", str(args.prefill_budget),
           "--prefix-cache", str(args.prefix_cache),
           "--kv-dtype", args.kv_dtype,
           "--quant-policy", args.quant_policy,
           "--spec", args.spec, "--spec-k", str(args.spec_k),
           "--draft-layers", str(args.draft_layers),
           "--draft-embed-dim", str(args.draft_embed_dim),
           "--draft-heads", str(args.draft_heads),
           "--warmup", str(args.warmup)]
    if getattr(args, "slo", ""):
        cmd += ["--slo", args.slo]
    if args.draft_checkpoint:
        cmd += ["--draft-checkpoint", args.draft_checkpoint]
    if args.rope:
        cmd.append("--rope")
    if args.checkpoint:
        cmd += ["--checkpoint", args.checkpoint]
    if getattr(args, "shard", ""):
        cmd += ["--shard", args.shard]
    return cmd


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    m = p.add_argument_group("model")
    m.add_argument("--checkpoint", default="",
                   help="TrainState or params msgpack from train.lm (default: "
                        "seeded random init — pure perf mode)")
    m.add_argument("--seq-len", type=int, default=784)
    m.add_argument("--num-levels", type=int, default=16)
    m.add_argument("--embed-dim", type=int, default=64)
    m.add_argument("--num-layers", type=int, default=2)
    m.add_argument("--num-heads", type=int, default=4)
    m.add_argument("--kv-heads", type=int, default=0)
    m.add_argument("--attention-window", type=int, default=0)
    m.add_argument("--rope", action="store_true")
    e = p.add_argument_group("engine/server")
    e.add_argument("--num-slots", type=int, default=8)
    e.add_argument("--max-pending", type=int, default=128)
    e.add_argument("--timeout-s", type=float, default=0.0,
                   help="per-request deadline, 0 = none")
    e.add_argument("--prefill-chunks", default="32,128,512",
                   help="static chunk-size set for batched prefill; empty = "
                        "legacy prefill-as-decode (the A/B switch)")
    e.add_argument("--prefill-budget", type=int, default=1,
                   help="prefill chunk invocations per engine step (decode "
                        "interleaving)")
    e.add_argument("--prefix-cache", type=int, default=0,
                   help="prefix KV cache LRU entries, 0 = off")
    e.add_argument("--prefix-cache-bytes", type=int, default=0,
                   help="measured-byte budget for the prefix cache on top of "
                        "the entry count (0 = entry-count LRU only)")
    e.add_argument("--kv-layout", default="contiguous",
                   choices=("contiguous", "paged"),
                   help="KV store layout: 'paged' decouples slot count from "
                        "max context via a fixed page pool (DESIGN.md §27)")
    e.add_argument("--page-size", type=int, default=64,
                   help="paged layout: tokens per KV page")
    e.add_argument("--num-pages", type=int, default=0,
                   help="paged layout: pool size in pages (0 = capacity "
                        "parity with the contiguous cache)")
    e.add_argument("--kv-dtype", default="model",
                   choices=("model", "fp32", "bf16", "int8", "fp8"),
                   help="KV-cache plane dtype: int8/fp8 = quantize-on-write "
                        "planes with per-head scales (~half/quarter decode "
                        "bytes, ~2-4x slots per HBM budget) — the quant A/B "
                        "switch; 'model' keeps the bitwise-pinned fp32 path")
    e.add_argument("--quant-policy", default="off",
                   choices=("off", "w8", "w8a8"),
                   help="weight-matmul path: w8 = int8 kernels + per-channel "
                        "scales (f32 activations), w8a8 = int8 activations "
                        "too (int8 x int8 -> int32 matmul)")
    e.add_argument("--spec", default="off",
                   choices=("off", "ngram", "draft-lm"),
                   help="speculative decoding (the A/B switch): 'ngram' = "
                        "free host-side n-gram/prompt-lookup self-speculation "
                        "(big wins on --scenario chat), 'draft-lm' = a small "
                        "draft LM sharing the tokenizer")
    e.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens per verify step (verify program width "
                        "= spec_k + 1, one compile)")
    e.add_argument("--draft-layers", type=int, default=1,
                   help="draft LM: transformer layers")
    e.add_argument("--draft-embed-dim", type=int, default=0,
                   help="draft LM: embed dim (0 = half the target's)")
    e.add_argument("--draft-heads", type=int, default=0,
                   help="draft LM: heads (0 = the target's)")
    e.add_argument("--draft-checkpoint", default="",
                   help="trained draft-LM params msgpack (default: seeded "
                        "init)")
    e.add_argument("--slo", default="",
                   help="SLO spec 'ttft=0.5,e2e=2.0,window=30' (obs/slo.py): "
                        "the router (fleet mode) and every replica track "
                        "attainment against it — 'slo' drain events, summary "
                        "dicts, per-replica windows in fleet_snapshot; empty "
                        "= no promise")
    e.add_argument("--tenants", default="",
                   help="tenant service classes + traffic mix, e.g. "
                        "'paid:w=4,prio=2,share=0.25,slo=ttft:0.3;"
                        "free:w=1,preempt=1,share=0.75' — w/prio/rate/burst/"
                        "cap/preempt/slo are the scheduler's service-class "
                        "grammar (quotas, weighted-fair + priority dequeue, "
                        "slot caps, preemption), share= is this loadgen's "
                        "offered-traffic fraction; empty = one anonymous "
                        "tenant (the pre-tenancy behavior)")
    e.add_argument("--warmup", type=int, default=1,
                   help="pre-measurement warmup rounds: compile the decode, "
                        "every prefill chunk size, and the prefix-cache install "
                        "path, then reset the engine's counters — so latency "
                        "percentiles measure the schedule, not XLA (0 = off)")
    e.add_argument("--shard", default="",
                   help="replica-internal serve mesh, e.g. 'tp=2,dp=2' "
                        "(serving/shard.py): every replica shards its params "
                        "over tp chips and its slots over dp groups; on CPU "
                        "the loadgen grows the replicas' host-device count "
                        "via XLA_FLAGS to fit tp*dp virtual chips")
    f = p.add_argument_group("fleet (0 replicas = the in-process server)")
    f.add_argument("--tiers", default="",
                   help="disaggregated prefill/decode tiers, e.g. "
                        "'prefill:1,decode:2' (roles assigned to replicas by "
                        "position, DESIGN.md §25): prefill-tier replicas "
                        "prefill and ship KV planes to decode-tier replicas "
                        "over the framed wire; empty = a unified fleet")
    f.add_argument("--replicas", type=int, default=0,
                   help="run a serving.Router fleet of N replica PROCESSES "
                        "(serving/replica.py) instead of the in-process server")
    f.add_argument("--affinity", choices=("on", "off"), default="on",
                   help="prefix-affinity routing vs least-loaded baseline "
                        "(the router A/B switch)")
    f.add_argument("--echo", action="store_true",
                   help="fleet mode: spawn jax-free --echo replicas "
                        "(deterministic tokens, --echo-delay-s per token) — "
                        "the router-mechanics/elasticity smoke workload")
    f.add_argument("--echo-delay-s", type=float, default=0.0,
                   help="echo replicas: per-token sleep (keeps work in "
                        "flight so load actually accumulates)")
    f.add_argument("--replica-platform", default="cpu",
                   help="JAX_PLATFORMS for replica processes; '' = inherit "
                        "the environment (e.g. to put each replica's engine "
                        "on the accelerator)")
    f.add_argument("--router-max-pending", type=int, default=0,
                   help="router admission queue bound (0 = unbounded)")
    f.add_argument("--heartbeat-dir", default="",
                   help="replica liveness dir (default: a temp dir)")
    f.add_argument("--heartbeat-timeout-s", type=float, default=20.0,
                   help="beat staleness that counts a replica as hung")
    f.add_argument("--max-restarts", type=int, default=3,
                   help="per-replica restart budget")
    f.add_argument("--backoff-s", type=float, default=0.5,
                   help="restart backoff base (exponential, capped)")
    s = p.add_argument_group("elasticity (fleet mode)")
    s.add_argument("--autoscale", choices=("on", "off"), default="off",
                   help="drive scale_up/scale_down from the fleet_snapshot "
                        "load signal (hysteresis policy below; needs "
                        "--snapshot-interval-s > 0)")
    s.add_argument("--min-replicas", type=int, default=0,
                   help="scale-down floor (0 = --replicas, i.e. never shrink)")
    s.add_argument("--max-replicas", type=int, default=0,
                   help="scale-up cap (0 = --replicas when autoscaling, "
                        "unbounded for manual scaling)")
    s.add_argument("--scale-up-age-s", type=float, default=0.5,
                   help="queue head older than this counts as overloaded")
    s.add_argument("--scale-up-util", type=float, default=0.95,
                   help="in-flight/capacity at/above this counts as overloaded")
    s.add_argument("--scale-down-util", type=float, default=0.25,
                   help="empty queue + utilization at/below this counts idle")
    s.add_argument("--scale-sustain-up", type=int, default=2,
                   help="consecutive overloaded snapshots before a scale-up")
    s.add_argument("--scale-sustain-down", type=int, default=4,
                   help="consecutive idle snapshots before a scale-down")
    s.add_argument("--scale-cooldown-s", type=float, default=3.0,
                   help="dead time after any scale action")
    s.add_argument("--scale-slo-floor", type=float, default=0.0,
                   help="SLO-attainment objective: windowed attainment below "
                        "this floor counts as overloaded (grow) and BLOCKS "
                        "every shrink — the autoscaler scales on the promise, "
                        "not raw utilization (0 = utilization-only policy)")
    s.add_argument("--scale-slo-tenant", default="",
                   help="watch THIS tenant's windowed attainment from "
                        "fleet_snapshot's tenants section (the high tier) "
                        "instead of the fleet-wide window")
    s.add_argument("--scale-slo-min-requests", type=int, default=5,
                   help="minimum completions in the window before attainment "
                        "is trusted (noise guard)")
    s.add_argument("--warm-prefixes", type=int, default=8,
                   help="hot affinity prefixes a new replica replays before "
                        "it is marked ready (0 = cold starts)")
    s.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="how long a retiring/reloading replica may finish "
                        "in-flight work before stragglers redispatch")
    gf = p.add_argument_group("gray failures (fleet mode, DESIGN.md §23)")
    gf.add_argument("--straggler-k", type=float, default=0.0,
                    help="straggler ejection: a replica whose windowed "
                         "dispatch p95 exceeds k x the fleet-median peer p95 "
                         "is flipped to 'degraded' (no new dispatch, "
                         "in-flight finishes, probed back after the "
                         "cooldown); 0 = off")
    gf.add_argument("--eject-min-samples", type=int, default=8,
                    help="windowed samples required on the scored replica "
                         "before ejection can trip (noise guard)")
    gf.add_argument("--eject-cooldown-s", type=float, default=5.0,
                    help="degraded dwell before the probe re-opens dispatch")
    gf.add_argument("--hedge", choices=("on", "off"), default="off",
                    help="hedged dispatch: a request still pending past the "
                         "hedge deadline gets a speculative second copy on "
                         "another replica; first completion wins, the loser "
                         "is cancelled over the wire")
    gf.add_argument("--hedge-after-s", type=float, default=0.0,
                    help="fixed hedge deadline in seconds (0 = derive from "
                         "the fleet's windowed dispatch-latency quantile)")
    gf.add_argument("--hedge-quantile", type=float, default=95.0,
                    help="quantile of the windowed fleet dispatch latency "
                         "the derived hedge deadline starts from")
    gf.add_argument("--hedge-factor", type=float, default=2.0,
                    help="multiplier on the quantile for the derived "
                         "deadline")
    gf.add_argument("--chaos", default="",
                    help="network-chaos spec (resilience/netfaults.py "
                         "grammar, e.g. 'delay:replica=1,ms=800,count=20;"
                         "corrupt:replica=0,after=5'): route every "
                         "router<->replica connection through a seeded "
                         "in-process fault-injecting proxy")
    gf.add_argument("--chaos-seed", type=int, default=0,
                    help="seed for the chaos proxy's corrupt-byte positions")
    gf.add_argument("--framed-wire", choices=("on", "off"), default="on",
                    help="negotiate length+CRC wire framing with replicas "
                         "that advertise it ('off' pins the legacy newline "
                         "protocol — the back-compat A/B switch)")
    g = p.add_argument_group("load")
    g.add_argument("--scenario", choices=("batch", "chat"), default="batch",
                   help="'batch' = independent requests (open/closed loop); "
                        "'chat' = multi-turn sessions, each turn resubmitting "
                        "prior context + reply (the prefix-affinity workload)")
    g.add_argument("--sessions", type=int, default=8,
                   help="chat: concurrent sessions")
    g.add_argument("--turns", type=int, default=4,
                   help="chat: turns per session")
    g.add_argument("--turn-user-tokens", type=int, default=4,
                   help="chat: fresh 'user' tokens appended between turns")
    g.add_argument("--mode", choices=("open", "closed"), default="open")
    g.add_argument("--rate", type=float, default=8.0,
                   help="open loop: Poisson arrival rate, req/s")
    g.add_argument("--arrival-pattern", choices=("poisson", "burst"),
                   default="poisson",
                   help="open loop: 'burst' sends --burst-size requests "
                        "back-to-back then idles --burst-idle-s (the "
                        "autoscaler exercise: spike -> grow, valley -> shrink)")
    g.add_argument("--burst-size", type=int, default=8,
                   help="burst pattern: requests per spike")
    g.add_argument("--burst-idle-s", type=float, default=1.0,
                   help="burst pattern: idle valley between spikes")
    g.add_argument("--burst-tenant", default="",
                   help="with --tenants: only THIS tenant's arrival stream "
                        "bursts (back-to-back spikes) while the others stay "
                        "Poisson — the contended two-tenant scenario the "
                        "tenant-burst artifact drives (best-effort spikes, "
                        "paid holds its SLO)")
    g.add_argument("--concurrency", type=int, default=4,
                   help="closed loop: clients with one request in flight each")
    g.add_argument("--requests", type=int, default=32)
    g.add_argument("--prompt-dist", choices=("custom", "long"), default="custom",
                   help="'long' = prompt-heavy mixture (seq_len/2..3/4) that "
                        "exercises prefill; 'custom' uses --prompt-lens")
    g.add_argument("--prompt-lens", default="0,16,64",
                   help="comma list; each request draws uniformly from it")
    g.add_argument("--shared-prefix-len", type=int, default=0,
                   help="force a common first-N-token prefix across prompts "
                        "(exercises the prefix KV cache)")
    g.add_argument("--max-new-tokens", type=int, default=32,
                   help="each request draws its length from [1, this]")
    g.add_argument("--temperature", type=float, default=0.0)
    g.add_argument("--top-k", type=int, default=0)
    g.add_argument("--top-p", type=float, default=1.0)
    g.add_argument("--seed", type=int, default=0)
    p.add_argument("--telemetry", default="",
                   help="serve JSONL path (render with tools/telemetry_report.py)")
    p.add_argument("--trace-dir", default="",
                   help="distributed-tracing span dir: this loadgen writes "
                        "loadgen.jsonl (client spans + per-request trace_id "
                        "origin), the router/server and every replica write "
                        "their own span files under it — render with "
                        "tools/trace_report.py")
    p.add_argument("--snapshot-interval-s", type=float, default=0.0,
                   help="fleet mode: the router emits a fleet_snapshot "
                        "metrics-timeline event every N seconds (the "
                        "elasticity load signal; needs --telemetry, 0 = off)")
    p.add_argument("--summary-json", default="",
                   help="write the run summary (percentiles + prefill stats) "
                        "as one JSON document — the committed-artifact format")
    args = p.parse_args(argv)
    if args.scenario == "batch":
        if args.mode == "open" and args.rate <= 0:
            raise SystemExit("--rate must be > 0 in open-loop mode")
        if args.mode == "closed" and args.concurrency < 1:
            raise SystemExit("--concurrency must be >= 1 in closed-loop mode")
    elif args.sessions < 1 or args.turns < 1:
        raise SystemExit("--sessions and --turns must be >= 1 in chat mode")
    if args.max_new_tokens < 1:
        raise SystemExit("--max-new-tokens must be >= 1")
    if args.echo and args.replicas < 1:
        raise SystemExit("--echo needs --replicas N (echo replicas are a "
                         "fleet-mode workload)")
    tier_roles: list[str] = []
    if args.tiers:
        from csed_514_project_distributed_training_using_pytorch_tpu.serving.tiers import (
            parse_tier_spec,
        )

        if args.replicas < 1:
            raise SystemExit("--tiers needs --replicas N (tiered serving is "
                             "a fleet-mode workload)")
        try:
            tier_roles = parse_tier_spec(args.tiers)
        except ValueError as exc:
            raise SystemExit(str(exc))
        if len(tier_roles) != args.replicas:
            raise SystemExit(
                f"--tiers names {len(tier_roles)} replica role(s) but "
                f"--replicas is {args.replicas} — the spec assigns roles by "
                f"position and must cover the whole fleet")
    shard_tp = shard_dp = 1
    if args.shard:
        from csed_514_project_distributed_training_using_pytorch_tpu.serving.tiers import (
            parse_shard_spec,
        )

        if args.echo:
            raise SystemExit("--shard needs a real engine (echo replicas "
                             "build no mesh)")
        try:
            shard_tp, shard_dp = parse_shard_spec(args.shard)
        except ValueError as exc:
            raise SystemExit(str(exc))
    if args.burst_tenant:
        known = set(tenant_shares(args.tenants)) if args.tenants else set()
        if args.burst_tenant not in known:
            # A typo here would silently disable ALL bursting and report an
            # unloaded run as the loaded leg of an A/B — fail loudly instead.
            raise SystemExit(
                f"--burst-tenant {args.burst_tenant!r} is not one of the "
                f"--tenants names {sorted(known) or '(none declared)'}")

    vocab_size = args.num_levels + 1
    tracer = None
    if args.trace_dir:
        # This loadgen is the trace ORIGIN: it writes loadgen.jsonl (the
        # outermost "client" spans) and every downstream process writes its own
        # span file under the same dir — see utils/trace.py.
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.trace import (
            Tracer,
        )

        tracer = Tracer(os.path.join(args.trace_dir, "loadgen.jsonl"),
                        proc="loadgen")
    engine = server = router = None
    if args.replicas > 0:
        # Fleet mode: the model lives in the replica processes; this process
        # stays backend-free (the router supervises accelerator owners).
        import tempfile

        from csed_514_project_distributed_training_using_pytorch_tpu.obs.slo import (
            SLOSpec,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.serving.router import (
            Router,
        )
        from csed_514_project_distributed_training_using_pytorch_tpu.serving.scheduler import (
            parse_tenants,
        )

        # Replica processes must import this package no matter the caller's
        # cwd — ship the repo root (already first on OUR sys.path, line 53)
        # through their PYTHONPATH.
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = (f"{repo_root}:{env['PYTHONPATH']}"
                             if env.get("PYTHONPATH") else repo_root)
        if shard_tp * shard_dp > 1 and (args.replica_platform or "cpu") == "cpu":
            # A CPU replica has one host device by default; grow it so the
            # tp*dp serve mesh has chips to land on (the same trick the test
            # suite uses — a multi-process CPU "mesh" of virtual devices).
            flag = (f"--xla_force_host_platform_device_count="
                    f"{shard_tp * shard_dp}")
            env["XLA_FLAGS"] = (f"{env['XLA_FLAGS']} {flag}"
                                if env.get("XLA_FLAGS") else flag)
        autoscale = None
        if args.autoscale == "on":
            from csed_514_project_distributed_training_using_pytorch_tpu.serving.autoscaler import (
                AutoscalePolicy,
            )

            autoscale = AutoscalePolicy(
                min_replicas=args.min_replicas or args.replicas,
                max_replicas=args.max_replicas or args.replicas,
                up_queue_age_s=args.scale_up_age_s,
                up_utilization=args.scale_up_util,
                down_utilization=args.scale_down_util,
                sustain_up=args.scale_sustain_up,
                sustain_down=args.scale_sustain_down,
                cooldown_s=args.scale_cooldown_s,
                slo_floor=args.scale_slo_floor or None,
                slo_tenant=args.scale_slo_tenant or None,
                slo_min_requests=args.scale_slo_min_requests)
        router = Router(
            build_replica_command(args), num_replicas=args.replicas,
            platform=args.replica_platform or None,
            max_pending=args.router_max_pending,
            default_timeout_s=args.timeout_s or None,
            affinity=args.affinity == "on",
            heartbeat_dir=args.heartbeat_dir or tempfile.mkdtemp(
                prefix="serve_hb_"),
            heartbeat_timeout_s=args.heartbeat_timeout_s,
            max_restarts=args.max_restarts, backoff_s=args.backoff_s,
            telemetry=args.telemetry, trace_dir=args.trace_dir,
            snapshot_interval_s=args.snapshot_interval_s,
            autoscale=autoscale,
            min_replicas=args.min_replicas or None,
            max_replicas=args.max_replicas or None,
            warm_prefixes=args.warm_prefixes,
            drain_timeout_s=args.drain_timeout_s,
            straggler_k=args.straggler_k,
            eject_min_samples=args.eject_min_samples,
            eject_cooldown_s=args.eject_cooldown_s,
            hedge=args.hedge == "on",
            hedge_after_s=args.hedge_after_s,
            hedge_quantile=args.hedge_quantile,
            hedge_factor=args.hedge_factor,
            chaos=args.chaos, chaos_seed=args.chaos_seed,
            framed_wire=args.framed_wire == "on",
            slo=SLOSpec.parse(args.slo),
            # The router is the fleet's ONE quota-charging front door; the
            # replica argv deliberately omits --tenants (per-request tenancy
            # fields ride the wire instead) so admission is never charged
            # twice.
            tenants=parse_tenants(args.tenants), env=env,
            replica_extra_args=([["--tier", role] for role in tier_roles]
                                if tier_roles else None))
        front = router.start()
        if not router.wait_ready(timeout=600):
            router.stop(drain=False)
            raise SystemExit("fleet did not come up within 600s "
                             "(or crash-looped its restart budget away — "
                             "check the replica command/stderr)")
    else:
        # The in-process baseline is built by the SAME code path as a fleet
        # replica (model construction, checkpoint-format fallback, warmup
        # recipe) — one owner, so the single-engine and fleet sides of an A/B
        # can never drift apart.
        if (shard_tp * shard_dp > 1
                and os.environ.get("JAX_PLATFORMS", "cpu") == "cpu"):
            # Same trick as the fleet path, applied to OUR process: grow the
            # single host CPU device into tp*dp virtual chips. XLA reads the
            # flag at backend INITIALIZATION (first devices() call, inside
            # the engine build below), so setting it here is early enough
            # even though the package import already loaded the jax module.
            flag = (f"--xla_force_host_platform_device_count="
                    f"{shard_tp * shard_dp}")
            os.environ["XLA_FLAGS"] = \
                (os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
        from csed_514_project_distributed_training_using_pytorch_tpu.serving.replica import (
            build_engine_server,
        )

        engine, server = build_engine_server(
            args, trace=(os.path.join(args.trace_dir, "server.jsonl")
                         if args.trace_dir else None))
        front = server.start()
    if tracer is not None:
        front = _TracedFront(front, tracer)

    t0 = time.monotonic()
    sessions_done = None
    try:
        if args.scenario == "chat":
            comps, rejections, sessions_done = run_chat(front, args, vocab_size)
        else:
            specs = make_workload(args, vocab_size)
            if args.mode == "open":
                futures, rejections = run_open_loop(
                    front, specs, args.rate, np.random.default_rng(args.seed + 1),
                    pattern=args.arrival_pattern,
                    burst_size=args.burst_size,
                    burst_idle_s=args.burst_idle_s,
                    burst_tenant=args.burst_tenant)
            else:
                futures, rejections = run_closed_loop(front, specs,
                                                      args.concurrency)
            comps = [f.result() for f in futures]
        rejected = rejections["rejected"]
    except BaseException:
        # Never orphan replica processes on a failed run.
        try:
            front.stop(drain=False)
        except Exception:
            pass
        raise
    # Wall stops when the last completion is in hand: stop() below pays stats
    # collection + replica teardown, which served no tokens and must not
    # deflate the committed tokens_per_s.
    wall = time.monotonic() - t0
    router_summary = None
    if router is not None:
        router_summary = router.stop(timeout=600)   # graceful drain + stats
    else:
        server.stop()                               # graceful drain (a no-op by now)
    if tracer is not None:
        tracer.close()     # after stop(): every client span's callback has run

    ok = sum(c.ok for c in comps)
    timeouts = sum(c.finish == "timeout" for c in comps)
    shed_comps = sum(c.finish == "shed" for c in comps)
    new_tokens = sum(c.new_tokens for c in comps)
    label = (f"chat ({args.sessions} sessions x {args.turns} turns)"
             if args.scenario == "chat" else f"{args.mode}-loop")
    print(f"{label}: {len(comps)} completed ({ok} ok, {timeouts} timeout, "
          f"{shed_comps} shed, {rejected} rejected, "
          f"{rejections['quota_rejected']} over-quota, "
          f"{rejections['shed_submits']} shed-at-submit) in {wall:.2f}s"
          + (f", {sessions_done}/{args.sessions} sessions ran to completion"
             if sessions_done is not None else ""))

    def comp_tenant(c) -> str:
        t = getattr(c, "tenant", None)
        if t is None:
            t = getattr(getattr(c, "request", None), "tenant", None)
        return t or "default"

    tenant_rows = None
    if args.tenants:
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
            percentiles as _pcts,
        )

        tenant_rows = {}
        for t in sorted({comp_tenant(c) for c in comps}
                        | set(rejections["by_tenant"])):
            tc = [c for c in comps if comp_tenant(c) == t]
            rej = rejections["by_tenant"].get(t) or {}
            tenant_rows[t] = {
                "requests": len(tc),
                "ok": sum(c.ok for c in tc),
                "timeout": sum(c.finish == "timeout" for c in tc),
                "shed": sum(c.finish == "shed" for c in tc),
                "preemptions": sum(getattr(c, "preemptions", 0) for c in tc),
                "new_tokens": sum(c.new_tokens for c in tc),
                "ttft_s": _pcts([c.ttft_s for c in tc]),
                "e2e_s": _pcts([c.e2e_s for c in tc]),
                **rej,
            }
            row = tenant_rows[t]
            p95 = (row["ttft_s"] or {}).get("p95")
            print(f"tenant {t}: {row['requests']} requests "
                  f"({row['ok']} ok, {row['timeout']} timeout, "
                  f"{row['shed']} shed, {row['preemptions']} preemption(s)), "
                  f"ttft p95 {'-' if p95 is None else f'{p95:.3f}'}s")
    if router is not None:
        rs = router_summary
        pc = rs.get("prefix_cache") or {}
        hit_rate = (pc["hits"] / pc["queries"] if pc.get("queries") else None)
        aff = rs["affinity_rate"]
        print(f"fleet: {args.replicas} replicas, affinity {args.affinity}: "
              f"{new_tokens} tokens, {new_tokens / wall:.1f} tokens/s, "
              f"affinity rate {'-' if aff is None else f'{aff:.2f}'}, "
              f"prefix hit rate {'-' if hit_rate is None else f'{hit_rate:.2f}'}")
        print(f"resilience: {rs['redispatches']} redispatches "
              f"({rs['redispatched_requests']} requests), "
              f"{rs['replica_restarts']} replica restart(s), "
              f"{rs['duplicates']} duplicate completion(s)")
        if (rs.get("ejections") or rs.get("hedges")
                or rs.get("wire_corrupt")):
            win = rs.get("hedge_win_rate")
            print(f"gray failures: {rs.get('ejections', 0)} ejection(s), "
                  f"{rs.get('probes', 0)} probe recover(ies), "
                  f"{rs.get('hedges', 0)} hedge(s) "
                  f"(win rate {'-' if win is None else f'{win:.2f}'}), "
                  f"{rs.get('wire_corrupt', 0)} typed wire fault(s)")
        if rs.get("handoffs") or rs.get("handoff_failures"):
            disagg = sum(getattr(c, "disagg", False) for c in comps)
            print(f"tiers ({args.tiers or '?'}): {rs.get('handoffs', 0)} "
                  f"kv handoff(s), {rs.get('handoff_bytes', 0)} bytes shipped, "
                  f"{rs.get('handoff_failures', 0)} bounced to local prefill, "
                  f"{disagg} request(s) served disaggregated")
        sp = rs.get("spec") or {}
        if sp:
            rate = sp.get("acceptance_rate")
            tps = sp.get("accepted_tokens_per_step")
            print(f"spec: {sp.get('mode')} k={sp.get('k')}: "
                  f"{sp['accepted']}/{sp['proposed']} drafts accepted "
                  f"(rate {'-' if rate is None else f'{rate:.2f}'}), "
                  f"{'-' if tps is None else f'{tps:.2f}'} accepted tok/step "
                  f"fleet-wide")
        fleet_slo = rs.get("slo")
        if fleet_slo:
            att = fleet_slo.get("attainment")
            print(f"slo: attainment "
                  f"{'-' if att is None else f'{att:.3f}'} "
                  f"({fleet_slo.get('met')}/{fleet_slo.get('requests')} met "
                  f"vs {args.slo})")
        if rs.get("preemptions") or rs.get("resumes"):
            print(f"preemption: {rs.get('preemptions')} park(s), "
                  f"{rs.get('resumes')} resume(s) fleet-wide")
        sc = rs.get("scale") or {}
        if rs.get("scale_events"):
            print(f"elasticity: {sc.get('scale_ups', 0)} scale-up(s), "
                  f"{sc.get('retired', 0)} graceful retire(s), "
                  f"{sc.get('reloads', 0)} reload(s); "
                  f"replicas ready p50 "
                  f"{rs.get('replicas_ready_p50') or '-'} / max "
                  f"{rs.get('replicas_ready_max') or '-'} "
                  f"(target ended at {rs.get('target')})")
    else:
        occ = engine.slot_occupancy             # None when no step ever ran
        print(f"generated {new_tokens} tokens, {new_tokens / wall:.1f} tokens/s, "
              f"slot occupancy {'-' if occ is None else f'{occ:.2f}'}, "
              f"decode compilations {engine.trace_count}")
        prefill_rate = (engine.prefill_tokens / engine.prefill_wall_s
                        if engine.prefill_wall_s else None)
        sp = engine.spec_stats()
        if sp:
            rate = sp.get("acceptance_rate")
            tps = sp.get("accepted_tokens_per_step")
            print(f"spec: {sp['mode']} k={sp['k']}: "
                  f"{sp['accepted']}/{sp['proposed']} drafts accepted "
                  f"(rate {'-' if rate is None else f'{rate:.2f}'}), "
                  f"{'-' if tps is None else f'{tps:.2f}'} accepted tok/step, "
                  f"{engine.generated_tokens} tokens in {engine.steps} "
                  f"program invocations")
        srv_slo = server.slo_summary()
        if srv_slo:
            att = srv_slo.get("attainment")
            print(f"slo: attainment "
                  f"{'-' if att is None else f'{att:.3f}'} "
                  f"({srv_slo.get('met')}/{srv_slo.get('requests')} met "
                  f"vs {args.slo})")
        if engine.preemptions or engine.resumes:
            print(f"preemption: {engine.preemptions} park(s), "
                  f"{engine.resumes} resume(s)")
        hits = engine.prefix_cache.stats() if engine.prefix_cache else None
        print(f"prefilled {engine.prefill_tokens} prompt tokens in "
              f"{engine.prefill_invocations} chunks "
              f"({'-' if prefill_rate is None else f'{prefill_rate:.1f}'} tokens/s, "
              f"sizes {list(engine.prefill_chunk_sizes) or 'off'})"
              + (f", prefix hits {hits['hits']}/{hits['queries']} "
                 f"({hits['hit_tokens']} tokens reused)" if hits else ""))
        acct = engine.byte_accounting()
        print(f"bytes (measured): kv {acct['kv_dtype']} / weights "
              f"{acct['quant_policy']}, {acct['kv_bytes_per_slot']} B/slot, "
              f"{acct['decode_bytes_per_token']:.0f} B decode read/token, "
              f"{acct['slots_at_budget']} slots per "
              f"{acct['hbm_budget_bytes'] >> 30} GiB budget")
    if args.telemetry:
        print(f"serve telemetry -> {args.telemetry} "
              f"(render: python tools/telemetry_report.py {args.telemetry})")
    trace_summary = None
    if args.trace_dir:
        # Reduce the span files the run just wrote (loadgen + router/server +
        # every replica) to the critical-path summary; the full per-request
        # trees render via tools/trace_report.py.
        from csed_514_project_distributed_training_using_pytorch_tpu.utils.trace import (
            read_spans,
            summarize_traces,
        )

        spans, _ = read_spans([args.trace_dir])
        trace_summary = summarize_traces(spans)
        seg = trace_summary["segments"]
        top = sorted(seg, key=lambda n: -(seg[n]["p50"] or 0))[:3]
        path = ", ".join(f"{n} p50 {(seg[n]['p50'] or 0) * 1e3:.1f}ms"
                         for n in top)
        print(f"trace: {trace_summary['traces']} traces, "
              f"{trace_summary['spans']} spans, "
              f"{trace_summary['orphans']} orphans, "
              f"{trace_summary['redispatched']} redispatched"
              + (f"; critical path {path}" if path else ""))
        print(f"trace spans -> {args.trace_dir} "
              f"(render: python tools/trace_report.py {args.trace_dir}"
              + (f" {args.telemetry}" if args.telemetry else "") + ")")
    if args.summary_json:
        import json

        from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
            percentiles,
        )

        doc = {
            "scenario": args.scenario,
            "mode": args.mode if args.scenario == "batch" else None,
            "requests": len(comps), "ok": ok, "timeout": timeouts,
            "shed": shed_comps, "rejected": rejected,
            "quota_rejected": rejections["quota_rejected"],
            "shed_submits": rejections["shed_submits"],
            "tenants_spec": args.tenants or None,
            "burst_tenant": args.burst_tenant or None,
            "tenants": tenant_rows,
            "wall_s": wall,
            "prompt_dist": args.prompt_dist,
            "prompt_lens": prompt_len_mix(args),
            "shared_prefix_len": args.shared_prefix_len,
            "num_slots": args.num_slots,
            "prefill_chunk_budget": args.prefill_budget,
            "prefix_cache_entries": args.prefix_cache,
            "kv_dtype": args.kv_dtype,
            "quant_policy": args.quant_policy,
            "spec": args.spec,
            "spec_k": args.spec_k if args.spec != "off" else None,
            "new_tokens": new_tokens,
            "tokens_per_s": new_tokens / wall if wall else None,
            "ttft_s": percentiles([c.ttft_s for c in comps]),
            "e2e_s": percentiles([c.e2e_s for c in comps]),
            "queue_wait_s": percentiles([c.queue_wait_s for c in comps]),
            "slo": args.slo or None,
        }
        if args.scenario == "chat":
            doc.update(sessions=args.sessions, turns=args.turns,
                       turn_user_tokens=args.turn_user_tokens,
                       sessions_done=sessions_done)
        if router is not None:
            rs = router_summary
            pc = rs.get("prefix_cache") or {}
            doc.update(
                replicas=args.replicas, affinity=args.affinity,
                echo=args.echo, autoscale=args.autoscale,
                arrival_pattern=(args.arrival_pattern
                                 if args.scenario == "batch"
                                 and args.mode == "open" else None),
                scale=rs.get("scale"),
                scale_events=rs.get("scale_events"),
                target=rs.get("target"),
                replicas_ready_p50=rs.get("replicas_ready_p50"),
                replicas_ready_max=rs.get("replicas_ready_max"),
                replicas_ready_min=rs.get("replicas_ready_min"),
                affinity_rate=rs["affinity_rate"],
                redispatches=rs["redispatches"],
                redispatched_requests=rs["redispatched_requests"],
                duplicate_completions=rs["duplicates"],
                hedge=args.hedge, straggler_k=args.straggler_k or None,
                chaos=args.chaos or None,
                ejections=rs.get("ejections"),
                probes=rs.get("probes"),
                hedges=rs.get("hedges"),
                hedge_wins=rs.get("hedge_wins"),
                hedge_win_rate=rs.get("hedge_win_rate"),
                wire_corrupt=rs.get("wire_corrupt"),
                replica_restarts=rs["replica_restarts"],
                prefix_cache=rs.get("prefix_cache"),
                prefix_hit_rate=(pc["hits"] / pc["queries"]
                                 if pc.get("queries") else None),
                spec_stats=rs.get("spec"),
                tiers=args.tiers or None,
                shard=args.shard or None,
                handoffs=rs.get("handoffs"),
                handoff_bytes=rs.get("handoff_bytes"),
                handoff_failures=rs.get("handoff_failures"),
                disagg_requests=sum(getattr(c, "disagg", False)
                                    for c in comps),
                per_replica=[{k: r[k] for k in ("replica", "state", "restarts",
                                                "dispatched", "completed",
                                                "tier", "handoffs")
                              if k in r}
                             for r in rs["per_replica"]],
                slo_attainment=rs.get("slo"),
                replica_latency=rs.get("replica_latency"),
                tenant_summary=rs.get("tenants"),
                preemptions=rs.get("preemptions"),
                resumes=rs.get("resumes"),
                router_queue=rs.get("queue"))
        else:
            doc.update(
                shard=args.shard or None,
                bytes=engine.byte_accounting(),
                prefill_chunk_sizes=list(engine.prefill_chunk_sizes),
                prefill_tokens=engine.prefill_tokens,
                prefill_chunks=engine.prefill_invocations,
                prefill_wall_s=engine.prefill_wall_s,
                prefill_tokens_per_s=prefill_rate,
                prefix_cache=hits,
                prefix_hit_rate=(hits["hits"] / hits["queries"]
                                 if hits and hits["queries"] else None),
                decode_compilations=engine.trace_count,
                prefill_compilations=dict(engine.prefill_trace_counts),
                decode_invocations=engine.steps,
                generated_tokens=engine.generated_tokens,
                spec_stats=engine.spec_stats(),
                slo_attainment=server.slo_summary(),
                tenant_summary=server.tenant_summaries() or None,
                preemptions=engine.preemptions,
                resumes=engine.resumes,
                verify_compilations=dict(engine.verify_trace_counts))
        if trace_summary is not None:
            # The run carries its trace with it: where the spans live plus the
            # span-derived critical-path percentiles, next to the serve
            # percentiles above — an A/B pair of summaries is self-contained.
            from csed_514_project_distributed_training_using_pytorch_tpu.utils.trace import (
                reconcile_ttft,
            )

            events = []
            if args.telemetry and os.path.exists(args.telemetry):
                from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
                    read_jsonl,
                )

                events = read_jsonl(args.telemetry)
            doc["trace"] = {
                "dir": args.trace_dir,
                "traces": trace_summary["traces"],
                "spans": trace_summary["spans"],
                "orphans": trace_summary["orphans"],
                "redispatched": trace_summary["redispatched"],
                "segments": trace_summary["segments"],
                "ttft_s": trace_summary["ttft_s"],
                "e2e_s": trace_summary["e2e_s"],
                "ttft_reconciliation": reconcile_ttft(trace_summary, events),
            }
        with open(args.summary_json, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"summary json -> {args.summary_json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
