"""Acceptance harness for the numerical immune system (train/step.py --guard).

Four legs over the SAME tiny supervised workload (train.distributed, one CPU
process, synthetic MNIST fixture, 4 steps/epoch x 3 epochs), gates asserted by
exit code and the whole ledger written to ``--out-dir``:

1. **faulted** — grad poison armed (``spike:step=6,scale=1e6`` +
   ``nan:step=9``) under the supervisor with ``--guard --anomaly-exit 1``: the
   guard must detect BOTH injections, apply identity updates instead of
   garbage, exit 65 ("poisoned") at each offending epoch boundary; the
   supervisor must roll back to the newest HEALTHY checkpoint and restart with
   the accumulated ``--skip-steps`` set (scattered second poison also arms
   fingerprint-verify), and the run must complete.
2. **oracle** — NO faults, trained start-to-finish with the faulted leg's
   final skip set: final params must be **bitwise identical** to the faulted
   supervised run's final checkpoint (the rollback-and-skip contract: a cured
   run IS the run that never saw the poison).
3/4. **flag pins** — guard-on-no-faults vs guard-off: bitwise identical
   (the guard adds verdict+select ops but an anomaly-free verdict selects the
   fresh update exactly), pinning today's trainer behavior.

Goodput: the faulted leg's joined telemetry+supervisor streams must decompose
with ``rollback_badput_s > 0``, ``restart_badput_s == 0`` (no process crashed
— the math did), and segments summing to wall +/-1%; the oracle leg must show
both badputs exactly 0.0.

Checkpoint hygiene: every file in the faulted store decodes with all-finite
params, and every rollback resume target carried a clean health stamp — a
poisoned state is never checkpointed, and never resumed from.

Committed artifact: ``bench_results/anomaly_train_cpu/`` (summary.json +
goodput.json + the two telemetry streams). ``--quick`` skips the flag-pin
legs (CI smoke).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PKG = "csed_514_project_distributed_training_using_pytorch_tpu"

SPIKE_STEP, NAN_STEP = 6, 9
FAULTS = f"spike:step={SPIKE_STEP},scale=1e6;nan:step={NAN_STEP}"
INJECTIONS = 2


def train_cmd(*extra: str) -> list[str]:
    return ["-m", f"{PKG}.train.distributed",
            "--epochs", "3", "--global-batch-size", "64",
            "--batch-size-test", "256",
            "--max-train-examples", "256", "--max-test-examples", "256",
            "--keep-checkpoints", "5", *extra]


def leaves_of(path: str, *, params_only: bool = False):
    import jax
    from flax import serialization

    with open(path, "rb") as f:
        tree = serialization.msgpack_restore(f.read())
    if params_only:
        # The flag-pin comparison: a guarded checkpoint carries 9 extra
        # detector scalars by design — the pin is about the MODEL trajectory
        # (params + optimizer state + step), not the carry bookkeeping.
        tree = {k: tree[k] for k in ("params", "velocity", "step")}
    return jax.tree_util.tree_leaves(tree)


def assert_bitwise(path_a: str, path_b: str, what: str, *,
                   params_only: bool = False) -> int:
    import numpy as np

    la = leaves_of(path_a, params_only=params_only)
    lb = leaves_of(path_b, params_only=params_only)
    assert len(la) == len(lb), f"{what}: leaf count {len(la)} != {len(lb)}"
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)
    return len(la)


def run_leg(workdir: str, cmd_extra: list[str], *, faults: str = "",
            supervised: bool = False, telemetry: str = "run.jsonl"):
    """One training leg in its own cwd; returns (store_dir, supervise result or
    exit code)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
        supervisor as sup,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.train.launch import (
        launch,
    )

    os.makedirs(workdir, exist_ok=True)
    cwd = os.getcwd()
    # Children run from the leg's scratch cwd — they must still find the repo.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    if repo not in existing.split(os.pathsep):
        os.environ["PYTHONPATH"] = (f"{repo}{os.pathsep}{existing}"
                                    if existing else repo)
    if faults:
        os.environ["RESILIENCE_FAULTS"] = faults
    else:
        os.environ.pop("RESILIENCE_FAULTS", None)
    try:
        os.chdir(workdir)
        store = os.path.join(os.getcwd(), "results", "checkpoints")
        cmd = train_cmd(*cmd_extra) + ["--telemetry", telemetry]
        if supervised:
            cfg = sup.SupervisorConfig(
                num_processes=1, platform="cpu", devices_per_process=1,
                max_restarts=4, backoff_s=0.0, checkpoint_dir=store,
                attempt_timeout_s=600,
                telemetry=os.path.join(os.getcwd(), "supervisor.jsonl"))
            return store, sup.supervise(cmd, cfg)
        rc = launch(cmd, num_processes=1, platform="cpu",
                    devices_per_process=1, timeout=600)
        return store, rc
    finally:
        os.environ.pop("RESILIENCE_FAULTS", None)
        os.chdir(cwd)


def attempt_anomaly_counts(run_jsonl: str) -> list[int]:
    """Per-attempt detected-anomaly count: split the preserved multi-attempt
    telemetry at each manifest, take the attempt's final cumulative counter
    (each attempt resumes from a CLEAN checkpoint, so its baseline is 0)."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
        read_jsonl,
    )

    counts: list[int] = []
    for row in read_jsonl(run_jsonl):
        if row.get("event") == "manifest":
            counts.append(0)
        elif row.get("event") == "anomaly" and counts:
            counts[-1] = max(counts[-1], int(row.get("anomalies") or 0))
    return counts


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--out-dir", default="bench_results/anomaly_train_cpu")
    p.add_argument("--work-dir", default="",
                   help="scratch dir for the runs (default: <out-dir>/work, "
                        "removed on success)")
    p.add_argument("--quick", action="store_true",
                   help="skip the flag-pin legs (CI smoke)")
    args = p.parse_args(argv)

    import numpy as np  # noqa: F401  (assert_bitwise)

    from csed_514_project_distributed_training_using_pytorch_tpu.obs import (
        goodput,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.resilience import (
        poison,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint as ckpt,
    )

    out_dir = os.path.abspath(args.out_dir)
    work = os.path.abspath(args.work_dir or os.path.join(out_dir, "work"))
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(out_dir, exist_ok=True)
    summary: dict = {"faults": FAULTS, "injections": INJECTIONS}
    gates: dict[str, bool] = {}

    # -- leg 1: faulted supervised run --------------------------------------
    print(f"[anomaly-bench] leg 1/4: faulted supervised run ({FAULTS})")
    f_store, res = run_leg(os.path.join(work, "faulted"),
                           ["--guard", "--anomaly-exit", "1"],
                           faults=FAULTS, supervised=True)
    skip = poison.format_skip_steps(res.skip_windows)
    summary["faulted"] = {
        "status": res.status, "attempts": res.attempts,
        "restarts": res.restarts, "rollbacks": res.rollbacks,
        "skip_windows": skip,
        "resume_history": res.resume_history,
    }
    gates["faulted_completes"] = res.status == "ok"
    gates["two_rollbacks"] = res.rollbacks == INJECTIONS
    gates["skip_covers_injections"] = res.skip_windows == (
        (SPIKE_STEP, SPIKE_STEP + 1), (NAN_STEP, NAN_STEP + 1))

    # Every injection detected (per-attempt anomaly counters sum to the
    # injection count — each injection is detected exactly once, by the
    # attempt that first met it outside a skip window).
    run_jsonl = os.path.join(work, "faulted", "run.jsonl")
    counts = attempt_anomaly_counts(run_jsonl)
    summary["faulted"]["per_attempt_anomalies"] = counts
    gates["every_injection_detected"] = sum(counts) == INJECTIONS

    # No poisoned state ever checkpointed: every surviving store file decodes
    # with all-finite params; every rollback resume target was stamped clean.
    manifest = ckpt.load_manifest(f_store)
    finite = True
    for e in manifest["entries"]:
        for leaf in leaves_of(os.path.join(f_store, e["file"])):
            import numpy as _np
            arr = _np.asarray(leaf)
            if arr.dtype.kind == "f" and not _np.isfinite(arr).all():
                finite = False
    gates["checkpoints_all_finite"] = finite
    stamps = {e["file"]: (e.get("health") or {}) for e in manifest["entries"]}
    resumed_clean = all(
        stamps.get(os.path.basename(r), {}).get("clean", True) is True
        for r in res.resume_history if r)
    gates["rollback_targets_clean"] = resumed_clean
    summary["faulted"]["manifest_stamps"] = {
        e["file"]: e.get("health") for e in manifest["entries"]}

    # -- leg 2: unfaulted oracle with the same skip set ---------------------
    print(f"[anomaly-bench] leg 2/4: oracle (no faults, --skip-steps {skip})")
    o_store, rc = run_leg(os.path.join(work, "oracle"),
                          ["--guard", "--skip-steps", skip])
    gates["oracle_completes"] = rc == 0
    n_leaves = assert_bitwise(ckpt.newest_valid_checkpoint(f_store),
                              ckpt.newest_valid_checkpoint(o_store),
                              "faulted-final vs oracle-final")
    gates["bitwise_oracle_match"] = True
    summary["oracle"] = {"exit": rc, "leaves_compared": n_leaves}

    # -- goodput: rollback replay charged to rollback_badput ----------------
    faulted_gp = goodput.decompose([os.path.join(work, "faulted")])
    seg = faulted_gp["segments"]
    gates["rollback_badput_positive"] = seg["rollback_badput_s"] > 0.0
    gates["no_crash_badput"] = seg["restart_badput_s"] == 0.0
    total = sum(seg.values())
    gates["segments_sum_to_wall"] = (
        abs(total - faulted_gp["wall_s"]) <= 0.01 * faulted_gp["wall_s"]
        and faulted_gp["unaccounted_s"] <= 0.01 * faulted_gp["wall_s"])
    oracle_gp = goodput.decompose([os.path.join(work, "oracle", "run.jsonl")])
    gates["oracle_zero_badput"] = (
        oracle_gp["segments"]["restart_badput_s"] == 0.0
        and oracle_gp["segments"]["rollback_badput_s"] == 0.0)
    summary["goodput"] = {"faulted": faulted_gp, "oracle": oracle_gp}

    # -- legs 3/4: flag-off pins --------------------------------------------
    if not args.quick:
        print("[anomaly-bench] leg 3/4: guard-on clean pin")
        g_store, rc_g = run_leg(os.path.join(work, "pin_guard"), ["--guard"])
        print("[anomaly-bench] leg 4/4: guard-off pin")
        p_store, rc_p = run_leg(os.path.join(work, "pin_plain"), [])
        gates["pin_legs_complete"] = rc_g == 0 and rc_p == 0
        assert_bitwise(ckpt.newest_valid_checkpoint(g_store),
                       ckpt.newest_valid_checkpoint(p_store),
                       "guard-on-clean vs guard-off", params_only=True)
        gates["guard_flag_bitwise_inert"] = True

    summary["gates"] = gates
    summary["ok"] = all(gates.values())

    # Commit the artifact: summary + goodput + the two faulted streams.
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1, default=str)
    with open(os.path.join(out_dir, "goodput.json"), "w") as f:
        json.dump(summary["goodput"], f, indent=1, default=str)
    for name in ("run.jsonl", "supervisor.jsonl"):
        src = os.path.join(work, "faulted", name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(out_dir, name))

    print(f"[anomaly-bench] gates: "
          + "  ".join(f"{k}={'PASS' if v else 'FAIL'}"
                      for k, v in gates.items()))
    print(f"[anomaly-bench] artifact: {out_dir} "
          f"({'OK' if summary['ok'] else 'FAILED'})")
    if summary["ok"]:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
