"""Pipeline bubble accounting: measured schedule idle vs the stated math.

``parallel/pipeline.py`` states the textbook bubble fraction ``(S-1)/(M+S-1)`` (M
microbatches, S stages) but never measured it (r4 verdict item 4). This tool does:
with the per-microbatch SIZE held fixed, a step costs ``t(M) = c*(M+S-1) + o`` —
``c`` the per-tick time (every device executes every tick in the SPMD formulation;
fill/drain ticks compute masked garbage, which IS the bubble), ``o`` fixed dispatch
overhead. Measuring ``t`` at several M and least-squares fitting (c, o) yields:

- ``per_tick_s``        — c
- ``measured_bubble_fraction``  at each M: ``c*(S-1) / (t(M) - o)``
- ``predicted_bubble_fraction`` at each M: ``(S-1)/(M+S-1)``

agreement of the two columns is the experimental verification of the schedule's
tick model; disagreement would mean ticks are NOT uniform (e.g. ppermute latency
scaling with load). Timing uses the chained two-point protocol
(``utils/benchmarks.chained_diff_time``) so the tunnelled backends' ~70 ms
dispatch tax cannot masquerade as bubble.

Usage: ``python tools/bench_pipeline_bubble.py [--stages 4] [--schedule gpipe|1f1b]
[--out artifact.json]`` — prints ONE JSON document; CPU-drivable
(``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

# Script-mode import path: ``python tools/bench_pipeline_bubble.py`` puts tools/
# on sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MB, SEQ, EMBED = 8, 8, 64      # microbatch size / tokens / width per tick (fixed)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--stages", type=int, default=4)
    parser.add_argument("--microbatch-counts", type=int, nargs="+",
                        default=[2, 4, 8, 16, 32])
    parser.add_argument("--schedule", choices=("gpipe", "1f1b"), default="gpipe")
    parser.add_argument("--backward", action="store_true",
                        help="time fwd+bwd (value_and_grad) instead of forward-only")
    parser.add_argument("--out", default=None, help="also write the JSON here")
    args = parser.parse_args()
    if len(set(args.microbatch_counts)) < 2:
        parser.error("--microbatch-counts needs >= 2 distinct values — the "
                     "t = c*(M+S-1) + o fit is underdetermined with one point")

    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models.transformer import (
        TransformerBlock,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.parallel import (
        make_mesh, pipeline as pp,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        chained_diff_time,
    )

    S = args.stages
    mesh = make_mesh(S, axis_names=("stage",))
    block = TransformerBlock(num_heads=4, dropout_rate=0.0)
    x0 = jnp.zeros((1, SEQ, EMBED), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(0), S)
    stacked = pp.stack_stage_params(
        [block.init({"params": k}, x0)["params"] for k in keys])
    stage_fn = lambda p, x: block.apply({"params": p}, x)

    rows = []
    for m in args.microbatch_counts:
        xs = jnp.asarray(np.random.default_rng(m).normal(
            size=(m, MB, SEQ, EMBED)).astype(np.float32))

        def run_once(xs):
            y = pp.pipeline_apply(mesh, stage_fn, stacked, xs,
                                  schedule=args.schedule)
            return jnp.sum(y ** 2)

        if args.backward:
            val_fn = jax.value_and_grad(
                lambda sp, xs: jnp.sum(pp.pipeline_apply(
                    mesh, stage_fn, sp, xs, schedule=args.schedule) ** 2))

            def chain(n):
                def body(carry, _):
                    sp, acc = carry
                    v, g = val_fn(sp, xs)
                    # Serialize each iteration on the previous grads (1e-20 rounds
                    # away; the compiler cannot prove it, so nothing is elided).
                    sp = jax.tree_util.tree_map(lambda a, b: a + 1e-20 * b, sp, g)
                    return (sp, acc + v), None

                def run(sp):
                    (sp, acc), _ = jax.lax.scan(body, (sp, 0.0), None, length=n)
                    return acc + jax.tree_util.tree_leaves(sp)[0].ravel()[0]

                compiled = jax.jit(run)
                return lambda: float(compiled(stacked))
        else:
            def chain(n):
                def body(x, _):
                    y = pp.pipeline_apply(mesh, stage_fn, stacked, x,
                                          schedule=args.schedule)
                    return y + 1e-20 * x, None

                def run(x):
                    y, _ = jax.lax.scan(body, x, None, length=n)
                    return jnp.sum(y[0, 0, 0])

                compiled = jax.jit(run)
                return lambda: float(compiled(xs))

        per_iter, _, (n2, t2), converged = chained_diff_time(chain)
        rows.append({"microbatches": m, "ticks": m + S - 1,
                     "step_seconds": per_iter, "converged": converged,
                     "chain_n2": n2})
        print(f"M={m}: {per_iter:.6f} s/step (ticks={m + S - 1}, "
              f"converged={converged})", file=sys.stderr)

    # Least-squares t = c*ticks + o over the measured rows.
    ticks = np.array([r["ticks"] for r in rows], float)
    ts = np.array([r["step_seconds"] for r in rows], float)
    A = np.stack([ticks, np.ones_like(ticks)], axis=1)
    (c, o), residuals, *_ = np.linalg.lstsq(A, ts, rcond=None)
    for r, t in zip(rows, ts):
        r["predicted_bubble_fraction"] = round((S - 1) / r["ticks"], 4)
        r["measured_bubble_fraction"] = round(float(c * (S - 1) / (t - o)), 4)

    dev = jax.devices()[0]
    doc = {
        "metric": "pipeline schedule bubble (measured vs (S-1)/(M+S-1))",
        "stages": S, "schedule": args.schedule,
        "direction": "fwd+bwd" if args.backward else "fwd",
        "microbatch_size": MB, "seq": SEQ, "embed": EMBED,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "per_tick_s": float(c), "fixed_overhead_s": float(o),
        "fit_residual": float(residuals[0]) if len(residuals) else 0.0,
        "rows": rows,
    }
    print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
