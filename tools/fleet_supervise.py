"""Run a training fleet under the resilience supervisor: crash → restart from the
newest valid checkpoint, hang → teardown + restart, SIGTERM → cooperative preemption.

The command after ``--`` is what each fleet process runs (same contract as
``train.launch``: every process gets the same command plus rendezvous env). Give the
trainer the resilience flags and the supervisor the matching dirs::

    python tools/fleet_supervise.py --num-processes 2 --platform cpu \\
        --max-restarts 3 --heartbeat-timeout 300 \\
        --checkpoint-dir results/checkpoints --heartbeat-dir results/heartbeats \\
        --telemetry results/supervisor.jsonl -- \\
        -m csed_514_project_distributed_training_using_pytorch_tpu.train.distributed \\
        --epochs 6 --keep-checkpoints 3 --handle-preemption

Kill a worker mid-run (``kill -9 <pid>``, or arm ``RESILIENCE_FAULTS`` — see
``resilience/faults.py``) and watch the supervisor tear the fleet down and resume it
from the last checkpoint whose checksum verifies. SIGTERM the supervisor itself to
preempt the whole run: it forwards the signal, the trainers stop at the next epoch
boundary with a durable checkpoint, and everything exits 75 ("preempted, resumable").

A ``--guard`` trainer that trips its ``--anomaly-exit`` policy exits 65
("poisoned": the math failed, not the process) — the supervisor then rolls back
to the newest HEALTHY (health-stamped-clean) checkpoint and restarts with a
``--skip-steps`` window covering the poisoned steps; repeated poison widens the
window, scattered poison arms cross-replica fingerprint verification.

Exit status: 0 on success, 75 when preempted, otherwise the fleet's failing exit code.
Render the supervisor's telemetry (restart events) with ``tools/telemetry_report.py``.
"""

from __future__ import annotations

import argparse
import os
import sys

# Script-mode import path: ``python tools/fleet_supervise.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csed_514_project_distributed_training_using_pytorch_tpu.resilience.supervisor import (  # noqa: E402
    SupervisorConfig,
    supervise,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n", 1)[0],
        usage="python tools/fleet_supervise.py [options] -- <python args>")
    p.add_argument("--num-processes", type=int, default=2)
    p.add_argument("--platform", default=None,
                   help="force a JAX platform in children (e.g. cpu for emulation)")
    p.add_argument("--devices-per-process", type=int, default=1)
    p.add_argument("--port", type=int, default=None,
                   help="coordinator port (default: a free one per attempt)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restart budget (attempts = restarts + 1)")
    p.add_argument("--backoff", type=float, default=1.0,
                   help="restart backoff seconds (doubles per restart)")
    p.add_argument("--backoff-max", type=float, default=30.0)
    p.add_argument("--checkpoint-dir", default="",
                   help="versioned checkpoint store (trainer --keep-checkpoints) to "
                        "resume from; newest VALID checkpoint wins, torn writes are "
                        "skipped")
    p.add_argument("--heartbeat-dir", default="",
                   help="fleet liveness dir; auto-appended to the child command")
    p.add_argument("--heartbeat-timeout", type=float, default=0.0,
                   help="seconds of beat staleness that counts as hung (0 off); "
                        "set comfortably above one epoch's wall time")
    p.add_argument("--attempt-timeout", type=float, default=0.0,
                   help="wall-clock bound per attempt (0 = unbounded)")
    p.add_argument("--fingerprint-verify", action="store_true",
                   help="compare cross-replica heartbeat param fingerprints "
                        "(--guard trainers emit them): a mismatch at the same "
                        "step is classified 'desync' and rolled back like "
                        "poison. Auto-armed when poison lands at scattered "
                        "steps")
    p.add_argument("--telemetry", default="",
                   help="supervisor JSONL (restart events) path")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="everything after -- runs as: python <command>")
    args = p.parse_args(argv)
    command = args.command[1:] if args.command[:1] == ["--"] else args.command
    if not command:
        p.error("no command given — pass e.g. `-- -m <module> [args]`")

    cfg = SupervisorConfig(
        num_processes=args.num_processes, platform=args.platform,
        devices_per_process=args.devices_per_process, port=args.port,
        max_restarts=args.max_restarts, backoff_s=args.backoff,
        backoff_max_s=args.backoff_max, checkpoint_dir=args.checkpoint_dir,
        heartbeat_dir=args.heartbeat_dir,
        heartbeat_timeout_s=args.heartbeat_timeout,
        attempt_timeout_s=args.attempt_timeout, telemetry=args.telemetry,
        fingerprint_verify=args.fingerprint_verify)
    result = supervise(command, cfg)
    print(f"[supervisor] {result.status}: exit {result.exit_code}, "
          f"{result.attempts} attempt(s), {result.restarts} restart(s)")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
