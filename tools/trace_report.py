"""Render distributed-tracing span JSONL: critical paths, slow traces, Chrome export.

Input is whatever a traced serving run left behind — the span files under a
``--trace-dir`` (``loadgen.jsonl``, ``router.jsonl`` or ``server.jsonl``, one
``replica<i>.jsonl`` per replica; see ``utils/trace.py`` for the span schema)
plus, optionally, the run's serve/route telemetry JSONL. Pass files or
directories in any mix: span events are assembled into per-request trees by
``trace_id``, every non-span event feeds the TTFT reconciliation.

The report answers "where did request 1234's milliseconds go":

- **critical path**: per-segment exclusive seconds (router queue wait, routing,
  failed dispatch hops, replica queue wait, prefill, speculative draft/verify,
  first-token decode, decode tail, resolve, transport/scheduling overhead)
  reduced to p50/p95/mean across all traces;
- **slowest N**: the worst end-to-end traces with their full span trees —
  every span, time-offset and duration, in cross-process anchored order, with
  redispatch hops (and their crash/preempt/hang causes) called out;
- **reconciliation**: span-derived TTFT percentiles against the latency
  telemetry's own (route events for fleets, serve events for a single server)
  — the cross-check that the tracing plane measures the same reality the
  percentile tables report;
- **orphans**: traces with no terminal span (no ``resolve``/``client``) — a
  stranded future or a lost span file; zero in a healthy run;
- **Chrome export** (``--chrome out.json``): trace-event JSON loadable in
  ``chrome://tracing`` / Perfetto — one track per process (router first, then
  replicas, then clients), one lane per request, span attrs searchable under
  ``args``. ``--validate`` gates the export against the trace-event schema
  (every span has pid/tid/ts/dur, pids resolve to process names, every event
  carries its trace_id) and exits nonzero on problems or orphans — the CI
  trace-smoke contract.

Usage::

    python tools/trace_report.py results/trace/
    python tools/trace_report.py results/trace/ results/router.jsonl \\
        --slowest 3 --chrome results/chrome_trace.json --validate
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Script-mode import path: ``python tools/trace_report.py`` puts tools/ on
# sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csed_514_project_distributed_training_using_pytorch_tpu.utils.trace import (  # noqa: E402
    SEGMENTS,
    chrome_trace,
    lifecycle_timeline,
    read_spans,
    reconcile_ttft,
    summarize_traces,
    validate_chrome,
)


def _ms(x) -> str:
    return "-" if x is None else f"{x * 1e3:.1f}"


def print_segments(summary: dict) -> None:
    seg = summary["segments"]
    if not seg:
        print("no segment time recorded")
        return
    head = "segment".ljust(20) + "".join(c.rjust(12)
                                         for c in ("p50 ms", "p95 ms", "mean ms"))
    print(head)
    print("-" * len(head))
    for name in SEGMENTS:
        if name not in seg:
            continue
        row = seg[name]
        print(name.ljust(20) + _ms(row.get("p50")).rjust(12)
              + _ms(row.get("p95")).rjust(12) + _ms(row.get("mean")).rjust(12))


def print_trace_tree(tid: str, spans: list[dict], down: dict) -> None:
    """One trace's spans in anchored order, offsets relative to trace start."""
    causes = ", ".join(c or "?" for c in down["redispatch_causes"])
    print(f"  trace {tid}: e2e {_ms(down['e2e_s'])}ms, "
          f"ttft {_ms(down['ttft_s'])}ms, finish {down['finish'] or '?'}, "
          f"{down['hops']} hop(s)" + (f" (redispatch: {causes})" if causes else ""))
    ids = ", ".join(f"{proc}#{rid}" for proc, rid
                    in sorted(down["request_ids"].items()))
    if ids:
        print(f"    request ids: {ids}")
    for s in spans:
        attrs = {k: v for k, v in s.items()
                 if k not in ("event", "trace_id", "name", "proc", "ts",
                              "dur_s", "t_s", "request_id")}
        extra = "".join(f" {k}={v}" for k, v in sorted(attrs.items()))
        print(f"    +{(s['ts'] - down['start']) * 1e3:8.1f}ms "
              f"{_ms(s.get('dur_s')).rjust(8)}ms  "
              f"{(s.get('proc') or '?').ljust(10)} {s['name']}{extra}")


def print_reconciliation(rec: dict | None) -> None:
    if rec is None:
        print("ttft reconciliation: no latency events alongside the spans "
              "(pass the run's --telemetry JSONL too)")
        return
    print(f"ttft reconciliation (span-derived vs '{rec['source']}' events):")
    for q in ("p50", "p95"):
        ratio = rec.get(f"{q}_ratio")
        print(f"  {q}: span {_ms(rec['span'].get(q))}ms vs "
              f"event {_ms(rec['events'].get(q))}ms"
              + (f"  ({ratio:.3f}x)" if ratio is not None else ""))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="+",
                   help="span JSONL files/dirs, optionally mixed with the "
                        "run's telemetry JSONL (for TTFT reconciliation)")
    p.add_argument("--slowest", type=int, default=5,
                   help="how many worst-e2e traces get their full span tree "
                        "printed (0 = none)")
    p.add_argument("--chrome", default="",
                   help="write Chrome trace-event JSON here "
                        "(chrome://tracing / Perfetto)")
    p.add_argument("--validate", action="store_true",
                   help="exit nonzero on orphan traces or a Chrome export "
                        "that fails the trace-event schema check")
    args = p.parse_args(argv)

    spans, events = read_spans(args.paths)
    if not spans:
        print("no spans found (was the run traced? pass --trace-dir to "
              "tools/serve_loadgen.py)")
        return 1
    summary = summarize_traces(spans)

    print(f"== {summary['traces']} traces, {summary['spans']} spans, "
          f"{summary['redispatched']} redispatched, "
          f"{summary['orphans']} orphan(s)")
    ttft, e2e = summary["ttft_s"], summary["e2e_s"]
    if e2e:
        print(f"   e2e p50 {_ms(e2e.get('p50'))}ms  p95 {_ms(e2e.get('p95'))}ms"
              + (f"   ttft p50 {_ms(ttft.get('p50'))}ms  "
                 f"p95 {_ms(ttft.get('p95'))}ms" if ttft else ""))
    print()
    print_segments(summary)
    print()
    print_reconciliation(reconcile_ttft(summary, events))

    lifecycle = lifecycle_timeline(spans)
    if lifecycle:
        # The fleet's own history (scale_up/scale_down/reload), excluded from
        # the per-request accounting above but rendered as its own timeline —
        # offsets relative to the earliest REQUEST span so the scale actions
        # line up with the traffic that caused them.
        base = min((s["ts"] for s in spans
                    if s.get("name") not in ("scale", "reload")),
                   default=lifecycle[0]["ts"])
        print(f"\nfleet lifecycle ({len(lifecycle)} scale/reload event(s)):")
        for s in lifecycle:
            attrs = "".join(f" {k}={s[k]}" for k in
                            ("action", "replica", "target", "reason",
                             "checkpoint") if s.get(k) not in (None, ""))
            print(f"  +{(s['ts'] - base) * 1e3:8.1f}ms  {s['name']}{attrs}")

    if args.slowest > 0:
        traces = summary["by_trace"]
        print(f"\nslowest {min(args.slowest, len(traces))} trace(s):")
        by_id = {}
        for s in spans:
            by_id.setdefault(s.get("trace_id"), []).append(s)
        for tid in list(traces)[:args.slowest]:
            print_trace_tree(
                tid, sorted(by_id[tid], key=lambda s: (s["ts"],
                                                       s.get("dur_s") or 0)),
                traces[tid])

    if summary["orphans"]:
        print(f"\nWARNING: {summary['orphans']} orphan trace(s) — no terminal "
              f"resolve/client span: {', '.join(summary['orphan_ids'][:8])}")

    problems = []
    if args.chrome:
        doc = chrome_trace(spans)
        problems = validate_chrome(doc)
        with open(args.chrome, "w") as f:
            json.dump(doc, f)
        n_x = sum(e.get("ph") == "X" for e in doc["traceEvents"])
        print(f"\nchrome trace -> {args.chrome} ({n_x} events, "
              f"{'valid' if not problems else f'{len(problems)} problem(s)'}) "
              f"— load in chrome://tracing or https://ui.perfetto.dev")
        for prob in problems[:10]:
            print(f"  {prob}")

    if args.validate and (problems or summary["orphans"]):
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `trace_report ... | head` closing the pipe mid-span-tree is normal
        # usage, not an error worth a traceback.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
