"""fleet_top: a live console dashboard over the router's telemetry stream.

``top`` for the serving fleet: tails a ``serving/router.py`` telemetry JSONL
(the file ``--snapshot-interval-s`` populates with ``fleet_snapshot`` lines)
and renders the current fleet state in place — queue depth/age, utilization,
per-replica occupancy and state, scale/restart counters, and SLO attainment
(fleet-wide and per replica, when the run carries a spec — ``--slo`` on
``tools/serve_loadgen.py``). Point it at a live run's file from another
terminal; it follows appends like ``tail -f``.

Backend-free BY DOCTRINE (graftlint ``backend-purity``): this process watches
a fleet, it must never claim a device — no jax import, transitively. It is
also crash-tolerant by construction: lines arrive through an incremental
tailer that only parses COMPLETE lines (a writer mid-line never confuses it)
and the files it reads are append-only.

Usage::

    python tools/fleet_top.py results/router.jsonl              # follow
    python tools/fleet_top.py results/router.jsonl --once       # one frame
    python tools/fleet_top.py results/router.jsonl --interval 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class JsonlTail:
    """Incremental JSONL follower: each ``poll()`` returns the rows appended
    since the last one, parsing only COMPLETE lines (the trailing partial line
    a mid-emit writer leaves stays buffered until its newline arrives). A
    file that does not exist yet polls as empty — the dashboard can start
    before the run does. Truncation (a fresh run reusing the path) resets the
    offset, so the dashboard follows the new run instead of going silent."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._partial = b""

    def poll(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._offset:          # truncated: a new run took the path
            self._offset = 0
            self._partial = b""
        rows: list[dict] = []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read()
            self._offset = f.tell()
        data = self._partial + chunk
        lines = data.split(b"\n")
        self._partial = lines.pop()      # b"" after a complete final line
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue                 # a malformed interior line: skip, keep tailing
        return rows


class FleetState:
    """The dashboard's reduction of the event stream: last snapshot, config,
    recent scale/replica transitions, drain summary."""

    def __init__(self, events_tail: int = 6):
        self.config: dict | None = None
        self.snapshot: dict | None = None
        self.summary: dict | None = None
        self.slo: dict | None = None
        self.anomaly: dict | None = None     # latest --guard verdict
        self.rollbacks = 0                   # poisoned/desync restarts seen
        self.snapshots = 0
        self.recent: list[str] = []
        self._events_tail = events_tail

    def feed(self, rows) -> None:
        for r in rows:
            kind = r.get("event")
            if kind == "router_config":
                self.config = r
                self.summary = None      # a new run superseded the old drain
            elif kind == "fleet_snapshot":
                self.snapshot = r
                self.snapshots += 1
            elif kind == "router_summary":
                self.summary = r
            elif kind == "slo":
                self.slo = r
            elif kind == "anomaly":
                self.anomaly = r
            elif kind in ("scale", "replica", "eject", "hedge", "chaos",
                          "restart", "tier", "kv_handoff", "promote",
                          "canary"):
                t = r.get("t_s")
                stamp = "-" if t is None else f"+{t:.1f}s"
                if kind == "scale":
                    what = (f"scale {r.get('action')} -> target "
                            f"{r.get('target')}")
                elif kind == "eject":
                    what = (f"replica {r.get('replica')} "
                            + ("EJECTED (degraded)"
                               if r.get("action") == "eject"
                               else "probed back to ready"))
                elif kind == "hedge":
                    what = (f"hedge: request {r.get('request_id')} -> "
                            f"replica {r.get('replica')}")
                elif kind == "chaos":
                    what = (f"chaos {r.get('kind')} on replica "
                            f"{r.get('replica')} ({r.get('dir')})")
                elif kind == "tier":
                    what = (f"replica {r.get('replica')} joined tier "
                            f"{r.get('tier')}")
                elif kind == "kv_handoff":
                    what = (f"kv handoff {r.get('from_replica')} -> "
                            f"{r.get('to_replica')}: "
                            + (f"{r.get('bytes')} bytes" if r.get("ok")
                               else f"FAILED ({r.get('reason')})"))
                elif kind == "restart":
                    if r.get("reason") in ("poisoned", "desync"):
                        self.rollbacks += 1
                    what = (f"restart ({r.get('reason')})"
                            + (f" skipping {r['skip']}" if r.get("skip")
                               else ""))
                elif kind == "promote":
                    what = (f"promote {r.get('action')}: "
                            f"{os.path.basename(r.get('candidate') or '?')}"
                            + (f" ({r.get('reason')})" if r.get("reason")
                               else ""))
                elif kind == "canary":
                    what = (f"canary {r.get('verdict')} on replica "
                            f"{r.get('replica')}: "
                            f"{os.path.basename(r.get('candidate') or '?')}"
                            + (f" ({r.get('reason')})" if r.get("reason")
                               else ""))
                else:
                    what = (f"replica {r.get('replica')} {r.get('action')}"
                            + (f" ({r.get('reason')})" if r.get("reason")
                               else ""))
                self.recent.append(f"{stamp}  {what}")
                self.recent = self.recent[-self._events_tail:]


def _fmt(x, digits: int = 3) -> str:
    if x is None:
        return "-"
    if isinstance(x, float):
        return f"{x:.{digits}f}"
    return str(x)


def _bar(frac: float | None, width: int = 12) -> str:
    if frac is None:
        return " " * width
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


def render(state: FleetState, path: str) -> str:
    """One dashboard frame as a string (pure: testable without a tty)."""
    lines: list[str] = []
    snap = state.snapshot or {}
    cfg = state.config or {}
    queue = snap.get("queue") or {}
    util = snap.get("utilization")
    lines.append(f"fleet_top — {path}"
                 + ("  [DRAINED]" if state.summary else ""))
    lines.append(
        f"  target {_fmt(snap.get('target') or cfg.get('replicas'))}"
        f"  ready {_fmt(snap.get('replicas_ready'))}"
        f"  util {_bar(util)} {_fmt(util)}"
        f"  inflight {_fmt(snap.get('inflight'))}"
        f"/{_fmt(snap.get('capacity_up'))}")
    lines.append(
        f"  queue depth {_fmt(queue.get('depth'))}"
        f"  oldest {_fmt(queue.get('oldest_age_s'))}s"
        f"  requests {_fmt(snap.get('requests'))}"
        f"  ok {_fmt(snap.get('ok'))}"
        f"  redispatches {_fmt(snap.get('redispatches'))}"
        f"  restarts {_fmt(snap.get('restarts'))}")
    if (snap.get("replicas_degraded") or snap.get("ejections")
            or snap.get("hedges") or snap.get("wire_corrupt")):
        # The gray-failure row (DESIGN.md §23): who is sitting out, how often
        # the fleet hedged around slowness, and how much wire damage was
        # contained as typed faults.
        lines.append(
            f"  degraded {_fmt(snap.get('replicas_degraded'))}"
            f"  ejections {_fmt(snap.get('ejections'))}"
            f"  hedges {_fmt(snap.get('hedges'))}"
            f" (wins {_fmt(snap.get('hedge_wins'))})"
            f"  wire corrupt {_fmt(snap.get('wire_corrupt'))}")
    if snap.get("handoffs") or snap.get("handoff_failures"):
        # The disaggregation row (DESIGN.md §25): how much prefill→decode KV
        # traffic the tiers are moving, and whether any handoffs bounced back
        # to a classic local prefill.
        lines.append(
            f"  handoffs {_fmt(snap.get('handoffs'))}"
            f"  bytes {_fmt(snap.get('handoff_bytes'))}"
            f"  failed {_fmt(snap.get('handoff_failures'))}")
    if state.anomaly or state.rollbacks:
        # The training-integrity row (--guard runs): detected anomalies, the
        # identity-skipped steps, and how many supervised rollbacks the run
        # has absorbed.
        a = state.anomaly or {}
        lines.append(
            f"  anomalies {_fmt(a.get('anomalies'))}"
            f" ({_fmt(a.get('nonfinite'))} nonfinite,"
            f" {_fmt(a.get('spikes'))} spikes)"
            f"  skipped {_fmt(a.get('skipped'))}"
            f"  rollbacks {_fmt(state.rollbacks)}"
            + (f"  skip {a['skip']}" if a.get("skip") else ""))
    slo = snap.get("slo")
    if slo:
        lines.append(
            f"  SLO window: attainment {_bar(slo.get('attainment'))} "
            f"{_fmt(slo.get('attainment'))} over {slo.get('requests')} "
            f"request(s)")
    elif state.slo:
        run = state.slo
        lines.append(
            f"  SLO run-level ({run.get('source')}): "
            f"{_fmt(run.get('attainment'))} "
            f"({run.get('met')}/{run.get('requests')} met)")
    tens = snap.get("tenants") or {}
    if tens:
        # The per-tenant live rows: who is in flight, who is queued, who is
        # being shed, and whether each tier's windowed promise holds — the
        # at-a-glance view of "paid traffic protected, best-effort absorbing".
        lines.append("")
        lines.append(f"  {'tenant':<10} {'infl':>4} {'queued':>6} "
                     f"{'shed':>5} {'quota':>5} {'slo-att':>8} {'slo-n':>5}")
        for name in sorted(tens):
            r = tens[name] or {}
            slo = r.get("slo") or {}
            lines.append(
                f"  {name:<10} {_fmt(r.get('inflight')):>4} "
                f"{_fmt(r.get('queued')):>6} {_fmt(r.get('shed')):>5} "
                f"{_fmt(r.get('quota_rejected')):>5} "
                f"{_fmt(slo.get('attainment')):>8} "
                f"{_fmt(slo.get('requests')):>5}")
    per = snap.get("per_replica") or []
    if per:
        lines.append("")
        head = (f"  {'rep':>3} {'state':<9} {'infl':>4} {'cap':>4} "
                f"{'occ':>6} {'restarts':>8} {'done':>6}")
        # The gray-failure columns appear once any replica has been ejected
        # or received a hedge copy — "degraded" shows in the state column;
        # these show the history.
        has_gray = any(r.get("ejections") or r.get("hedges") for r in per)
        if has_gray:
            head += f" {'eject':>5} {'hedge':>5}"
        has_slo = any(r.get("slo") for r in per)
        if has_slo:
            head += f" {'slo-att':>8} {'slo-n':>5}"
        # The tier columns appear once any replica declares a non-unified
        # role — which tier it serves and how many handoffs it took part in.
        has_tier = any(r.get("tier") for r in per)
        if has_tier:
            head += f" {'tier':>8} {'hand':>5}"
        # The pages column appears once any replica runs the paged KV layout:
        # in-use/free pool pages plus cumulative admission refusals — pool
        # pressure reads here before it reads as queue depth.
        has_pages = any(r.get("kv_pages") for r in per)
        if has_pages:
            head += f" {'pages':>11} {'refuse':>6}"
        lines.append(head)
        for r in per:
            row = (f"  {r.get('replica'):>3} {str(r.get('state')):<9} "
                   f"{_fmt(r.get('inflight')):>4} {_fmt(r.get('capacity')):>4} "
                   f"{_fmt(r.get('occupancy')):>6} "
                   f"{_fmt(r.get('restarts')):>8} "
                   f"{_fmt(r.get('completed')):>6}")
            if has_gray:
                row += (f" {_fmt(r.get('ejections')):>5} "
                        f"{_fmt(r.get('hedges')):>5}")
            if has_slo:
                rs = r.get("slo") or {}
                row += (f" {_fmt(rs.get('attainment')):>8} "
                        f"{_fmt(rs.get('requests')):>5}")
            if has_tier:
                row += (f" {str(r.get('tier') or '-'):>8} "
                        f"{_fmt(r.get('handoffs')):>5}")
            if has_pages:
                kp = r.get("kv_pages") or {}
                pages = (f"{_fmt(kp.get('in_use'))}/{_fmt(kp.get('free'))}"
                         if kp else "-")
                row += (f" {pages:>11} {_fmt(kp.get('refusals')):>6}")
            lines.append(row)
    if state.recent:
        lines.append("")
        lines.append("  recent events:")
        lines.extend(f"    {e}" for e in state.recent)
    if state.summary:
        s = state.summary
        lines.append("")
        lines.append(
            f"  drained: {_fmt(s.get('requests'))} requests, "
            f"tokens/s {_fmt(s.get('tokens_per_s'))}, "
            f"ttft p95 {_fmt(((s.get('ttft_s') or {}).get('p95')))}s"
            + (f", slo attainment {_fmt((s.get('slo') or {}).get('attainment'))}"
               if s.get("slo") else ""))
    if not state.snapshot and not state.summary:
        lines.append("  (waiting for fleet_snapshot events — is the run "
                     "emitting with --snapshot-interval-s > 0?)")
    lines.append("")
    lines.append(f"  {state.snapshots} snapshot(s) seen — ctrl-c to quit")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("telemetry", help="the router's telemetry JSONL to tail")
    p.add_argument("--interval", type=float, default=1.0,
                   help="refresh seconds (follow mode)")
    p.add_argument("--once", action="store_true",
                   help="render one frame from the file's current contents "
                        "and exit (no ANSI, no loop — scripts/tests)")
    args = p.parse_args(argv)

    tail = JsonlTail(args.telemetry)
    state = FleetState()
    if args.once:
        state.feed(tail.poll())
        print(render(state, args.telemetry))
        return 0
    try:
        while True:
            state.feed(tail.poll())
            frame = render(state, args.telemetry)
            # Home + clear-to-end per frame: repaint without scrollback spam.
            sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
