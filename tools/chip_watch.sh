#!/bin/bash
# Poll for a live TPU window; when one opens, run the serialized hardware
# follow-ups (tools/hw_followups.sh). The tunnelled chip claim is exclusive and
# a killed holder can wedge the lease for hours, so the probe is a short-leash
# child that exits cleanly on success and is SIGTERM'd on timeout.
#
#   bash tools/chip_watch.sh [max_polls] [sleep_seconds]
set -u
cd "$(dirname "$0")/.."
MAX_POLLS=${1:-40}
SLEEP_S=${2:-600}
OUT=${HW_OUT:-/tmp/hw_r3}
mkdir -p "$OUT"

for ((i = 1; i <= MAX_POLLS; i++)); do
  echo "[chip_watch] poll $i/$MAX_POLLS $(date -u +%H:%M:%S)"
  timeout --signal=TERM 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    > "$OUT/poll.out" 2>&1
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "[chip_watch] TPU LIVE — running hw_followups.sh"
    HW_OUT="$OUT" bash tools/hw_followups.sh 2>&1 | tee "$OUT/followups.log"
    frc=${PIPESTATUS[0]}
    echo "[chip_watch] followups done rc=$frc"
    exit "$frc"
  fi
  echo "[chip_watch] not reachable (rc=$rc)"
  [ "$i" -lt "$MAX_POLLS" ] && sleep "$SLEEP_S"
done
echo "[chip_watch] gave up after $MAX_POLLS polls"
exit 1
