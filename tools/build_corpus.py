"""Build a sharded token corpus (``data/stream.py`` format) from text files.

The streaming loader (DESIGN.md §26) consumes a directory of fixed-length
token-sequence shards plus a ``corpus.json`` manifest. This tool is the one
producer of that layout: it byte-level-tokenizes any set of text/binary files
(ids 0..255 — the zero-vocabulary-file tokenizer, deterministic by
construction), packs the concatenated stream into ``seq_len`` sequences,
reserves a held-out tail as the eval split, and writes the rest as uint16
``.npy`` shards with recorded sha256 — the loader verifies each shard on first
touch, so a corpus edited under a checkpoint is an error, not a reshuffle.

Everything is deterministic in the inputs: files are processed in the order
given (sort them yourself for path-set stability), packing drops the ragged
byte tail, and the eval split is the LAST ``--eval-frac`` of sequences (no
RNG anywhere — shuffling is the loader's job, keyed by ``(seed, epoch)``).

``--synthetic-chars N`` generates a deterministic pseudo-text stream instead
of reading inputs — the fixture generator (``tests/fixtures/corpus_tiny`` is
committed output of this mode) and the quick way to exercise the pipeline on
a machine with no corpus at hand.

Usage::

    python tools/build_corpus.py --out corpus/ --seq-len 128 \\
        --shard-sequences 512 --eval-frac 0.1 README.md DESIGN.md src/*.py
    python tools/build_corpus.py --out tests/fixtures/corpus_tiny \\
        --seq-len 64 --shard-sequences 48 --eval-frac 0.2 \\
        --synthetic-chars 12000 --synthetic-seed 7
"""

from __future__ import annotations

import argparse
import hashlib
import io
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from csed_514_project_distributed_training_using_pytorch_tpu.data.stream import (  # noqa: E402
    META_NAME,
)

BYTE_VOCAB = 256


def synthetic_text(chars: int, seed: int) -> bytes:
    """Deterministic pseudo-text: word-ish tokens over a small alphabet with
    punctuation/newlines — enough structure that a byte LM has something to
    learn, zero external inputs."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, chars]))
    words = ["the", "model", "serves", "tokens", "shard", "stream", "epoch",
             "batch", "cursor", "resume", "canary", "promote", "fleet",
             "replica", "goodput", "train", "deploy", "rollback", "manifest",
             "checkpoint"]
    out: list[str] = []
    n = 0
    while n < chars:
        w = words[int(rng.integers(len(words)))]
        sep = "\n" if rng.random() < 0.08 else (". " if rng.random() < 0.1
                                                else " ")
        out.append(w + sep)
        n += len(w) + len(sep)
    return "".join(out).encode("ascii")[:chars]


def pack_stream(stream: bytes, seq_len: int) -> np.ndarray:
    """Byte ids → ``[N, seq_len]`` uint16 sequences, ragged tail dropped."""
    ids = np.frombuffer(stream, dtype=np.uint8).astype(np.uint16)
    n = len(ids) // seq_len
    if n == 0:
        raise SystemExit(f"input stream has {len(ids)} tokens — fewer than one "
                         f"sequence of {seq_len}")
    return ids[:n * seq_len].reshape(n, seq_len)


def _write_npy(path: str, arr: np.ndarray) -> str:
    """Atomic .npy write; returns the sha256 the manifest records."""
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    data = buf.getvalue()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return hashlib.sha256(data).hexdigest()


def build(out_dir: str, sequences: np.ndarray, *, shard_sequences: int,
          eval_frac: float, tokenizer: str = "byte",
          vocab: int = BYTE_VOCAB) -> dict:
    """Write the corpus directory and return its meta (also written as
    ``corpus.json``). Split rule: the last ``ceil(eval_frac * N)`` sequences
    are the eval split (at least one full train shard must remain)."""
    n = len(sequences)
    n_eval = int(np.ceil(eval_frac * n)) if eval_frac > 0 else 0
    if n - n_eval < 1:
        raise SystemExit(f"--eval-frac {eval_frac} leaves {n - n_eval} train "
                         f"sequences of {n} — nothing to train on")
    train, eval_split = sequences[:n - n_eval], sequences[n - n_eval:]
    os.makedirs(out_dir, exist_ok=True)
    shards = []
    for i, start in enumerate(range(0, len(train), shard_sequences)):
        chunk = train[start:start + shard_sequences]
        name = f"shard_{i:04d}.npy"
        digest = _write_npy(os.path.join(out_dir, name), chunk)
        shards.append({"file": name, "sequences": int(len(chunk)),
                       "sha256": digest})
    meta = {"version": 1, "tokenizer": tokenizer, "vocab": int(vocab),
            "seq_len": int(sequences.shape[1]), "shards": shards,
            "eval": None}
    if n_eval:
        digest = _write_npy(os.path.join(out_dir, "eval.npy"), eval_split)
        meta["eval"] = {"file": "eval.npy", "sequences": int(n_eval),
                        "sha256": digest}
    tmp = os.path.join(out_dir, META_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(out_dir, META_NAME))
    return meta


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="tokenize/pack text files into a sharded token corpus")
    ap.add_argument("inputs", nargs="*", help="text files to tokenize, in order")
    ap.add_argument("--out", required=True, help="corpus output directory")
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--shard-sequences", type=int, default=512,
                    help="sequences per shard file")
    ap.add_argument("--eval-frac", type=float, default=0.1,
                    help="held-out tail fraction (0 disables the eval split)")
    ap.add_argument("--synthetic-chars", type=int, default=0,
                    help="generate N chars of deterministic pseudo-text "
                         "instead of reading inputs")
    ap.add_argument("--synthetic-seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.seq_len < 2:
        ap.error(f"--seq-len must be >= 2, got {args.seq_len}")
    if args.shard_sequences < 1:
        ap.error(f"--shard-sequences must be >= 1, got {args.shard_sequences}")
    if bool(args.inputs) == bool(args.synthetic_chars):
        ap.error("pass input files XOR --synthetic-chars")
    if args.synthetic_chars:
        stream = synthetic_text(args.synthetic_chars, args.synthetic_seed)
    else:
        parts = []
        for path in args.inputs:
            with open(path, "rb") as f:
                parts.append(f.read())
        stream = b"\n".join(parts)
    sequences = pack_stream(stream, args.seq_len)
    meta = build(args.out, sequences, shard_sequences=args.shard_sequences,
                 eval_frac=args.eval_frac)
    n_eval = meta["eval"]["sequences"] if meta["eval"] else 0
    print(f"wrote {args.out}: {len(meta['shards'])} shard(s), "
          f"{sum(s['sequences'] for s in meta['shards'])} train + {n_eval} eval "
          f"sequences of seq_len {meta['seq_len']}, vocab {meta['vocab']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
