"""Elastic-fleet acceptance harness: 2→4→1 under kill, rolling reload, warm A/B.

Runs the three PR-9 acceptance legs against REAL jax CPU replicas and writes
the committed artifact (``bench_results/elastic_fleet_cpu/``):

1. **elastic_kill** — a 2→4→1 replica elasticity run (two ``scale_up``s
   mid-load, three graceful ``scale_down``s at the tail) with replica 1
   hard-killed MID-DECODE by fault injection. Gate: every request completes
   with greedy output token-identical to an uninterrupted single-engine run
   of the same workload (zero lost), and the traced run has zero orphan
   traces. The scale-event timeline joined against the ``fleet_snapshot``
   series goes to ``timeline.json``.
2. **reload** — a live ``Router.reload(new_checkpoint)`` under continuous
   load. Gate: every request ok, both replicas rolled, and the
   ``fleet_snapshot`` timeline never shows ready capacity below N−1 once the
   fleet is up.
3. **warm_ab** — scale-up warm-start A/B on a shared-prefix workload:
   ``warm_prefixes=8`` (the new replica replays the fleet's hot prefixes
   before going ready) vs ``warm_prefixes=0`` (cold). Gate: the new
   replica's post-ready prefix-cache hit rate is strictly higher warm than
   cold (the replay's own compulsory misses are excluded by the replica —
   counters reset after warm).

Exits nonzero if any gate fails — the CI ``elasticity-smoke`` contract.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_elastic_fleet.py \\
        --out bench_results/elastic_fleet_cpu
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PKG = "csed_514_project_distributed_training_using_pytorch_tpu"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One tiny-model config for every leg: small enough that a replica compiles in
# seconds on CPU, big enough that prompts/prefixes exercise chunked prefill.
TINY = dict(seq_len=48, levels=9, embed=16, layers=1, heads=2, slots=2,
            max_pending=2)


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (f"{REPO}:{env['PYTHONPATH']}"
                         if env.get("PYTHONPATH") else REPO)
    return env


def _engine_cmd(prefix_cache: int = 0):
    cmd = ["-m", f"{PKG}.serving.replica",
           "--num-levels", str(TINY["levels"] - 1),
           "--seq-len", str(TINY["seq_len"]),
           "--embed-dim", str(TINY["embed"]),
           "--num-layers", str(TINY["layers"]),
           "--num-heads", str(TINY["heads"]),
           "--num-slots", str(TINY["slots"]),
           "--max-pending", str(TINY["max_pending"]),
           "--seed", "0", "--heartbeat-interval-s", "0.02"]
    if prefix_cache:
        cmd += ["--prefix-cache", str(prefix_cache),
                "--prefill-chunks", "8,32"]
    return cmd


def _router(out_dir, name, cmd, n, **kw):
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.router import (
        Router,
    )

    kw.setdefault("heartbeat_dir", os.path.join(out_dir, f"hb_{name}"))
    kw.setdefault("heartbeat_timeout_s", 30.0)
    kw.setdefault("backoff_s", 0.2)
    kw.setdefault("connect_timeout_s", 300.0)
    kw.setdefault("drain_timeout_s", 60.0)
    kw.setdefault("telemetry", os.path.join(out_dir, f"{name}.jsonl"))
    return Router(cmd, num_replicas=n, env=_env(), **kw)


def _workload(n=40, seed=11):
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n):
        p = rng.integers(0, TINY["levels"] - 1,
                         size=int(rng.integers(1, 12))).astype(np.int32)
        reqs.append((p, int(rng.integers(2, 8))))
    return reqs


def _reference(reqs):
    """The same workload through ONE in-process engine, no faults."""
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
        Request,
    )

    model = lm.TransformerLM(vocab_size=TINY["levels"],
                             seq_len=TINY["seq_len"], embed_dim=TINY["embed"],
                             num_layers=TINY["layers"],
                             num_heads=TINY["heads"])
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    engine = ContinuousBatchingEngine(model, params, num_slots=TINY["slots"])
    comps = engine.run([Request(prompt=p, max_new_tokens=m, request_id=i)
                        for i, (p, m) in enumerate(reqs)])
    return {c.request.request_id: np.asarray(c.tokens) for c in comps}


def leg_elastic_kill(out_dir: str) -> dict:
    """2→4→1 with replica 1 killed mid-decode; token-identity gate."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        trace,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
        read_jsonl,
    )

    print("== leg 1: 2→4→1 elasticity under kill-mid-decode")
    reqs = _workload(40)
    ref = _reference(reqs)
    trace_dir = os.path.join(out_dir, "trace_elastic")
    env_key = "RESILIENCE_FAULTS"
    old = os.environ.get(env_key)
    os.environ[env_key] = (f"kill:proc=1,step=4,"
                           f"flag={os.path.join(out_dir, 'kill_flag')}")
    try:
        router = _router(out_dir, "elastic", _engine_cmd(), 2,
                         min_replicas=1, max_replicas=4,
                         trace_dir=trace_dir,
                         snapshot_interval_s=0.2).start()
        try:
            assert router.wait_ready(timeout=300), "fleet never came up"
            t0 = time.monotonic()
            futs = [router.submit(p, max_new_tokens=m) for p, m in reqs[:20]]
            assert router.scale_up() is not None          # 2 -> 3
            assert router.scale_up() is not None          # 3 -> 4
            futs += [router.submit(p, max_new_tokens=m) for p, m in reqs[20:]]
            assert router.wait_ready(timeout=300), "scale-up never ready"
            peak_ready = sum(r.state == "ready" for r in router.replicas)
            comps = [f.result(timeout=300) for f in futs]
            deadline = time.monotonic() + 120
            while (router.replicas[1].restarts < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            for _ in range(3):                            # 4 -> 1
                assert router.scale_down() is not None
            deadline = time.monotonic() + 120
            while (sum(r.state == "retired" for r in router.replicas) < 3
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            wall = time.monotonic() - t0
        finally:
            summ = router.stop(timeout=120)
    finally:
        if old is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = old
    lost = sum(not c.ok for c in comps)
    mismatched = sum(
        not np.array_equal(np.asarray(c.tokens), ref[i])
        for i, c in enumerate(comps))
    spans, _ = trace.read_spans([trace_dir])
    tsumm = trace.summarize_traces(spans)
    rows = read_jsonl(os.path.join(out_dir, "elastic.jsonl"))
    timeline = {
        "snapshots": [
            {"t_s": r.get("t_s"), "queue_depth": (r.get("queue") or {})
             .get("depth"), "oldest_age_s": (r.get("queue") or {})
             .get("oldest_age_s"), "utilization": r.get("utilization"),
             "target": r.get("target"),
             "replicas_ready": r.get("replicas_ready")}
            for r in rows if r.get("event") == "fleet_snapshot"],
        "scale_events": [
            {k: r.get(k) for k in ("t_s", "action", "replica", "target",
                                   "reason")}
            for r in rows if r.get("event") == "scale"],
    }
    with open(os.path.join(out_dir, "timeline.json"), "w") as f:
        json.dump(timeline, f, indent=1)
    leg = {
        "requests": len(comps), "lost": lost,
        "token_mismatches": mismatched,
        "peak_ready_replicas": peak_ready,
        "scale": summ["scale"],
        "redispatches": summ["redispatches"],
        "replica_restarts": summ["replica_restarts"],
        "duplicates": summ["duplicates"],
        "traces": tsumm["traces"], "orphan_traces": tsumm["orphans"],
        "lifecycle_events": len(trace.lifecycle_timeline(spans)),
        "wall_s": round(wall, 3),
        "ok": (lost == 0 and mismatched == 0 and peak_ready == 4
               and summ["scale"]["retired"] == 3
               and summ["redispatches"] >= 1
               and tsumm["orphans"] == 0),
    }
    print(f"   {len(comps)} requests, {lost} lost, {mismatched} token "
          f"mismatches vs single-engine reference; peak {peak_ready} ready; "
          f"scale {summ['scale']}; {summ['redispatches']} redispatches; "
          f"{tsumm['orphans']} orphan traces -> "
          f"{'OK' if leg['ok'] else 'FAIL'}")
    return leg


def leg_reload(out_dir: str) -> dict:
    """Live rolling reload under load; capacity-never-below-N-1 gate."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
        read_jsonl,
    )

    print("== leg 2: rolling Router.reload under load")
    # A REAL checkpoint to roll onto: the same architecture with fresh params
    # (seed 1) — the "new params" the fleet picks up without dropping traffic.
    import jax
    import jax.numpy as jnp

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    model = lm.TransformerLM(vocab_size=TINY["levels"],
                             seq_len=TINY["seq_len"], embed_dim=TINY["embed"],
                             num_layers=TINY["layers"],
                             num_heads=TINY["heads"])
    new_params = model.init({"params": jax.random.PRNGKey(1)},
                            jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    ckpt = os.path.join(out_dir, "rolled_params.msgpack")
    checkpoint.save_params(ckpt, new_params)

    router = _router(out_dir, "reload", _engine_cmd(), 2,
                     snapshot_interval_s=0.1).start()
    try:
        assert router.wait_ready(timeout=300), "fleet never came up"
        stop_load = []
        futs = []
        rng = np.random.default_rng(17)

        def load():
            while not stop_load:
                try:
                    futs.append(router.submit(
                        rng.integers(0, TINY["levels"] - 1,
                                     size=4).astype(np.int32),
                        max_new_tokens=4))
                except Exception:     # router stopping under a failed roll
                    return
                time.sleep(0.05)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
            out = router.reload(ckpt, timeout_s=300)
        finally:
            stop_load.append(True)
            t.join(timeout=10)
        comps = [f.result(timeout=120) for f in futs]
    finally:
        summ = router.stop(timeout=120)
    rows = read_jsonl(os.path.join(out_dir, "reload.jsonl"))
    ready = [r["replicas_ready"] for r in rows
             if r.get("event") == "fleet_snapshot"]
    first_full = next((i for i, v in enumerate(ready) if v == 2), None)
    min_ready = min(ready[first_full:]) if first_full is not None else None
    lost = sum(not c.ok for c in comps)
    leg = {
        "requests": len(comps), "lost": lost,
        "reloaded": out["reloaded"], "reload_wall_s": round(out["wall_s"], 3),
        "snapshots": len(ready), "min_ready_after_full": min_ready,
        "ok": (lost == 0 and out["reloaded"] == [0, 1]
               and min_ready is not None and min_ready >= 1),
    }
    print(f"   {len(comps)} requests during roll, {lost} lost; "
          f"reloaded {out['reloaded']} in {out['wall_s']:.1f}s; ready-replica "
          f"timeline min {min_ready} (N-1 = 1) over {len(ready)} snapshots "
          f"-> {'OK' if leg['ok'] else 'FAIL'}")
    return leg


def _warm_run(out_dir: str, warm_prefixes: int) -> dict:
    """One warm A/B side: build hot prefixes on replica 0, scale up, offer a
    second wave, read the NEW replica's post-ready prefix-cache hit rate."""
    name = f"warm{warm_prefixes}"
    router = _router(out_dir, name, _engine_cmd(prefix_cache=8), 1,
                     max_replicas=2, warm_prefixes=warm_prefixes).start()
    rng = np.random.default_rng(23)
    prefixes = [rng.integers(0, TINY["levels"] - 1, size=24).astype(np.int32)
                for _ in range(6)]

    def wave(per_prefix, tail, seed):
        r2 = np.random.default_rng(seed)
        w = []
        for p in prefixes:
            for _ in range(per_prefix):
                suffix = r2.integers(0, TINY["levels"] - 1,
                                     size=tail).astype(np.int32)
                w.append(np.concatenate([p, suffix]))
        return w

    try:
        assert router.wait_ready(timeout=300)
        futs = [router.submit(p, max_new_tokens=3) for p in wave(1, 4, 5)]
        [f.result(timeout=300) for f in futs]
        idx = router.scale_up()
        assert idx is not None
        assert router.wait_ready(timeout=300)
        warmed = router.replicas[idx].warmed
        # The second wave: 3 requests per hot prefix, offered all at once so
        # replica 0 (capacity 4) overflows and the new replica takes spill.
        futs = [router.submit(p, max_new_tokens=3) for p in wave(3, 4, 9)]
        comps = [f.result(timeout=300) for f in futs]
        lost = sum(not c.ok for c in comps)
    finally:
        summ = router.stop(timeout=120)
    per = {r["replica"]: r for r in summ["per_replica"]}
    pc = ((per[idx].get("stats") or {}).get("engine") or {}).get(
        "prefix_cache") or {}
    rate = (pc["hits"] / pc["queries"]) if pc.get("queries") else None
    return {"warm_prefixes": warm_prefixes, "warmed": warmed,
            "new_replica": idx, "lost": lost,
            "new_replica_queries": pc.get("queries"),
            "new_replica_hits": pc.get("hits"),
            "new_replica_hit_rate": rate}


def leg_warm_ab(out_dir: str) -> dict:
    """Warm-start vs cold-start scale-up on a shared-prefix workload."""
    print("== leg 3: warm-start vs cold-start scale-up A/B")
    warm = _warm_run(out_dir, 8)
    cold = _warm_run(out_dir, 0)
    ok = (warm["lost"] == 0 and cold["lost"] == 0
          and warm["new_replica_hit_rate"] is not None
          and (cold["new_replica_hit_rate"] is None
               or warm["new_replica_hit_rate"]
               > cold["new_replica_hit_rate"]))
    leg = {"warm": warm, "cold": cold, "ok": ok}
    print(f"   new-replica prefix hit rate: warm "
          f"{warm['new_replica_hit_rate']} "
          f"({warm['new_replica_hits']}/{warm['new_replica_queries']}, "
          f"{warm['warmed']} prefixes replayed) vs cold "
          f"{cold['new_replica_hit_rate']} "
          f"({cold['new_replica_hits']}/{cold['new_replica_queries']}) -> "
          f"{'OK' if ok else 'FAIL'}")
    return leg


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--out", default="bench_results/elastic_fleet_cpu",
                   help="artifact directory (summary.json, timeline.json)")
    p.add_argument("--legs", default="kill,reload,warm",
                   help="comma subset of kill,reload,warm")
    args = p.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    legs = [l for l in args.legs.split(",") if l]
    doc = {"config": TINY, "platform": os.environ.get("JAX_PLATFORMS", "")}
    if "kill" in legs:
        doc["elastic_kill"] = leg_elastic_kill(args.out)
    if "reload" in legs:
        doc["reload"] = leg_reload(args.out)
    if "warm" in legs:
        doc["warm_ab"] = leg_warm_ab(args.out)
    ok = all(doc[k]["ok"] for k in ("elastic_kill", "reload", "warm_ab")
             if k in doc)
    doc["ok"] = ok
    path = os.path.join(args.out, "summary.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"{'ALL GATES OK' if ok else 'GATE FAILURE'}; summary -> {path}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
