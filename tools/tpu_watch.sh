#!/bin/bash
# TPU health watcher — ONE PATIENT CLAIMANT, not timeout-probe cycling.
#
# The axon TPU claim is exclusive and granted FIFO when the current lease ends. A
# watcher that probes with `timeout N python -c ...` every minute (a) can't reliably
# kill a probe whose SIGTERM is deferred inside the C++ claim wait, and (b) piles
# abandoned claimants into the grant queue, lengthening the cascade the eventual
# winner waits behind. Instead: run a single python child that BLOCKS on the claim
# for as long as it takes; when the stale lease expires, it is granted within
# seconds, logs HEALTHY, releases, and the loop exits. A child that errors out
# quickly (transient init failure) is retried after a pause.
set -o pipefail
LOG=/root/repo/bench_results/hw_r5/tpu_watch.log
ERR=/tmp/tpu_watch_stderr.txt
echo "$(date -u +%H:%M:%S) patient claimant queued" >> "$LOG"
while true; do
  OUT=$(python - <<'PY' 2>"$ERR" | tail -1
import time; t0 = time.time()
import jax
d = jax.devices()
import jax.numpy as jnp
y = (jnp.ones((8, 8)) + 1).block_until_ready()
print('HEALTHY %.1fs %s' % (time.time() - t0, d[0].device_kind))
PY
)
  RC=$?
  TS=$(date -u +%H:%M:%S)
  case "$OUT" in
    "HEALTHY "*)
      echo "$TS $OUT — running the r5 capture checklist" >> "$LOG"
      # The window may be short and may not recur: capture everything in verdict
      # priority order immediately, then commit, so a recovery during idle turns
      # (or even during driver time) is never wasted.
      HW_OUT=/root/repo/bench_results/hw_r5 bash /root/repo/tools/hw_followups.sh \
        >> "$LOG" 2>&1
      cd /root/repo \
        && git add bench_results/hw_r5 \
        && git commit -m "hw_r5: hardware captures from the recovered chip window

Auto-captured by tools/tpu_watch.sh the moment the claim was granted, in the
checklist's verdict-priority order (tools/hw_followups.sh)." \
        >> "$LOG" 2>&1 || true
      break;;
    *) echo "$TS claimant exited rc=$RC: ${OUT:-$(tail -1 "$ERR")}" >> "$LOG"
       sleep 60;;
  esac
done
